"""Fused 1x1-conv+BN Pallas kernel + FusedBottleneck layer tests
(round 3, VERDICT #1: the cuDNN-platform-engine analog).

Interpreter mode on the CPU rig; jnp implementations are the oracles.
End-to-end ResNet numbers live in bench/PROFILE.md (round-3 section).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers.fused import FusedBottleneck
from deeplearning4j_tpu.ops.pallas.conv_bn import matmul_bn_act


def _oracle(x, w, a, b, relu_in, prologue):
    xh = x * a + b if prologue else x
    if prologue and relu_in:
        xh = jnp.maximum(xh, 0.0)
    y = xh @ w
    return y, jnp.sum(y, 0), jnp.sum(y * y, 0)


class TestMatmulBnAct:
    @pytest.mark.parametrize("prologue,relu_in",
                             [(True, True), (True, False), (False, False)])
    def test_forward_and_grads_match(self, prologue, relu_in):
        rng = np.random.default_rng(0)
        m, k, n = 300, 32, 48              # m % block_m != 0 → pad path
        x = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(k, n)).astype(np.float32) * 0.1)
        a = jnp.asarray(rng.uniform(0.5, 1.5, k).astype(np.float32))
        b = jnp.asarray(rng.normal(size=k).astype(np.float32) * 0.2)
        args = (x, w, a, b) if prologue else (x, w)

        y, s1, s2 = matmul_bn_act(*args, relu_in=relu_in, block_m=64)
        yo, s1o, s2o = _oracle(x, w, a, b, relu_in, prologue)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yo),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s1o),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(s2), np.asarray(s2o),
                                   rtol=1e-4, atol=1e-3)

        # grads through y AND the stats outputs (the BN-training chain)
        def loss_k(*args2):
            y, s1, s2 = matmul_bn_act(*args2, relu_in=relu_in, block_m=64)
            return (jnp.sum(jnp.sin(y)) + jnp.sum(s1 * 0.3)
                    + jnp.sum(jnp.sqrt(jnp.abs(s2))))

        def loss_o(*args2):
            if prologue:
                y, s1, s2 = _oracle(*args2, relu_in, True)
            else:
                y, s1, s2 = _oracle(args2[0], args2[1], a, b, relu_in, False)
            return (jnp.sum(jnp.sin(y)) + jnp.sum(s1 * 0.3)
                    + jnp.sum(jnp.sqrt(jnp.abs(s2))))

        gk = jax.grad(loss_k, argnums=tuple(range(len(args))))(*args)
        go = jax.grad(loss_o, argnums=tuple(range(len(args))))(*args)
        for i, (u, v) in enumerate(zip(gk, go)):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"arg{i}")

    def test_auto_block_pick(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(100, 16)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
        y, s1, s2 = matmul_bn_act(x, w)     # block_m=0 → auto
        yo, s1o, s2o = _oracle(x, w, None, None, False, False)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yo),
                                   rtol=1e-5, atol=1e-5)


def _bottleneck_oracle(p, x, stride, project, eps=1e-5):
    def bn(y, g, b):
        axes = tuple(range(y.ndim - 1))
        mean = jnp.mean(y, axis=axes)
        var = jnp.var(y, axis=axes)
        return (y - mean) * jax.lax.rsqrt(var + eps) * g + b

    xs = x[:, ::stride[0], ::stride[1], :] if stride != (1, 1) else x
    n, h, w, c = xs.shape
    y1 = xs.reshape(-1, c) @ p["W_a"]
    z1 = jnp.maximum(bn(y1, p["gamma_a"], p["beta_a"]), 0).reshape(n, h, w, -1)
    y2 = jax.lax.conv_general_dilated(
        z1, p["W_b3"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    z2 = jnp.maximum(bn(y2, p["gamma_b3"], p["beta_b3"]), 0)
    y3 = bn(z2.reshape(n * h * w, -1) @ p["W_c"], p["gamma_c"], p["beta_c"])
    if project:
        sc = bn(xs.reshape(-1, c) @ p["W_proj"],
                p["gamma_proj"], p["beta_proj"])
    else:
        sc = xs.reshape(n * h * w, -1)
    return jnp.maximum(y3 + sc, 0).reshape(n, h, w, -1)


class TestFusedBottleneck:
    @pytest.mark.parametrize("project,stride,cin",
                             [(True, (1, 1), 16), (True, (2, 2), 32),
                              (False, (1, 1), 32)])
    def test_matches_unfused_composition(self, project, stride, cin):
        rng = np.random.default_rng(0)
        lay = FusedBottleneck(filters=(8, 8, 32), stride=stride,
                              project=project)
        it = InputType.convolutional(8, 8, cin)
        params = lay.init_params(jax.random.key(0), it)
        state = lay.init_state(it)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, cin)).astype(np.float32))
        out, new_state = lay.apply(params, state, x, train=True)
        ref = _bottleneck_oracle(params, x, stride, project)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        # running stats moved off init
        assert not np.allclose(np.asarray(new_state["mean_a"]), 0.0)

        gk = jax.grad(lambda p: jnp.sum(
            lay.apply(p, state, x, train=True)[0] ** 2))(params)
        go = jax.grad(lambda p: jnp.sum(
            _bottleneck_oracle(p, x, stride, project) ** 2))(params)
        for k in gk:
            np.testing.assert_allclose(np.asarray(gk[k]), np.asarray(go[k]),
                                       rtol=3e-3, atol=3e-3, err_msg=k)

    def test_eval_uses_running_stats(self):
        rng = np.random.default_rng(2)
        lay = FusedBottleneck(filters=(4, 4, 8), project=True)
        it = InputType.convolutional(4, 4, 8)
        params = lay.init_params(jax.random.key(0), it)
        state = lay.init_state(it)
        x = jnp.asarray(rng.normal(size=(2, 4, 4, 8)).astype(np.float32))
        _, trained = lay.apply(params, state, x, train=True)
        out1, s1 = lay.apply(params, trained, x, train=False)
        out2, s2 = lay.apply(params, trained, x, train=False)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        # eval must not move the running stats
        np.testing.assert_array_equal(np.asarray(s1["mean_a"]),
                                      np.asarray(trained["mean_a"]))

    def test_resnet50_fused_builds_and_runs(self):
        from deeplearning4j_tpu.models import resnet50
        net = resnet50(height=32, width=32, num_classes=10, fused=True)
        net.init()
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        out = net.output(x)
        assert np.asarray(out).shape == (2, 10)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_checkpoint_remap_fused_unfused(self):
        """Unfused checkpoint → fused graph (and back) is numerically the
        same network in eval mode."""
        from deeplearning4j_tpu.models import resnet50
        from deeplearning4j_tpu.models.zoo import remap_bottleneck_params
        rng = np.random.default_rng(3)
        net_u = resnet50(height=32, width=32, num_classes=10,
                         fused=False).init()
        net_f = resnet50(height=32, width=32, num_classes=10, fused=True).init()
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)).astype(np.float32))
        # train one step worth of stats so running mean/var are non-trivial
        _, net_u.state_, _ = net_u._forward(net_u.params_, net_u.state_, x,
                                            train=True,
                                            rng=jax.random.key(0))

        pf, sf = remap_bottleneck_params(net_u.params_, net_u.state_,
                                         to_fused=True)
        assert set(pf) == set(net_f.params_), "fused key sets must match"
        net_f.params_, net_f.state_ = pf, sf
        out_u = net_u.output(x)
        out_f = net_f.output(x)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_u),
                                   rtol=2e-4, atol=2e-4)

        pu, su = remap_bottleneck_params(pf, sf, to_fused=False)
        assert set(pu) == set(net_u.params_)
        for k in pu:
            jax.tree.map(np.testing.assert_array_equal,
                         pu[k], net_u.params_[k])


class TestFusedConvDefault:
    """ISSUE 11 satellite: FusedBottleneck is the DEFAULT conv-zoo
    lowering behind ``config.fused_conv`` (on by default); an explicit
    ``fused=`` argument always wins.  The numeric pin against the
    unfused path is ``test_checkpoint_remap_fused_unfused`` above —
    here the default graph is proven to be the fused one AND to match
    the unfused oracle on the same weights."""

    def test_default_follows_config_and_explicit_wins(self):
        from deeplearning4j_tpu.config import set_config
        from deeplearning4j_tpu.models import resnet50

        def bottleneck_layers(net):
            return [v.obj for v in net.conf.vertices
                    if isinstance(v.obj, FusedBottleneck)]

        try:
            assert bottleneck_layers(
                resnet50(height=32, width=32, num_classes=4)), \
                "config.fused_conv=True (default) must build FusedBottleneck"
            assert not bottleneck_layers(
                resnet50(height=32, width=32, num_classes=4, fused=False))
            set_config(fused_conv=False)
            assert not bottleneck_layers(
                resnet50(height=32, width=32, num_classes=4))
            assert bottleneck_layers(
                resnet50(height=32, width=32, num_classes=4, fused=True))
        finally:
            set_config(fused_conv=True)

    def test_default_graph_matches_unfused_oracle(self):
        """The shipped default (fused) evaluates to the same function as
        the unfused graph under remapped weights."""
        from deeplearning4j_tpu.models import resnet50
        from deeplearning4j_tpu.models.zoo import remap_bottleneck_params
        rng = np.random.default_rng(7)
        net_d = resnet50(height=32, width=32, num_classes=4).init()
        assert any(isinstance(v.obj, FusedBottleneck)
                   for v in net_d.conf.vertices)
        net_u = resnet50(height=32, width=32, num_classes=4,
                         fused=False).init()
        pu, su = remap_bottleneck_params(net_d.params_, net_d.state_,
                                         to_fused=False)
        net_u.params_, net_u.state_ = pu, su
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(net_u.output(x)),
                                   np.asarray(net_d.output(x)),
                                   rtol=2e-4, atol=2e-4)
