"""tpudl.analyze — AST linter + registry-backed rules.

Acceptance (ISSUE 2): seeded defects per lint family — host-sync-in-jit
(TPU301), missing block_until_ready (TPU302), traced control flow
(TPU303), bare shard_map import (TPU304), bad metric name (TPU305) —
each reported with its rule ID and a non-zero exit; clean code exits 0.
"""

import textwrap

from deeplearning4j_tpu.analyze import check_metric_names, check_op_catalog, lint_paths
from deeplearning4j_tpu.analyze.__main__ import main as analyze_main
from deeplearning4j_tpu.analyze.lint import LINT_RULES, register_lint_rule
from deeplearning4j_tpu.obs.registry import MetricsRegistry


def _lint_source(tmp_path, source, name="mod.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return lint_paths([str(path)])


# ------------------------------------------------------------ TPU301
def test_host_sync_in_jit(tmp_path):
    report = _lint_source(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            v = float(x.sum())
            a = np.asarray(x)
            b = x.mean().item()
            return x * v
        """)
    hits = report.by_rule("TPU301")
    assert len(hits) == 3
    assert report.exit_code() == 1


def test_static_shape_reads_in_jit_are_fine(tmp_path):
    report = _lint_source(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def step(x, n):
            scale = float(n)            # static arg — host value already
            k = int(x.shape[0])         # trace-time constant
            return x * scale / k
        """)
    assert report.by_rule("TPU301") == []
    assert report.exit_code() == 0


# ------------------------------------------------------------ TPU302
def test_timing_without_block_until_ready(tmp_path):
    report = _lint_source(tmp_path, """
        import time
        import jax

        step = jax.jit(lambda x: x * 2)

        def bench(x):
            t0 = time.perf_counter()
            for _ in range(8):
                out = step(x)
            return time.perf_counter() - t0
        """)
    hits = report.by_rule("TPU302")
    assert len(hits) == 1 and "bench" in hits[0].message
    assert report.exit_code() == 1


def test_timing_with_sync_fence_is_fine(tmp_path):
    report = _lint_source(tmp_path, """
        import time
        import jax

        step = jax.jit(lambda x: x * 2)

        def bench(x):
            t0 = time.perf_counter()
            out = step(x)
            jax.block_until_ready(out)
            return time.perf_counter() - t0

        def host_only_timing():
            t0 = time.perf_counter()
            total = sum(range(1000))
            return time.perf_counter() - t0
        """)
    assert report.by_rule("TPU302") == []
    assert report.exit_code() == 0


# ------------------------------------------------------------ TPU303
def test_traced_python_control_flow(tmp_path):
    report = _lint_source(tmp_path, """
        import jax

        @jax.jit
        def step(x, threshold):
            if threshold > 0.5:
                x = x + 1
            return x
        """)
    hits = report.by_rule("TPU303")
    assert len(hits) == 1 and "threshold" in hits[0].message
    assert report.exit_code() == 1


def test_identity_checks_and_static_args_are_fine(tmp_path):
    report = _lint_source(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("causal",))
        def step(x, mask=None, causal=False):
            if mask is not None:
                x = x * mask
            if causal:
                x = x + 1
            return x
        """)
    assert report.by_rule("TPU303") == []
    assert report.exit_code() == 0


# ------------------------------------------------------------ TPU304
def test_bare_shard_map_import(tmp_path):
    report = _lint_source(tmp_path, """
        from jax.experimental.shard_map import shard_map

        def run(mesh, f):
            return shard_map(f, mesh=mesh, in_specs=None, out_specs=None)
        """)
    assert len(report.by_rule("TPU304")) == 1
    assert report.exit_code() == 1


def test_jax_compat_import_is_fine(tmp_path):
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.utils.jax_compat import shard_map, pcast
        """)
    assert report.by_rule("TPU304") == []


# ------------------------------------------------------------ TPU305/306
def test_bad_metric_name_reported():
    registry = MetricsRegistry(validate_names=False)
    registry.counter("bad_metric")
    report = check_metric_names(registry)
    hits = report.by_rule("TPU305")
    assert hits and hits[0].path == "bad_metric"
    assert report.exit_code() == 1


def test_metric_suffix_rules():
    registry = MetricsRegistry(validate_names=False)
    registry.counter("tpudl_test_widgets")       # counter without _total
    registry.histogram("tpudl_test_latency")     # histogram without suffix
    report = check_metric_names(registry)
    messages = " ".join(d.message for d in report.by_rule("TPU305"))
    assert "_total" in messages and "_seconds" in messages


def test_obs_check_shim_warns_and_still_works():
    """The deprecated ``obs.check`` alias: importing it raises a
    DeprecationWarning and its ``lint`` is selfcheck's metric_lint."""
    import importlib
    import sys as _sys
    import warnings

    from deeplearning4j_tpu.obs import selfcheck
    _sys.modules.pop("deeplearning4j_tpu.obs.check", None)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        check = importlib.import_module("deeplearning4j_tpu.obs.check")
    assert any(issubclass(w.category, DeprecationWarning) for w in caught)
    assert check.lint is selfcheck.metric_lint
    registry = MetricsRegistry(validate_names=False)
    registry.counter("tpudl_test_rogue")
    problems = check.lint(registry)
    assert any("_total" in p for p in problems)


def test_op_catalog_is_consistent():
    assert check_op_catalog().exit_code() == 0


# ------------------------------------------------------------ harness
def test_syntax_error_reported_as_tpu300(tmp_path):
    report = _lint_source(tmp_path, "def broken(:\n")
    assert report.by_rule("TPU300")
    assert report.exit_code() == 1


def test_missing_lint_path_is_not_a_clean_pass(tmp_path):
    """A typo'd --lint target must not read as a green gate."""
    report = lint_paths([str(tmp_path / "no_such_dir_or_file.py")])
    missing = report.by_rule("TPU300")
    assert len(missing) == 1 and "does not exist" in missing[0].message
    assert report.exit_code() == 1
    assert analyze_main(["--lint", str(tmp_path / "nope")]) == 1


def test_combined_modes_accumulate_context(tmp_path):
    from deeplearning4j_tpu.analyze.diagnostics import Report
    a = Report(context={"files_linted": 100, "label": "x"})
    b = Report(context={"files_linted": 1, "label": "y"})
    a.extend(b)
    assert a.context["files_linted"] == 101
    assert a.context["label"] == "y"


def test_cli_lint_seeded_and_clean(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import pmap\n")
    assert analyze_main(["--lint", str(bad)]) == 1
    assert "TPU304" in capsys.readouterr().out
    good = tmp_path / "good.py"
    good.write_text("import jax.numpy as jnp\n")
    assert analyze_main(["--lint", str(good)]) == 0


def test_rule_registry_is_pluggable(tmp_path):
    @register_lint_rule("TPU999")
    def _no_todo(mod):
        from deeplearning4j_tpu.analyze.diagnostics import Diagnostic
        return [Diagnostic("TPU999", "custom rule fired", path=mod.path)]
    try:
        report = _lint_source(tmp_path, "x = 1\n")
        assert report.by_rule("TPU999")
    finally:
        LINT_RULES.pop("TPU999", None)


# ------------------------------------------------------------ TPU308
def test_swallowed_exception_in_training_loop(tmp_path):
    report = _lint_source(tmp_path, """
        def fit_epoch(trainer, iterator, rng):
            for batch in iterator:
                try:
                    trainer.fit_batch(batch, rng)
                except Exception:
                    continue

        def exchange_loop(transport, messages):
            for msg in messages:
                try:
                    transport.exchange(0, msg)
                except:
                    pass
        """)
    hits = report.by_rule("TPU308")
    assert len(hits) == 2
    assert report.exit_code() == 1
    assert "swallows" in hits[0].message


def test_swallowed_exception_clean_cases(tmp_path):
    report = _lint_source(tmp_path, """
        import logging

        def fit_epoch(trainer, iterator, rng):
            for batch in iterator:
                try:
                    trainer.fit_batch(batch, rng)
                except Exception:
                    logging.exception("step failed")   # recorded, not silent
                except ValueError:
                    pass                               # narrow catch: fine

        def fit_with_collection(trainer, batches):
            errors = []
            for b in batches:
                try:
                    trainer.fit_batch(b, None)
                except Exception as e:
                    errors.append(e)                   # bookkeeping: fine
            return errors

        def parse_optional_configs(paths):
            # not a training-path function name: out of scope
            for p in paths:
                try:
                    open(p).read()
                except Exception:
                    continue

        def fit_once(trainer, batch):
            try:
                trainer.fit_batch(batch, None)         # no loop: out of scope
            except Exception:
                pass

        def fit_with_nested_teardown(trainer, batches):
            for b in batches:
                def _cleanup():
                    # lives in a nested def: not on the per-iteration
                    # path, and _cleanup carries no training token
                    try:
                        b.close()
                    except Exception:
                        pass
                trainer.fit_batch(b, None)
                _cleanup()
        """)
    assert report.by_rule("TPU308") == []
    assert report.exit_code() == 0


# ------------------------------------------------------------ TPU309
def test_jit_built_in_request_path(tmp_path):
    report = _lint_source(tmp_path, """
        import jax

        def handle_predict(model, requests):
            for x in requests:
                fwd = jax.jit(model.apply)     # compiled per request
                out = fwd(x)
            return out

        class Handler:
            def do_POST(self):
                fn = jax.jit(self.model.apply)  # per-request handler
                return fn(self.body)

        def serve_one(model, x):
            return jax.jit(model.apply)(x)      # inline, no loop needed
        """)
    hits = report.by_rule("TPU309")
    assert len(hits) == 3
    assert report.exit_code() == 1
    assert "re-compiles" in hits[0].message


def test_jit_in_setup_paths_is_fine(tmp_path):
    report = _lint_source(tmp_path, """
        import jax

        def make_predict_fn(model):
            return jax.jit(model.apply)        # one-time builder

        def build_infer_step(model):
            return jax.jit(model.apply)        # one-time builder

        def serve_loop(engine, requests):
            for x in requests:
                engine.predict(x)              # CALLS cached forward

        def load_weights(path):
            fwd = jax.jit(lambda p, x: x)      # no serving token
            return fwd
        """)
    assert report.by_rule("TPU309") == []
    assert report.exit_code() == 0


# ------------------------------------------------------------ TPU310
def test_span_without_with_block(tmp_path):
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.obs import tracing
        from deeplearning4j_tpu.obs.tracing import span

        def step_loop(step, batches):
            for b in batches:
                tracing.span("step")           # never entered
                loss = step(b)
            return loss

        def fit(step, batches):
            s = span("fit", epochs=1)          # bare imported name
            for b in batches:
                step(b)
        """)
    hits = report.by_rule("TPU310")
    assert len(hits) == 2
    assert report.exit_code() == 1
    assert "never entered" in hits[0].message


def test_span_with_block_and_factories_are_fine(tmp_path):
    report = _lint_source(tmp_path, """
        import contextlib
        from deeplearning4j_tpu.obs import tracing

        def step_loop(step, batches):
            with tracing.span("epoch"):
                for b in batches:
                    with tracing.span("step", n=1) as sp:
                        step(b)

        def stacked(step):
            with contextlib.ExitStack() as stack:
                stack.enter_context(tracing.span("outer"))
                step()

        def span_factory(name):
            return tracing.span(name)          # caller will `with` it
        """)
    assert report.by_rule("TPU310") == []
    assert report.exit_code() == 0


def test_flight_recorder_io_inside_jit(tmp_path):
    report = _lint_source(tmp_path, """
        import jax
        from deeplearning4j_tpu.obs import flight_recorder
        from deeplearning4j_tpu.obs.flight_recorder import record

        @jax.jit
        def step(params, x):
            flight_recorder.dump(reason="step")   # trace-time only
            record("step", n=1)                   # trace-time only
            return params

        def drive(step, batches):
            for b in batches:
                step(b)
                flight_recorder.record("step")    # host side: fine
        """)
    hits = report.by_rule("TPU310")
    assert len(hits) == 2
    assert report.exit_code() == 1
    assert "trace time" in hits[0].message


def test_flight_recorder_aliases_and_unrelated_receivers(tmp_path):
    """Receiver matching follows real import bindings: a module alias
    (``import ...flight_recorder as fr``) is caught, and an unrelated
    local object that happens to be named ``recorder`` is not."""
    report = _lint_source(tmp_path, """
        import jax
        import deeplearning4j_tpu.obs.flight_recorder as fr

        @jax.jit
        def step(params, x):
            fr.record("step", n=1)                # trace-time only
            return params

        @jax.jit
        def other_step(params, recorder):
            recorder.record(params)               # NOT flight_recorder
            return params
        """)
    hits = report.by_rule("TPU310")
    assert len(hits) == 1
    assert "step" in hits[0].message


def test_flight_recorder_dotted_imports_are_caught(tmp_path):
    """Un-aliased dotted imports reach the module by its FULL dotted
    path — both ``import a.b.flight_recorder`` + a fully-dotted call and
    ``from deeplearning4j_tpu import obs`` + ``obs.tracing.span`` must
    flag, not just aliased/bare-name receivers."""
    report = _lint_source(tmp_path, """
        import jax
        import deeplearning4j_tpu.obs.flight_recorder
        from deeplearning4j_tpu import obs

        @jax.jit
        def step(params, x):
            deeplearning4j_tpu.obs.flight_recorder.record("s")  # traced
            return params

        def step_loop(step, batches):
            for b in batches:
                obs.tracing.span("step")          # never entered
                step(b)
        """)
    hits = report.by_rule("TPU310")
    assert len(hits) == 2
    assert report.exit_code() == 1


# ------------------------------------------------------------ TPU311
def test_net_io_in_step_path(tmp_path):
    report = _lint_source(tmp_path, """
        import socket
        import urllib.request
        from http.client import HTTPConnection

        def step_batch(self, batch):
            urllib.request.urlopen("http://ui:9090/remote/stats",
                                   data=b"{}")
            return batch

        def iteration_done(self, model, it, epoch, score):
            conn = HTTPConnection("coordinator", 9090)
            conn.request("POST", "/remote/stats")

        def fit_loop(step, batches):
            sock = socket.create_connection(("telemetry", 4317))
            for b in batches:
                step(b)
        """)
    hits = report.by_rule("TPU311")
    assert len(hits) == 3
    assert report.exit_code() == 1
    assert "RemoteStatsRouter" in hits[0].message


def test_net_io_outside_step_path_is_fine(tmp_path):
    """Network I/O in non-step-path functions (setup, serving handlers
    with their own rules, plain helpers) and host-local socket attribute
    reads are not TPU311's business."""
    report = _lint_source(tmp_path, """
        import socket
        import urllib.request

        def fetch_config(url):
            return urllib.request.urlopen(url).read()

        def make_coordinator_endpoint(port):
            return socket.create_server(("127.0.0.1", port))

        def step_batch(self, batch):
            host = socket.gethostname()        # host-local, no connect
            return batch, host
        """)
    assert report.by_rule("TPU311") == []
    assert report.exit_code() == 0


def test_net_io_aliased_and_from_imports_are_caught(tmp_path):
    report = _lint_source(tmp_path, """
        import urllib.request as _rq
        from urllib.request import urlopen
        from urllib import request

        def on_epoch_end(self, model, epoch, info):
            urlopen("http://ui/remote/stats")

        def stats_push(records):
            _rq.urlopen("http://ui/remote/stats")
            request.urlopen("http://ui/remote/stats")
        """)
    hits = report.by_rule("TPU311")
    assert len(hits) == 3


def test_obs_remote_itself_is_exempt(tmp_path):
    """The router's flush thread is WHERE the network I/O belongs."""
    (tmp_path / "obs").mkdir()
    report = _lint_source(tmp_path, """
        import urllib.request

        def _flush_step_batch(self, payload):
            urllib.request.urlopen(self.endpoint, data=payload)
        """, name="obs/remote.py")
    assert report.by_rule("TPU311") == []


# ------------------------------------------------------------ TPU312
def test_exit_outside_supervision_flagged(tmp_path):
    """A stray os._exit/sys.exit in library code defeats supervision:
    no flight dump, an unexplained rc for the supervisor."""
    report = _lint_source(tmp_path, """
        import os
        import sys

        def _on_exchange_error(self, err):
            os._exit(1)

        def run_epoch(self, batches):
            for b in batches:
                if not self.step(b):
                    sys.exit(2)
        """)
    hits = report.by_rule("TPU312")
    assert len(hits) == 2
    assert report.exit_code() == 1
    assert "supervision" in hits[0].message


def test_exit_under_main_guard_is_fine(tmp_path):
    """The CLI idiom — sys.exit(main()) under the __main__ guard — is
    the process's contract with its shell, not library control flow."""
    report = _lint_source(tmp_path, """
        import sys

        def main():
            return 0

        if __name__ == "__main__":
            sys.exit(main())
        """)
    assert report.by_rule("TPU312") == []
    assert report.exit_code() == 0


def test_exit_aliased_and_from_imports_are_caught(tmp_path):
    report = _lint_source(tmp_path, """
        import os as _o
        import sys as _s
        from os import _exit
        from sys import exit as bail

        def worker_loop():
            _o._exit(3)

        def drain():
            _s.exit(1)
            _exit(4)
            bail(5)
        """)
    assert len(report.by_rule("TPU312")) == 4


def test_watchdog_and_supervisor_modules_are_exempt(tmp_path):
    """Deliberate process death has exactly two sanctioned homes."""
    source = """
        import os

        def _fire(self):
            os._exit(87)
        """
    (tmp_path / "obs").mkdir()
    report = _lint_source(tmp_path, source, name="obs/flight_recorder.py")
    assert report.by_rule("TPU312") == []
    (tmp_path / "resilience").mkdir()
    report = _lint_source(tmp_path, source,
                          name="resilience/supervisor.py")
    assert report.by_rule("TPU312") == []
    # the exemption is a path-SEGMENT match: a module that merely
    # string-suffix-matches a sanctioned path must still flag
    (tmp_path / "jobs").mkdir()
    report = _lint_source(tmp_path, source, name="jobs/flight_recorder.py")
    assert len(report.by_rule("TPU312")) == 1
    # ...and a module that merely IMPORTS os without exiting never flags
    report = _lint_source(tmp_path, """
        import os

        def workdir():
            return os.getcwd()
        """)
    assert report.by_rule("TPU312") == []


# ------------------------------------------------------------ TPU313
def test_deploy_outside_gate_flags_online_loop_function(tmp_path):
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import ModelRegistry

        def online_retrain_round(registry, name, candidate):
            registry.deploy(name, candidate)
        """)
    hits = report.by_rule("TPU313")
    assert len(hits) == 1 and "deploy" in hits[0].message
    assert report.exit_code() == 1


def test_deploy_outside_gate_sees_through_class_names(tmp_path):
    """OnlineTrainer.run_once is loop code even though the method name
    itself carries no online token; hot_swap counts as a deploy."""
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve.registry import ModelRegistry

        class OnlineTrainer:
            def run_once(self):
                self.registry.hot_swap("m", "cand.zip")
        """)
    assert len(report.by_rule("TPU313")) == 1


def test_deploy_outside_gate_exempts_gate_module_and_tests(tmp_path):
    """online/gate.py IS the sanctioned deploy path; tests exercise
    ungated deploys on purpose."""
    source = """
        from deeplearning4j_tpu.serve import ModelRegistry

        def deploy_candidate_round(registry):
            registry.deploy("m", "cand.zip")
        """
    (tmp_path / "online").mkdir()
    report = _lint_source(tmp_path, source, name="online/gate.py")
    assert report.by_rule("TPU313") == []
    (tmp_path / "tests").mkdir()
    report = _lint_source(tmp_path, source, name="tests/mod.py")
    assert report.by_rule("TPU313") == []
    report = _lint_source(tmp_path, source, name="test_deploys.py")
    assert report.by_rule("TPU313") == []


def test_deploy_outside_gate_needs_registry_import_and_loop_tokens(tmp_path):
    """An unrelated object's .deploy, a module that never imports
    ModelRegistry, and the gated deploy_if_better all stay clean."""
    report = _lint_source(tmp_path, """
        def online_round(orchestrator):
            orchestrator.deploy("k8s-manifest")   # no ModelRegistry here
        """)
    assert report.by_rule("TPU313") == []
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import ModelRegistry

        def setup_serving(registry, path):
            registry.deploy("m", path)            # not loop code
        """)
    assert report.by_rule("TPU313") == []
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import ModelRegistry

        class OnlineTrainer:
            def run_once(self):
                self.deployer.deploy_if_better("m", "cand.zip")   # gated
        """)
    assert report.by_rule("TPU313") == []
    assert report.exit_code() == 0


# ------------------------------------------------------------ TPU314
def test_upcast_in_serving_path_flags_astype_and_dequantize(tmp_path):
    """Seeded defects: a float32 astype and a per-request dequantize in
    serving-token functions each flag with the rule ID."""
    report = _lint_source(tmp_path, """
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.quantize import dequantize_weight

        def predict_quantized(params, x):
            w = params["W_q"].astype(jnp.float32)
            return x @ (w * params["W_scale"])

        def handle_request(self, params, x):
            w = dequantize_weight(params["W_q"], params["W_scale"])
            return x @ w
        """)
    hits = report.by_rule("TPU314")
    assert len(hits) == 2
    assert any("astype" in h.message for h in hits)
    assert any("dequantize" in h.message for h in hits)
    assert report.exit_code() == 1


def test_upcast_in_serving_path_flags_http_handlers_and_f64(tmp_path):
    """do_POST is per-request by contract; float64 widens too, and the
    keyword form astype(dtype=...) must not escape."""
    report = _lint_source(tmp_path, """
        import numpy as np

        class Handler:
            def do_POST(self):
                x = self.read_body().astype(np.float64)
                return self.answer(x)

        def predict(params, x):
            return x.astype(dtype=np.float32) @ params["W"]
        """)
    assert len(report.by_rule("TPU314")) == 2


def test_upcast_in_serving_path_exemptions(tmp_path):
    """Builders (deploy-time dequant), non-serving functions (loss math
    may upcast), narrowing casts, and reasoned pragmas all stay clean."""
    report = _lint_source(tmp_path, """
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.quantize import dequantize_weight

        def build_serving_weights(params):
            # one-time deploy-time dequant: exactly where it belongs
            return dequantize_weight(params["W_q"], params["W_scale"])

        def compute_score_array(z, labels):
            z = z.astype(jnp.float32)       # loss math upcasts by design
            return z - labels

        def predict(params, x):
            x = x.astype(jnp.bfloat16)      # narrowing is the point
            return x @ params["W"]
        """)
    assert report.by_rule("TPU314") == []
    assert report.exit_code() == 0
    report = _lint_source(tmp_path, """
        import numpy as np

        def predict_rows(x):
            # tpudl: ok(TPU314) — host-side JSON decode, not an HBM tensor
            return np.asarray(x).astype(np.float32)
        """)
    assert report.by_rule("TPU314") == []


# ------------------------------------------------------------ TPU315
def test_live_compile_in_restart_path_flags_jit_and_aot_chain(tmp_path):
    """Seeded defects: a jax.jit build inside a deploy-path function and
    an eager lower().compile() inside a resume-path function each flag
    — restart paths warm from the artifact store, they don't compile."""
    report = _lint_source(tmp_path, """
        import jax

        def deploy_model(net, zip_path):
            fwd = jax.jit(lambda p, x: net.forward(p, x))   # live compile
            return fwd

        def resume_training(step, abstract_args):
            return step.lower(*abstract_args).compile()     # eager AOT
        """)
    hits = report.by_rule("TPU315")
    assert len(hits) == 2
    assert any("jax.jit built" in h.message for h in hits)
    assert any("lower().compile()" in h.message for h in hits)
    assert report.exit_code() == 1


def test_live_compile_in_restart_path_respawn_and_rollback(tmp_path):
    """The supervisor-shaped tokens flag too; calling an ALREADY-built
    jitted function on a restart path is fine (that is the warm path)."""
    report = _lint_source(tmp_path, """
        from jax import jit

        def respawn_worker(fn):
            return jit(fn)

        def rollback_version(warmed_step, args):
            return warmed_step(*args)        # dispatch, not a build
        """)
    hits = report.by_rule("TPU315")
    assert len(hits) == 1
    assert "respawn_worker" in hits[0].message


def test_live_compile_in_restart_path_exemptions(tmp_path):
    """Builder-token factories compile by design; re.compile must not
    false-positive; non-restart functions are out of scope; and the
    store module itself (the baker) is path-exempt."""
    report = _lint_source(tmp_path, """
        import re
        import jax

        def build_deploy_forward(net):
            return jax.jit(net.forward)      # one-time factory

        def deploy_manifest(pattern, text):
            return re.compile(pattern).match(text)   # not an AOT chain

        def train_step_builder(fn):
            return jax.jit(fn)               # no restart token
        """)
    assert report.by_rule("TPU315") == []
    assert report.exit_code() == 0
    # the store module bakes (lower+compile) — exactly its job
    store_dir = tmp_path / "train"
    store_dir.mkdir()
    report = _lint_source(
        tmp_path, """
        def bake_for_deploy(fn, abstract_args):
            return fn.lower(*abstract_args).compile()
        """, name="train/artifact_store.py")
    assert report.by_rule("TPU315") == []


# ------------------------------------------------------------ TPU316
def test_deploy_bypasses_router_flags_direct_registry_calls(tmp_path):
    """Seeded defects: registry.deploy in a router-token function and
    self.registry.hot_swap in a Router-named class each flag — a
    router-managed model swaps only through the atomic fan-out."""
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import ReplicaRouter

        def swap_router_fleet(registry, path):
            registry.deploy("m", path)            # bypasses the fan-out

        class FleetRouterManager:
            def promote(self, path):
                self.model_registry.hot_swap("m", path)
        """)
    hits = report.by_rule("TPU316")
    assert len(hits) == 2
    assert any("swap_router_fleet" in h.message for h in hits)
    assert any("hot_swap" in h.message for h in hits)
    assert report.exit_code() == 1
    # any routing-plane import scopes the module — a fleet manager that
    # only names the Autoscaler can bypass the fan-out just as easily
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import Autoscaler

        def rebalance_fleet(registry, path):
            registry.deploy("m", path)
        """)
    assert len(report.by_rule("TPU316")) == 1


def test_deploy_bypasses_router_scoping(tmp_path):
    """Setup code (no router token) may deploy; router.deploy and the
    gate's deploy_if_better are the sanctioned doors; modules that
    never touch the routing plane are out of scope entirely."""
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import ModelRegistry, ReplicaRouter

        def start_serving(registry, path):
            registry.deploy("m", path)       # BEFORE the router attaches
            return ReplicaRouter(registry, "m", replicas=2)

        def swap_replica_fleet(router, deployer, path):
            router.deploy(path)                        # the fan-out door
            deployer.deploy_if_better("m", path)       # the gated door
        """)
    assert report.by_rule("TPU316") == []
    assert report.exit_code() == 0
    # no ReplicaRouter import → no routing plane → out of scope
    report = _lint_source(tmp_path, """
        from deeplearning4j_tpu.serve import ModelRegistry

        def swap_router_fleet(registry, path):
            registry.deploy("m", path)
        """)
    assert report.by_rule("TPU316") == []


def test_deploy_bypasses_router_exempt_modules(tmp_path):
    """serve/router.py (its registry hooks ARE the fan-out) and
    online/gate.py (the sanctioned gated caller) stay clean."""
    for name in ("serve/router.py", "online/gate.py"):
        (tmp_path / name.split("/")[0]).mkdir(exist_ok=True)
        report = _lint_source(tmp_path, """
            from deeplearning4j_tpu.serve import ReplicaRouter

            def fan_out_routed_deploy(self, registry, path):
                return registry.deploy("m", path)
            """, name=name)
        assert report.by_rule("TPU316") == [], name


# ------------------------------------------------------------ TPU317
def test_hardcoded_axis_name_flags_sharding_ctor_literals(tmp_path):
    """Seeded defects: axis string literals in PartitionSpec/P/
    NamedSharding calls — including tuple-nested and the pre-rename
    'stage' — each flag; the fix hint names the AXIS_* constants."""
    report = _lint_source(tmp_path, """
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def place(mesh, params):
            a = P("data", "model")                     # two literals
            b = NamedSharding(mesh, P(("data", "pipe")))   # tuple-nested
            c = P("stage")                             # pre-rename axis
            return a, b, c
        """)
    hits = report.by_rule("TPU317")
    assert len(hits) == 5
    assert any("AXIS_DATA" in h.message for h in hits)
    assert any("renamed 'pipe'" in h.message for h in hits)
    assert report.exit_code() == 1


def test_hardcoded_axis_name_scope_and_exemptions(tmp_path):
    """Constants, variables and non-sharding calls stay clean; the
    single source of truth (parallel/mesh.py) is path-exempt; a
    reasoned pragma suppresses."""
    report = _lint_source(tmp_path, """
        from jax.sharding import PartitionSpec as P
        from deeplearning4j_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL

        def layouts(axis):
            ok1 = P(AXIS_DATA, AXIS_MODEL)     # the constants
            ok2 = P(axis)                      # parameterized
            ok3 = dict(model="resnet")         # not a sharding ctor
            return ok1, ok2, ok3
        """)
    assert report.by_rule("TPU317") == []
    assert report.exit_code() == 0
    # parallel/mesh.py spells the strings once — exempt by path
    (tmp_path / "parallel").mkdir(exist_ok=True)
    report = _lint_source(tmp_path, """
        from jax.sharding import PartitionSpec as P
        MESH_AXES = ("pipe", "data", "model")
        REPL = P("data")
        """, name="parallel/mesh.py")
    assert report.by_rule("TPU317") == []
    # suppression pragma with a reason is honored
    report = _lint_source(tmp_path, """
        from jax.sharding import PartitionSpec as P

        def one_off(mesh):
            return P("data")  # tpudl: ok(TPU317) — doc example, not wiring
        """)
    assert report.by_rule("TPU317") == []
    assert report.suppressed


# ------------------------------------------------------------ TPU318
def test_adhoc_latency_in_serving_path_flagged(tmp_path):
    """A time delta measured in a request handler that never reaches a
    registry sink is invisible to SLO burn-rate evaluation."""
    report = _lint_source(tmp_path, """
        import time

        def handle_request(self, x):
            t0 = time.perf_counter()
            out = self.engine.predict(x)
            latency = time.perf_counter() - t0
            if latency > 0.5:
                print("slow request", latency)
            return out
        """)
    hits = report.by_rule("TPU318")
    assert len(hits) == 1 and "handle_request" in hits[0].message
    assert "histogram" in hits[0].message
    assert report.exit_code() == 1


def test_adhoc_latency_in_step_path_flagged(tmp_path):
    report = _lint_source(tmp_path, """
        import time

        def train_step(self, batch):
            start = time.monotonic()
            loss = self._step(batch)
            self.last_step_s = time.monotonic() - start
            return loss
        """)
    hits = report.by_rule("TPU318")
    assert len(hits) == 1 and "train_step" in hits[0].message


def test_latency_that_reaches_a_registry_sink_is_fine(tmp_path):
    report = _lint_source(tmp_path, """
        import time
        from deeplearning4j_tpu.obs.registry import get_registry

        def handle_request(self, x):
            t0 = time.perf_counter()
            out = self.engine.predict(x)
            get_registry().histogram(
                "tpudl_serve_latency_seconds").observe(
                time.perf_counter() - t0)
            return out

        def fit_batch(self, batch):
            t0 = time.perf_counter()
            loss = self._step(batch)
            self.router.notify_step(step_seconds=time.perf_counter() - t0)
            return loss
        """)
    assert report.by_rule("TPU318") == []
    assert report.exit_code() == 0


def test_cadence_checks_and_non_serving_functions_are_fine(tmp_path):
    """now - self._last_flush is a cooldown decision, not a latency;
    deltas outside serving/step-path functions are out of scope."""
    report = _lint_source(tmp_path, """
        import time

        def serve_step(self):
            now = time.monotonic()
            if now - self._last_up > self.cooldown_s:
                self._scale_up()
                self._last_up = now

        def build_serving_engine(self):
            t0 = time.perf_counter()
            engine = self._compile()
            print("cold start took", time.perf_counter() - t0)
            return engine

        def load_config(path):
            t0 = time.perf_counter()
            cfg = open(path).read()
            return cfg, time.perf_counter() - t0
        """)
    assert report.by_rule("TPU318") == []
    assert report.exit_code() == 0


def test_obs_measurement_modules_are_exempt_from_tpu318(tmp_path):
    (tmp_path / "obs").mkdir(exist_ok=True)
    report = _lint_source(tmp_path, """
        import time

        def observe_request(self, x):
            t0 = time.perf_counter()
            out = self._forward(x)
            self._raw_latency = time.perf_counter() - t0
            return out
        """, name="obs/probe.py")
    assert report.by_rule("TPU318") == []


# ------------------------------------------------------------ TPU319
def test_hardcoded_device_count_in_layout_code_flagged(tmp_path):
    """An integer literal compared against the device count inside
    layout/reshard/arbiter-token functions: true exactly until the
    first elastic grow/borrow changes the width."""
    report = _lint_source(tmp_path, """
        import jax

        def build_layout(spec):
            if jax.device_count() == 8:
                return spec

        def reshard_params(params):
            assert len(jax.devices()) >= 4
            return params

        def arbiter_flip(pool):
            return 2 < jax.local_device_count()
        """)
    hits = report.by_rule("TPU319")
    assert len(hits) == 3
    assert "build_layout" in hits[0].message
    assert "derive" in hits[0].message
    assert report.exit_code() == 1


def test_derived_widths_and_out_of_scope_functions_are_fine(tmp_path):
    """Widths derived from the spec/inventory never flag; device-count
    comparisons outside layout/reshard/arbiter functions are out of
    scope; comparing two non-literal expressions is fine."""
    report = _lint_source(tmp_path, """
        import jax

        def build_layout(spec):
            if jax.device_count() >= spec.total():
                return spec

        def resize_gang(widths):
            n = jax.device_count()
            return [w for w in widths if w <= n or n > min(widths)]

        def print_banner():
            if jax.device_count() == 1:
                print("single device")
        """)
    assert report.by_rule("TPU319") == []
    assert report.exit_code() == 0


def test_tpu319_test_paths_exempt_and_pragma_honored(tmp_path):
    """Tests pin concrete widths on purpose (exempt by path); elsewhere
    a reasoned suppression pragma is honored."""
    (tmp_path / "tests").mkdir(exist_ok=True)
    report = _lint_source(tmp_path, """
        import jax

        def layout_case():
            assert jax.device_count() == 8
        """, name="tests/test_widths.py")
    assert report.by_rule("TPU319") == []
    report = _lint_source(tmp_path, """
        import jax

        def describe_mesh():
            single = jax.device_count() == 1  # tpudl: ok(TPU319) — banner text only
            return "single" if single else "multi"
        """)
    assert report.by_rule("TPU319") == []
    assert report.suppressed
