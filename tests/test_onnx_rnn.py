"""ONNX recurrent + control-flow import (VERDICT r4 missing #1 / next
#4): LSTM/GRU/RNN node handlers vs torch-exported goldens, If/Loop/Scan
subgraphs, and train-after-import (fine-tune through imported weights)."""

import io

import numpy as np
import pytest

from deeplearning4j_tpu.importers import onnx_wire as wire
from deeplearning4j_tpu.importers.onnx_import import import_onnx_model

from test_onnx_import import _model_bytes, _node, _vi  # noqa: F401


def _torch_export(model, args, input_names, output_names, **kw):
    """torch.onnx.export without the ``onnx`` package: the legacy
    exporter produces the serialized ModelProto itself and only imports
    ``onnx`` in ``_add_onnxscript_fn`` (a no-op without onnxscript
    custom functions) — stub that one step out."""
    torch = pytest.importorskip("torch")
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils
    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda proto, custom: proto
    try:
        buf = io.BytesIO()
        torch.onnx.export(model, args, buf, input_names=input_names,
                          output_names=output_names, dynamo=False, **kw)
        return buf.getvalue()
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


class TestTorchRecurrentGoldens:
    """torch-exported recurrent classifiers imported and matched."""

    def _roundtrip(self, mod, x_np, rtol=2e-5):
        torch = pytest.importorskip("torch")
        buf = _torch_export(mod, (torch.tensor(x_np),), ["x"], ["y"])
        m = import_onnx_model(buf)
        with torch.no_grad():
            want = mod(torch.tensor(x_np))
        if isinstance(want, tuple):
            want = want[0]
        got = np.asarray(m(x_np))
        np.testing.assert_allclose(got, want.numpy(), rtol=rtol, atol=1e-5)
        return m

    def test_lstm_classifier(self):
        torch = pytest.importorskip("torch")

        class Net(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lstm = torch.nn.LSTM(8, 16, batch_first=False)
                self.fc = torch.nn.Linear(16, 5)

            def forward(self, x):
                y, _ = self.lstm(x)
                return self.fc(y[-1])

        torch.manual_seed(0)
        x = np.random.default_rng(0).normal(size=(7, 3, 8)).astype(np.float32)
        self._roundtrip(Net().eval(), x)

    def test_gru_classifier_bidirectional(self):
        torch = pytest.importorskip("torch")

        class Net(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.gru = torch.nn.GRU(6, 10, bidirectional=True)
                self.fc = torch.nn.Linear(20, 4)

            def forward(self, x):
                y, _ = self.gru(x)
                return self.fc(y[-1])

        torch.manual_seed(1)
        x = np.random.default_rng(1).normal(size=(5, 2, 6)).astype(np.float32)
        self._roundtrip(Net().eval(), x)

    def test_vanilla_rnn(self):
        torch = pytest.importorskip("torch")

        class Net(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.rnn = torch.nn.RNN(4, 8, nonlinearity="tanh")

            def forward(self, x):
                y, h = self.rnn(x)
                return y

        torch.manual_seed(2)
        x = np.random.default_rng(2).normal(size=(6, 2, 4)).astype(np.float32)
        self._roundtrip(Net().eval(), x)

    def test_lstm_finetune_step(self):
        """Train-after-import: gradients flow through the imported LSTM
        weights; one SGD step reduces the loss (VERDICT r4 weak #7)."""
        import jax
        import jax.numpy as jnp
        torch = pytest.importorskip("torch")

        class Net(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lstm = torch.nn.LSTM(8, 16)
                self.fc = torch.nn.Linear(16, 5)

            def forward(self, x):
                y, _ = self.lstm(x)
                return self.fc(y[-1])

        torch.manual_seed(3)
        buf = _torch_export(Net().eval(),
                            (torch.zeros(7, 3, 8),), ["x"], ["y"])
        m = import_onnx_model(buf)
        rng = np.random.default_rng(3)
        x = rng.normal(size=(7, 3, 8)).astype(np.float32)
        labels = rng.integers(0, 5, 3)

        params = {k: jnp.asarray(v) for k, v in m.initializers.items()}

        def loss_fn(params, x):
            saved = m.initializers, m._device_inits
            m.initializers, m._device_inits = params, None
            try:
                logits = m(x)
            finally:
                m.initializers, m._device_inits = saved
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(3), labels])

        loss0, grads = jax.value_and_grad(loss_fn)(params, x)
        flat = jax.tree_util.tree_leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
        assert any(float(jnp.abs(g).max()) > 0 for g in flat)
        new_params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                            params, grads)
        loss1 = loss_fn(new_params, x)
        assert float(loss1) < float(loss0)


class TestRnnSpecSemantics:
    """Hand-built wire graphs: spec corners torch doesn't export."""

    def _run(self, node, inits, inputs, outputs, feeds):
        buf = _model_bytes([node], inits, inputs, outputs)
        return import_onnx_model(buf)(**feeds)

    def test_lstm_sequence_lens_and_reverse(self):
        rng = np.random.default_rng(4)
        T, B, I, H = 5, 3, 4, 6
        x = rng.normal(size=(T, B, I)).astype(np.float32)
        W = rng.normal(0, 0.3, (1, 4 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.3, (1, 4 * H, H)).astype(np.float32)
        lens = np.asarray([5, 3, 1], np.int32)

        node = _node("LSTM", ["x", "W", "R", "", "lens"],
                     ["Y", "Yh", "Yc"], hidden_size=H)
        y, yh, yc = self._run(
            node, {"W": W, "R": R, "lens": lens},
            {"x": [T, B, I], "lens": [B]},
            {"Y": [T, 1, B, H], "Yh": [1, B, H], "Yc": [1, B, H]},
            {"x": x, "lens": lens})
        y = np.asarray(y)
        # outputs past each row's length are zero; Yh is the value AT the
        # last valid step
        assert np.all(y[3:, 0, 1] == 0) and np.all(y[1:, 0, 2] == 0)
        np.testing.assert_allclose(np.asarray(yh)[0, 1], y[2, 0, 1],
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(yh)[0, 2], y[0, 0, 2],
                                   rtol=1e-6)

        # reverse direction = forward on time-reversed input (full lens)
        node_r = _node("LSTM", ["x", "W", "R"], ["Y2"],
                       hidden_size=H)
        node_r["attribute"].append(
            {"name": "direction", "s": b"reverse", "type": 3})
        y_rev = np.asarray(self._run(
            node_r, {"W": W, "R": R}, {"x": [T, B, I]},
            {"Y2": [T, 1, B, H]}, {"x": x}))
        node_f = _node("LSTM", ["xr", "W", "R"], ["Y3"], hidden_size=H)
        y_fwd = np.asarray(self._run(
            node_f, {"W": W, "R": R}, {"xr": [T, B, I]},
            {"Y3": [T, 1, B, H]}, {"xr": x[::-1].copy()}))
        np.testing.assert_allclose(y_rev, y_fwd[::-1], rtol=1e-5)

    def test_gru_linear_before_reset_variants_differ(self):
        rng = np.random.default_rng(5)
        T, B, I, H = 4, 2, 3, 5
        x = rng.normal(size=(T, B, I)).astype(np.float32)
        W = rng.normal(0, 0.4, (1, 3 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.4, (1, 3 * H, H)).astype(np.float32)
        Bv = rng.normal(0, 0.2, (1, 6 * H)).astype(np.float32)
        outs = {}
        for lbr in (0, 1):
            node = _node("GRU", ["x", "W", "R", "B"], ["Y"],
                         hidden_size=H, linear_before_reset=lbr)
            outs[lbr] = np.asarray(self._run(
                node, {"W": W, "R": R, "B": Bv}, {"x": [T, B, I]},
                {"Y": [T, 1, B, H]}, {"x": x}))
        assert not np.allclose(outs[0], outs[1])


class TestControlFlow:
    def test_if_branches(self):
        then_g = {"name": "then", "node": [_node("Add", ["a", "one"], ["o"])],
                  "output": [_vi("o", [2])]}
        else_g = {"name": "else", "node": [_node("Sub", ["a", "one"], ["o"])],
                  "output": [_vi("o", [2])]}
        node = {"op_type": "If", "input": ["cond"], "output": ["y"],
                "name": "if0",
                "attribute": [{"name": "then_branch", "g": then_g, "type": 5},
                              {"name": "else_branch", "g": else_g, "type": 5}]}
        buf = _model_bytes([node],
                           {"one": np.ones(2, np.float32),
                            "a": np.asarray([3.0, 4.0], np.float32)},
                           {"cond": []}, {"y": [2]})
        m = import_onnx_model(buf)
        np.testing.assert_allclose(np.asarray(m(np.asarray(True))), [4, 5])
        np.testing.assert_allclose(np.asarray(m(np.asarray(False))), [2, 3])

    def test_loop_accumulator_with_scan_output(self):
        """Loop body: v = v + a; scan output captures each iteration."""
        body = {
            "name": "body",
            "node": [_node("Add", ["v_in", "a"], ["v_out"]),
                     _node("Identity", ["v_out"], ["scan0"])],
            "input": [_vi("iter", []), _vi("cond_in", []),
                      _vi("v_in", [2])],
            "output": [_vi("cond_in", []), _vi("v_out", [2]),
                       _vi("scan0", [2])],
        }
        node = {"op_type": "Loop", "input": ["M", "cond", "v0"],
                "output": ["v_final", "trace"], "name": "loop0",
                "attribute": [{"name": "body", "g": body, "type": 5}]}
        buf = _model_bytes(
            [node],
            {"M": np.asarray(4, np.int64),
             "cond": np.asarray(True),
             "a": np.asarray([1.0, 2.0], np.float32)},
            {"v0": [2]}, {"v_final": [2], "trace": [4, 2]})
        m = import_onnx_model(buf)
        v_final, trace = m(np.zeros(2, np.float32))
        np.testing.assert_allclose(np.asarray(v_final), [4.0, 8.0])
        np.testing.assert_allclose(np.asarray(trace),
                                   [[1, 2], [2, 4], [3, 6], [4, 8]])

    def test_loop_dynamic_cond_freezes_state(self):
        """cond goes false after 2 iterations → carried var frozen."""
        body = {
            "name": "body",
            "node": [_node("Add", ["v_in", "one"], ["v_out"]),
                     _node("Less", ["v_out", "limit"], ["cond_out"])],
            "input": [_vi("iter", []), _vi("cond_in", []), _vi("v_in", [])],
            "output": [_vi("cond_out", []), _vi("v_out", [])],
        }
        node = {"op_type": "Loop", "input": ["M", "cond", "v0"],
                "output": ["v_final"], "name": "loop1",
                "attribute": [{"name": "body", "g": body, "type": 5}]}
        buf = _model_bytes(
            [node],
            {"M": np.asarray(10, np.int64), "cond": np.asarray(True),
             "one": np.asarray(1.0, np.float32),
             "limit": np.asarray(2.0, np.float32)},
            {"v0": []}, {"v_final": []})
        m = import_onnx_model(buf)
        # v: 0→1 (cond 1<2 true) →2 (2<2 false; stop) — final is 2
        assert float(m(np.asarray(0.0, np.float32))) == 2.0

    def test_scan_cumulative_sum(self):
        body = {
            "name": "body",
            "node": [_node("Add", ["s_in", "xt"], ["s_out"]),
                     _node("Identity", ["s_out"], ["y_t"])],
            "input": [_vi("s_in", [2]), _vi("xt", [2])],
            "output": [_vi("s_out", [2]), _vi("y_t", [2])],
        }
        node = {"op_type": "Scan", "input": ["s0", "xs"],
                "output": ["s_final", "ys"], "name": "scan0",
                "attribute": [{"name": "body", "g": body, "type": 5},
                              {"name": "num_scan_inputs", "i": 1,
                               "type": 2}]}
        xs = np.asarray([[1, 1], [2, 2], [3, 3]], np.float32)
        buf = _model_bytes([node], {}, {"s0": [2], "xs": [3, 2]},
                           {"s_final": [2], "ys": [3, 2]})
        m = import_onnx_model(buf)
        s_final, ys = m(np.zeros(2, np.float32), xs)
        np.testing.assert_allclose(np.asarray(s_final), [6, 6])
        np.testing.assert_allclose(np.asarray(ys), np.cumsum(xs, 0))

    def test_control_flow_jits(self):
        """If under jit: both branches trace, selection at runtime."""
        import jax
        then_g = {"name": "t", "node": [_node("Mul", ["a", "a"], ["o"])],
                  "output": [_vi("o", [3])]}
        else_g = {"name": "e", "node": [_node("Neg", ["a"], ["o"])],
                  "output": [_vi("o", [3])]}
        node = {"op_type": "If", "input": ["cond"], "output": ["y"],
                "name": "if1",
                "attribute": [{"name": "then_branch", "g": then_g, "type": 5},
                              {"name": "else_branch", "g": else_g, "type": 5}]}
        buf = _model_bytes([node], {"a": np.asarray([1., 2., 3.],
                                                    np.float32)},
                           {"cond": []}, {"y": [3]})
        m = import_onnx_model(buf)
        f = jax.jit(m.as_fn())
        np.testing.assert_allclose(np.asarray(f(np.asarray(True))),
                                   [1, 4, 9])
        np.testing.assert_allclose(np.asarray(f(np.asarray(False))),
                                   [-1, -2, -3])


class TestOnnxMlpFinetune:
    def test_mlp_gradient_step_reduces_loss(self):
        """Train-after-import golden (VERDICT r4 weak #7): imported ONNX
        MLP fine-tunes — finite grads through imported weights, loss
        decreases after one SGD step."""
        import jax
        import jax.numpy as jnp
        rng = np.random.default_rng(6)
        W1 = rng.normal(0, 0.4, (6, 16)).astype(np.float32)
        b1 = np.zeros(16, np.float32)
        W2 = rng.normal(0, 0.4, (16, 3)).astype(np.float32)
        b2 = np.zeros(3, np.float32)
        buf = _model_bytes(
            [_node("Gemm", ["x", "W1", "b1"], ["h"]),
             _node("Relu", ["h"], ["a"]),
             _node("Gemm", ["a", "W2", "b2"], ["y"])],
            {"W1": W1, "b1": b1, "W2": W2, "b2": b2},
            {"x": [4, 6]}, {"y": [4, 3]})
        m = import_onnx_model(buf)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        labels = rng.integers(0, 3, 4)

        params = {k: jnp.asarray(v) for k, v in m.initializers.items()}

        def loss_fn(params):
            saved = m.initializers, m._device_inits
            m.initializers, m._device_inits = params, None
            try:
                logits = m(x)
            finally:
                m.initializers, m._device_inits = saved
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(4), labels])

        loss0, grads = jax.value_and_grad(loss_fn)(params)
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
        new_params = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g,
                                            params, grads)
        assert float(loss_fn(new_params)) < float(loss0)


class TestTransformerBlockGolden:
    def test_causal_transformer_block_import(self):
        """A full torch transformer block (fused QKV, multi-head causal
        attention via Trilu/Where, layernorm, GELU FFN, residuals)
        exported to ONNX and imported with forward parity — the
        transformer-inference op set exercised end-to-end."""
        import math
        torch = pytest.importorskip("torch")

        class Block(torch.nn.Module):
            def __init__(self, d=32, h=4, ff=64):
                super().__init__()
                self.qkv = torch.nn.Linear(d, 3 * d)
                self.o = torch.nn.Linear(d, d)
                self.ln1 = torch.nn.LayerNorm(d)
                self.ln2 = torch.nn.LayerNorm(d)
                self.f1 = torch.nn.Linear(d, ff)
                self.f2 = torch.nn.Linear(ff, d)
                self.h = h

            def forward(self, x):
                B, T, D = x.shape
                qkv = self.qkv(x).reshape(B, T, 3, self.h, D // self.h) \
                                 .permute(2, 0, 3, 1, 4)
                q, k, v = qkv[0], qkv[1], qkv[2]
                s = torch.matmul(q, k.transpose(-1, -2)) \
                    / math.sqrt(D // self.h)
                mask = torch.triu(torch.ones(T, T, dtype=torch.bool), 1)
                s = s.masked_fill(mask, -1e9)
                a = torch.softmax(s, -1)
                y = torch.matmul(a, v).permute(0, 2, 1, 3).reshape(B, T, D)
                x = self.ln1(x + self.o(y))
                return self.ln2(
                    x + self.f2(torch.nn.functional.gelu(self.f1(x))))

        torch.manual_seed(0)
        mod = Block().eval()
        x_np = np.random.default_rng(9).normal(
            size=(2, 10, 32)).astype(np.float32)
        buf = _torch_export(mod, (torch.tensor(x_np),), ["x"], ["y"],
                            opset_version=17)
        m = import_onnx_model(buf)
        got = np.asarray(m(x_np))
        want = mod(torch.tensor(x_np)).detach().numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
