"""Model zoo: build, forward-shape, and learn tests for BASELINE workloads."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import models
from deeplearning4j_tpu.models import bert
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator


def test_mlp_mnist_builds():
    net = models.mlp_mnist().init()
    # 784*500+500 + 500*100+100 + 100*10+10 (MLPMnistTwoLayer)
    assert net.num_params() == 784 * 500 + 500 + 500 * 100 + 100 + 100 * 10 + 10
    out = net.output(np.zeros((2, 784), np.float32))
    assert out.shape == (2, 10)


def test_lenet_builds_and_forward():
    net = models.lenet().init()
    out = net.output(np.zeros((2, 28, 28, 1), np.float32))
    assert out.shape == (2, 10)


def test_simple_cnn_forward():
    net = models.simple_cnn(height=32, width=32).init()
    out = net.output(np.zeros((2, 32, 32, 3), np.float32))
    assert out.shape == (2, 10)


def test_resnet50_structure():
    """ResNet-50 (BASELINE headline model): parameter count must match the
    canonical v1 architecture (~25.58M for 1000 classes)."""
    net = models.resnet50(height=32, width=32, num_classes=1000).init()
    n = net.num_params()
    assert 25_400_000 < n < 25_700_000, n
    out = net.output(np.zeros((2, 32, 32, 3), np.float32))
    assert out.shape == (2, 1000)


def test_lstm_classifier_learns():
    from deeplearning4j_tpu.data import datasets
    net = models.lstm_classifier(timesteps=32, hidden=32).init()
    tr = datasets.uci_har(batch_size=32, train=True, n_synthetic=600, timesteps=32)
    te = datasets.uci_har(batch_size=64, train=False, n_synthetic=600, timesteps=32)
    net.fit(tr, epochs=4)
    acc = net.evaluate(te).accuracy()
    assert acc > 0.5, acc  # 6 classes, chance ≈ 0.17


def test_text_gen_lstm_builds():
    net = models.text_gen_lstm(vocab_size=30, hidden=16, timesteps=20).init()
    x = np.zeros((2, 20, 30), np.float32)
    out = net.output(x)
    assert out.shape == (2, 20, 30)


def test_vgg16_param_count():
    net = models.vgg16(num_classes=1000)
    # conf-level param check without materializing 138M params on CPU:
    types = net.conf.input_types()
    assert net.conf.output_type().flat_size() == 1000
    assert len(net.conf.layers) == 21  # 13 conv + 5 pool + 2 dense + 1 out


# ------------------------------------------------------------------ BERT
def test_bert_tiny_mlm_trains():
    config = bert.BertConfig.tiny()
    model = bert.BertForMaskedLM(config, seed=0)
    rng = np.random.default_rng(0)
    b, t = 8, 16

    def make_batch():
        ids = rng.integers(5, 1000, (b, t))
        labels = ids.copy()
        weights = np.zeros((b, t), np.float32)
        mask_pos = rng.integers(0, t, (b, 3))
        for i in range(b):
            weights[i, mask_pos[i]] = 1.0
        masked = ids.copy()
        for i in range(b):
            masked[i, mask_pos[i]] = 3  # [MASK]
        return {"input_ids": masked.astype(np.int32),
                "labels": labels.astype(np.int32),
                "label_weights": weights,
                "attention_mask": np.ones((b, t), np.float32)}

    batches = [make_batch() for _ in range(8)]
    from deeplearning4j_tpu.train import Adam
    loss_first = model.fit(batches[:1], updater=Adam(1e-3))
    loss_last = model.fit(batches * 4, updater=Adam(1e-3))
    assert loss_last < loss_first, (loss_first, loss_last)


def test_bert_save_load(tmp_path):
    config = bert.BertConfig.tiny()
    model = bert.BertForMaskedLM(config, seed=1)
    path = str(tmp_path / "bert.zip")
    model.save(path)
    restored = bert.BertForMaskedLM.load(path)
    ids = np.random.default_rng(0).integers(0, 1000, (2, 8)).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(model.predict_mlm(ids)),
        np.asarray(restored.predict_mlm(ids)), rtol=1e-6)


def test_bert_attention_mask_blocks_padding():
    config = bert.BertConfig.tiny()
    params = bert.init_params(config, jax.random.key(0))
    ids = np.random.default_rng(0).integers(5, 1000, (1, 8)).astype(np.int32)
    mask = np.ones((1, 8), np.float32)
    mask[0, 4:] = 0.0
    h1 = bert.encode(params, config, jnp.asarray(ids), attention_mask=jnp.asarray(mask))
    ids2 = ids.copy()
    ids2[0, 4:] = 7  # change PADDING content only
    h2 = bert.encode(params, config, jnp.asarray(ids2), attention_mask=jnp.asarray(mask))
    # unmasked positions must be unaffected by padding content
    np.testing.assert_allclose(np.asarray(h1[0, :4]), np.asarray(h2[0, :4]),
                               rtol=1e-5, atol=1e-6)
