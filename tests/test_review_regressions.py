"""Regression tests for review findings (round-1 code review)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, OutputLayer, RnnOutputLayer, LSTM, SubsamplingLayer,
    LearnedSelfAttentionLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator, AsyncDataSetIterator
from deeplearning4j_tpu.evaluation.classification import Evaluation


def test_dense_after_lstm_time_distributed():
    """Dense fed by an RNN layer = time-distributed (preprocessor-pair
    parity), then RnnOutputLayer trains."""
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.1)).list()
            .layer(LSTM(n_out=8))
            .layer(DenseLayer(n_out=6, activation="relu"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4, 10))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 10, 4)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10, 3)
    y = np.zeros((2, 10, 3), np.float32)
    y[..., 0] = 1.0
    net.fit(ArrayDataSetIterator(x, y, 2), epochs=1)  # must not crash


def test_output_layer_after_rnn_rejected():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(LSTM(n_out=8))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.recurrent(4, 10))
            .build())
    with pytest.raises(ValueError, match="RnnOutputLayer"):
        MultiLayerNetwork(conf).init()


def test_frozen_layer_fit_without_explicit_init():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.5)).list()
            .layer(DenseLayer(n_out=8, activation="relu", frozen=True))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf)  # NO .init()
    x = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 32)]
    net.fit(ArrayDataSetIterator(x, y, 16), epochs=2)
    # frozen layer params unchanged
    net2 = MultiLayerNetwork(conf).init()
    np.testing.assert_array_equal(np.asarray(net.params_[0]["W"]),
                                  np.asarray(net2.params_[0]["W"]))
    # unfrozen layer params DID change
    assert not np.allclose(np.asarray(net.params_[1]["W"]),
                           np.asarray(net2.params_[1]["W"]))


def test_avg_pool_exclude_pad():
    layer = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2),
                             stride=(2, 2), padding=(1, 1))
    x = jnp.ones((1, 2, 2, 1))
    y, _ = layer.apply({}, {}, x)
    # corner windows contain exactly 1 real element → exclude-pad avg = 1.0
    np.testing.assert_allclose(np.asarray(y), 1.0, rtol=1e-6)


def test_evaluation_single_sigmoid_output():
    ev = Evaluation()
    labels = np.array([[0.0], [1.0], [1.0], [0.0]])
    preds = np.array([[0.3], [0.9], [0.2], [0.6]])
    ev.eval(labels, preds)
    assert ev.confusion.shape == (2, 2)
    assert ev.accuracy() == 0.5


def test_learned_self_attention_no_projection():
    layer = LearnedSelfAttentionLayer(project_input=False, n_queries=3, n_heads=1)
    itype = InputType.recurrent(8, 6)
    assert layer.has_params()
    params = layer.init_params(jax.random.key(0), itype)
    assert "Q" in params
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 8)).astype(np.float32))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (2, 3, 8)


def test_async_iterator_early_break_releases_producer():
    base = ArrayDataSetIterator(np.zeros((1000, 4), np.float32),
                                np.zeros((1000, 2), np.float32), batch_size=10)
    async_it = AsyncDataSetIterator(base, queue_size=2)
    before = threading.active_count()
    for i, _ in enumerate(async_it):
        if i == 3:
            break
    time.sleep(0.5)  # give the producer time to observe the stop flag
    assert threading.active_count() <= before + 1


def test_minibatch_false_scales_loss():
    def build(mb):
        b = NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.0)).mini_batch(mb)
        return MultiLayerNetwork(
            b.list()
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(3)).build()).init()

    x = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.zeros(8, np.int64)]
    it = ArrayDataSetIterator(x, y, 8)
    n1 = build(True)
    n1.fit(it, epochs=1)
    n2 = build(False)
    n2.fit(it, epochs=1)
    np.testing.assert_allclose(n2.score(), n1.score() * 8, rtol=1e-5)


def test_per_layer_updater_override_and_serde():
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.builder()
            .seed(2).updater(Sgd(0.0)).list()   # global lr 0 — only override moves
            .layer(DenseLayer(n_out=8, activation="relu", updater=Adam(0.05)))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    js = conf.to_json()  # must not raise on the embedded updater
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.layers[0].updater.learning_rate == 0.05

    net = MultiLayerNetwork(conf).init()
    w0_before = np.asarray(net.params_[0]["W"]).copy()
    w1_before = np.asarray(net.params_[1]["W"]).copy()
    x = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 32)]
    net.fit(ArrayDataSetIterator(x, y, 16), epochs=2)
    # layer 0 (Adam override) moved; layer 1 (global sgd lr=0) did not
    assert not np.allclose(np.asarray(net.params_[0]["W"]), w0_before)
    np.testing.assert_array_equal(np.asarray(net.params_[1]["W"]), w1_before)


def test_async_iterator_full_queue_epoch_end_terminates():
    """_DONE sentinel must arrive even when the consumer is slow and the
    queue is full at producer finish."""
    base = ArrayDataSetIterator(np.zeros((50, 4), np.float32),
                                np.zeros((50, 2), np.float32), batch_size=10)
    async_it = AsyncDataSetIterator(base, queue_size=2)
    seen = 0
    for _ in async_it:
        time.sleep(0.05)  # slower than producer
        seen += 1
    assert seen == 5
