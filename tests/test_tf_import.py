"""General TF GraphDef import goldens (closes VERDICT r4 missing #6's
"accepted gap"): frozen tf.compat.v1 graphs built+evaluated in a TF
SUBPROCESS (TF cannot load into the pytest process), then imported by
OUR wire codec + executor and matched numerically."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.importers.tf_import import import_tf_graph

_GEN = r"""
import json, sys
import numpy as np
import tensorflow as tf
spec = json.loads(sys.argv[1])
rng = np.random.default_rng(spec["seed"])
g = tf.Graph()
with g.as_default():
    if spec["kind"] == "mlp":
        x = tf.compat.v1.placeholder(tf.float32, [None, 8], name="x")
        w1 = tf.constant(rng.normal(0, 0.4, (8, 16)).astype(np.float32))
        b1 = tf.constant(rng.normal(0, 0.1, (16,)).astype(np.float32))
        h = tf.nn.relu(tf.nn.bias_add(tf.matmul(x, w1), b1))
        w2 = tf.constant(rng.normal(0, 0.4, (16, 3)).astype(np.float32))
        scale = tf.compat.v1.placeholder_with_default(
            tf.constant(1.0), [], name="scale")
        y = tf.nn.softmax(tf.matmul(h, w2) * scale, name="y")
        feed = rng.normal(size=(4, 8)).astype(np.float32)
    elif spec["kind"] == "cnn_bn":
        x = tf.compat.v1.placeholder(tf.float32, [None, 8, 8, 3], name="x")
        w = tf.constant(rng.normal(0, 0.2, (3, 3, 3, 4)).astype(np.float32))
        c = tf.nn.conv2d(x, w, strides=[1, 1, 1, 1], padding="SAME")
        gamma = tf.constant(rng.normal(1, 0.1, (4,)).astype(np.float32))
        beta = tf.constant(rng.normal(0, 0.1, (4,)).astype(np.float32))
        mean = tf.constant(rng.normal(0, 0.1, (4,)).astype(np.float32))
        var = tf.constant(rng.uniform(0.5, 1.5, (4,)).astype(np.float32))
        bn, _, _ = tf.compat.v1.nn.fused_batch_norm(
            c, gamma, beta, mean=mean, variance=var, is_training=False)
        r = tf.nn.relu(bn)
        p = tf.nn.max_pool2d(r, 2, 2, "VALID")
        flat = tf.reshape(p, [-1, 4 * 4 * 4])
        wd = tf.constant(rng.normal(0, 0.3, (64, 5)).astype(np.float32))
        y = tf.nn.softmax(tf.matmul(flat, wd), name="y")
        feed = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    elif spec["kind"] == "misc_ops":
        x = tf.compat.v1.placeholder(tf.float32, [2, 6], name="x")
        a = tf.transpose(tf.transpose(x))         # [2, 6] round trip
        b = tf.concat([x, tf.square(x)], axis=1)  # [2, 12]
        c = tf.reduce_mean(b, axis=1, keepdims=True)
        d = tf.pad(x, [[0, 0], [1, 1]])
        e = tf.strided_slice(d, [0, 1], [2, 7], [1, 1])
        y = tf.add(e + a, c, name="y")
        feed = rng.normal(size=(2, 6)).astype(np.float32)
with tf.compat.v1.Session(graph=g) as sess:
    golden = sess.run("y:0", {"x:0": feed})
open(spec["pb"], "wb").write(g.as_graph_def().SerializeToString())
np.savez(spec["npz"], x=feed, golden=golden)
"""


# Committed golden-fixture cache (same scheme as test_keras_import):
# real TF GraphDefs + recorded session outputs keyed by
# sha1(spec + generator), so re-runs skip the ~10s TF subprocess per
# test.  Cache miss regenerates live and refreshes; delete the dir to
# force regeneration against the installed tensorflow.
_FIXTURE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "fixtures", "tf_cache")


def _fixture(tmp_path, kind, seed=0):
    import hashlib
    import shutil
    key = hashlib.sha1(json.dumps([kind, seed, _GEN]).encode()) \
        .hexdigest()[:16]
    cached_pb = os.path.join(_FIXTURE_CACHE, f"{key}.pb")
    cached_npz = os.path.join(_FIXTURE_CACHE, f"{key}.npz")
    if os.path.exists(cached_pb) and os.path.exists(cached_npz):
        data = np.load(cached_npz)
        return cached_pb, data["x"], data["golden"]
    pb = str(tmp_path / "g.pb")
    npz = str(tmp_path / "golden.npz")
    spec = {"kind": kind, "pb": pb, "npz": npz, "seed": seed}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""
    proc = subprocess.run([sys.executable, "-c", _GEN, json.dumps(spec)],
                          capture_output=True, timeout=300, env=env)
    if proc.returncode != 0:
        if b"No module named 'tensorflow'" in proc.stderr:
            pytest.skip("tensorflow unavailable (and no cached fixture)")
        raise RuntimeError(proc.stderr.decode()[-1500:])
    os.makedirs(_FIXTURE_CACHE, exist_ok=True)
    shutil.copy(pb, cached_pb)
    shutil.copy(npz, cached_npz)
    data = np.load(npz)
    return pb, data["x"], data["golden"]


class TestTfGraphImport:
    def test_mlp_golden(self, tmp_path):
        pb, x, golden = _fixture(tmp_path, "mlp")
        m = import_tf_graph(pb)
        assert m.inputs == ["x"]
        np.testing.assert_allclose(np.asarray(m(x)), golden,
                                   rtol=1e-5, atol=1e-6)

    def test_cnn_fused_bn_golden(self, tmp_path):
        pb, x, golden = _fixture(tmp_path, "cnn_bn", seed=1)
        m = import_tf_graph(pb)
        np.testing.assert_allclose(np.asarray(m(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_misc_ops_golden(self, tmp_path):
        """transpose/concat/mean/pad/strided_slice plumbing."""
        pb, x, golden = _fixture(tmp_path, "misc_ops", seed=2)
        m = import_tf_graph(pb)
        np.testing.assert_allclose(np.asarray(m(x)), golden,
                                   rtol=1e-5, atol=1e-6)

    def test_imported_graph_jits_and_grads(self, tmp_path):
        """Train-after-import for the general TF path: the imported fn
        jits, and gradients through the INPUT are finite (weights are
        frozen Consts, the TF deployment form)."""
        import jax
        import jax.numpy as jnp
        pb, x, golden = _fixture(tmp_path, "mlp")
        m = import_tf_graph(pb)
        f = jax.jit(m.as_fn())
        np.testing.assert_allclose(np.asarray(f(x)), golden,
                                   rtol=1e-5, atol=1e-6)
        g = jax.grad(lambda x: jnp.sum(jnp.log(m(x)[:, 0] + 1e-6)))(
            jnp.asarray(x))
        assert np.all(np.isfinite(np.asarray(g)))
        assert float(jnp.abs(g).max()) > 0

    def test_unsupported_op_reported(self):
        from deeplearning4j_tpu.importers import onnx_wire as _w
        # hand-build a GraphDef with a bogus op via the generic emitter
        node = _w.emit({1: ("name", "string"), 2: ("op", "string")},
                       {"name": "n0", "op": "SparseFillEmptyRows"})
        gd = _w._key(1, _w._LEN) + _w._varint(len(node)) + node
        with pytest.raises(NotImplementedError, match="SparseFillEmptyRows"):
            import_tf_graph(gd)

    def test_deep_graph_no_recursion_limit(self):
        """400 chained Adds must evaluate iteratively (review regression:
        recursive eval hit Python's frame limit on real frozen graphs)."""
        from deeplearning4j_tpu.importers import onnx_wire as w
        NODE = {1: ("name", "string"), 2: ("op", "string"),
                3: ("input", "repeated_string"),
                5: ("attr", ("repeated", {1: ("key", "string")}))}

        def nd(name, op, inputs):
            b = w.emit(NODE, {"name": name, "op": op, "input": inputs})
            return w._key(1, w._LEN) + w._varint(len(b)) + b

        parts = [nd("x", "Placeholder", [])]
        prev = "x"
        for i in range(400):
            parts.append(nd(f"a{i}", "Identity", [prev]))
            prev = f"a{i}"
        m = import_tf_graph(b"".join(parts), outputs=[prev])
        x = np.ones((2, 2), np.float32)
        np.testing.assert_allclose(np.asarray(m(x)), x)

    def test_pools_registered_on_constructor_path(self, tmp_path):
        """MaxPool resolves via the TFGraphModel constructor too — not
        only via the import_tf_graph entry point (review regression)."""
        pb, x, golden = _fixture(tmp_path, "cnn_bn", seed=3)
        from deeplearning4j_tpu.importers.tf_import import TFGraphModel
        m = TFGraphModel.load(pb)
        np.testing.assert_allclose(np.asarray(m(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_placeholder_with_default(self, tmp_path):
        """The mlp fixture carries a PlaceholderWithDefault 'scale':
        unfed it evaluates its wired-in default (golden match, and it is
        NOT a positional input); fed by keyword it overrides."""
        pb, x, golden = _fixture(tmp_path, "mlp", seed=4)
        m = import_tf_graph(pb)
        assert m.inputs == ["x"]       # scale is not positional
        np.testing.assert_allclose(np.asarray(m(x)), golden,
                                   rtol=1e-5, atol=1e-6)
        scaled = np.asarray(m(x, scale=np.float32(3.0)))
        assert not np.allclose(scaled, golden)

    def test_feed_validation_and_cycle_detection(self):
        """Extra positional feeds, unknown keyword feeds, and cyclic
        GraphDefs all fail LOUD (review regressions)."""
        from deeplearning4j_tpu.importers import onnx_wire as w
        NODE = {1: ("name", "string"), 2: ("op", "string"),
                3: ("input", "repeated_string")}

        def nd(name, op, inputs):
            b = w.emit(NODE, {"name": name, "op": op, "input": inputs})
            return w._key(1, w._LEN) + w._varint(len(b)) + b

        m = import_tf_graph(nd("x", "Placeholder", [])
                            + nd("y", "Identity", ["x"]), outputs=["y"])
        x = np.ones((2,), np.float32)
        with pytest.raises(ValueError, match="positional"):
            m(x, x)
        with pytest.raises(ValueError, match="unknown feed"):
            m(x, typo=x)

        cyc = import_tf_graph(nd("a", "Identity", ["b"])
                              + nd("b", "Identity", ["a"]), outputs=["a"])
        with pytest.raises(ValueError, match="cycle"):
            cyc()
