"""HealthMonitor: the stats stream gets a judge.  ISSUE-7 acceptance:
a faults-injected NaN at trainer.step is detected within ONE step,
increments tpudl_health_anomalies_total, and fires a flight-recorder
dump whose header names the anomaly."""

import math
import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import cluster_workers  # noqa: E402

from deeplearning4j_tpu.data.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.obs import flight_recorder  # noqa: E402
from deeplearning4j_tpu.obs.health import (HealthConfig,  # noqa: E402
                                           HealthHalt, HealthMonitor,
                                           robust_zscore, stragglers)
from deeplearning4j_tpu.obs.registry import (MetricsRegistry,  # noqa: E402
                                             get_registry, set_registry)
from deeplearning4j_tpu.resilience import faults  # noqa: E402
from deeplearning4j_tpu.train.trainer import Trainer  # noqa: E402


@pytest.fixture
def registry():
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


def _trainer(monitor, seed=9):
    net = cluster_workers._small_net(seed=seed)
    return Trainer(net, listeners=[monitor]), net


def _anomaly_count(registry, kind):
    return registry.labeled_counter(
        "tpudl_health_anomalies_total",
        label_names=("kind",)).labeled_value(kind=kind)


# ============================================== the NaN acceptance rig
class TestNaNDetection:
    def test_injected_nan_detected_within_one_step(self, registry,
                                                   tmp_path):
        dump = str(tmp_path / "health_box.jsonl")
        monitor = HealthMonitor(actions=("warn", "dump"), dump_path=dump)
        trainer, net = _trainer(monitor)
        x, y = cluster_workers.global_batch(n=16, seed=0)
        batch = DataSet(x, y)
        key = jax.random.key(0)
        with faults.inject("trainer.step@3:nan"):
            for i in range(6):
                key, sub = jax.random.split(key)
                trainer.step_batch(batch, sub)
                if i < 3:
                    assert not monitor.anomalies       # healthy so far
                if i == 3:
                    # detected the SAME step the fault fired
                    assert monitor.anomalies, "NaN not caught in-step"
        kinds = [a["kind"] for a in monitor.anomalies]
        assert kinds[0] == "non_finite_loss"
        assert monitor.anomalies[0]["iteration"] == 3
        assert _anomaly_count(registry, "non_finite_loss") >= 1
        # the black box fired on a SEMANTIC anomaly; its header names it
        lines = flight_recorder.read_dump(dump)
        header = next(l for l in lines if l["type"] == "header")
        assert header["reason"] == "health:non_finite_loss"
        assert header["detail"]["kind"] == "non_finite_loss"
        assert header["detail"]["iteration"] == 3
        assert any(l["type"] == "thread" for l in lines)

    def test_halt_action_stops_training(self, registry):
        monitor = HealthMonitor(actions=("halt",))
        trainer, net = _trainer(monitor)
        x, y = cluster_workers.global_batch(n=16, seed=0)
        key = jax.random.key(0)
        with faults.inject("trainer.step@2:nan"):
            with pytest.raises(HealthHalt) as err:
                for _ in range(5):
                    key, sub = jax.random.split(key)
                    trainer.step_batch(DataSet(x, y), sub)
        assert err.value.kind == "non_finite_loss"
        assert net.iteration == 2      # halted before step 3 ever ran

    def test_checkpoint_action_saves_now(self, registry, tmp_path):
        from deeplearning4j_tpu.io.checkpoint import CheckpointListener
        ckpt = CheckpointListener(str(tmp_path))
        monitor = HealthMonitor(actions=("checkpoint",),
                                checkpoint_listener=ckpt)
        trainer, net = _trainer(monitor)
        x, y = cluster_workers.global_batch(n=16, seed=0)
        key = jax.random.key(0)
        with faults.inject("trainer.step@1:nan"):
            for _ in range(3):
                key, sub = jax.random.split(key)
                trainer.step_batch(DataSet(x, y), sub)
        saved = [n for n in os.listdir(str(tmp_path))
                 if n.startswith("checkpoint_iter")]
        assert saved, "checkpoint action produced no checkpoint"
        actions = registry.labeled_counter(
            "tpudl_health_actions_total", label_names=("action",))
        assert actions.labeled_value(action="checkpoint") >= 1

    def test_checkpoint_action_requires_listener(self):
        with pytest.raises(ValueError):
            HealthMonitor(actions=("checkpoint",))
        with pytest.raises(ValueError):
            HealthMonitor(actions=("explode",))


# ================================================== loss-stream checks
class TestLossStream:
    def test_loss_spike_zscore(self, registry):
        monitor = HealthMonitor(
            config=HealthConfig(min_samples=8, spike_zscore=8.0))
        for i in range(20):
            monitor.iteration_done(None, i, 0, 1.0 + 0.01 * (i % 3))
        assert not monitor.anomalies
        monitor.iteration_done(None, 20, 0, 50.0)     # 50x the median
        kinds = [a["kind"] for a in monitor.anomalies]
        assert kinds == ["loss_spike"]
        assert _anomaly_count(registry, "loss_spike") == 1

    def test_no_spike_during_warmup_or_smooth_descent(self, registry):
        monitor = HealthMonitor(
            config=HealthConfig(min_samples=8, spike_zscore=8.0))
        # warmup: even a wild value is not judged before min_samples
        monitor.iteration_done(None, 0, 0, 100.0)
        monitor.iteration_done(None, 1, 0, 1.0)
        # smooth descent never flags
        for i in range(2, 40):
            monitor.iteration_done(None, i, 0, 2.0 * 0.95 ** i + 0.01 * (i % 2))
        assert not monitor.anomalies

    def test_robust_zscore_helper(self):
        window = [1.0, 1.1, 0.9, 1.05, 0.95, 1.0, 1.02]
        assert robust_zscore(window, 1.0) < 1.0
        assert robust_zscore(window, 10.0) > 8.0
        assert robust_zscore([1.0, 1.0], 2.0) is None      # too small
        assert robust_zscore([1.0, 1.0, 1.0], 1.0) is None  # flat, on-median
        assert robust_zscore([1.0, 1.0, 1.0], 2.0) == math.inf


# ================================================= stats-stream checks
def _stats(grad_norm=1.0, zero_fraction=0.0, param_mm=1.0, update_mm=1e-3,
           layer="0"):
    return {
        "params": {layer: {"norm": 10.0, "mean_magnitude": param_mm}},
        "gradients": {layer: {"norm": grad_norm,
                              "zero_fraction": zero_fraction}},
        "updates": {layer: {"norm": 0.1, "mean_magnitude": update_mm}},
    }


class TestStatsStream:
    def test_grad_explosion_and_vanish_bands(self, registry):
        monitor = HealthMonitor(
            config=HealthConfig(grad_norm_max=100.0, grad_norm_min=1e-6))
        monitor.stats_ready(None, 0, 0, 1.0, _stats(grad_norm=1.0))
        assert not monitor.anomalies
        monitor.stats_ready(None, 1, 0, 1.0, _stats(grad_norm=1e5))
        assert [a["kind"] for a in monitor.anomalies] == ["grad_explosion"]
        monitor.stats_ready(None, 2, 0, 1.0, _stats(grad_norm=1e-9))
        assert [a["kind"] for a in monitor.anomalies] == \
            ["grad_explosion", "grad_vanish"]

    def test_non_finite_grad(self, registry):
        monitor = HealthMonitor()
        monitor.stats_ready(None, 0, 0, 1.0,
                            _stats(grad_norm=float("nan")))
        assert [a["kind"] for a in monitor.anomalies] == ["non_finite_grad"]

    def test_dead_units_fraction(self, registry):
        monitor = HealthMonitor(
            config=HealthConfig(dead_fraction_max=0.9))
        monitor.stats_ready(None, 0, 0, 1.0, _stats(zero_fraction=0.5))
        assert not monitor.anomalies
        monitor.stats_ready(None, 1, 0, 1.0, _stats(zero_fraction=0.99))
        assert [a["kind"] for a in monitor.anomalies] == ["dead_units"]
        assert monitor.anomalies[0]["layer"] == "0"

    def test_update_ratio_out_of_band(self, registry):
        monitor = HealthMonitor(
            config=HealthConfig(update_ratio_band=(-6.0, -1.0)))
        monitor.stats_ready(None, 0, 0, 1.0,
                            _stats(param_mm=1.0, update_mm=1e-3))
        assert not monitor.anomalies
        # updates as large as params: the LR is way too hot
        monitor.stats_ready(None, 1, 0, 1.0,
                            _stats(param_mm=1.0, update_mm=1.0))
        assert [a["kind"] for a in monitor.anomalies] == ["update_ratio"]
        # frozen: updates 1e-9 of params
        monitor.stats_ready(None, 2, 0, 1.0,
                            _stats(param_mm=1.0, update_mm=1e-9))
        assert [a["kind"] for a in monitor.anomalies] == \
            ["update_ratio", "update_ratio"]

    def test_device_stats_carry_zero_fraction(self, registry):
        """The on-device stats tree now includes the dead-unit signal
        (obs.stats._stats_of), so the monitor's dead-unit check rides
        the SAME fused program as the rest of the stats."""
        from deeplearning4j_tpu.obs.stats import device_layer_stats
        import jax.numpy as jnp
        stats = device_layer_stats([{"w": jnp.asarray([0.0, 0.0, 0.0, 4.0])}])
        assert float(stats["0"]["zero_fraction"]) == pytest.approx(0.75)

    def test_monitor_rides_real_stats_sampling(self, registry):
        """End-to-end: the monitor's wants_model_stats triggers the
        trainer's stats step; a frozen-updates anomaly is detected from
        REAL device stats (updater LR 0 → update:param ratio b0rked)."""
        from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.train import Sgd
        conf = (NeuralNetConfiguration.builder().seed(3)
                .updater(Sgd(1e-12)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        monitor = HealthMonitor(frequency=2,
                                config=HealthConfig(
                                    update_ratio_band=(-6.0, -1.0)))
        trainer = Trainer(net, listeners=[monitor])
        x, y = cluster_workers.global_batch(n=16, seed=2)
        key = jax.random.key(0)
        for _ in range(3):
            key, sub = jax.random.split(key)
            trainer.step_batch(DataSet(x, y), sub)
        assert any(a["kind"] == "update_ratio" for a in monitor.anomalies)


# ==================================================== straggler helper
def test_stragglers_helper():
    assert stragglers({"a": 0.01, "b": 0.011, "c": 0.05}, factor=2.0) \
        == ["c"]
    assert stragglers({"a": 0.01, "b": 0.011}, factor=2.0) == []
    assert stragglers({"a": 0.01}, factor=2.0) == []       # need >= 2
    assert stragglers({"a": 0.01, "b": None, "c": 0.05}, factor=2.0) \
        == ["c"]
    # absolute-excess jitter guard: a worker whose millisecond median
    # doubled under host scheduler noise is NOT a straggler (relative
    # ratio alone would flag w1 here — observed flake on a loaded box)
    assert stragglers({"w0": 0.252, "w1": 0.0151, "w2": 0.0069,
                       "w3": 0.0071}, factor=2.0) == ["w0"]
    # ... but the guard yields once the excess clears min_excess_s
    assert stragglers({"a": 0.01, "b": 0.011, "c": 0.04},
                      factor=2.0, min_excess_s=0.02) == ["c"]
    assert stragglers({"a": 0.01, "b": 0.011, "c": 0.04},
                      factor=2.0, min_excess_s=0.05) == []
