"""Stats/UI lite tests (VERDICT #9): StatsListener histograms + norms →
storage → static HTML report.

Parity anchors: ``deeplearning4j-ui-model StatsListener.java``,
``InMemoryStatsStorage`` / ``FileStatsStorage``, UI scoped per SURVEY §2.8.
"""

import json

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.stats import (
    StatsListener, InMemoryStatsStorage, FileStatsStorage, render_html_report,
    NUM_BINS)
from deeplearning4j_tpu.train import Adam, Trainer


def _net():
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return ListDataSetIterator([DataSet(x[i:i + 16], y[i:i + 16])
                                for i in range(0, n, 16)])


class TestStatsListener:
    def test_records_norms_and_histograms(self):
        storage = InMemoryStatsStorage()
        net = _net()
        Trainer(net, listeners=[StatsListener(storage, frequency=2)]).fit(
            _data(), epochs=2)
        stats = [r for r in storage.all() if r["type"] == "stats"]
        scores = [r for r in storage.all() if r["type"] == "score"]
        assert stats and scores                       # both record kinds
        rec = stats[0]
        assert set(rec["params"]) == {"0", "1"}       # both layers
        layer0 = rec["params"]["0"]
        for key in ("norm", "mean", "stdev", "mean_magnitude", "min", "max"):
            assert isinstance(layer0[key], float)
        assert len(layer0["hist_counts"]) == NUM_BINS
        # histogram covers all parameter entries of the layer
        n_params = sum(np.asarray(p).size for p in net.params_[0].values())
        assert sum(layer0["hist_counts"]) == n_params
        # gradient + update groups present with sane norms
        assert rec["gradients"]["0"]["norm"] > 0
        assert rec["updates"]["0"]["norm"] > 0

    def test_sampling_frequency(self):
        storage = InMemoryStatsStorage()
        net = _net()
        Trainer(net, listeners=[StatsListener(storage, frequency=4)]).fit(
            _data(), epochs=3)                        # 12 iterations
        stats = [r for r in storage.all() if r["type"] == "stats"]
        assert [r["iteration"] for r in stats] == [0, 4, 8]

    def test_tbptt_records_scores(self):
        """tBPTT path has no stats step — every iteration must still land
        a score record (review regression)."""
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=4))
                .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(3, 8))
                .backprop_type("tbptt", fwd_length=4, back_length=4).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 8, 3)).astype(np.float32)
        y = np.zeros((4, 8, 2), np.float32); y[..., 0] = 1
        it = ListDataSetIterator([DataSet(x, y)])
        storage = InMemoryStatsStorage()
        Trainer(net, listeners=[StatsListener(storage, frequency=1)]).fit(
            it, epochs=3)
        records = storage.all()
        # one static init record + one score record per iteration
        assert [r["type"] for r in records].count("init") == 1
        assert len(records) == 4

    def test_file_storage_replay(self, tmp_path):
        path = str(tmp_path / "stats.jsonl")
        storage = FileStatsStorage(path)
        net = _net()
        Trainer(net, listeners=[StatsListener(storage, frequency=2)]).fit(
            _data(), epochs=1)
        storage.close()
        # file is valid jsonl and replays into a fresh storage
        with open(path) as f:
            lines = [json.loads(l) for l in f]
        assert lines
        replay = FileStatsStorage(path)
        assert len(replay.all()) == len(lines)
        replay.close()


class TestHtmlReport:
    def test_training_produces_openable_report(self, tmp_path):
        """The VERDICT acceptance shape: training MLPMnist-style produces
        an openable HTML report with score + per-layer sections."""
        storage = InMemoryStatsStorage()
        net = _net()
        Trainer(net, listeners=[StatsListener(storage, frequency=2)]).fit(
            _data(), epochs=2)
        out = render_html_report(storage, str(tmp_path / "report.html"))
        html = open(out).read()
        assert html.startswith("<html>")
        assert "Score (loss)" in html
        assert "params: L2 norm per layer" in html
        assert "gradients: L2 norm per layer" in html
        assert "updates: L2 norm per layer" in html
        assert "mean-magnitude ratio" in html
        assert "<svg" in html and "<polyline" in html and "<rect" in html

    def test_report_empty_storage_no_crash(self, tmp_path):
        out = render_html_report(InMemoryStatsStorage(),
                                 str(tmp_path / "empty.html"))
        assert "<html>" in open(out).read()


class TestModelTab:
    def test_init_record_and_model_svg(self, tmp_path):
        """StatsInitializationReport parity: one static topology record,
        rendered as the Model section of the report."""
        from deeplearning4j_tpu.obs.stats import model_topology, render_html
        net = _net()
        storage = InMemoryStatsStorage()
        Trainer(net, listeners=[StatsListener(storage, frequency=2)]).fit(
            _data(), epochs=1)
        inits = [r for r in storage.all() if r["type"] == "init"]
        assert len(inits) == 1
        names = [n["name"] for n in inits[0]["model"]["nodes"]]
        assert names[0] == "input" and len(names) == 3
        html = render_html(storage)
        assert "<h2>Model</h2>" in html and "DenseLayer" in html

    def test_graph_topology(self):
        from deeplearning4j_tpu.obs.stats import model_topology
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.vertices import ElementWiseVertex
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
                .graph()
                .add_inputs("in")
                .set_input_types(InputType.feed_forward(6))
                .add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "d1")
                .add_vertex("skip", ElementWiseVertex(op="add"), "d1", "d2")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "skip")
                .set_outputs("out").build())
        topo = model_topology(ComputationGraph(conf).init())
        kinds = {n["name"]: n["kind"] for n in topo["nodes"]}
        assert kinds["in"] == "input"
        assert kinds["skip"] == "ElementWiseVertex"
        assert ["d1", "skip"] in topo["edges"] and ["d2", "skip"] in topo["edges"]
        assert topo["outputs"] == ["out"]
