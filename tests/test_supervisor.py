"""Self-healing gangs (ISSUE 8): the ClusterSupervisor detects worker
death (SIGKILL — real, uncatchable), tears the surviving gang down,
respawns from the latest verified checkpoint, and the supervised run's
per-step losses still match an uninterrupted run to 1e-6.

Acceptance pins:
- kill-and-heal: a faults-injected SIGKILL of one worker mid-fit leads
  to automatic gang respawn from the latest verified checkpoint; the
  completed run's per-step losses AND final params match the
  uninterrupted run to 1e-6 (dropout active — the RNG trajectory is
  really replayed);
- restart-budget exhaustion raises :class:`GangFailedError` with every
  incident's flight dumps attached;
- the restart/degrade/halt decision (budget per worker slot, shrink
  floor at ``min_workers``) is pinned at the unit level.
"""

import functools
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import cluster_workers  # noqa: E402

from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)  # noqa: E402
from deeplearning4j_tpu.obs.ui_server import UIServer  # noqa: E402
from deeplearning4j_tpu.resilience import faults  # noqa: E402
from deeplearning4j_tpu.resilience.retry import RetryPolicy  # noqa: E402
from deeplearning4j_tpu.resilience.supervisor import (  # noqa: E402
    GENERATION_ENV, RESUME_ENV, ClusterSupervisor, GangFailedError)

_ENV = {"PYTHONPATH": os.path.dirname(os.path.abspath(__file__))
        + os.pathsep + os.environ.get("PYTHONPATH", "")}


@pytest.fixture
def registry():
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


@pytest.fixture(autouse=True)
def _no_ambient_fault_plan():
    faults.clear_fault_plan()
    yield
    faults.clear_fault_plan()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.read().decode()


# ========================================================= kill and heal
def test_kill_and_heal_matches_uninterrupted_losses(tmp_path, registry):
    """THE acceptance test: worker 1 SIGKILLs itself before step 7
    commits (generation 0); the supervisor tears down, respawns both
    workers resuming from their verified checkpoints, and every
    worker's completed trajectory (replayed tail + final params)
    matches the uninterrupted single-process run to 1e-6."""
    refs = {pid: cluster_workers.run_reference_fit(pid) for pid in (0, 1)}

    server = UIServer(port=0)
    try:
        fn = functools.partial(cluster_workers.supervised_train_worker,
                               workdir=str(tmp_path), kill_at=7, kill_pid=1)
        sup = ClusterSupervisor(
            fn, n_processes=2, checkpoint_dir=str(tmp_path),
            max_restarts=2, port=25011, timeout=240.0,
            remote_ui=server.url, cluster_store=server.cluster,
            extra_env=_ENV,
            backoff=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                jitter=0.0))
        run = sup.run()

        # --- recovery happened, exactly once, for the killed slot
        assert run.recovered and len(run.incidents) == 1
        incident = run.incidents[0]
        assert incident.reason == "killed"
        assert any(slot == 1 and rc is not None and rc < 0
                   for slot, rc in incident.exits)
        assert incident.restarted
        assert incident.resumed_from is None  # gen 0 started from scratch
        assert incident.mttr_s is not None and incident.mttr_s > 0
        assert run.generations == 2 and run.slots == [0, 1]

        # --- the 1e-6 contract, per worker
        results = {r["pid"]: r for r in run.results}
        assert sorted(results) == [0, 1]
        for pid in (0, 1):
            losses_ref, params_ref = refs[pid]
            r = results[pid]
            assert r["generation"] == 1
            start = r["end_iteration"] - len(r["losses"])
            np.testing.assert_allclose(r["losses"], losses_ref[start:],
                                       atol=1e-6)
            np.testing.assert_allclose(r["params"], params_ref, atol=1e-6)
        # the killed worker actually replayed its tail from the resume
        # point, not from scratch and not from nothing
        assert 0 < len(results[1]["losses"]) < len(refs[1][0])

        # --- generation-aware federation: the respawned workers
        # re-registered under generation 1 and /cluster annotates it
        summary = json.loads(_get(server.url + "cluster.json"))
        for w in ("w0", "w1"):
            assert summary["workers"][w]["generation"] == 1
            assert summary["workers"][w]["restarts"] == 1
        assert summary["restarts"], "restart annotations missing"
        assert summary["restarts"][0]["to_generation"] == 1
        html = _get(server.url + "cluster")
        assert "generation" in html and "Restarts" in html
        body = _get(server.url + "metrics")
        assert 'tpudl_cluster_worker_generation{worker="w1"} 1' in body

        # --- supervisor metrics
        assert registry.counter(
            "tpudl_resilience_gang_restarts_total").value == 1
    finally:
        server.stop()


# ==================================================== budget exhaustion
def test_restart_budget_exhaustion_raises_with_flight_dumps(registry):
    """Worker slot 1 dies EVERY generation; with max_restarts=1 the
    second death exhausts the budget and GangFailedError carries every
    incident — including the SIGTERMed survivor's black boxes."""
    fn = functools.partial(cluster_workers.repeatedly_dying_worker,
                           die_pid=1, kill_at=2)
    sup = ClusterSupervisor(
        fn, n_processes=2, max_restarts=1, port=25211, timeout=120.0,
        extra_env=_ENV,
        backoff=RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0))
    with pytest.raises(GangFailedError) as exc_info:
        sup.run()
    err = exc_info.value
    assert len(err.incidents) == 2
    assert all(i.reason == "killed" for i in err.incidents)
    assert err.incidents[0].restarted
    assert not err.incidents[1].restarted        # the budget was spent
    assert "max_restarts=1" in str(err)
    # per-incident flight dumps attached: the SIGKILLed worker can't
    # dump (that's the point of SIGKILL), but the surviving sibling's
    # SIGTERM handler writes its black box during teardown
    assert err.flight_dumps, "no flight dumps attached to the failure"
    headers = [line for dump in err.flight_dumps.values()
               for line in dump if line.get("type") == "header"]
    assert headers, "dumps carry no header lines"
    assert registry.counter(
        "tpudl_resilience_gang_restarts_total").value == 1


# ============================================== degradation: the policy
def test_budget_decision_restart_then_shrink_then_halt():
    """The restart/degrade/halt flow, pinned without spawning: budget is
    per worker slot; shrink drops only the exhausted slot; the
    min_workers floor turns shrink into halt."""
    sup = ClusterSupervisor(cluster_workers.trivial_worker, n_processes=3,
                            max_restarts=1, degradation="shrink",
                            min_workers=1)
    restarts = {}
    assert sup._apply_budget([1], [0, 1, 2], restarts) == \
        ("restart", [0, 1, 2])
    assert sup._apply_budget([1], [0, 1, 2], restarts) == \
        ("shrink", [0, 2])
    assert sup._apply_budget([0], [0, 2], restarts) == ("restart", [0, 2])
    assert sup._apply_budget([0], [0, 2], restarts) == ("shrink", [2])
    # last slot over budget: the min_workers floor forces halt
    sup2 = ClusterSupervisor(cluster_workers.trivial_worker, n_processes=2,
                             max_restarts=0, degradation="shrink",
                             min_workers=2)
    assert sup2._apply_budget([1], [0, 1], {}) == ("halt", [0, 1])


def test_budget_decision_halt_policy():
    sup = ClusterSupervisor(cluster_workers.trivial_worker, n_processes=2,
                            max_restarts=1, degradation="halt")
    restarts = {}
    assert sup._apply_budget([0], [0, 1], restarts)[0] == "restart"
    assert sup._apply_budget([0], [0, 1], restarts)[0] == "halt"
    with pytest.raises(ValueError, match="degradation"):
        ClusterSupervisor(cluster_workers.trivial_worker,
                          degradation="explode")


# ================================================== child env plumbing
def test_child_env_plumbing(tmp_path):
    """Respawned children get stable slot identity, the generation
    stamp, the resume pointer (only when a verified checkpoint exists),
    and a stripped fault plan."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    sup = ClusterSupervisor(cluster_workers.trivial_worker, n_processes=2,
                            checkpoint_dir=str(tmp_path))
    # generation 0, no checkpoint yet: no resume pointer, no stripping
    env = sup._child_env(0, [0, 1], sup._latest_checkpoint())(1)
    assert env["DL4J_TPU_WORKER_ID"] == "w1"
    assert env[GENERATION_ENV] == "0"
    assert RESUME_ENV not in env
    assert faults.ENV_VAR not in env
    # a verified checkpoint appears (per-worker subdir layout)
    net = MultiLayerNetwork(cluster_workers._supervised_conf(1)).init()
    net.save(str(tmp_path / "w0" / "checkpoint_iter3_epoch0.zip"))
    found = sup._latest_checkpoint()
    assert found and found.endswith("checkpoint_iter3_epoch0.zip")
    env = sup._child_env(1, [0, 1], found)(0)
    assert env["DL4J_TPU_WORKER_ID"] == "w0"
    assert env[GENERATION_ENV] == "1"
    assert env[RESUME_ENV] == str(tmp_path)
    assert env[faults.ENV_VAR] == ""     # the drill fires exactly once
    # after a shrink, process index 0 can own slot 2
    env = sup._child_env(2, [2], found)(0)
    assert env["DL4J_TPU_WORKER_ID"] == "w2"


def test_classify_failures():
    sup = ClusterSupervisor(cluster_workers.trivial_worker)
    assert sup._classify([(1, -9)]) == "killed"
    assert sup._classify([(0, 87)]) == "stalled"
    assert sup._classify([(0, 1)]) == "crashed"
    assert sup._classify([(0, 1), (1, 87)]) == "stalled"


# ============================================= shrink degradation (e2e)
@pytest.mark.slow
def test_shrink_degradation_completes_with_healthy_subset():
    """Slot 1 dies every generation; degradation="shrink" drops it once
    the budget is spent and the remaining worker finishes the run."""
    fn = functools.partial(cluster_workers.slot_gated_dying_worker, steps=4)
    sup = ClusterSupervisor(
        fn, n_processes=2, max_restarts=1, degradation="shrink",
        min_workers=1, port=25411, timeout=120.0, extra_env=_ENV,
        backoff=RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0))
    run = sup.run()
    assert run.slots == [0]
    assert len(run.incidents) == 2
    assert run.incidents[1].degraded_to == [0]
    results = {r["slot"] for r in run.results}
    assert results == {"w0"}
