"""Tier-1 gate for the whole-program dataflow analyzer (TPU5xx).

Three layers: (a) the framework tree itself must be dataflow-clean —
the same ``analyze --dataflow --self`` contract the CLI enforces;
(b) seeded-defect fixture packages under ``tests/fixtures/dataflow/``
prove each rule fires *interprocedurally* (the defect and the
detection site live in different modules) and that the negative and
pragma variants stay quiet; (c) the satellites — SARIF round-trip,
``--changed`` scoping, pragma-debt report, source-cache content-hash
fallback — each get a deterministic check.
"""

import dataclasses
import json
import os

import pytest

from deeplearning4j_tpu.analyze import (
    analyze_dataflow_paths,
    build_project,
    collect_pragmas,
    env_table_markdown,
    pragma_report,
    report_to_sarif,
    sarif_to_findings,
)
from deeplearning4j_tpu.analyze.__main__ import (
    _filter_report_to,
    changed_files,
    main as analyze_main,
)
from deeplearning4j_tpu.analyze.source import cache_stats, load_source
from deeplearning4j_tpu.config import Config, ENV_KNOBS

import deeplearning4j_tpu

PACKAGE_DIR = os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "dataflow")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


@pytest.fixture(scope="module")
def project():
    """ONE whole-program model of the real tree, shared by every test
    here — the build walks ~150 files and is the expensive part."""
    return build_project([PACKAGE_DIR])


@pytest.fixture(scope="module")
def package_report(project):
    return analyze_dataflow_paths([PACKAGE_DIR], project=project)


# ------------------------------------------------------- self-gate + graph
def test_framework_tree_is_dataflow_clean(package_report):
    """The acceptance gate: zero unsuppressed TPU5xx on the tree."""
    tpu5 = [d for d in package_report.diagnostics
            if d.rule.startswith("TPU5")]
    assert tpu5 == [], "TPU5xx findings in the tree:\n" + "\n".join(
        d.render() for d in tpu5)
    assert package_report.exit_code() == 0


def test_package_model_coverage(package_report):
    ctx = package_report.context
    assert ctx["files_analyzed"] > 100
    assert ctx["env_vars"] >= 25


def test_callgraph_cross_module_resolution_floor(project):
    """Resolution-health floor: a resolver regression that hollows the
    call graph (so interprocedural rules silently see nothing) trips
    this long before a missed finding would.  The real tree currently
    resolves ~880 cross-module edges; 500 leaves refactor headroom."""
    assert len(project.graph.cross_module_edges()) >= 500
    assert project.graph.resolved_edges() >= 2000


def test_dataflow_self_cli_exits_zero():
    assert analyze_main(["--dataflow", "--self"]) == 0


# ------------------------------------------------------------- fixtures
# (dir, expected rule, detection-site basename, defect-site basename) —
# detection and defect sites are in DIFFERENT modules by construction.
POSITIVE_CASES = [
    ("tpu501_pos", "TPU501", "loop.py", "steps.py"),
    ("tpu502_pos", "TPU502", "report.py", "driver.py"),
    ("tpu503_pos", "TPU503", "reader.py", "writer.py"),
    ("tpu504_pos", "TPU504", "alloc.py", "step.py"),
]


@pytest.mark.parametrize("case, rule, anchor, defect", POSITIVE_CASES)
def test_fixture_positive_fires_interprocedurally(case, rule, anchor, defect):
    report = analyze_dataflow_paths([fixture(case)])
    hits = [d for d in report.diagnostics if d.rule == rule]
    assert hits, f"{rule} did not fire on {case}"
    anchored = {os.path.basename((d.path or "").rpartition(":")[0])
                for d in hits}
    assert anchor in anchored
    # the module holding the defect is not the module holding the anchor
    assert anchor != defect
    assert report.exit_code() == 1


@pytest.mark.parametrize("case", [
    "tpu501_neg", "tpu502_neg", "tpu503_neg", "tpu504_neg",
])
def test_fixture_negative_stays_quiet(case):
    report = analyze_dataflow_paths([fixture(case)])
    tpu5 = [d for d in report.diagnostics if d.rule.startswith("TPU5")]
    assert tpu5 == [], "\n".join(d.render() for d in tpu5)


@pytest.mark.parametrize("case, rule", [
    ("tpu501_pragma", "TPU501"),
    ("tpu502_pragma", "TPU502"),
    ("tpu503_pragma", "TPU503"),
    ("tpu504_pragma", "TPU504"),
])
def test_fixture_pragma_suppresses(case, rule):
    report = analyze_dataflow_paths([fixture(case)])
    assert [d for d in report.diagnostics if d.rule.startswith("TPU5")] == []
    assert rule in {d.rule for d in report.suppressed}
    assert report.exit_code() == 0


def test_tpu503_drift_names_both_sides():
    """The positive case is a spelling drift: the set-never-read and the
    read-never-set finding must both surface, each naming its variable."""
    report = analyze_dataflow_paths([fixture("tpu503_pos")])
    msgs = " ".join(d.message for d in report.diagnostics)
    assert "DL4J_TPU_GANG_TOKEN" in msgs
    assert "DL4J_TPU_GANG_TOKEN_ID" in msgs
    assert len([d for d in report.diagnostics if d.rule == "TPU503"]) == 2


# ---------------------------------------------------------------- SARIF
def test_sarif_round_trip():
    """report → SARIF 2.1.0 → findings preserves every field the JSON
    schema carries, including the suppressed flag."""
    report = analyze_dataflow_paths(
        [fixture("tpu501_pos"), fixture("tpu502_pragma")])
    doc = report_to_sarif(report)
    assert doc["version"] == "2.1.0"
    json.dumps(doc)  # must be serializable as-is

    back = sarif_to_findings(doc)
    active = [f for f in back if not f["suppressed"]]
    suppressed = [f for f in back if f["suppressed"]]
    expect = json.loads(report.to_json())["diagnostics"]
    assert [(f["rule"], f["path"], f["message"]) for f in active] == \
           [(f["rule"], f["path"], f["message"]) for f in expect]
    assert {f["rule"] for f in suppressed} == {"TPU502"}

    # every referenced rule is described in the driver's rule catalog
    rules = {r["id"] for r in
             doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {f["rule"] for f in back} <= rules


def test_sarif_cli(capsys):
    rc = analyze_main(["--dataflow", fixture("tpu501_pos"),
                       "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    assert {r["ruleId"] for r in doc["runs"][0]["results"]} == {"TPU501"}


# -------------------------------------------------------------- --changed
def test_changed_files_lists_existing_python():
    files = changed_files("HEAD")
    assert isinstance(files, list)
    for f in files:
        assert f.endswith(".py") and os.path.isfile(f)


def test_filter_report_scopes_findings():
    report = analyze_dataflow_paths([fixture("tpu503_pos")])
    assert len(report.diagnostics) == 2
    keep = {os.path.abspath(os.path.join(fixture("tpu503_pos"),
                                         "reader.py"))}
    _filter_report_to(report, keep)
    assert [os.path.basename((d.path or "").rpartition(":")[0])
            for d in report.diagnostics] == ["reader.py"]


# --------------------------------------------------------------- pragmas
def test_collect_pragmas_inventory():
    recs = collect_pragmas(
        [os.path.join(fixture("tpu501_pragma"), "loop.py")], blame=False)
    assert len(recs) == 1
    assert recs[0]["rules"] == ["TPU501"]
    assert recs[0]["stale_rules"] == []
    assert "post-donation read" in recs[0]["reason"]


def test_pragma_report_flags_stale_rule_ids(tmp_path):
    bad = tmp_path / "stale.py"
    bad.write_text("x = 1  # tpudl: ok(TPU999) — rule retired long ago\n")
    report = pragma_report([str(bad)], blame=False)
    assert any(d.rule == "TPU400" and "TPU999" in d.message
               for d in report.diagnostics)


# ------------------------------------------------------------ source cache
def test_cache_content_hash_fallback(tmp_path):
    """A same-second, same-size rewrite must not serve the stale AST:
    the whole-second mtime marks the stat key untrustworthy, so the
    content hash re-checks and the new text reparses."""
    p = tmp_path / "mod.py"
    whole = 1_700_000_000 * 10**9  # whole-second mtime_ns, far from now
    p.write_text("x = 1\n")
    os.utime(p, ns=(whole, whole))
    sf1 = load_source(str(p))
    p.write_text("x = 2\n")  # identical byte count
    os.utime(p, ns=(whole, whole))  # identical (mtime_ns, size) key
    sf2 = load_source(str(p))
    assert sf2 is not sf1
    assert sf2.text == "x = 2\n"


def test_cache_fast_path_skips_hashing(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("x = 1\n")
    ns = 1_700_000_000 * 10**9 + 123_456_789  # sub-second, far from now
    os.utime(p, ns=(ns, ns))
    sf1 = load_source(str(p))
    before = cache_stats()
    sf2 = load_source(str(p))
    after = cache_stats()
    assert sf2 is sf1
    assert after["hits"] == before["hits"] + 1
    assert after["hash_verifies"] == before["hash_verifies"]


# ------------------------------------------------------------- env table
def test_every_config_knob_is_declared():
    """TPU503's declaration registry must cover every Config field —
    a new field without an ENV_KNOBS entry would surface as a drift
    finding the moment only one side of the contract exists."""
    for f in dataclasses.fields(Config):
        var = Config.env_var_for(f.name)
        assert var in ENV_KNOBS, f"{var} missing from config.ENV_KNOBS"


def test_env_table_embedded_in_docs(project):
    """docs/static_analysis.md embeds the generated env-var table
    verbatim — same can't-drift contract as the rule catalog."""
    with open(os.path.join(REPO_ROOT, "docs", "static_analysis.md")) as f:
        doc = f.read()
    table = env_table_markdown(project)
    assert "DL4J_TPU_COORDINATOR" in table
    assert table in doc, \
        "env table drifted — regenerate with " \
        "python -c 'from deeplearning4j_tpu.analyze import " \
        "env_table_markdown; print(env_table_markdown())'"
