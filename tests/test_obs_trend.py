"""tpudl.obs.trend (ISSUE 16): the perf-trajectory sentinel.

Acceptance pins:
- the committed r01–r05 trajectory classifies honestly: BENCH r01–r04
  real, BENCH_r05 (the legacy tunnel-down shape: rc=1, value 0.0, an
  error string, no ``status`` key) and every MULTICHIP dryrun record
  ``stale``, MULTICHIP_r05 (rc=124) ``failed`` — and the gate reports
  ZERO regressions over them (a tunnel-down is never a perf drop);
- the staleness verdict names r04 as the last real TPU measurement;
- a synthetic r06 with ResNet-50 MFU 0.20 in a temp dir is flagged as
  a regression naming the metric and the trailing-window baseline, and
  ``--check`` exits nonzero on it;
- both the legacy AND the current structured skip shapes classify
  ``stale`` — never ``regression`` (the bench.py honesty fix).
"""

import copy
import json
import shutil

import pytest

from deeplearning4j_tpu.obs import trend


def _committed():
    return trend.load_trajectory()   # repo-root records


# ------------------------------------------------- committed trajectory
def test_committed_records_classify_honestly():
    by = {r.label: r for r in _committed()}
    for rnd in (1, 2, 3, 4):
        rec = by[f"BENCH_r{rnd:02d}"]
        assert rec.status == "real" and rec.metrics, rec
        assert rec.metrics["resnet50_train_images_per_sec_per_chip"] > 0
    r05 = by["BENCH_r05"]
    assert r05.status == "stale"          # legacy tunnel-down, NOT failed
    assert trend.looks_tunnel_down(r05.reason)
    for rnd in (1, 2, 3, 4):
        rec = by[f"MULTICHIP_r{rnd:02d}"]
        assert rec.status == "stale" and "dryrun" in rec.reason
    assert by["MULTICHIP_r05"].status == "failed"
    assert "rc=124" in by["MULTICHIP_r05"].reason


def test_committed_trajectory_has_zero_false_regressions():
    # five stale/failed rounds must read as staleness, not perf drops
    assert trend.gate(_committed()) == []


def test_staleness_names_the_r04_frontier():
    verdict = trend.staleness(_committed())
    assert verdict["stale"] is True
    assert verdict["last_real_round"] == 4
    assert verdict["rounds_since_real"] == 1
    assert "r04" in verdict["message"]


def test_roadmap_targets_pending_until_a_record_past_r04():
    rows = {r["metric"]: r for r in trend.roadmap_status(_committed())}
    assert rows["resnet50_mfu"]["status"] == "pending"
    assert rows["bert_mfu"]["status"] == "pending"
    assert rows["resnet50_mfu"]["target"] == pytest.approx(0.40)
    assert rows["bert_mfu"]["target"] == pytest.approx(0.65)


def test_check_cli_exits_zero_on_the_committed_trajectory(capsys):
    assert trend.main(["--check"]) == 0
    out = capsys.readouterr().out
    assert "BENCH_r05: stale" in out
    assert "regressions: none" in out


# ------------------------------------------------------ the skip shapes
def test_legacy_r05_skip_shape_is_stale_never_regression():
    # the exact BENCH_r05 shape: rc=1, value 0.0, error text, NO status
    raw = {"rc": 1, "parsed": {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0, "unit": "images/sec/chip", "vs_baseline": 0.0,
        "error": "device probe timed out after 300s (tunnel down?)",
        "detail": {}}}
    status, reason, metrics = trend.classify_bench(raw)
    assert status == "stale" and metrics == {}
    assert "timed out" in reason


def test_current_structured_skip_shape_is_stale():
    # the post-fix shape bench.py writes: status="skipped", rc=0
    raw = {"rc": 0, "parsed": {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0, "unit": "images/sec/chip", "vs_baseline": 0.0,
        "status": "skipped",
        "error": "TPU tunnel down: jax fell back to CPU",
        "detail": {"note": "see BENCH_r04"}}}
    status, reason, metrics = trend.classify_bench(raw)
    assert status == "stale" and metrics == {}


def test_non_tunnel_legacy_error_is_failed_not_stale():
    raw = {"rc": 1, "parsed": {"value": 0.0, "detail": {},
                               "error": "segfault in the XLA runtime"}}
    assert trend.classify_bench(raw)[0] == "failed"


def test_multichip_dryrun_is_stale_and_measured_is_real():
    dryrun = {"rc": 0, "ok": True, "tail": "dryrun ok"}
    assert trend.classify_multichip(dryrun)[0] == "stale"
    measured = {"rc": 0, "ok": True,
                "per_chip_scaling_efficiency": 0.93,
                "straggler_skew": 1.1}
    status, _, metrics = trend.classify_multichip(measured)
    assert status == "real"
    assert metrics["per_chip_scaling_efficiency"] == pytest.approx(0.93)


def test_corrupt_record_classifies_failed_not_crash(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text("{torn json")
    records = trend.load_trajectory(str(tmp_path))
    assert len(records) == 1 and records[0].status == "failed"
    assert trend.gate(records) == []


# --------------------------------------------------- synthetic r06 gate
def _seed_r06(tmp_path, mfu=0.20):
    """A temp trajectory = the committed BENCH records + an r06 whose
    ResNet-50 MFU slid to ``mfu`` (throughput stays plausible)."""
    for rec in trend.load_trajectory():
        if rec.kind == "bench":
            shutil.copy(rec.path, tmp_path / f"BENCH_r{rec.round:02d}.json")
    with open(tmp_path / "BENCH_r04.json") as f:
        raw = copy.deepcopy(json.load(f))
    raw["parsed"]["detail"]["mfu"] = mfu
    raw["parsed"]["value"] = 2200.0
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(raw))
    return str(tmp_path)


def test_synthetic_r06_mfu_slide_is_flagged_with_baseline(tmp_path):
    records = trend.load_trajectory(_seed_r06(tmp_path))
    regressions = trend.gate(records)
    assert len(regressions) == 1
    reg = regressions[0]
    assert reg.metric == "resnet50_mfu"
    assert reg.record == "BENCH_r06"
    assert reg.value == pytest.approx(0.20)
    # baseline = median over the trailing window of REAL records that
    # measured the metric (the MFU stamp exists from r03 on)
    history = [r.metrics["resnet50_mfu"] for r in records
               if r.status == "real" and r.round < 6
               and "resnet50_mfu" in r.metrics]
    import statistics
    assert reg.baseline == pytest.approx(statistics.median(history))
    assert reg.window == len(history)
    rendered = reg.render()
    assert "resnet50_mfu" in rendered
    assert "trailing-window median" in rendered


def test_check_cli_exits_nonzero_on_the_synthetic_regression(tmp_path,
                                                             capsys):
    root = _seed_r06(tmp_path)
    assert trend.main(["--check", "--dir", root]) == 1
    out = capsys.readouterr().out
    assert "resnet50_mfu" in out
    assert "regression" in out


def test_roadmap_targets_flip_once_r06_lands(tmp_path):
    records = trend.load_trajectory(_seed_r06(tmp_path))
    rows = {r["metric"]: r for r in trend.roadmap_status(records)}
    assert rows["resnet50_mfu"]["status"] == "fail"     # 0.20 < 0.40
    assert rows["resnet50_mfu"]["value"] == pytest.approx(0.20)
    assert rows["bert_mfu"]["status"] == "fail"         # 0.523 < 0.65


def test_stale_r06_never_reads_as_regression(tmp_path):
    # a tunnel-down r06 on top of the committed history: staleness
    # moves, the gate stays silent
    for rec in trend.load_trajectory():
        if rec.kind == "bench":
            shutil.copy(rec.path, tmp_path / f"BENCH_r{rec.round:02d}.json")
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(
        {"rc": 0, "parsed": {"value": 0.0, "status": "skipped",
                             "error": "tunnel down", "detail": {}}}))
    records = trend.load_trajectory(str(tmp_path))
    assert trend.gate(records) == []
    verdict = trend.staleness(records)
    assert verdict["last_real_round"] == 4
    assert verdict["rounds_since_real"] == 2


# ----------------------------------------------------- write-time stamp
def test_stamp_verdict_marks_skip_records_stale():
    record = {"value": 0.0, "status": "skipped",
              "error": "tunnel down", "detail": {}}
    stamp = trend.stamp_verdict(record)
    assert record["trend"] is stamp
    assert stamp["verdict"] == "stale" and stamp["regressions"] == []


def test_stamp_verdict_flags_a_regressing_record():
    import os
    r04 = os.path.join(trend.default_records_dir(), "BENCH_r04.json")
    with open(r04) as f:
        parsed = copy.deepcopy(json.load(f)["parsed"])
    parsed["detail"]["mfu"] = 0.20
    parsed["value"] = 2200.0
    stamp = trend.stamp_verdict(parsed)
    assert stamp["verdict"] == "regression"
    assert any("resnet50_mfu" in line for line in stamp["regressions"])


def test_stamp_verdict_ok_on_a_healthy_record():
    import os
    r04 = os.path.join(trend.default_records_dir(), "BENCH_r04.json")
    with open(r04) as f:
        parsed = copy.deepcopy(json.load(f)["parsed"])
    stamp = trend.stamp_verdict(parsed)
    assert stamp["verdict"] == "ok" and stamp["regressions"] == []


def test_stamp_verdict_never_raises_on_a_broken_trajectory(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text("{torn")
    record = {"value": 1.0, "detail": {}}
    stamp = trend.stamp_verdict(record, records_dir=str(tmp_path))
    assert stamp["verdict"] in ("ok", "failed", "unknown")
    assert "trend" in record
