"""ONNX importer tests (reference: nd4j/samediff-import-onnx
``OnnxFrameworkImporter`` — protobuf graph → executable graph).

Fixtures are synthesized with the in-repo wire encoder (no onnx package
in this environment), then parsed back through the importer — the same
protobuf bytes a real export produces for this op subset."""

import numpy as np
import pytest

from deeplearning4j_tpu.importers import onnx_wire as wire
from deeplearning4j_tpu.importers.onnx_import import OnnxModel, import_onnx_model


def _vi(name, shape):
    return {"name": name,
            "type": {"tensor_type": {
                "elem_type": 1,
                "shape": {"dim": [{"dim_value": d} for d in shape]}}}}


def _model_bytes(nodes, initializers, inputs, outputs, opset=17):
    graph = {"name": "g", "node": nodes,
             "initializer": [wire.array_to_tensor(n, a)
                             for n, a in initializers.items()],
             "input": [_vi(n, s) for n, s in inputs.items()],
             "output": [_vi(n, s) for n, s in outputs.items()]}
    model = {"ir_version": 8, "graph": graph,
             "opset_import": [{"domain": "", "version": opset}]}
    return wire.emit(wire.MODEL, model)


def _node(op, ins, outs, **attrs):
    node = {"op_type": op, "input": ins, "output": outs, "name": outs[0]}
    alist = []
    for k, v in attrs.items():
        if isinstance(v, float):
            alist.append({"name": k, "f": v, "type": 1})
        elif isinstance(v, int):
            alist.append({"name": k, "i": v, "type": 2})
        elif isinstance(v, (list, tuple)):
            alist.append({"name": k, "ints": list(v), "type": 7})
        else:
            raise TypeError(k)
    if alist:
        node["attribute"] = alist
    return node


def test_wire_roundtrip():
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = wire.array_to_tensor("w", arr)
    buf = wire.emit(wire.TENSOR, t)
    back = wire.tensor_to_array(wire.parse(buf, wire.TENSOR))
    np.testing.assert_array_equal(back, arr)


def test_mlp_gemm_relu_softmax():
    rng = np.random.default_rng(0)
    w1 = rng.normal(0, 0.5, (8, 4)).astype(np.float32)   # [out, in], transB
    b1 = rng.normal(0, 0.1, (8,)).astype(np.float32)
    w2 = rng.normal(0, 0.5, (3, 8)).astype(np.float32)
    b2 = rng.normal(0, 0.1, (3,)).astype(np.float32)
    buf = _model_bytes(
        nodes=[_node("Gemm", ["x", "w1", "b1"], ["h"], transB=1),
               _node("Relu", ["h"], ["hr"]),
               _node("Gemm", ["hr", "w2", "b2"], ["logits"], transB=1),
               _node("Softmax", ["logits"], ["probs"], axis=-1)],
        initializers={"w1": w1, "b1": b1, "w2": w2, "b2": b2},
        inputs={"x": [2, 4]}, outputs={"probs": [2, 3]})
    model = import_onnx_model(buf)
    assert model.input_names == ["x"]
    x = rng.normal(size=(2, 4)).astype(np.float32)
    got = np.asarray(model(x))

    h = np.maximum(x @ w1.T + b1, 0)
    logits = h @ w2.T + b2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True), atol=1e-5)


def test_conv_bn_pool_flatten():
    rng = np.random.default_rng(1)
    w = rng.normal(0, 0.3, (5, 2, 3, 3)).astype(np.float32)  # OIHW
    b = rng.normal(0, 0.1, (5,)).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 5).astype(np.float32)
    bias = rng.normal(0, 0.1, 5).astype(np.float32)
    mean = rng.normal(0, 0.1, 5).astype(np.float32)
    var = rng.uniform(0.5, 1.5, 5).astype(np.float32)
    buf = _model_bytes(
        nodes=[_node("Conv", ["x", "w", "b"], ["c"], kernel_shape=[3, 3],
                     pads=[1, 1, 1, 1]),
               _node("BatchNormalization",
                     ["c", "scale", "bias", "mean", "var"], ["bn"],
                     epsilon=1e-5),
               _node("Relu", ["bn"], ["r"]),
               _node("MaxPool", ["r"], ["p"], kernel_shape=[2, 2],
                     strides=[2, 2]),
               _node("Flatten", ["p"], ["f"]),],
        initializers={"w": w, "b": b, "scale": scale, "bias": bias,
                      "mean": mean, "var": var},
        inputs={"x": [1, 2, 8, 8]}, outputs={"f": [1, 5 * 4 * 4]})
    model = import_onnx_model(buf)
    x = rng.normal(size=(1, 2, 8, 8)).astype(np.float32)
    got = np.asarray(model(x))
    assert got.shape == (1, 5 * 4 * 4)

    # reference conv in pure numpy (NCHW, pad 1)
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    conv = np.zeros((1, 5, 8, 8), np.float32)
    for o in range(5):
        for i in range(2):
            for u in range(8):
                for v in range(8):
                    conv[0, o, u, v] += np.sum(
                        xp[0, i, u:u + 3, v:v + 3] * w[o, i])
        conv[0, o] += b[o]
    bn = ((conv - mean[None, :, None, None])
          / np.sqrt(var[None, :, None, None] + 1e-5)
          * scale[None, :, None, None] + bias[None, :, None, None])
    r = np.maximum(bn, 0)
    pooled = r.reshape(1, 5, 4, 2, 4, 2).max(axis=(3, 5))
    np.testing.assert_allclose(got, pooled.reshape(1, -1), atol=1e-4)


def test_imported_model_jits_and_grads():
    import jax
    import jax.numpy as jnp
    w = np.eye(4, dtype=np.float32)
    buf = _model_bytes(
        nodes=[_node("MatMul", ["x", "w"], ["y"]),
               _node("Tanh", ["y"], ["z"])],
        initializers={"w": w}, inputs={"x": [2, 4]}, outputs={"z": [2, 4]})
    model = import_onnx_model(buf)
    fn = jax.jit(model.as_fn())
    x = jnp.ones((2, 4))
    np.testing.assert_allclose(np.asarray(fn(x)),
                               np.tanh(np.ones((2, 4))), atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(model.as_fn()(x)))(x)
    np.testing.assert_allclose(np.asarray(g),
                               1 - np.tanh(1.0) ** 2, atol=1e-5)


def test_unsupported_op_reported():
    buf = _model_bytes(nodes=[_node("LSTM", ["x"], ["y"])],
                       initializers={}, inputs={"x": [1, 2]},
                       outputs={"y": [1, 2]})
    with pytest.raises(NotImplementedError, match="LSTM"):
        import_onnx_model(buf)


def test_missing_input_reported():
    buf = _model_bytes(nodes=[_node("Relu", ["x"], ["y"])],
                       initializers={}, inputs={"x": [1, 2]},
                       outputs={"y": [1, 2]})
    with pytest.raises(ValueError, match="missing graph inputs"):
        import_onnx_model(buf)()


def test_proto3_zero_attribute_omitted_on_wire():
    """proto3 serializers omit zero scalars: keepdims=0 arrives as
    name+type only.  The importer must not fall back to the default."""
    node = _node("ReduceMean", ["x"], ["y"], axes=[1])
    node["attribute"].append({"name": "keepdims", "type": 2})  # i=0 omitted
    buf = _model_bytes(nodes=[node], initializers={},
                       inputs={"x": [2, 3]}, outputs={"y": [2]})
    model = import_onnx_model(buf)
    x = np.ones((2, 3), np.float32)
    assert np.asarray(model(x)).shape == (2,)   # keepdims honored as 0


def test_conv_same_lower_vs_upper():
    """SAME_LOWER puts the surplus pad element at the BEGINNING; with an
    even kernel the two modes differ by a one-pixel shift."""
    w = np.zeros((1, 1, 2, 2), np.float32)
    w[0, 0, 0, 0] = 1.0    # kernel picks the top-left of its window
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)

    def run(auto_pad):
        node = _node("Conv", ["x", "w"], ["y"], kernel_shape=[2, 2])
        node["attribute"].append({"name": "auto_pad", "s": auto_pad.encode(),
                                  "type": 3})
        buf = _model_bytes(nodes=[node], initializers={"w": w},
                           inputs={"x": [1, 1, 4, 4]},
                           outputs={"y": [1, 1, 4, 4]})
        return np.asarray(import_onnx_model(buf)(x))[0, 0]

    upper = run("SAME_UPPER")    # pad at end → y[i,j] = x[i,j]
    lower = run("SAME_LOWER")    # pad at start → y[i,j] = x[i-1,j-1]
    np.testing.assert_array_equal(upper, x[0, 0])
    np.testing.assert_array_equal(lower[1:, 1:], x[0, 0, :-1, :-1])
    np.testing.assert_array_equal(lower[0], 0.0)


def test_softmax_opset12_flatten_semantics():
    """opset <13: default axis=1 with flatten-to-2D (normalize over ALL
    trailing dims), not single-axis."""
    graph = {"name": "g",
             "node": [_node("Softmax", ["x"], ["y"])],
             "initializer": [],
             "input": [_vi("x", [2, 2, 3])], "output": [_vi("y", [2, 2, 3])]}
    buf = wire.emit(wire.MODEL, {"ir_version": 7, "graph": graph,
                                 "opset_import": [{"domain": "",
                                                   "version": 12}]})
    x = np.random.default_rng(3).normal(size=(2, 2, 3)).astype(np.float32)
    got = np.asarray(import_onnx_model(buf)(x))
    flat = x.reshape(2, 6)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    ref = (e / e.sum(-1, keepdims=True)).reshape(2, 2, 3)
    np.testing.assert_allclose(got, ref, atol=1e-5)
    # sums over the flattened trailing dims are 1, per-axis sums are not
    np.testing.assert_allclose(got.reshape(2, 6).sum(-1), 1.0, atol=1e-5)


def test_pool_auto_pad_same_upper():
    """tf2onnx 'same' pooling exports carry auto_pad, not explicit pads."""
    node = _node("MaxPool", ["x"], ["y"], kernel_shape=[2, 2],
                 strides=[2, 2])
    node["attribute"].append({"name": "auto_pad", "s": b"SAME_UPPER",
                              "type": 3})
    buf = _model_bytes(nodes=[node], initializers={},
                       inputs={"x": [1, 1, 5, 5]}, outputs={"y": [1, 1, 3, 3]})
    x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (1, 1, 3, 3)          # ceil(5/2), not floor
    np.testing.assert_array_equal(got[0, 0], [[6, 8, 9], [16, 18, 19],
                                              [21, 23, 24]])


def test_maxpool_ceil_mode():
    """ceil_mode=1: output size is ceil((size-k)/s)+1 — 6→3 for k=3,s=2
    (floor mode gives 2; the last window is partial)."""
    buf = _model_bytes(
        nodes=[_node("MaxPool", ["x"], ["y"], kernel_shape=[3, 3],
                     strides=[2, 2], ceil_mode=1)],
        initializers={}, inputs={"x": [1, 1, 6, 6]},
        outputs={"y": [1, 1, 3, 3]})
    x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (1, 1, 3, 3)
    assert got[0, 0, -1, -1] == 35.0    # partial corner window max
    # floor mode on the same input: 2x2
    buf2 = _model_bytes(
        nodes=[_node("MaxPool", ["x"], ["y"], kernel_shape=[3, 3],
                     strides=[2, 2])],
        initializers={}, inputs={"x": [1, 1, 6, 6]},
        outputs={"y": [1, 1, 2, 2]})
    assert np.asarray(import_onnx_model(buf2)(x)).shape == (1, 1, 2, 2)


def test_ceil_mode_drops_window_in_overhang():
    """A window starting entirely past the input (stride > kernel) is
    dropped, onnxruntime-style — not emitted as -inf."""
    buf = _model_bytes(
        nodes=[_node("MaxPool", ["x"], ["y"], kernel_shape=[1, 1],
                     strides=[2, 2], ceil_mode=1)],
        initializers={}, inputs={"x": [1, 1, 4, 4]},
        outputs={"y": [1, 1, 2, 2]})
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (1, 1, 2, 2)
    assert np.all(np.isfinite(got))


def test_avgpool_count_include_pad_with_ceil():
    """count_include_pad=1 counts explicit pad cells but not ceil
    overhang: k=2,s=2,pads=[1,0],ceil on [1,1,4] → windows (pad,x0),
    (x1,x2), (x3,ceil) with denominators 2,2,1."""
    buf = _model_bytes(
        nodes=[_node("AveragePool", ["x"], ["y"], kernel_shape=[2],
                     strides=[2], pads=[1, 0], ceil_mode=1,
                     count_include_pad=1)],
        initializers={}, inputs={"x": [1, 1, 4]}, outputs={"y": [1, 1, 3]})
    x = np.asarray([[[2.0, 4.0, 6.0, 8.0]]], np.float32)
    got = np.asarray(import_onnx_model(buf)(x))
    np.testing.assert_allclose(got[0, 0], [(0 + 2) / 2, (4 + 6) / 2, 8 / 1],
                               atol=1e-6)


def test_reshape_zero_copies_input_dim():
    shape = np.asarray([0, -1], np.int64)
    buf = _model_bytes(
        nodes=[_node("Reshape", ["x", "shape"], ["y"])],
        initializers={"shape": shape},
        inputs={"x": [2, 3, 4]}, outputs={"y": [2, 12]})
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (2, 12)
    np.testing.assert_array_equal(got, x.reshape(2, 12))


def test_elementwise_and_shape_ops():
    rng = np.random.default_rng(2)
    a = rng.normal(size=(2, 3)).astype(np.float32)
    buf = _model_bytes(
        nodes=[_node("Unsqueeze", ["x"], ["u"], axes=[0]),
               _node("Transpose", ["u"], ["t"], perm=[0, 2, 1]),
               _node("Squeeze", ["t"], ["s"], axes=[0]),
               _node("Mul", ["s", "s"], ["m"]),
               _node("ReduceMean", ["m"], ["out"], axes=[1], keepdims=0)],
        initializers={}, inputs={"x": [2, 3]}, outputs={"out": [3]})
    model = import_onnx_model(buf)
    got = np.asarray(model(a))
    np.testing.assert_allclose(got, (a.T ** 2).mean(axis=1), atol=1e-6)


def test_reduce_mean_opset18_axes_input():
    # opset >= 18 passes `axes` as a second input, not an attribute
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 3, 4)).astype(np.float32)
    axes = np.asarray([2], np.int64)
    buf = _model_bytes(
        nodes=[_node("ReduceMean", ["x", "axes"], ["y"], keepdims=0)],
        initializers={"axes": axes},
        inputs={"x": [2, 3, 4]}, outputs={"y": [2, 3]}, opset=18)
    got = np.asarray(import_onnx_model(buf)(x))
    np.testing.assert_allclose(got, x.mean(axis=2), atol=1e-6)
