"""ONNX importer tests (reference: nd4j/samediff-import-onnx
``OnnxFrameworkImporter`` — protobuf graph → executable graph).

Fixtures are synthesized with the in-repo wire encoder (no onnx package
in this environment), then parsed back through the importer — the same
protobuf bytes a real export produces for this op subset."""

import numpy as np
import pytest

from deeplearning4j_tpu.importers import onnx_wire as wire
from deeplearning4j_tpu.importers.onnx_import import OnnxModel, import_onnx_model


def _vi(name, shape):
    return {"name": name,
            "type": {"tensor_type": {
                "elem_type": 1,
                "shape": {"dim": [{"dim_value": d} for d in shape]}}}}


def _model_bytes(nodes, initializers, inputs, outputs, opset=17):
    graph = {"name": "g", "node": nodes,
             "initializer": [wire.array_to_tensor(n, a)
                             for n, a in initializers.items()],
             "input": [_vi(n, s) for n, s in inputs.items()],
             "output": [_vi(n, s) for n, s in outputs.items()]}
    model = {"ir_version": 8, "graph": graph,
             "opset_import": [{"domain": "", "version": opset}]}
    return wire.emit(wire.MODEL, model)


def _node(op, ins, outs, **attrs):
    node = {"op_type": op, "input": ins, "output": outs, "name": outs[0]}
    alist = []
    for k, v in attrs.items():
        if isinstance(v, float):
            alist.append({"name": k, "f": v, "type": 1})
        elif isinstance(v, int):
            alist.append({"name": k, "i": v, "type": 2})
        elif isinstance(v, (list, tuple)):
            alist.append({"name": k, "ints": list(v), "type": 7})
        else:
            raise TypeError(k)
    if alist:
        node["attribute"] = alist
    return node


def test_wire_roundtrip():
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = wire.array_to_tensor("w", arr)
    buf = wire.emit(wire.TENSOR, t)
    back = wire.tensor_to_array(wire.parse(buf, wire.TENSOR))
    np.testing.assert_array_equal(back, arr)


def test_mlp_gemm_relu_softmax():
    rng = np.random.default_rng(0)
    w1 = rng.normal(0, 0.5, (8, 4)).astype(np.float32)   # [out, in], transB
    b1 = rng.normal(0, 0.1, (8,)).astype(np.float32)
    w2 = rng.normal(0, 0.5, (3, 8)).astype(np.float32)
    b2 = rng.normal(0, 0.1, (3,)).astype(np.float32)
    buf = _model_bytes(
        nodes=[_node("Gemm", ["x", "w1", "b1"], ["h"], transB=1),
               _node("Relu", ["h"], ["hr"]),
               _node("Gemm", ["hr", "w2", "b2"], ["logits"], transB=1),
               _node("Softmax", ["logits"], ["probs"], axis=-1)],
        initializers={"w1": w1, "b1": b1, "w2": w2, "b2": b2},
        inputs={"x": [2, 4]}, outputs={"probs": [2, 3]})
    model = import_onnx_model(buf)
    assert model.input_names == ["x"]
    x = rng.normal(size=(2, 4)).astype(np.float32)
    got = np.asarray(model(x))

    h = np.maximum(x @ w1.T + b1, 0)
    logits = h @ w2.T + b2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True), atol=1e-5)


def test_conv_bn_pool_flatten():
    rng = np.random.default_rng(1)
    w = rng.normal(0, 0.3, (5, 2, 3, 3)).astype(np.float32)  # OIHW
    b = rng.normal(0, 0.1, (5,)).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 5).astype(np.float32)
    bias = rng.normal(0, 0.1, 5).astype(np.float32)
    mean = rng.normal(0, 0.1, 5).astype(np.float32)
    var = rng.uniform(0.5, 1.5, 5).astype(np.float32)
    buf = _model_bytes(
        nodes=[_node("Conv", ["x", "w", "b"], ["c"], kernel_shape=[3, 3],
                     pads=[1, 1, 1, 1]),
               _node("BatchNormalization",
                     ["c", "scale", "bias", "mean", "var"], ["bn"],
                     epsilon=1e-5),
               _node("Relu", ["bn"], ["r"]),
               _node("MaxPool", ["r"], ["p"], kernel_shape=[2, 2],
                     strides=[2, 2]),
               _node("Flatten", ["p"], ["f"]),],
        initializers={"w": w, "b": b, "scale": scale, "bias": bias,
                      "mean": mean, "var": var},
        inputs={"x": [1, 2, 8, 8]}, outputs={"f": [1, 5 * 4 * 4]})
    model = import_onnx_model(buf)
    x = rng.normal(size=(1, 2, 8, 8)).astype(np.float32)
    got = np.asarray(model(x))
    assert got.shape == (1, 5 * 4 * 4)

    # reference conv in pure numpy (NCHW, pad 1)
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    conv = np.zeros((1, 5, 8, 8), np.float32)
    for o in range(5):
        for i in range(2):
            for u in range(8):
                for v in range(8):
                    conv[0, o, u, v] += np.sum(
                        xp[0, i, u:u + 3, v:v + 3] * w[o, i])
        conv[0, o] += b[o]
    bn = ((conv - mean[None, :, None, None])
          / np.sqrt(var[None, :, None, None] + 1e-5)
          * scale[None, :, None, None] + bias[None, :, None, None])
    r = np.maximum(bn, 0)
    pooled = r.reshape(1, 5, 4, 2, 4, 2).max(axis=(3, 5))
    np.testing.assert_allclose(got, pooled.reshape(1, -1), atol=1e-4)


def test_imported_model_jits_and_grads():
    import jax
    import jax.numpy as jnp
    w = np.eye(4, dtype=np.float32)
    buf = _model_bytes(
        nodes=[_node("MatMul", ["x", "w"], ["y"]),
               _node("Tanh", ["y"], ["z"])],
        initializers={"w": w}, inputs={"x": [2, 4]}, outputs={"z": [2, 4]})
    model = import_onnx_model(buf)
    fn = jax.jit(model.as_fn())
    x = jnp.ones((2, 4))
    np.testing.assert_allclose(np.asarray(fn(x)),
                               np.tanh(np.ones((2, 4))), atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(model.as_fn()(x)))(x)
    np.testing.assert_allclose(np.asarray(g),
                               1 - np.tanh(1.0) ** 2, atol=1e-5)


def test_unsupported_op_reported():
    buf = _model_bytes(nodes=[_node("StringNormalizer", ["x"], ["y"])],
                       initializers={}, inputs={"x": [1, 2]},
                       outputs={"y": [1, 2]})
    with pytest.raises(NotImplementedError, match="StringNormalizer"):
        import_onnx_model(buf)


def test_missing_input_reported():
    buf = _model_bytes(nodes=[_node("Relu", ["x"], ["y"])],
                       initializers={}, inputs={"x": [1, 2]},
                       outputs={"y": [1, 2]})
    with pytest.raises(ValueError, match="missing graph inputs"):
        import_onnx_model(buf)()


def test_proto3_zero_attribute_omitted_on_wire():
    """proto3 serializers omit zero scalars: keepdims=0 arrives as
    name+type only.  The importer must not fall back to the default."""
    node = _node("ReduceMean", ["x"], ["y"], axes=[1])
    node["attribute"].append({"name": "keepdims", "type": 2})  # i=0 omitted
    buf = _model_bytes(nodes=[node], initializers={},
                       inputs={"x": [2, 3]}, outputs={"y": [2]})
    model = import_onnx_model(buf)
    x = np.ones((2, 3), np.float32)
    assert np.asarray(model(x)).shape == (2,)   # keepdims honored as 0


def test_conv_same_lower_vs_upper():
    """SAME_LOWER puts the surplus pad element at the BEGINNING; with an
    even kernel the two modes differ by a one-pixel shift."""
    w = np.zeros((1, 1, 2, 2), np.float32)
    w[0, 0, 0, 0] = 1.0    # kernel picks the top-left of its window
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)

    def run(auto_pad):
        node = _node("Conv", ["x", "w"], ["y"], kernel_shape=[2, 2])
        node["attribute"].append({"name": "auto_pad", "s": auto_pad.encode(),
                                  "type": 3})
        buf = _model_bytes(nodes=[node], initializers={"w": w},
                           inputs={"x": [1, 1, 4, 4]},
                           outputs={"y": [1, 1, 4, 4]})
        return np.asarray(import_onnx_model(buf)(x))[0, 0]

    upper = run("SAME_UPPER")    # pad at end → y[i,j] = x[i,j]
    lower = run("SAME_LOWER")    # pad at start → y[i,j] = x[i-1,j-1]
    np.testing.assert_array_equal(upper, x[0, 0])
    np.testing.assert_array_equal(lower[1:, 1:], x[0, 0, :-1, :-1])
    np.testing.assert_array_equal(lower[0], 0.0)


def test_softmax_opset12_flatten_semantics():
    """opset <13: default axis=1 with flatten-to-2D (normalize over ALL
    trailing dims), not single-axis."""
    graph = {"name": "g",
             "node": [_node("Softmax", ["x"], ["y"])],
             "initializer": [],
             "input": [_vi("x", [2, 2, 3])], "output": [_vi("y", [2, 2, 3])]}
    buf = wire.emit(wire.MODEL, {"ir_version": 7, "graph": graph,
                                 "opset_import": [{"domain": "",
                                                   "version": 12}]})
    x = np.random.default_rng(3).normal(size=(2, 2, 3)).astype(np.float32)
    got = np.asarray(import_onnx_model(buf)(x))
    flat = x.reshape(2, 6)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    ref = (e / e.sum(-1, keepdims=True)).reshape(2, 2, 3)
    np.testing.assert_allclose(got, ref, atol=1e-5)
    # sums over the flattened trailing dims are 1, per-axis sums are not
    np.testing.assert_allclose(got.reshape(2, 6).sum(-1), 1.0, atol=1e-5)


def test_pool_auto_pad_same_upper():
    """tf2onnx 'same' pooling exports carry auto_pad, not explicit pads."""
    node = _node("MaxPool", ["x"], ["y"], kernel_shape=[2, 2],
                 strides=[2, 2])
    node["attribute"].append({"name": "auto_pad", "s": b"SAME_UPPER",
                              "type": 3})
    buf = _model_bytes(nodes=[node], initializers={},
                       inputs={"x": [1, 1, 5, 5]}, outputs={"y": [1, 1, 3, 3]})
    x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (1, 1, 3, 3)          # ceil(5/2), not floor
    np.testing.assert_array_equal(got[0, 0], [[6, 8, 9], [16, 18, 19],
                                              [21, 23, 24]])


def test_maxpool_ceil_mode():
    """ceil_mode=1: output size is ceil((size-k)/s)+1 — 6→3 for k=3,s=2
    (floor mode gives 2; the last window is partial)."""
    buf = _model_bytes(
        nodes=[_node("MaxPool", ["x"], ["y"], kernel_shape=[3, 3],
                     strides=[2, 2], ceil_mode=1)],
        initializers={}, inputs={"x": [1, 1, 6, 6]},
        outputs={"y": [1, 1, 3, 3]})
    x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (1, 1, 3, 3)
    assert got[0, 0, -1, -1] == 35.0    # partial corner window max
    # floor mode on the same input: 2x2
    buf2 = _model_bytes(
        nodes=[_node("MaxPool", ["x"], ["y"], kernel_shape=[3, 3],
                     strides=[2, 2])],
        initializers={}, inputs={"x": [1, 1, 6, 6]},
        outputs={"y": [1, 1, 2, 2]})
    assert np.asarray(import_onnx_model(buf2)(x)).shape == (1, 1, 2, 2)


def test_ceil_mode_drops_window_in_overhang():
    """A window starting entirely past the input (stride > kernel) is
    dropped, onnxruntime-style — not emitted as -inf."""
    buf = _model_bytes(
        nodes=[_node("MaxPool", ["x"], ["y"], kernel_shape=[1, 1],
                     strides=[2, 2], ceil_mode=1)],
        initializers={}, inputs={"x": [1, 1, 4, 4]},
        outputs={"y": [1, 1, 2, 2]})
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (1, 1, 2, 2)
    assert np.all(np.isfinite(got))


def test_avgpool_count_include_pad_with_ceil():
    """count_include_pad=1 counts explicit pad cells but not ceil
    overhang: k=2,s=2,pads=[1,0],ceil on [1,1,4] → windows (pad,x0),
    (x1,x2), (x3,ceil) with denominators 2,2,1."""
    buf = _model_bytes(
        nodes=[_node("AveragePool", ["x"], ["y"], kernel_shape=[2],
                     strides=[2], pads=[1, 0], ceil_mode=1,
                     count_include_pad=1)],
        initializers={}, inputs={"x": [1, 1, 4]}, outputs={"y": [1, 1, 3]})
    x = np.asarray([[[2.0, 4.0, 6.0, 8.0]]], np.float32)
    got = np.asarray(import_onnx_model(buf)(x))
    np.testing.assert_allclose(got[0, 0], [(0 + 2) / 2, (4 + 6) / 2, 8 / 1],
                               atol=1e-6)


def test_reshape_zero_copies_input_dim():
    shape = np.asarray([0, -1], np.int64)
    buf = _model_bytes(
        nodes=[_node("Reshape", ["x", "shape"], ["y"])],
        initializers={"shape": shape},
        inputs={"x": [2, 3, 4]}, outputs={"y": [2, 12]})
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    got = np.asarray(import_onnx_model(buf)(x))
    assert got.shape == (2, 12)
    np.testing.assert_array_equal(got, x.reshape(2, 12))


def test_elementwise_and_shape_ops():
    rng = np.random.default_rng(2)
    a = rng.normal(size=(2, 3)).astype(np.float32)
    buf = _model_bytes(
        nodes=[_node("Unsqueeze", ["x"], ["u"], axes=[0]),
               _node("Transpose", ["u"], ["t"], perm=[0, 2, 1]),
               _node("Squeeze", ["t"], ["s"], axes=[0]),
               _node("Mul", ["s", "s"], ["m"]),
               _node("ReduceMean", ["m"], ["out"], axes=[1], keepdims=0)],
        initializers={}, inputs={"x": [2, 3]}, outputs={"out": [3]})
    model = import_onnx_model(buf)
    got = np.asarray(model(a))
    np.testing.assert_allclose(got, (a.T ** 2).mean(axis=1), atol=1e-6)


def test_reduce_mean_opset18_axes_input():
    # opset >= 18 passes `axes` as a second input, not an attribute
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 3, 4)).astype(np.float32)
    axes = np.asarray([2], np.int64)
    buf = _model_bytes(
        nodes=[_node("ReduceMean", ["x", "axes"], ["y"], keepdims=0)],
        initializers={"axes": axes},
        inputs={"x": [2, 3, 4]}, outputs={"y": [2, 3]}, opset=18)
    got = np.asarray(import_onnx_model(buf)(x))
    np.testing.assert_allclose(got, x.mean(axis=2), atol=1e-6)


# ===================== round-4 opset breadth =====================
def _snode(op, ins, outs, strings=None, tensors=None, **attrs):
    """_node + string/tensor attributes."""
    node = _node(op, ins, outs, **attrs)
    alist = node.setdefault("attribute", [])
    for k, v in (strings or {}).items():
        alist.append({"name": k, "s": v.encode(), "type": 3})
    for k, (tn, ta) in (tensors or {}).items():
        alist.append({"name": k, "t": wire.array_to_tensor(tn, ta), "type": 4})
    return node


def _run1(node, feeds, outputs, opset=17, extra_inits=None):
    inputs = {k: list(np.shape(v)) for k, v in feeds.items()}
    buf = _model_bytes([node], extra_inits or {}, inputs, outputs, opset=opset)
    return import_onnx_model(buf)(**feeds)


class TestRound4Ops:
    def test_unary_family(self):
        rng = np.random.default_rng(0)
        x = rng.uniform(0.1, 0.9, (2, 5)).astype(np.float32)
        for op, ref in [("Ceil", np.ceil), ("Floor", np.floor),
                        ("Round", np.rint), ("Sign", np.sign),
                        ("Sin", np.sin), ("Cos", np.cos),
                        ("Atan", np.arctan), ("Asin", np.arcsin),
                        ("Reciprocal", np.reciprocal),
                        ("Softplus", lambda v: np.log1p(np.exp(v)))]:
            got = np.asarray(_run1(_node(op, ["x"], ["y"]), {"x": x},
                                   {"y": list(x.shape)}))
            np.testing.assert_allclose(got, ref(x), rtol=1e-5, atol=1e-6,
                                       err_msg=op)

    def test_activations(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(3, 4)).astype(np.float32)
        got = np.asarray(_run1(_node("Elu", ["x"], ["y"], alpha=0.7),
                               {"x": x}, {"y": [3, 4]}))
        np.testing.assert_allclose(got, np.where(x > 0, x, 0.7 * (np.exp(x) - 1)),
                                   rtol=1e-5, atol=1e-6)
        got = np.asarray(_run1(_node("HardSigmoid", ["x"], ["y"],
                                     alpha=0.25, beta=0.4),
                               {"x": x}, {"y": [3, 4]}))
        np.testing.assert_allclose(got, np.clip(0.25 * x + 0.4, 0, 1),
                                   rtol=1e-5)
        got = np.asarray(_run1(_node("ThresholdedRelu", ["x"], ["y"], alpha=0.3),
                               {"x": x}, {"y": [3, 4]}))
        np.testing.assert_allclose(got, np.where(x > 0.3, x, 0))
        slope = np.asarray([0.1, 0.2, 0.3, 0.4], np.float32)
        got = np.asarray(_run1(_node("PRelu", ["x", "s"], ["y"]),
                               {"x": x, "s": slope}, {"y": [3, 4]}))
        np.testing.assert_allclose(got, np.where(x >= 0, x, slope * x),
                                   rtol=1e-6)
        got = np.asarray(_run1(_node("LogSoftmax", ["x"], ["y"], axis=-1),
                               {"x": x}, {"y": [3, 4]}))
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(got, np.log(e / e.sum(-1, keepdims=True)),
                                   rtol=1e-5, atol=1e-6)

    def test_variadic_and_compare(self):
        rng = np.random.default_rng(2)
        a, b, c = (rng.normal(size=(2, 3)).astype(np.float32) for _ in range(3))
        inputs = {"a": [2, 3], "b": [2, 3], "c": [2, 3]}
        buf = _model_bytes([_node("Sum", ["a", "b", "c"], ["y"])], {},
                           inputs, {"y": [2, 3]})
        np.testing.assert_allclose(np.asarray(import_onnx_model(buf)(a, b, c)),
                                   a + b + c, rtol=1e-6)
        buf = _model_bytes([_node("Mean", ["a", "b", "c"], ["y"])], {},
                           inputs, {"y": [2, 3]})
        np.testing.assert_allclose(np.asarray(import_onnx_model(buf)(a, b, c)),
                                   (a + b + c) / 3, rtol=1e-6)
        buf = _model_bytes([_node("Max", ["a", "b", "c"], ["y"])], {},
                           inputs, {"y": [2, 3]})
        np.testing.assert_allclose(np.asarray(import_onnx_model(buf)(a, b, c)),
                                   np.maximum(np.maximum(a, b), c))
        got = np.asarray(_run1(_node("Less", ["x", "z"], ["y"]),
                               {"x": a, "z": b}, {"y": [2, 3]}))
        np.testing.assert_array_equal(got, a < b)
        got = np.asarray(_run1(_node("Where", ["m", "x", "z"], ["y"]),
                               {"m": a > 0, "x": a, "z": b}, {"y": [2, 3]}))
        np.testing.assert_allclose(got, np.where(a > 0, a, b))

    def test_reductions_axes_input_opset18(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(2, 3, 4)).astype(np.float32)
        axes = np.asarray([1], np.int64)
        node = _node("ReduceSum", ["x", "axes"], ["y"], keepdims=0)
        got = np.asarray(_run1(node, {"x": x}, {"y": [2, 4]}, opset=18,
                               extra_inits={"axes": axes}))
        np.testing.assert_allclose(got, x.sum(1), rtol=1e-5)
        node = _node("ReduceL2", ["x"], ["y"], axes=[0, 2], keepdims=1)
        got = np.asarray(_run1(node, {"x": x}, {"y": [1, 3, 1]}))
        np.testing.assert_allclose(got, np.sqrt((x * x).sum((0, 2),
                                                            keepdims=True)),
                                   rtol=1e-5)
        node = _node("ReduceLogSumExp", ["x"], ["y"], axes=[2], keepdims=0)
        got = np.asarray(_run1(node, {"x": x}, {"y": [2, 3]}))
        np.testing.assert_allclose(
            got, np.log(np.exp(x).sum(2)), rtol=1e-5)
        node = _node("ArgMax", ["x"], ["y"], axis=2, keepdims=0)
        got = np.asarray(_run1(node, {"x": x}, {"y": [2, 3]}))
        np.testing.assert_array_equal(got, x.argmax(2))

    def test_shape_structure_ops(self):
        rng = np.random.default_rng(4)
        x = rng.normal(size=(2, 3, 4)).astype(np.float32)
        got = np.asarray(_run1(_node("Shape", ["x"], ["y"]), {"x": x},
                               {"y": [3]}))
        np.testing.assert_array_equal(got, [2, 3, 4])
        got = np.asarray(_run1(_node("Cast", ["x"], ["y"], to=7), {"x": x},
                               {"y": [2, 3, 4]}))
        # int64 target; jax demotes to int32 when x64 is off
        assert got.dtype in (np.int32, np.int64)
        got = np.asarray(_run1(_node("Expand", ["x", "s"], ["y"]),
                               {"x": x[:1]}, {"y": [2, 3, 4]},
                               extra_inits={"s": np.asarray([2, 1, 4],
                                                            np.int64)}))
        np.testing.assert_allclose(got, np.broadcast_to(x[:1], (2, 3, 4)))
        got = np.asarray(_run1(_node("Tile", ["x", "r"], ["y"]),
                               {"x": x}, {"y": [2, 6, 4]},
                               extra_inits={"r": np.asarray([1, 2, 1],
                                                            np.int64)}))
        np.testing.assert_allclose(got, np.tile(x, (1, 2, 1)))
        got = np.asarray(_run1(
            _snode("ConstantOfShape", ["s"], ["y"],
                   tensors={"value": ("v", np.asarray([2.5], np.float32))}),
            {}, {"y": [2, 2]},
            extra_inits={"s": np.asarray([2, 2], np.int64)}))
        np.testing.assert_allclose(got, np.full((2, 2), 2.5))

    def test_slice_split_pad(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        got = np.asarray(_run1(
            _node("Slice", ["x", "st", "en", "ax", "sp"], ["y"]),
            {"x": x}, {"y": [2, 3]},
            extra_inits={"st": np.asarray([1, 0], np.int64),
                         "en": np.asarray([3, 2 ** 31 - 1], np.int64),
                         "ax": np.asarray([0, 1], np.int64),
                         "sp": np.asarray([1, 2], np.int64)}))
        np.testing.assert_allclose(got, x[1:3, ::2])
        buf = _model_bytes(
            [_node("Split", ["x"], ["a", "b", "c"], axis=1, split=[1, 2, 3])],
            {}, {"x": [4, 6]}, {"a": [4, 1], "b": [4, 2], "c": [4, 3]})
        a, b, c = import_onnx_model(buf)(x)
        np.testing.assert_allclose(np.asarray(a), x[:, :1])
        np.testing.assert_allclose(np.asarray(c), x[:, 3:])
        got = np.asarray(_run1(
            _node("Pad", ["x", "p", "v"], ["y"]),
            {"x": x}, {"y": [6, 8]},
            extra_inits={"p": np.asarray([1, 1, 1, 1], np.int64),
                         "v": np.asarray(7.0, np.float32)}))
        want = np.pad(x, ((1, 1), (1, 1)), constant_values=7.0)
        np.testing.assert_allclose(got, want)
        got = np.asarray(_run1(
            _snode("Pad", ["x", "p"], ["y"], strings={"mode": "reflect"}),
            {"x": x}, {"y": [6, 6]},
            extra_inits={"p": np.asarray([1, 0, 1, 0], np.int64)}))
        np.testing.assert_allclose(got, np.pad(x, ((1, 1), (0, 0)),
                                               mode="reflect"))

    def test_cumsum_topk_trilu(self):
        x = np.asarray([[3.0, 1.0, 2.0, 5.0], [4.0, 0.0, 6.0, 1.0]],
                       np.float32)
        got = np.asarray(_run1(_node("CumSum", ["x", "ax"], ["y"]),
                               {"x": x}, {"y": [2, 4]},
                               extra_inits={"ax": np.asarray(1, np.int64)}))
        np.testing.assert_allclose(got, np.cumsum(x, 1))
        got = np.asarray(_run1(
            _node("CumSum", ["x", "ax"], ["y"], exclusive=1),
            {"x": x}, {"y": [2, 4]},
            extra_inits={"ax": np.asarray(1, np.int64)}))
        want = np.concatenate([np.zeros((2, 1)), np.cumsum(x, 1)[:, :-1]], 1)
        np.testing.assert_allclose(got, want)
        buf = _model_bytes([_node("TopK", ["x", "k"], ["v", "i"], axis=1)],
                           {"k": np.asarray([2], np.int64)},
                           {"x": [2, 4]}, {"v": [2, 2], "i": [2, 2]})
        v, i = import_onnx_model(buf)(x)
        np.testing.assert_allclose(np.asarray(v), np.sort(x, 1)[:, ::-1][:, :2])
        sq = np.arange(16, dtype=np.float32).reshape(4, 4)
        got = np.asarray(_run1(_node("Trilu", ["x"], ["y"], upper=0),
                               {"x": sq}, {"y": [4, 4]}))
        np.testing.assert_allclose(got, np.tril(sq))

    def test_conv_transpose_vs_torch(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F
        rng = np.random.default_rng(6)
        x = rng.normal(size=(2, 3, 5, 5)).astype(np.float32)
        w = rng.normal(0, 0.4, (3, 4, 3, 3)).astype(np.float32)  # [in,out,kh,kw]
        b = rng.normal(0, 0.1, (4,)).astype(np.float32)
        node = _node("ConvTranspose", ["x", "w", "b"], ["y"],
                     strides=[2, 2], pads=[1, 1, 1, 1])
        got = np.asarray(_run1(node, {"x": x}, {"y": [2, 4, 9, 9]},
                               extra_inits={"w": w, "b": b}))
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  torch.tensor(b), stride=2,
                                  padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_norms_vs_torch(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F
        rng = np.random.default_rng(7)
        x = rng.normal(size=(2, 6, 4, 4)).astype(np.float32)
        scale = rng.uniform(0.5, 1.5, (6,)).astype(np.float32)
        bias = rng.normal(0, 0.2, (6,)).astype(np.float32)
        got = np.asarray(_run1(
            _node("InstanceNormalization", ["x", "s", "b"], ["y"],
                  epsilon=1e-5),
            {"x": x}, {"y": [2, 6, 4, 4]},
            extra_inits={"s": scale, "b": bias}))
        want = F.instance_norm(torch.tensor(x), weight=torch.tensor(scale),
                               bias=torch.tensor(bias), eps=1e-5).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        got = np.asarray(_run1(
            _node("LRN", ["x"], ["y"], size=3, alpha=1e-3, beta=0.75,
                  bias=1.0),
            {"x": x}, {"y": [2, 6, 4, 4]}))
        want = F.local_response_norm(torch.tensor(x), 3, alpha=1e-3,
                                     beta=0.75, k=1.0).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        xt = rng.normal(size=(2, 5, 8)).astype(np.float32)
        g = rng.uniform(0.5, 1.5, (8,)).astype(np.float32)
        bt = rng.normal(0, 0.2, (8,)).astype(np.float32)
        got = np.asarray(_run1(
            _node("LayerNormalization", ["x", "s", "b"], ["y"], axis=-1),
            {"x": xt}, {"y": [2, 5, 8]}, extra_inits={"s": g, "b": bt}))
        want = F.layer_norm(torch.tensor(xt), (8,), torch.tensor(g),
                            torch.tensor(bt)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_depth_space_roundtrip_and_einsum(self):
        rng = np.random.default_rng(8)
        x = rng.normal(size=(1, 8, 2, 2)).astype(np.float32)
        d2s = _run1(_node("DepthToSpace", ["x"], ["y"], blocksize=2),
                    {"x": x}, {"y": [1, 2, 4, 4]})
        back = np.asarray(_run1(_node("SpaceToDepth", ["x"], ["y"],
                                      blocksize=2),
                                {"x": np.asarray(d2s)}, {"y": [1, 8, 2, 2]}))
        np.testing.assert_allclose(back, x)   # DCR d2s ∘ s2d == identity
        a = rng.normal(size=(2, 3)).astype(np.float32)
        bm = rng.normal(size=(3, 4)).astype(np.float32)
        got = np.asarray(_run1(
            _snode("Einsum", ["a", "b"], ["y"], strings={"equation": "ij,jk->ik"}),
            {"a": a, "b": bm}, {"y": [2, 4]}))
        np.testing.assert_allclose(got, a @ bm, rtol=1e-5, atol=1e-5)

    def test_gather_elements_and_global_max(self):
        rng = np.random.default_rng(9)
        x = rng.normal(size=(3, 4)).astype(np.float32)
        idx = np.asarray([[0, 1, 1, 0]], np.int64)
        got = np.asarray(_run1(_node("GatherElements", ["x", "i"], ["y"],
                                     axis=0),
                               {"x": x}, {"y": [1, 4]},
                               extra_inits={"i": idx}))
        np.testing.assert_allclose(got, np.take_along_axis(x, idx, 0))
        xc = rng.normal(size=(2, 3, 4, 4)).astype(np.float32)
        got = np.asarray(_run1(_node("GlobalMaxPool", ["x"], ["y"]),
                               {"x": xc}, {"y": [2, 3, 1, 1]}))
        np.testing.assert_allclose(got, xc.max((2, 3), keepdims=True))
