"""Op-spec / catalog tests (reference: contrib/codegen-tools — op metadata
single-sourced, namespaces + docs generated from it)."""

import numpy as np

from deeplearning4j_tpu.ops import spec


# Pinned per-namespace op counts: dropping an op must fail here (the
# regression guarantee the reference gets from diffing generated code).
# Raising a count is fine — update the pin alongside the new op.
MIN_COUNTS = {"math": 121, "nn": 41, "cnn": 26, "loss": 22, "rnn": 8,
              "linalg": 34, "random": 18, "image": 21, "bitwise": 7,
              "scatter": 23, "base": 41}


def test_counts_pinned():
    got = spec.counts()
    for ns, n in MIN_COUNTS.items():
        assert got.get(ns, 0) >= n, f"{ns}: {got.get(ns, 0)} < pinned {n}"


def test_every_spec_resolves_to_callable():
    specs = spec.op_specs()
    assert len(specs) >= sum(MIN_COUNTS.values())
    for s in specs:
        fn = spec.resolve(s.qualified())
        assert callable(fn)


def test_resolve_unknown_raises():
    import pytest
    with pytest.raises(KeyError):
        spec.resolve("math.not_an_op")
    with pytest.raises(KeyError):
        spec.resolve("nope.exp")


def test_sample_ops_execute():
    x = np.asarray([1.0, 4.0], np.float32)
    assert np.allclose(spec.resolve("math.sqrt")(x), [1.0, 2.0])
    assert spec.resolve("bitwise.and_")(np.int32(6), np.int32(3)) == 2


def test_markdown_catalog(tmp_path):
    p = tmp_path / "OPS.md"
    text = spec.generate_markdown(str(p))
    assert p.exists()
    assert "## `math`" in text and "| `sqrt` |" in text
    assert f"{len(spec.op_specs())} ops" in text
