"""EMNIST / SVHN / TinyImageNet loaders + VGG19 builder
(reference: ``EmnistDataSetIterator.java``, ``SvhnDataFetcher.java``,
``TinyImageNetFetcher.java``, ``zoo/model/VGG19.java``).

No network in this environment, so these exercise the synthetic
fallback path (shape/one-hot contracts) plus the real-format readers
via tiny generated fixtures where the format is cheap to synthesize."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data.datasets import emnist, svhn, tiny_imagenet


def test_emnist_synthetic_shapes():
    it = emnist("balanced", batch_size=32, train=True, root="/nonexistent",
                n_synthetic=200)
    assert it.synthetic
    ds = next(iter(it))
    assert ds.features.shape == (32, 784)
    assert ds.labels.shape == (32, 47)
    np.testing.assert_allclose(np.asarray(ds.labels).sum(axis=1), 1.0)


def test_emnist_split_classes():
    it = emnist("letters", root="/nonexistent", n_synthetic=60, batch_size=8)
    assert next(iter(it)).labels.shape[1] == 26
    it = emnist("digits", root="/nonexistent", n_synthetic=60, batch_size=8,
                flatten=False)
    ds = next(iter(it))
    assert ds.features.shape[1:] == (28, 28, 1)
    assert ds.labels.shape[1] == 10
    with pytest.raises(ValueError):
        emnist("nope", root="/nonexistent")


def test_svhn_real_mat_file(tmp_path):
    from scipy.io import savemat
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, (32, 32, 3, 40)).astype(np.uint8)   # HWCN
    y = np.concatenate([rng.integers(1, 10, 36), [10] * 4]).astype(np.uint8)
    os.makedirs(tmp_path / "svhn")
    savemat(str(tmp_path / "svhn" / "train_32x32.mat"), {"X": x, "y": y[:, None]})
    it = svhn(batch_size=40, train=True, root=str(tmp_path), shuffle=False)
    assert not it.synthetic
    ds = next(iter(it))
    assert ds.features.shape == (40, 32, 32, 3)
    labels = np.argmax(np.asarray(ds.labels), axis=1)
    assert set(labels[-4:]) == {0}          # '10' remapped to digit 0
    assert float(np.max(ds.features)) <= 1.0


def test_svhn_synthetic_fallback():
    it = svhn(batch_size=16, root="/nonexistent", n_synthetic=64)
    assert it.synthetic
    assert next(iter(it)).features.shape == (16, 32, 32, 3)


def test_tiny_imagenet_real_layout(tmp_path):
    from PIL import Image
    wnids = ["n001", "n002"]
    for w in wnids:
        d = tmp_path / "tiny-imagenet-200" / "train" / w / "images"
        os.makedirs(d)
        for i in range(3):
            arr = np.full((64, 64, 3), 40 * (wnids.index(w) + i), np.uint8)
            Image.fromarray(arr).save(d / f"{w}_{i}.JPEG")
    val = tmp_path / "tiny-imagenet-200" / "val"
    os.makedirs(val / "images")
    Image.fromarray(np.zeros((64, 64, 3), np.uint8)).save(
        val / "images" / "val_0.JPEG")
    with open(val / "val_annotations.txt", "w") as f:
        f.write("val_0.JPEG\tn002\t0\t0\t0\t0\n")

    it = tiny_imagenet(batch_size=6, train=True, root=str(tmp_path),
                       shuffle=False)
    assert not it.synthetic
    ds = next(iter(it))
    assert ds.features.shape == (6, 64, 64, 3)
    assert ds.labels.shape == (6, 200)
    itv = tiny_imagenet(batch_size=1, train=False, root=str(tmp_path))
    assert np.argmax(np.asarray(next(iter(itv)).labels)) == 1   # n002


def test_tiny_imagenet_synthetic_fallback():
    it = tiny_imagenet(batch_size=8, root="/nonexistent", n_synthetic=64)
    assert it.synthetic
    ds = next(iter(it))
    assert ds.features.shape == (8, 64, 64, 3) and ds.labels.shape == (8, 200)


def test_vgg19_structure():
    from deeplearning4j_tpu.models import vgg19
    net = vgg19(num_classes=10)
    # VGG19 = 16 conv + 5 pool + 2 dense + output
    from deeplearning4j_tpu.nn.layers import ConvolutionLayer
    convs = [l for l in net.conf.layers if isinstance(l, ConvolutionLayer)]
    assert len(convs) == 16
