"""Smoke tests for the examples gallery (dl4j-examples parity): every
example must run end-to-end at tiny sizes on the test mesh."""

import numpy as np
import pytest

from examples import (bert_mlm_finetune, char_rnn_textgen,
                      data_parallel_training, early_stopping,
                      fault_tolerant_training, lenet_cifar10,
                      lstm_uci_har, mlp_mnist, model_serving,
                      multislice_dcn_training, online_learning,
                      pipeline_parallel_bert, replica_scaling,
                      training_dashboard, transfer_learning,
                      warm_restart, word2vec_embeddings)


def test_mlp_mnist_example():
    # 2 epochs: 1 epoch on 512 synthetic samples lands right at the 0.5
    # threshold and flips with jax-version numerics (0.46 on 0.4.x,
    # >0.5 on the rig's newer jax); 2 epochs is robustly >0.9
    acc = mlp_mnist.main(epochs=2, batch_size=64, hidden=32,
                         n_synthetic=512, verbose=False)
    assert acc > 0.5


def test_lenet_cifar10_example():
    acc = lenet_cifar10.main(epochs=1, batch_size=64, n_synthetic=256,
                             verbose=False)
    assert 0.0 <= acc <= 1.0


def test_lstm_uci_har_example():
    acc = lstm_uci_har.main(epochs=1, batch_size=32, n_synthetic=128,
                            verbose=False)
    assert 0.0 <= acc <= 1.0


@pytest.mark.slow
def test_char_rnn_example_generates_text():
    text = char_rnn_textgen.main(epochs=1, seq_len=16, batch_size=8,
                                 hidden=24, verbose=False)
    assert isinstance(text, str) and len(text) > 60


def test_bert_finetune_example_loss_decreases():
    losses = bert_mlm_finetune.main(epochs=3, seq_len=16, batch_size=8,
                                    verbose=False)
    assert losses[-1] < losses[0]


def test_transfer_learning_example_freezes_base():
    net = transfer_learning.main(pretrain_epochs=1, finetune_epochs=1,
                                 verbose=False)
    assert net.conf.layers[-1].n_out == 5


def test_early_stopping_example_stops_and_restores():
    result = early_stopping.main(max_epochs=8, patience=2, verbose=False)
    assert result.total_epochs <= 8
    assert np.isfinite(result.best_model_score)


def test_data_parallel_example():
    acc = data_parallel_training.main(epochs=2, verbose=False)
    assert acc > 0.5


def test_word2vec_example():
    model = word2vec_embeddings.main(epochs=8, vector_size=16, verbose=False)
    assert model.similarity("cat", "dog") > model.similarity("cat", "gpu")


def test_dashboard_example_writes_report(tmp_path):
    out = training_dashboard.main(epochs=2,
                                  report_path=str(tmp_path / "r.html"),
                                  verbose=False)
    html = open(out).read()
    assert "Score (loss)" in html and "histogram" in html.lower()


def test_multislice_dcn_example():
    losses = multislice_dcn_training.main(steps=6, verbose=False)
    assert losses[-1] < losses[0]


def test_model_serving_example(tmp_path):
    result = model_serving.main(train_epochs=1, workdir=str(tmp_path),
                                verbose=False)
    # deploy → hot-swap → rollback: three versions answered over HTTP
    assert result["versions_served"] == [1, 2, 3]
    assert result["final_version"] == 3


def test_warm_restart_example(tmp_path):
    result = warm_restart.main(workdir=str(tmp_path), verbose=False)
    # the restarted server answered from the artifact store: no XLA
    # trace on the request path, and the first response got faster
    assert result["zero_jit_after_warm"] is True
    assert result["warm"]["classes"] == warm_restart.N_CLASSES
    assert result["first_response_speedup"] > 1.0


def test_online_learning_example(tmp_path):
    result = online_learning.main(feedback_records=48, verbose=False,
                                  workdir=str(tmp_path))
    # deploy → live feedback → background gated swap → forced rollback:
    # three versions answered over HTTP, the last one a rollback
    assert result["versions"] == [1, 2, 3]
    assert result["rolled_back"] is True
    assert result["deploys"] >= 1


def test_replica_scaling_example(tmp_path):
    result = replica_scaling.main(workdir=str(tmp_path), verbose=False)
    # load ramp → autoscale → fan-out hot-swap → all-replica rollback:
    # the fleet grew, three versions served, nothing dropped or garbled
    assert result["replicas_grown_to"] >= 2
    assert result["versions"] == [1, 2, 3]
    assert result["rolled_back"] is True
    assert result["dropped"] == 0
    assert result["garbled"] == 0
    assert result["answered"] > 0


def test_fault_tolerant_training_example(tmp_path):
    drift = fault_tolerant_training.main(epochs=2, crash_at_step=11,
                                         checkpoint_dir=str(tmp_path),
                                         verbose=False)
    assert drift <= 1e-6


@pytest.mark.slow
def test_pipeline_parallel_bert_example():
    losses = pipeline_parallel_bert.main(steps=2, verbose=False)
    assert all(np.isfinite(l) for l in losses)
    assert losses[1] < losses[0]
