"""Transfer learning + early stopping (VERDICT #5).

Parity anchors: ``transferlearning/TransferLearning.java`` /
``FineTuneConfiguration.java`` and ``earlystopping/EarlyStoppingTrainer.java``.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.nn import (NeuralNetConfiguration, InputType,
                                   TransferLearning, FineTuneConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.train import (
    Adam, Sgd, Trainer, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    DataSetLossCalculator, ClassificationScoreCalculator,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition,
    MaxScoreIterationTerminationCondition,
    InvalidScoreIterationTerminationCondition, InMemoryModelSaver,
    LocalFileModelSaver)


def small_net(n_in=8, n_hidden=16, n_out=3, seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=n_hidden, activation="relu"))
            .layer(DenseLayer(n_out=n_hidden, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def blob_data(n=128, n_in=8, n_classes=3, seed=0, center_seed=42):
    """Gaussian blobs; ``center_seed`` fixes the class geometry so train
    (seed=0) and held-out (seed=9) sets share the same distribution."""
    centers = np.random.default_rng(center_seed + n_classes).normal(
        0, 3.0, (n_classes, n_in))
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, n)
    x = centers[y] + rng.normal(0, 0.5, (n, n_in))
    return DataSet(x.astype(np.float32),
                   np.eye(n_classes, dtype=np.float32)[y])


def batches(ds, bs=32):
    return ListDataSetIterator(
        [DataSet(ds.features[i:i + bs], ds.labels[i:i + bs])
         for i in range(0, ds.features.shape[0], bs)])


class TestTransferLearning:
    def test_feature_extractor_freezes_and_grafts(self):
        src = small_net()
        ds = blob_data()
        Trainer(src).fit(batches(ds), epochs=3)
        frozen_w_before = np.asarray(src.params_[0]["W"])

        net2 = (TransferLearning.builder(src)
                .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(1e-2)))
                .set_feature_extractor(1)          # freeze layers 0..1
                .remove_output_layer()
                .add_layer(OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
                .build())
        assert net2.layers[0].frozen and net2.layers[1].frozen
        assert not net2.layers[2].frozen
        # grafted weights identical to source
        np.testing.assert_array_equal(np.asarray(net2.params_[0]["W"]),
                                      frozen_w_before)
        # train on a 5-class problem; frozen layers must not move
        ds5 = blob_data(n_classes=5, seed=1)
        Trainer(net2).fit(batches(ds5), epochs=2)
        np.testing.assert_array_equal(np.asarray(net2.params_[0]["W"]),
                                      frozen_w_before)
        # new head DID move and the net is trainable end-to-end
        assert net2.output(ds5.features[:4]).shape == (4, 5)

    def test_nout_replace_reinits_neighbors(self):
        src = small_net()
        w1_before = np.asarray(src.params_[1]["W"])
        net2 = (TransferLearning.builder(src)
                .nout_replace(1, 32)               # widen hidden layer 1
                .build())
        assert net2.params_[1]["W"].shape == (16, 32)
        assert net2.params_[2]["W"].shape == (32, 3)   # nIn surgery propagated
        # untouched layer 0 is grafted, not re-initialized
        np.testing.assert_array_equal(np.asarray(net2.params_[0]["W"]),
                                      np.asarray(src.params_[0]["W"]))
        assert w1_before.shape != net2.params_[1]["W"].shape

    def test_fine_tune_overrides_cascade(self):
        src = small_net()
        net2 = (TransferLearning.builder(src)
                .fine_tune_configuration(FineTuneConfiguration(
                    updater=Sgd(0.5), l2=1e-3, dropout=0.8))
                .build())
        assert all(l.l2 == 1e-3 for l in net2.layers)
        assert all(l.dropout == 0.8 for l in net2.layers)
        from deeplearning4j_tpu.train.updaters import Sgd as SgdCfg
        assert isinstance(net2.conf.updater, SgdCfg)

    def test_config_json_round_trip_after_surgery(self):
        src = small_net()
        net2 = (TransferLearning.builder(src).set_feature_extractor(0)
                .remove_output_layer()
                .add_layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
                .build())
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        rt = MultiLayerConfiguration.from_json(net2.conf.to_json())
        assert rt.layers[0].frozen
        assert rt.layers[-1].n_out == 4

    def test_invalid_surgery_raises(self):
        src = small_net()
        with pytest.raises(ValueError):
            TransferLearning.builder(src).remove_layers_from_output(4)
        with pytest.raises(ValueError):
            TransferLearning.builder(MultiLayerNetwork(src.conf))  # uninitialized
        from deeplearning4j_tpu.nn.layers.core import ActivationLayer
        net_with_act = (TransferLearning.builder(src)
                        .add_layer(ActivationLayer(activation="tanh")))
        with pytest.raises(ValueError):
            net_with_act.nout_replace(3, 5)  # ActivationLayer has no n_out


class TestEarlyStopping:
    def _fit(self, config, net=None, data_seed=0):
        net = net or small_net()
        tr = batches(blob_data(seed=data_seed))
        return EarlyStoppingTrainer(config, net, tr).fit()

    def test_max_epochs_condition(self):
        net = small_net()
        te = batches(blob_data(seed=9))
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(4)]),
            net=net)
        assert result.total_epochs == 4
        assert result.termination_reason == "EpochTerminationCondition"
        assert "MaxEpochs" in result.termination_details
        assert len(result.score_vs_epoch) == 4
        assert result.best_model is not None

    def test_plateau_halts_and_restores_best(self):
        """Score stops improving → patience trips; best model (not last)
        is returned."""
        net = small_net()
        te = batches(blob_data(seed=9))
        saver = InMemoryModelSaver()
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[
                ScoreImprovementEpochTerminationCondition(
                    patience=3, min_improvement=1e-3),
                MaxEpochsTerminationCondition(50)],
            model_saver=saver), net=net)
        assert result.total_epochs < 50          # plateau tripped before cap
        best = result.best_model
        # best model's held-out loss matches the recorded best score
        calc = DataSetLossCalculator(te)
        np.testing.assert_allclose(calc.calculate_score(best),
                                   result.best_model_score, rtol=1e-4)
        assert result.best_model_epoch in result.score_vs_epoch

    def test_classification_score_maximized(self):
        net = small_net()
        te = batches(blob_data(seed=9))
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=ClassificationScoreCalculator(te, "accuracy"),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(5)]),
            net=net)
        assert 0.0 <= result.best_model_score <= 1.0
        # accuracy improves over random 1/3 on separable blobs
        assert result.best_model_score > 0.5

    def test_divergence_guard_iteration_condition(self):
        net = small_net()
        te = batches(blob_data(seed=9))
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(50)],
            iteration_termination_conditions=[
                MaxScoreIterationTerminationCondition(1e-9)]),  # trips instantly
            net=net)
        assert result.termination_reason == "IterationTerminationCondition"
        assert result.total_epochs == 1

    def test_local_file_saver(self, tmp_path):
        net = small_net()
        te = batches(blob_data(seed=9))
        saver = LocalFileModelSaver(str(tmp_path))
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            model_saver=saver), net=net)
        assert (tmp_path / "bestModel.zip").exists()
        loaded = saver.get_best_model()
        x = np.asarray(blob_data(seed=9).features[:4])
        np.testing.assert_allclose(np.asarray(loaded.output(x)),
                                   np.asarray(result.best_model.output(x)),
                                   rtol=1e-5)

    def test_invalid_score_condition(self):
        cond = InvalidScoreIterationTerminationCondition()
        assert cond.terminate(float("nan"))
        assert cond.terminate(float("inf"))
        assert not cond.terminate(1.0)

    def test_skipped_eval_epochs_dont_count_as_stale(self):
        """evaluate_every_n_epochs>1: patience counts evaluated epochs only."""
        net = small_net()
        te = batches(blob_data(seed=9))
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[
                ScoreImprovementEpochTerminationCondition(patience=2, min_improvement=1e9),
                MaxEpochsTerminationCondition(20)],
            evaluate_every_n_epochs=3), net=net)
        # min_improvement=1e9 → every eval is "no improvement"; evals happen
        # at epochs 0,3,6 → patience 2 trips at epoch 6, not at epoch 2
        assert result.total_epochs == 7

    def test_conditions_reset_between_fits(self):
        """A reused config starts clean (initialize() parity)."""
        cond = ScoreImprovementEpochTerminationCondition(patience=1, min_improvement=1e9)
        te = batches(blob_data(seed=9))
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[cond, MaxEpochsTerminationCondition(10)])
        r1 = self._fit(cfg)
        r2 = self._fit(cfg)          # fresh net, same config object
        assert r1.total_epochs == r2.total_epochs == 2

    def test_iteration_only_config_allowed(self):
        """A config terminating via iteration conditions alone is valid
        (review regression: 'train for at most N seconds' setups)."""
        from deeplearning4j_tpu.train import MaxTimeIterationTerminationCondition
        net = small_net()
        te = batches(blob_data(seed=9))
        result = self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            iteration_termination_conditions=[
                MaxTimeIterationTerminationCondition(0.0)]),  # trips at once
            net=net)
        assert result.termination_reason == "IterationTerminationCondition"

    def test_no_conditions_rejected(self):
        net = small_net()
        te = batches(blob_data(seed=9))
        with pytest.raises(ValueError):
            EarlyStoppingTrainer(EarlyStoppingConfiguration(
                score_calculator=DataSetLossCalculator(te)),
                net, batches(blob_data())).fit()

    def test_save_last_model(self, tmp_path):
        net = small_net()
        te = batches(blob_data(seed=9))
        saver = LocalFileModelSaver(str(tmp_path))
        self._fit(EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(te),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(2)],
            model_saver=saver, save_last_model=True), net=net)
        assert (tmp_path / "latestModel.zip").exists()
        assert saver.get_latest_model() is not None


class TestTransferDonationSafety:
    def test_source_survives_transfer_net_training(self):
        """Grafted params are deep copies — training either net must not
        delete the other's donated buffers."""
        src = small_net()
        ds = blob_data()
        Trainer(src).fit(batches(ds), epochs=1)
        net2 = (TransferLearning.builder(src).remove_output_layer()
                .add_layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .build())
        Trainer(net2).fit(batches(ds), epochs=1)     # donates net2 buffers
        _ = np.asarray(src.output(ds.features[:2]))  # src still alive
        Trainer(src).fit(batches(ds), epochs=1)      # donates src buffers
        _ = np.asarray(net2.output(ds.features[:2])) # net2 still alive
