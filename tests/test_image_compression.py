"""Image pipeline + general array compression tests (VERDICT missing #6,
partial #11: ImageRecordReader/ImageTransform chain; FLOAT16/INT8/GZIP
NDArray compressors).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.compression import (
    BasicNDArrayCompressor, CompressedArray, GzipCompressor, Int8Compressor,
    Float16Compressor)
from deeplearning4j_tpu.data.image import (
    NativeImageLoader, ImageRecordReader, ParentPathLabelGenerator,
    FlipImageTransform, CropImageTransform, RotateImageTransform,
    WarpImageTransform, ScaleImageTransform, ColorConversionTransform,
    ResizeImageTransform, PipelineImageTransform)
from deeplearning4j_tpu.data.records import FileSplit, RecordReaderDataSetIterator


def _write_images(root, classes=("cats", "dogs"), per_class=3, size=20):
    from PIL import Image
    rng = np.random.default_rng(0)
    for ci, cls in enumerate(classes):
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            arr[:, :, ci] = 250          # class-coded channel
            Image.fromarray(arr).save(os.path.join(d, f"img{i}.png"))


class TestImagePipeline:
    def test_loader_resize_and_channels(self, tmp_path):
        _write_images(str(tmp_path), per_class=1)
        path = str(tmp_path / "cats" / "img0.png")
        img = NativeImageLoader(14, 10, 3).load(path)
        assert img.shape == (14, 10, 3) and img.dtype == np.float32
        gray = NativeImageLoader(14, 10, 1).load(path)
        assert gray.shape == (14, 10, 1)

    def test_reader_to_dataset_flow(self, tmp_path):
        """The canonical flow: dir-of-class-dirs → ImageRecordReader →
        RecordReaderDataSetIterator → NHWC DataSet batches."""
        _write_images(str(tmp_path))
        reader = ImageRecordReader(16, 16, 3).initialize(
            FileSplit(str(tmp_path), allowed_extensions=[".png"]))
        assert reader.labels == ["cats", "dogs"]
        it = RecordReaderDataSetIterator(reader, batch_size=4, label_index=1,
                                         num_classes=reader.num_classes())
        batches = list(it)
        assert batches[0].features.shape == (4, 16, 16, 3)
        assert batches[0].labels.shape == (4, 2)
        total = sum(b.features.shape[0] for b in batches)
        assert total == 6
        np.testing.assert_allclose(
            np.asarray(np.concatenate([b.labels for b in batches])).sum(), 6.0)

    def test_transforms_preserve_shape(self):
        rng = np.random.default_rng(1)
        img = rng.uniform(0, 255, (24, 20, 3)).astype(np.float32)
        for t in (FlipImageTransform("horizontal"),
                  FlipImageTransform("random", seed=3),
                  CropImageTransform(4, seed=3),
                  RotateImageTransform(15, seed=3),
                  WarpImageTransform(3, seed=3),
                  ScaleImageTransform(1 / 255.0),
                  ColorConversionTransform(),
                  ResizeImageTransform(24, 20)):
            out = t(img)
            assert out.shape == img.shape, type(t).__name__
            assert np.all(np.isfinite(out))

    def test_flip_semantics(self):
        img = np.arange(12, dtype=np.float32).reshape(2, 2, 3)
        np.testing.assert_array_equal(
            FlipImageTransform("horizontal")(img), img[:, ::-1])
        np.testing.assert_array_equal(
            FlipImageTransform("vertical")(img), img[::-1])

    def test_pipeline_with_probabilities(self):
        img = np.full((8, 8, 3), 100.0, np.float32)
        pipe = PipelineImageTransform(
            [(ScaleImageTransform(2.0), 1.0),
             (ScaleImageTransform(100.0), 0.0)], seed=0)   # never applied
        np.testing.assert_allclose(pipe(img), img * 2.0)

    def test_augmented_reader(self, tmp_path):
        _write_images(str(tmp_path), per_class=2)
        pipe = PipelineImageTransform([FlipImageTransform("random", seed=1),
                                       ScaleImageTransform(1 / 255.0)], seed=1)
        reader = ImageRecordReader(16, 16, 3, transform=pipe).initialize(
            FileSplit(str(tmp_path), allowed_extensions=[".png"]))
        batch = next(iter(RecordReaderDataSetIterator(
            reader, batch_size=4, label_index=1, num_classes=2)))
        assert float(np.asarray(batch.features).max()) <= 1.0


class TestCompression:
    def test_gzip_lossless_round_trip(self):
        rng = np.random.default_rng(0)
        arr = rng.normal(size=(64, 32)).astype(np.float32)
        c = GzipCompressor().compress(arr)
        np.testing.assert_array_equal(GzipCompressor().decompress(c), arr)

    def test_float16_lossy_round_trip(self):
        arr = np.linspace(-3, 3, 1000, dtype=np.float32)
        c = Float16Compressor().compress(arr)
        assert c.compressed_bytes == arr.nbytes // 2
        out = Float16Compressor().decompress(c)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, arr, atol=2e-3)

    def test_int8_lossy_round_trip(self):
        arr = np.linspace(-1, 1, 255, dtype=np.float32)
        c = Int8Compressor().compress(arr)
        assert c.compressed_bytes == arr.nbytes // 4
        out = Int8Compressor().decompress(c)
        np.testing.assert_allclose(out, arr, atol=1.0 / 127 + 1e-6)
        assert c.ratio() == 4.0

    def test_registry_and_serde(self):
        comp = BasicNDArrayCompressor.get_instance()
        arr = np.random.default_rng(1).normal(size=(10, 10)).astype(np.float32)
        comp.set_default_compression("GZIP")
        c = comp.compress(arr)
        assert c.codec == "GZIP"
        blob = c.to_bytes()
        c2 = CompressedArray.from_bytes(blob)
        np.testing.assert_array_equal(comp.decompress(c2), arr)
        with pytest.raises(KeyError):
            comp.compress(arr, codec="LZ4")
        with pytest.raises(KeyError):
            comp.set_default_compression("SNAPPY")
        comp.set_default_compression("FLOAT16")   # restore default
