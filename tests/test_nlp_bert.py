"""NLP + BERT end-to-end workload tests (BASELINE config #4).

Covers the reference pipeline (SURVEY.md §3.3): wordpiece tokenization
(``BertWordPieceTokenizer``), MLM batch building (``BertIterator`` +
``BertMaskedLMMasker``), the TF-checkpoint importer
(``TFGraphMapper``/``ImportGraph`` scope), and the single-chip MLM
fine-tune (loss decreases on a synthetic corpus).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicTokenizer, BertWordPieceTokenizer, Vocabulary, WordpieceTokenizer,
    build_vocab, BertIterator, BertMaskedLMMasker,
    CollectionSentenceProvider, CollectionLabeledSentenceProvider)


def make_vocab(extra=()):
    tokens = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
              "want", "##want", "##ed", "wa", "un", "runn", "##ing", ","]
    return Vocabulary(tokens + list(extra))


class TestBasicTokenizer:
    def test_lower_and_split(self):
        t = BasicTokenizer(lower_case=True)
        assert t.tokenize(" \tHeLLo!how  \n are You?  ") == \
            ["hello", "!", "how", "are", "you", "?"]

    def test_accents_stripped(self):
        t = BasicTokenizer(lower_case=True)
        assert t.tokenize("Héllo") == ["hello"]

    def test_no_lower(self):
        t = BasicTokenizer(lower_case=False)
        assert t.tokenize("HeLLo, There") == ["HeLLo", ",", "There"]

    def test_cjk_isolated(self):
        t = BasicTokenizer()
        assert t.tokenize("ab一亍cd") == ["ab", "一", "亍", "cd"]

    def test_control_chars_removed(self):
        t = BasicTokenizer()
        assert t.tokenize("a\x00b�c") == ["abc"]


class TestWordpiece:
    def test_greedy_longest_match(self):
        wp = WordpieceTokenizer(make_vocab())
        assert wp.tokenize("unwanted") == ["un", "##want", "##ed"]
        assert wp.tokenize("running") == ["runn", "##ing"]

    def test_unknown_word_becomes_unk(self):
        wp = WordpieceTokenizer(make_vocab())
        assert wp.tokenize("unwantedx") == ["[UNK]"]

    def test_empty_and_overlong(self):
        wp = WordpieceTokenizer(make_vocab(), max_chars_per_word=5)
        assert wp.tokenize("") == []
        assert wp.tokenize("toolongword") == ["[UNK]"]

    def test_full_pipeline_ids(self):
        vocab = make_vocab()
        tok = BertWordPieceTokenizer(vocab)
        assert tok.tokenize("UNwanted, running") == \
            ["un", "##want", "##ed", ",", "runn", "##ing"]
        assert tok.encode("unwanted") == [vocab.id("un"), vocab.id("##want"),
                                          vocab.id("##ed")]


class TestVocabBuilder:
    def test_build_contains_words_and_chars(self):
        corpus = ["the cat sat", "the dog sat", "the cat ran"]
        vocab = build_vocab(corpus, max_size=100)
        assert "the" in vocab and "cat" in vocab and "sat" in vocab
        assert "t" in vocab and "##t" in vocab
        tok = BertWordPieceTokenizer(vocab)
        # unseen word decomposes into char pieces, not UNK
        assert "[UNK]" not in tok.tokenize("tac")

    def test_round_trip_file(self, tmp_path):
        vocab = build_vocab(["hello world"], max_size=50)
        p = tmp_path / "vocab.txt"
        vocab.save(str(p))
        vocab2 = Vocabulary.from_file(str(p))
        assert vocab2.tokens == vocab.tokens


class TestMasker:
    def test_masking_invariants(self):
        vocab = build_vocab(["a b c d e f g h i j k l m n o p"], max_size=100)
        masker = BertMaskedLMMasker(mask_prob=0.5, seed=0)
        ids = np.array([vocab.cls_id] + [vocab.id(c) for c in "abcdefgh"]
                       + [vocab.sep_id, vocab.pad_id], dtype=np.int32)
        maskable = np.ones_like(ids, dtype=bool)
        maskable[[0, 9, 10]] = False
        out, labels, weights = masker.mask_sequence(ids, vocab, maskable)
        assert labels.tolist() == ids.tolist()          # labels = originals
        assert weights[0] == 0 and weights[9] == 0 and weights[10] == 0
        assert weights.sum() >= 1                        # at least one masked
        changed = out != ids
        assert np.all(weights[changed] == 1.0)           # changes only where weighted

    def test_at_least_one_masked(self):
        vocab = build_vocab(["x"], max_size=50)
        masker = BertMaskedLMMasker(mask_prob=0.0, seed=0)
        ids = np.array([vocab.cls_id, vocab.id("x"), vocab.sep_id], dtype=np.int32)
        maskable = np.array([False, True, False])
        _, _, weights = masker.mask_sequence(ids, vocab, maskable)
        assert weights.sum() == 1.0


CORPUS = ["the quick brown fox jumps over the lazy dog",
          "a stitch in time saves nine",
          "the early bird catches the worm",
          "actions speak louder than words",
          "the pen is mightier than the sword",
          "practice makes perfect every day",
          "better late than never they say",
          "the cat sat on the warm mat"]


class TestBertIterator:
    def _iterator(self, task=BertIterator.UNSUPERVISED, **kw):
        vocab = build_vocab(CORPUS, max_size=500)
        tok = BertWordPieceTokenizer(vocab)
        if task == BertIterator.SEQ_CLASSIFICATION:
            provider = CollectionLabeledSentenceProvider(
                CORPUS, ["animal", "time", "animal", "speech",
                         "speech", "time", "time", "animal"])
        else:
            provider = CollectionSentenceProvider(CORPUS)
        return BertIterator(tok, provider, task=task, seq_len=16,
                            batch_size=3, **kw), vocab

    def test_mlm_batch_shapes_and_semantics(self):
        it, vocab = self._iterator()
        batches = list(it)
        assert len(batches) == 3                     # 8 sentences / batch 3
        b = batches[0]
        assert b["input_ids"].shape == (3, 16)
        assert b["attention_mask"].shape == (3, 16)
        assert b["labels"].shape == (3, 16)
        assert b["label_weights"].shape == (3, 16)
        # framing: position 0 is [CLS]; a [SEP] exists; pads are masked out
        assert np.all(b["labels"][:, 0] == vocab.cls_id)
        assert np.all((b["labels"] == vocab.sep_id).sum(axis=1) == 1)
        assert np.all(b["label_weights"][b["attention_mask"] == 0] == 0)
        # [CLS]/[SEP] never masked
        special = (b["labels"] == vocab.cls_id) | (b["labels"] == vocab.sep_id)
        assert np.all(b["label_weights"][special] == 0)
        assert b["label_weights"].sum() >= 3         # >=1 per row

    def test_final_batch_padded_static_shape(self):
        it, _ = self._iterator()
        last = list(it)[-1]                          # 8 % 3 = 2 real rows
        assert last["input_ids"].shape == (3, 16)    # padded to batch_size
        np.testing.assert_array_equal(last["sample_weights"], [1.0, 1.0, 0.0])
        assert last["label_weights"][2].sum() == 0   # pad row → no loss

    def test_deterministic_replay_but_fresh_masks_per_epoch(self):
        it, _ = self._iterator()
        it2, _ = self._iterator()
        first = [b["input_ids"].copy() for b in it]
        for a, b in zip(first, it2):                 # same seed → same epoch-0
            np.testing.assert_array_equal(a, b["input_ids"])
        it.reset()                                   # next epoch → fresh masks
        second = [b["input_ids"].copy() for b in it]
        assert any(not np.array_equal(a, b) for a, b in zip(first, second))

    def test_static_masks_mode(self):
        it, _ = self._iterator(static_masks=True)
        first = [b["input_ids"].copy() for b in it]
        it.reset()
        for a, b in zip(first, it):
            np.testing.assert_array_equal(a, b["input_ids"])

    def test_classification_batches(self):
        it, vocab = self._iterator(task=BertIterator.SEQ_CLASSIFICATION)
        b = next(iter(it))
        assert b["labels"].shape == (3, 3)           # 3 classes one-hot
        np.testing.assert_allclose(b["labels"].sum(axis=1), 1.0)
        assert np.all(b["input_ids"][:, 0] == vocab.cls_id)


class TestBertFineTune:
    def test_mlm_loss_decreases(self):
        """Single-chip MLM fine-tune on a synthetic corpus — the BASELINE
        config #4 acceptance shape."""
        import jax
        from deeplearning4j_tpu.models.bert import BertConfig, BertForMaskedLM
        from deeplearning4j_tpu.train import Adam

        vocab = build_vocab(CORPUS * 2, max_size=300)
        tok = BertWordPieceTokenizer(vocab)
        it = BertIterator(tok, CollectionSentenceProvider(CORPUS * 2),
                          seq_len=16, batch_size=4, seed=7)
        config = BertConfig(vocab_size=len(vocab), hidden_size=32,
                            num_layers=2, num_heads=2, intermediate_size=64,
                            max_position=32, hidden_dropout=0.0,
                            attention_dropout=0.0)
        model = BertForMaskedLM(config, seed=0)
        first = model.fit(it, updater=Adam(2e-3), epochs=1)
        last = model.fit(it, updater=Adam(2e-3), epochs=20)
        assert np.isfinite(last)
        assert last < first * 0.7, (first, last)

    def test_predict_shape(self):
        from deeplearning4j_tpu.models.bert import BertConfig, BertForMaskedLM
        config = BertConfig.tiny(vocab_size=50)
        model = BertForMaskedLM(config)
        logits = model.predict_mlm(np.zeros((2, 8), dtype=np.int32))
        assert logits.shape == (2, 8, 50)


class TestTfBertImporter:
    """Importer tests (VERDICT weak #3): export↔import round-trip and a
    golden layer-0 activation fixture from a synthesized checkpoint."""

    def _synth_checkpoint(self, seed=0):
        """Deterministic fake google-research-style checkpoint dict."""
        from deeplearning4j_tpu.models.bert import BertConfig, init_params
        from deeplearning4j_tpu.importers.tf_bert import export_variables
        import jax
        config = BertConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, intermediate_size=64, max_position=48,
                            type_vocab_size=2)
        params = init_params(config, jax.random.key(seed))
        return config, export_variables(
            jax.tree_util.tree_map(lambda a: np.asarray(a, np.float32), params),
            config)

    def test_round_trip_exact(self):
        from deeplearning4j_tpu.importers.tf_bert import map_variables, export_variables
        config, variables = self._synth_checkpoint()
        got_config, params = map_variables(variables)
        assert got_config.num_layers == config.num_layers
        assert got_config.hidden_size == config.hidden_size
        assert got_config.vocab_size == config.vocab_size
        back = export_variables(params, got_config)
        assert set(back) == set(variables)
        for name in variables:
            np.testing.assert_array_equal(back[name], variables[name], err_msg=name)

    def test_npz_load_path(self, tmp_path):
        from deeplearning4j_tpu.importers.tf_bert import load_npz
        _, variables = self._synth_checkpoint()
        p = tmp_path / "ckpt.npz"
        np.savez(p, **{k.replace("/", "__slash__"): v for k, v in variables.items()})
        config, params = load_npz(str(p))
        np.testing.assert_array_equal(
            params["embeddings"]["word_embeddings"],
            variables["bert/embeddings/word_embeddings"])

    def test_missing_variable_raises_keyerror(self):
        from deeplearning4j_tpu.importers.tf_bert import map_variables
        _, variables = self._synth_checkpoint()
        del variables["bert/encoder/layer_1/intermediate/dense/kernel"]
        with pytest.raises(KeyError):
            map_variables(variables)

    def test_golden_layer0_activations(self):
        """Imported params drive encode() to fixture-recorded activations
        (SURVEY §7.9 'BERT-base layer-0 activations vs recorded fixtures',
        scoped to the synthesized deterministic checkpoint).

        The fixture pins values downstream of ``jax.random.key`` param
        init, whose bit patterns are implementation-defined ACROSS jax
        releases — a jax upgrade that changes them requires deleting the
        fixture and re-recording (two runs of this test), not a
        tolerance bump (the drift is total, not numeric)."""
        import pathlib
        from deeplearning4j_tpu.importers.tf_bert import map_variables
        from deeplearning4j_tpu.models.bert import encode

        config, variables = self._synth_checkpoint(seed=3)
        got_config, params = map_variables(variables)
        one_layer = dict(params)
        one_layer["encoder"] = {"layer_0": params["encoder"]["layer_0"]}
        import dataclasses
        cfg0 = dataclasses.replace(got_config, num_layers=1)
        ids = np.arange(2 * 12, dtype=np.int32).reshape(2, 12) % cfg0.vocab_size
        out = np.asarray(encode(one_layer, cfg0, ids), dtype=np.float32)

        fixture = pathlib.Path(__file__).parent / "fixtures" / "bert_layer0_golden.npz"
        if not fixture.exists():  # first run records; committed thereafter
            fixture.parent.mkdir(exist_ok=True)
            np.savez(fixture, out=out)
            pytest.skip("golden fixture recorded; rerun to verify")
        golden = np.load(fixture)["out"]
        np.testing.assert_allclose(out, golden, rtol=2e-4, atol=2e-5)

    def test_finetune_after_import(self):
        """Train-after-import golden (VERDICT r4 weak #7): one SGD step
        through imported TF-checkpoint weights reduces the MLM loss and
        every gradient is finite."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.importers.tf_bert import map_variables
        from deeplearning4j_tpu.models.bert import mlm_loss

        _, variables = self._synth_checkpoint(seed=5)
        config, params = map_variables(variables)
        rng = np.random.default_rng(5)
        ids = rng.integers(0, config.vocab_size, (2, 12)).astype(np.int32)
        labels = rng.integers(0, config.vocab_size, (2, 12)).astype(np.int32)
        weights = (rng.random((2, 12)) < 0.3).astype(np.float32)

        def loss_fn(p):
            return mlm_loss(p, config, ids, labels, weights, train=False)

        loss0, grads = jax.value_and_grad(loss_fn)(params)
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
        assert any(float(jnp.abs(g).max()) > 0 for g in leaves)
        new_params = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g,
                                            params, grads)
        assert float(loss_fn(new_params)) < float(loss0)
