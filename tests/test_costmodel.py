"""Roofline cost model: cost_analysis extraction, peak table, per-step
MFU/HBM gauges through a real Trainer fit, and the bench stamp."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.obs import costmodel
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)


@pytest.fixture(autouse=True)
def _fresh_state():
    prev = set_registry(MetricsRegistry())
    costmodel.clear()
    yield
    costmodel.clear()
    set_registry(prev)


def _small_net(seed=3):
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(64)).build())
    return MultiLayerNetwork(conf).init()


class TestBackendPeaks:
    def test_cpu_fallback_is_estimated_and_positive(self):
        peaks = costmodel.backend_peaks()
        assert peaks.peak_flops > 0
        assert peaks.peak_bytes_per_s > 0
        assert peaks.estimated            # CPU has no real peak table row
        assert peaks.ridge_intensity > 0
        # the assumed peaks are visible on the scrape surface
        assert get_registry().gauge("tpudl_perf_peak_flops").value \
            == peaks.peak_flops

    def test_env_overrides_win(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PEAK_TFLOPS", "130")
        monkeypatch.setenv("DL4J_TPU_PEAK_HBM_GBPS", "819")
        peaks = costmodel.backend_peaks()
        assert peaks.peak_flops == 130e12
        assert peaks.peak_bytes_per_s == 819e9
        assert not peaks.estimated        # measured ceiling supplied

    def test_single_env_override_keeps_estimated(self, monkeypatch):
        """One override must not launder the OTHER, still-synthetic
        peak into a 'measured' stamp."""
        monkeypatch.setenv("DL4J_TPU_PEAK_TFLOPS", "1.5")
        monkeypatch.delenv("DL4J_TPU_PEAK_HBM_GBPS", raising=False)
        peaks = costmodel.backend_peaks()
        assert peaks.peak_flops == 1.5e12
        assert peaks.estimated            # bandwidth is still synthetic


class TestAnalyze:
    def test_jitted_matmul_costs_and_roofline(self):
        @jax.jit
        def mm(a, b):
            return jnp.dot(a, b)

        a = jnp.ones((64, 128), jnp.float32)
        b = jnp.ones((128, 32), jnp.float32)
        mm(a, b).block_until_ready()
        cost = costmodel.analyze_jitted(mm, costmodel.abstractify((a, b)),
                                        kind="test:mm")
        assert cost is not None
        # dot(64x128, 128x32) = 2*64*128*32 FLOPs
        assert cost.flops == pytest.approx(2 * 64 * 128 * 32)
        assert cost.bytes_accessed >= (64 * 128 + 128 * 32 + 64 * 32) * 4
        assert cost.arith_intensity > 0
        assert cost.bound in ("compute", "memory")
        assert cost.roofline_flops <= cost.peaks.peak_flops
        # idempotent: second sight is a cache hit, not a re-analysis
        assert not costmodel.should_analyze(mm)
        assert costmodel.costs_for(mm) is cost

    def test_abstractify_passes_none_and_keys(self):
        key = jax.random.key(0)
        out = costmodel.abstractify((jnp.ones((2, 3)), None, key))
        assert out[0].shape == (2, 3)
        assert out[1] is None
        assert out[2].shape == key.shape

    def test_analysis_failure_is_silent_and_cached(self):
        def not_jitted(x):
            return x

        assert costmodel.analyze_jitted(not_jitted, ((),), kind="x") is None
        assert not costmodel.should_analyze(not_jitted)   # failure cached

    def test_recycled_id_does_not_inherit_cost_entry(self):
        """CPython recycles ids once an object dies: an id-keyed entry
        whose weakref resolves to a DIFFERENT object must read as absent
        (and be evicted), never as the dead program's cost."""
        @jax.jit
        def f(x):
            return x + 1

        x = jnp.ones(3)
        f(x).block_until_ready()
        cost = costmodel.analyze_jitted(f, costmodel.abstractify((x,)),
                                        kind="test:f")
        assert cost is not None

        def imposter(x):
            return x

        with costmodel._LOCK:
            costmodel._COSTS[(id(imposter), None)] = \
                (costmodel._mkref(f), cost)
            costmodel._KINDS[id(imposter)] = (costmodel._mkref(f), "test:f")
            costmodel._FAILED[(id(imposter), None)] = \
                (costmodel._mkref(f), True)
        assert costmodel.costs_for(imposter) is None
        assert costmodel.program_kind(imposter) is None
        assert costmodel.should_analyze(imposter)   # FAILED entry stale too
        # the live fn's entries are untouched
        assert costmodel.costs_for(f) is cost

    def test_top_programs_purges_dead_entries(self):
        """A retired program (weakref dead) must be purged by
        top_programs, which still returns the live breakdown — the
        bench/dump cost breakdown must not vanish the moment any
        analyzed fn is garbage-collected."""
        import gc
        import weakref

        @jax.jit
        def live(x):
            return x * 3.0

        x = jnp.ones((4, 4))
        live(x).block_until_ready()
        cost = costmodel.analyze_jitted(live, costmodel.abstractify((x,)),
                                        kind="test:live")
        assert cost is not None

        class _Retired:
            pass

        obj = _Retired()
        dead_ref = weakref.ref(obj)
        del obj
        gc.collect()
        assert dead_ref() is None
        with costmodel._LOCK:
            costmodel._COSTS[(999999999, None)] = (dead_ref, cost)
        top = costmodel.top_programs(5)
        assert any(t["kind"] == "test:live" for t in top)
        with costmodel._LOCK:
            assert (999999999, None) not in costmodel._COSTS

    def test_per_signature_cost_entries(self):
        """One jit fn holds one compiled program PER call signature
        (serving buckets): bucket-16's wall time must be attributed
        bucket-16's FLOPs, never the first-analyzed bucket's."""
        @jax.jit
        def mm(a, b):
            return jnp.dot(a, b)

        b = jnp.ones((64, 32), jnp.float32)
        a8 = jnp.ones((8, 64), jnp.float32)
        a16 = jnp.ones((16, 64), jnp.float32)
        mm(a8, b).block_until_ready()
        mm(a16, b).block_until_ready()
        c8 = costmodel.analyze_jitted(mm, costmodel.abstractify((a8, b)),
                                      kind="test:mm", sig=8)
        assert c8 is not None
        assert costmodel.should_analyze(mm, sig=16)   # distinct program
        c16 = costmodel.analyze_jitted(mm, costmodel.abstractify((a16, b)),
                                       kind="test:mm", sig=16)
        assert c16.flops == pytest.approx(2 * c8.flops)
        assert costmodel.costs_for(mm, sig=8) is c8
        assert costmodel.costs_for(mm, sig=16) is c16
        costmodel.observe_step(mm, 0.01, sig=16)
        assert costmodel.last_observation()["cost"] is c16

    def test_schedule_analysis_runs_in_background(self):
        @jax.jit
        def f(x):
            return x * 2.0

        x = jnp.ones((16, 16))
        f(x).block_until_ready()
        costmodel.schedule_analysis(f, costmodel.abstractify((x,)),
                                    kind="test:bg")
        assert costmodel.drain(30.0)
        assert costmodel.costs_for(f) is not None
        assert not costmodel.should_analyze(f)
        # idempotent while analyzed
        costmodel.schedule_analysis(f, costmodel.abstractify((x,)),
                                    kind="test:bg")
        assert costmodel.drain(30.0)

    def test_disabled_by_config(self):
        from deeplearning4j_tpu.config import set_config
        set_config(costmodel=False)
        try:
            @jax.jit
            def f(x):
                return x * 2

            assert not costmodel.should_analyze(f)
            assert costmodel.analyze_jitted(
                f, costmodel.abstractify((jnp.ones(4),))) is None
        finally:
            set_config(costmodel=True)


class TestTrainerIntegration:
    def test_fit_publishes_mfu_and_program_series(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train import Trainer
        net = _small_net()
        trainer = Trainer(net)
        rng = np.random.default_rng(0)
        ds = DataSet(rng.normal(size=(16, 64)).astype(np.float32),
                     np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)])
        key = jax.random.key(0)
        trainer.step_batch(ds, key)        # compile + schedule analysis
        assert costmodel.drain(60.0)       # background analysis lands
        for _ in range(2):
            trainer.step_batch(ds, key)    # steady-state: observed
        reg = get_registry()
        assert reg.gauge("tpudl_perf_mfu").value > 0
        assert reg.gauge("tpudl_perf_hbm_util").value > 0
        assert reg.gauge("tpudl_perf_arith_intensity").value > 0
        assert 0 < reg.gauge("tpudl_perf_roofline_fraction").value <= 1.0
        # the program series carries the step-cache kind tag
        flops = reg.labeled_gauge("tpudl_perf_program_flops",
                                  label_names=("program",))
        assert flops.labeled_value(program="train:MultiLayerNetwork") > 0
        hist = reg.labeled_histogram("tpudl_perf_step_seconds")
        # the 2 post-analysis steps observed (compile step excluded)
        assert hist.labeled_count(program="train:MultiLayerNetwork") == 2
        top = costmodel.top_programs(5)
        assert top and top[0]["kind"] == "train:MultiLayerNetwork"
        assert top[0]["flops"] > 0

    def test_bench_detail_stamp_shape(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train import Trainer
        net = _small_net(seed=5)
        trainer = Trainer(net)
        rng = np.random.default_rng(1)
        ds = DataSet(rng.normal(size=(8, 64)).astype(np.float32),
                     np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)])
        key = jax.random.key(1)
        trainer.step_batch(ds, key)        # compile + schedule analysis
        assert costmodel.drain(60.0)
        trainer.step_batch(ds, key)        # observed against the cost
        stamp = costmodel.bench_detail()
        assert stamp is not None
        for field in ("mfu", "hbm_util", "arith_intensity",
                      "flops_per_step", "bytes_per_step", "program",
                      "backend", "roofline_bound"):
            assert stamp.get(field) is not None, field
        assert stamp["source"] == "xla_cost_analysis"
        assert stamp["mfu"] > 0


class TestServeIntegration:
    def test_engine_dispatch_observes_forward_cost(self):
        from deeplearning4j_tpu.serve import InferenceEngine
        net = _small_net(seed=7)
        engine = InferenceEngine(net, name="cm", max_batch=8,
                                 max_latency_ms=1.0, buckets=(8,))
        try:
            x = np.random.default_rng(0).normal(size=(4, 64)) \
                .astype(np.float32)
            engine.predict(x, timeout_s=60)   # compile + schedule analysis
            assert costmodel.drain(60.0)
            engine.predict(x, timeout_s=60)   # steady-state: observed
        finally:
            engine.shutdown()
        reg = get_registry()
        flops = reg.labeled_gauge("tpudl_perf_program_flops",
                                  label_names=("program",))
        assert flops.labeled_value(
            program="serve_forward:MultiLayerNetwork") > 0
        assert reg.gauge("tpudl_perf_mfu").value > 0


class TestFusedCheckFinite:
    """The NAN/INF panic scan batches every leaf into ONE fused device
    reduction (one host sync), and only walks per-leaf after a hit."""

    @pytest.fixture(autouse=True)
    def _panic(self):
        from deeplearning4j_tpu.config import set_config
        set_config(nan_panic=True, inf_panic=True)
        yield
        set_config(nan_panic=False, inf_panic=False)

    def test_clean_tree_passes(self):
        from deeplearning4j_tpu.obs.profiler import check_finite
        tree = {"a": jnp.ones((4, 4)), "b": [jnp.zeros(3),
                                             jnp.asarray([1, 2])]}
        check_finite(tree, "params")        # int leaves skipped, no raise

    def test_nan_is_found_and_anchored(self):
        from deeplearning4j_tpu.obs.profiler import (NonFiniteError,
                                                     check_finite)
        tree = {"ok": jnp.ones(3),
                "bad": jnp.asarray([1.0, float("nan"), 2.0])}
        with pytest.raises(NonFiniteError, match="NaN.*bad"):
            check_finite(tree, "params")

    def test_inf_is_found(self):
        from deeplearning4j_tpu.obs.profiler import (NonFiniteError,
                                                     check_finite)
        with pytest.raises(NonFiniteError, match="Inf"):
            check_finite([jnp.asarray([float("inf")])], "grads")

    def test_one_fused_program_per_structure(self):
        """Re-checking the same tree structure reuses ONE compiled
        reduction — not a jnp.any dispatch per leaf per call."""
        from deeplearning4j_tpu.obs.profiler import _finite_flags, check_finite
        from deeplearning4j_tpu.train.step_cache import jit_cache_entries
        tree = [jnp.ones((8, 8)) * i for i in range(6)]
        check_finite(tree, "params")
        before = jit_cache_entries(_finite_flags)
        for _ in range(5):
            check_finite(tree, "params")
        assert jit_cache_entries(_finite_flags) == before
