"""Metrics registry tests: metric semantics, the tpudl_<area>_<name>
convention, Prometheus text rendering, the /metrics endpoint, and the
``obs.selfcheck`` metric lint (plus its deprecated ``obs.check``
shim entry point)."""

import json
import math
import subprocess
import sys
import urllib.request

import pytest

from deeplearning4j_tpu.obs import registry as reg_mod
from deeplearning4j_tpu.obs.registry import (
    METRIC_NAME_RE, Counter, Gauge, Histogram, MetricsRegistry,
    install_standard_metrics)


@pytest.fixture
def registry():
    return MetricsRegistry()


def test_counter_gauge_semantics(registry):
    c = registry.counter("tpudl_test_things_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = registry.gauge("tpudl_test_level")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3


def test_histogram_buckets_cumulative(registry):
    h = registry.histogram("tpudl_test_latency_seconds",
                           buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    counts = h.bucket_counts()
    assert counts[0.01] == 1
    assert counts[0.1] == 3          # cumulative
    assert counts[1.0] == 4
    assert counts[math.inf] == 5
    assert h.count == 5
    assert abs(h.sum - 5.605) < 1e-9


def test_name_convention_enforced(registry):
    for bad in ("train_steps_total", "tpudl_steps", "tpudl_Train_x",
                "tpudl_train_", "notaprefix_train_steps_total"):
        with pytest.raises(ValueError):
            registry.counter(bad)
    assert METRIC_NAME_RE.match("tpudl_train_steps_total")


def test_reregistration_idempotent_but_type_safe(registry):
    a = registry.counter("tpudl_test_things_total")
    b = registry.counter("tpudl_test_things_total")
    assert a is b
    with pytest.raises(ValueError):
        registry.gauge("tpudl_test_things_total")


def test_prometheus_text_format(registry):
    c = registry.counter("tpudl_test_things_total", "things\nprocessed")
    c.inc(7)
    h = registry.histogram("tpudl_test_latency_seconds", "latency",
                           buckets=(0.5,))
    h.observe(0.25)
    text = registry.render_prometheus()
    lines = text.splitlines()
    assert "# HELP tpudl_test_latency_seconds latency" in lines
    assert "# TYPE tpudl_test_latency_seconds histogram" in lines
    assert "# TYPE tpudl_test_things_total counter" in lines
    # newlines in help are escaped per the exposition format
    assert "# HELP tpudl_test_things_total things\\nprocessed" in lines
    assert "tpudl_test_things_total 7" in lines
    assert 'tpudl_test_latency_seconds_bucket{le="0.5"} 1' in lines
    assert 'tpudl_test_latency_seconds_bucket{le="+Inf"} 1' in lines
    assert "tpudl_test_latency_seconds_sum 0.25" in lines
    assert "tpudl_test_latency_seconds_count 1" in lines
    assert text.endswith("\n")


def test_labeled_counter_and_gauge_semantics(registry):
    c = registry.labeled_counter("tpudl_test_requests_total", "requests",
                                 ("status",))
    c.inc(status="ok")
    c.inc(2, status="ok")
    c.inc(status="shed")
    assert c.labeled_value(status="ok") == 3
    assert c.labeled_value(status="shed") == 1
    assert c.value == 4                      # total across labels
    with pytest.raises(ValueError):
        c.inc(-1, status="ok")
    with pytest.raises(ValueError):
        c.inc(status="ok", bogus="x")        # undeclared label name
    with pytest.raises(ValueError):
        c.inc()                              # missing declared label
    g = registry.labeled_gauge("tpudl_test_version", "per-model version",
                               ("model",))
    g.set(3, model="a")
    g.set(7, model="b")
    assert g.labeled_value(model="a") == 3
    assert g.labeled_value(model="b") == 7
    # idempotent re-registration; label mismatch is a hard error
    assert registry.labeled_counter("tpudl_test_requests_total") is c
    with pytest.raises(ValueError):
        registry.labeled_counter("tpudl_test_requests_total",
                                 label_names=("other",))


def test_labeled_metrics_prometheus_render(registry):
    c = registry.labeled_counter("tpudl_test_requests_total", "reqs",
                                 ("status",))
    c.inc(5, status="ok")
    c.inc(status='we"ird\nvalue')
    text = registry.render_prometheus()
    lines = text.splitlines()
    assert "# TYPE tpudl_test_requests_total counter" in lines
    assert 'tpudl_test_requests_total{status="ok"} 5' in lines
    # label values escaped per the exposition format
    assert 'tpudl_test_requests_total{status="we\\"ird\\nvalue"} 1' in lines


def test_labeled_histogram_semantics(registry):
    h = registry.labeled_histogram("tpudl_test_prog_seconds", "per-program",
                                   buckets=(0.01, 0.1, 1.0),
                                   label_names=("program",))
    for v in (0.005, 0.05, 0.5):
        h.observe(v, program="train")
    h.observe(5.0, program="serve")
    assert h.labeled_count(program="train") == 3
    assert h.labeled_count(program="serve") == 1
    assert h.count == 4                       # aggregate across children
    assert abs(h.sum - 5.555) < 1e-9
    counts = h.bucket_counts(program="train")
    assert counts[0.01] == 1
    assert counts[0.1] == 2                   # cumulative
    assert counts[math.inf] == 3
    assert h.bucket_counts(program="serve")[1.0] == 0
    with pytest.raises(ValueError):
        h.observe(1.0)                        # missing declared label
    with pytest.raises(ValueError):
        h.observe(1.0, program="x", extra="y")
    # idempotent re-registration; bucket/label mismatches are hard errors
    assert registry.labeled_histogram("tpudl_test_prog_seconds",
                                      buckets=(0.01, 0.1, 1.0)) is h
    with pytest.raises(ValueError):
        registry.labeled_histogram("tpudl_test_prog_seconds",
                                   buckets=(0.5,))


def test_labeled_histogram_prometheus_render(registry):
    h = registry.labeled_histogram("tpudl_test_prog_seconds", "per-program",
                                   buckets=(0.5,), label_names=("program",))
    h.observe(0.25, program="train")
    h.observe(2.0, program="train")
    text = registry.render_prometheus()
    lines = text.splitlines()
    assert "# TYPE tpudl_test_prog_seconds histogram" in lines
    assert ('tpudl_test_prog_seconds_bucket{program="train",le="0.5"} 1'
            in lines)
    assert ('tpudl_test_prog_seconds_bucket{program="train",le="+Inf"} 2'
            in lines)
    assert 'tpudl_test_prog_seconds_sum{program="train"} 2.25' in lines
    assert 'tpudl_test_prog_seconds_count{program="train"} 2' in lines


def test_perf_family_installed_and_exposed(registry):
    """The tpudl_perf_* roofline family is part of the standard catalog
    and renders as one valid exposition (gauges + the labeled series)."""
    installed = install_standard_metrics(registry)
    for name in ("tpudl_perf_mfu", "tpudl_perf_hbm_util",
                 "tpudl_perf_arith_intensity",
                 "tpudl_perf_roofline_fraction", "tpudl_perf_peak_flops",
                 "tpudl_perf_peak_hbm_bytes", "tpudl_perf_program_flops",
                 "tpudl_perf_program_bytes", "tpudl_perf_step_seconds"):
        assert name in installed, name
    registry.gauge("tpudl_perf_mfu").set(0.42)
    registry.labeled_gauge("tpudl_perf_program_flops",
                           label_names=("program",)).set(
        1e9, program="train:Net")
    registry.labeled_histogram("tpudl_perf_step_seconds").observe(
        0.01, program="train:Net")
    text = registry.render_prometheus()
    assert "tpudl_perf_mfu 0.42" in text
    assert 'tpudl_perf_program_flops{program="train:Net"} 1000000000' in text
    assert ('tpudl_perf_step_seconds_count{program="train:Net"} 1'
            in text)


def test_every_standard_metric_has_a_docs_row():
    """Anti-drift (the obs.check pattern, both directions): the metric
    catalog table in docs/observability.md and install_standard_metrics
    agree exactly — a new metric without a docs row (or a stale docs
    row) fails here, not in a dashboard.  One source of truth:
    selfcheck's own parity check."""
    from deeplearning4j_tpu.obs.selfcheck import check_metric_doc_parity
    problems: list = []
    check_metric_doc_parity(problems)
    assert problems == []


def test_standard_metrics_install_and_lint(registry):
    from deeplearning4j_tpu.obs.selfcheck import metric_lint
    installed = install_standard_metrics(registry)
    assert "tpudl_train_steps_total" in installed
    assert "tpudl_train_step_seconds" in installed
    assert metric_lint(registry) == []
    # a rogue counter without _total is flagged
    registry._metrics["tpudl_test_rogue"] = Counter("tpudl_test_rogue")
    assert any("_total" in p for p in metric_lint(registry))


def test_deprecated_check_entry_point_runs_clean():
    """Existing CI invocations of the folded-away ``obs.check`` module
    keep working (the one-line shim over selfcheck's metric lint)."""
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.obs.check"],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_metrics_endpoint_after_training(tmp_path):
    """Acceptance: GET /metrics returns Prometheus text including
    tpudl_train_steps_total and the step-latency histogram after a fit."""
    from deeplearning4j_tpu.data import datasets
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.obs import UIServer, get_registry
    from deeplearning4j_tpu.train import Adam

    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784)).build())
    net = MultiLayerNetwork(conf).init()
    it = datasets.mnist(batch_size=64, train=True, n_synthetic=128)
    before = get_registry().counter("tpudl_train_steps_total").value
    net.fit(it, epochs=1)
    assert get_registry().counter("tpudl_train_steps_total").value \
        == before + 2

    server = UIServer(port=0)
    try:
        with urllib.request.urlopen(server.url + "metrics", timeout=5) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
    finally:
        server.stop()
    assert "tpudl_train_steps_total" in body
    assert 'tpudl_train_step_seconds_bucket{le="+Inf"}' in body
    assert "tpudl_train_step_seconds_count" in body


def test_metrics_writer_feeds_registry(tmp_path):
    from deeplearning4j_tpu.obs import MetricsWriter, get_registry
    before = get_registry().counter("tpudl_obs_records_total").value
    with MetricsWriter(str(tmp_path / "m.jsonl")) as w:
        w.write({"event": "x"})
        w.write({"event": "y"})
    assert get_registry().counter("tpudl_obs_records_total").value \
        == before + 2
