"""StableHLO export tests (round-1 dead-code item: ``autodiff/export.py``
had zero callers).  SameDiff-FlatBuffers serialization parity: trace →
portable artifact → serialize → reload → identical execution.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.export import (
    trace, export_stablehlo, stablehlo_text, save_exported, load_exported,
    export_model_forward)
from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer, LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net():
    conf = (NeuralNetConfiguration.builder().seed(4).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


class TestExport:
    def test_trace_exposes_jaxpr(self):
        jaxpr = trace(lambda x: jnp.tanh(x) * 2.0, jnp.zeros((2, 3)))
        text = str(jaxpr)
        assert "tanh" in text and "mul" in text

    def test_stablehlo_text_inspectable(self):
        text = stablehlo_text(lambda x: jnp.dot(x, x.T), jnp.zeros((4, 2)))
        assert "stablehlo" in text and "dot" in text

    def test_export_serialize_reload_execute(self, tmp_path):
        def fn(x, w):
            return jax.nn.relu(x @ w)

        x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 4)), jnp.float32)
        w = jnp.asarray(np.random.default_rng(1).normal(size=(4, 2)), jnp.float32)
        exported = export_stablehlo(fn, x, w)
        path = str(tmp_path / "fn.stablehlo")
        save_exported(exported, path)
        loaded = load_exported(path)
        np.testing.assert_allclose(np.asarray(loaded.call(x, w)),
                                   np.asarray(fn(x, w)), rtol=1e-6)

    def test_export_model_forward_round_trip(self, tmp_path):
        """The .sdz-for-serving analog: the exported artifact reproduces
        net.output exactly after reload."""
        net = _net()
        path = str(tmp_path / "model.stablehlo")
        export_model_forward(net, batch_size=4, path=path)
        loaded = load_exported(path)
        x = np.random.default_rng(2).normal(size=(4, 5)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(loaded.call(jnp.asarray(x))),
                                   np.asarray(net.output(x)), rtol=1e-5)

    def test_export_recurrent_model(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(LSTM(n_out=6))
                .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(3, 7)).build())
        net = MultiLayerNetwork(conf).init()
        exported = export_model_forward(net, batch_size=2)
        x = np.random.default_rng(3).normal(size=(2, 7, 3)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(exported.call(jnp.asarray(x))),
                                   np.asarray(net.output(x)), rtol=1e-5)
