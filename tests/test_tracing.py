"""Span tracing tests: nesting, exports, cross-process context
propagation, and the trainer's fit/epoch/step emission (all CPU)."""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.config import set_config
from deeplearning4j_tpu.obs import tracing


@pytest.fixture
def tracer():
    t = tracing.Tracer(enabled=True)
    with tracing.use_tracer(t):
        yield t


def test_span_nesting_and_attributes(tracer):
    with tracing.span("fit", model="test"):
        with tracing.span("epoch", epoch=0):
            with tracing.span("step", iteration=3) as s:
                s.set_attribute("score", 1.25)
    spans = {s.name: s for s in tracer.spans}
    assert set(spans) == {"fit", "epoch", "step"}
    assert spans["step"].parent_id == spans["epoch"].span_id
    assert spans["epoch"].parent_id == spans["fit"].span_id
    assert spans["fit"].parent_id is None
    # one trace, durations contain each other
    assert len({s.trace_id for s in tracer.spans}) == 1
    assert spans["fit"].duration_s >= spans["epoch"].duration_s \
        >= spans["step"].duration_s >= 0
    assert spans["step"].attributes == {"iteration": 3, "score": 1.25}


def test_disabled_tracing_is_noop():
    t = tracing.Tracer(enabled=False)
    with tracing.use_tracer(t):
        with tracing.span("fit") as s:
            assert s is tracing.NULL_SPAN
            s.set_attribute("x", 1)          # no-op surface
            assert tracing.current_span() is None
    assert t.spans == []


def test_sibling_spans_share_parent(tracer):
    with tracing.span("step"):
        with tracing.span("encode"):
            pass
        with tracing.span("exchange"):
            pass
    step = tracer.find("step")[0]
    assert tracer.find("encode")[0].parent_id == step.span_id
    assert tracer.find("exchange")[0].parent_id == step.span_id


def test_explicit_parent_for_thread_hops(tracer):
    # a worker thread has no ambient context — the parent rides explicitly
    with tracing.span("step") as sp:
        ctx = sp.context()
    with tracing.span("slice", parent=ctx) as child:
        pass
    assert child.parent_id == ctx.span_id
    assert child.trace_id == ctx.trace_id


def test_chrome_trace_export_is_valid(tracer, tmp_path):
    with tracing.span("fit"):
        with tracing.span("step", iteration=0):
            pass
    path = tracer.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert len(events) == 2
    for ev in events:
        assert ev["ph"] == "X" and ev["cat"] == "tpudl"
        assert isinstance(ev["ts"], float) and isinstance(ev["dur"], float)
        assert ev["dur"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert "span_id" in ev["args"]
    by_name = {ev["name"]: ev for ev in events}
    # child event temporally contained in the parent event
    fit, step = by_name["fit"], by_name["step"]
    assert fit["ts"] <= step["ts"]
    assert fit["ts"] + fit["dur"] >= step["ts"] + step["dur"] - 1e-3
    assert step["args"]["parent_id"] == fit["args"]["span_id"]


def test_jsonl_export(tracer, tmp_path):
    with tracing.span("fit", k="v"):
        pass
    path = tracer.export_jsonl(str(tmp_path / "spans.jsonl"))
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 1
    rec = lines[0]
    assert rec["name"] == "fit" and rec["attributes"] == {"k": "v"}
    assert rec["duration_s"] >= 0 and rec["parent_id"] is None


def test_jsonl_export_is_incremental(tracer, tmp_path):
    """Periodic flushing must not duplicate spans (per-path high-water)."""
    path = str(tmp_path / "spans.jsonl")
    with tracing.span("a"):
        pass
    tracer.export_jsonl(path)
    tracer.export_jsonl(path)                 # nothing new → no dupes
    with tracing.span("b"):
        pass
    tracer.export_jsonl(path)
    names = [json.loads(l)["name"] for l in open(path)]
    assert names == ["a", "b"]


def test_context_inject_extract_roundtrip(tracer):
    assert tracing.inject() is None          # no active span
    with tracing.span("parent") as p:
        raw = tracing.inject()
    ctx = tracing.extract(raw)
    assert ctx.trace_id == p.trace_id and ctx.span_id == p.span_id
    assert tracing.extract(None) is None
    assert tracing.extract("not json{") is None


def test_cross_process_context_via_env(tracer, monkeypatch):
    """The launcher hands DL4J_TPU_TRACE_CONTEXT to workers; a fresh
    Tracer in the child process parents its root spans under the
    launcher's span — simulated here by re-reading the env."""
    with tracing.span("launcher") as p:
        env = tracing.propagation_env()
    assert env["DL4J_TPU_TRACING"] == "1"
    monkeypatch.setenv(tracing.TRACE_CONTEXT_ENV,
                       env[tracing.TRACE_CONTEXT_ENV])
    child = tracing.Tracer(enabled=True)     # what the worker builds
    with tracing.use_tracer(child):
        with tracing.span("worker_root") as w:
            pass
    assert w.trace_id == p.trace_id
    assert w.parent_id == p.span_id
    # malformed env never breaks a worker
    monkeypatch.setenv(tracing.TRACE_CONTEXT_ENV, "}{garbage")
    assert tracing.Tracer(enabled=True)._remote_parent is None


def test_device_sync_attribution(tracer):
    import jax.numpy as jnp
    with tracing.span("step") as s:
        out = tracing.device_sync(jnp.ones((8,)) * 2)
    assert float(out[0]) == 2.0
    assert s.device_sync_s >= 0


def test_multilayer_fit_emits_step_spans():
    """Smoke: MultiLayerNetwork.fit under tracing produces nested
    fit → epoch → step spans with model attrs (acceptance criterion)."""
    from deeplearning4j_tpu.data import datasets
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Adam

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784)).build())
    net = MultiLayerNetwork(conf).init()
    it = datasets.mnist(batch_size=64, train=True, n_synthetic=192)

    t = tracing.Tracer(enabled=True)
    with tracing.use_tracer(t):
        net.fit(it, epochs=2)

    fits = t.find("fit")
    epochs = t.find("epoch")
    steps = t.find("step")
    assert len(fits) == 1 and len(epochs) == 2
    assert len(steps) == 6                    # 192/64 batches × 2 epochs
    assert all(e.parent_id == fits[0].span_id for e in epochs)
    epoch_ids = {e.span_id for e in epochs}
    assert all(s.parent_id in epoch_ids for s in steps)
    assert fits[0].attributes["model"] == "MultiLayerNetwork"
    assert fits[0].attributes["params"] == net.num_params()
    assert steps[0].attributes.get("compile") is True
    assert all("score" in s.attributes for s in steps)
    # tracing path syncs the loss → scores are real floats
    assert all(np.isfinite(s.attributes["score"]) for s in steps[1:])
