"""Compiled-artifact store (ISSUE 12): cross-process warm restarts with
zero JIT on the request path, stale-artifact refusal, manifest-backed
corruption refusal, and the pre-bake deploy path.

Restart coverage uses REAL subprocesses: an in-process "simulated
restart" (clear caches, re-warm the same programs) would both lie about
what a restart pays and tread on the one sequence the pool's
first-wins insert exists to prevent (destroying a live executable and
then running its deserialized twin)."""

import json
import os
import subprocess
import sys
import zipfile

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_net(width, seed=7):
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(width)).build())
    return MultiLayerNetwork(conf).init()


def _run_child(code, *argv, timeout=240):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "DL4J_TPU_COSTMODEL": "0",
           "PYTHONPATH": REPO_ROOT + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    for attempt in range(3):
        proc = subprocess.run([sys.executable, "-c", code, *argv],
                              capture_output=True, text=True, timeout=timeout,
                              cwd=REPO_ROOT, env=env)
        if proc.returncode in (-11, -6) and attempt < 2:
            # XLA:CPU intermittently corrupts its heap running/destroying
            # DESERIALIZED executables (the crash class the pool's
            # first-wins insert documents; reproduces on the pristine
            # pre-ISSUE-14 tree, machine-dependent).  A segfaulted child
            # proved nothing either way — rerun it; every warm-restart
            # assertion still gates on a run that completed.
            continue
        break
    assert proc.returncode == 0, \
        f"child failed rc={proc.returncode}\n{proc.stderr[-3000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


# --------------------------------------------------------------- bake side
def test_bake_embeds_versioned_indexed_artifacts(tmp_path):
    """Baking writes artifacts/ entries + an index whose every record
    carries the full refusal key (format/jax/backend/donation), and the
    zip stays manifest-intact — artifacts are inside the PR-4
    durability story, not beside it."""
    import jax

    from deeplearning4j_tpu.resilience.checkpoint import is_valid_checkpoint
    from deeplearning4j_tpu.train import artifact_store

    net = _build_net(width=24)
    zp = str(tmp_path / "model.zip")
    net.save(zp)
    assert artifact_store.read_index(zp) == []
    baked = artifact_store.ensure_zip_artifacts(zp, net=net,
                                                buckets=(1, 2, 4))
    assert baked == 3
    index = artifact_store.read_index(zp)
    assert len(index) == 3
    for entry in index:
        assert entry["kind"] == "serve_forward"
        assert entry["format"] == artifact_store.ARTIFACT_FORMAT
        assert entry["jax"] == jax.__version__
        assert entry["backend"] == jax.default_backend()
        assert entry["donation"] == ""
        assert entry["key"][-1] == "serve_forward"
    # the manifest covers the new entries: still a verified checkpoint
    assert is_valid_checkpoint(zp)
    # the portable StableHLO module rides along (SameDiff → StableHLO)
    with zipfile.ZipFile(zp) as zf:
        mlir = [n for n in zf.namelist() if n.endswith(".stablehlo.mlir")]
        assert len(mlir) == 3
        assert b"stablehlo" in zf.read(mlir[0]) or b"module" in zf.read(mlir[0])
    # idempotent: everything already baked for this env
    assert artifact_store.ensure_zip_artifacts(zp, net=net,
                                               buckets=(1, 2, 4)) == 0


# ------------------------------------------------- cross-process warm serve
_CHILD_SERVE = r"""
import json, os, sys
os.environ["DL4J_TPU_COSTMODEL"] = "0"
import numpy as np
from deeplearning4j_tpu.serve.registry import ModelRegistry
from deeplearning4j_tpu.obs.registry import get_registry
zp, buckets, width = sys.argv[1], json.loads(sys.argv[2]), int(sys.argv[3])
reg = ModelRegistry(max_batch=max(buckets), buckets=tuple(buckets))
eng = reg.deploy("m", zp).engine
rng = np.random.default_rng(0)
for b in buckets:
    out = reg.predict("m", rng.normal(size=(b, width)).astype(np.float32),
                      timeout_s=60)
    assert out.shape[0] == b
first = {"compiled_programs": eng.compiled_programs,
         "warm_programs": eng.warm_programs}
# warmed hot-swap: same architecture, new version — the swap window
# must not compile either
mv2 = reg.deploy("m", zp)
for b in buckets:
    reg.predict("m", rng.normal(size=(b, width)).astype(np.float32),
                timeout_s=60)
r = get_registry()
print(json.dumps({
    "first": first, "swap_version": mv2.version,
    "swap_compiled": mv2.engine.compiled_programs,
    "serve_recompiles": r.counter("tpudl_serve_recompiles_total").value,
    "hits": r.counter("tpudl_compile_artifact_hits_total").value,
    "loaded": r.counter("tpudl_compile_artifacts_loaded_total").value,
    "rejects": r.counter("tpudl_compile_artifact_rejects_total").value}))
reg.close()
"""


def test_cross_process_warm_restart_serves_with_zero_jit(tmp_path):
    """The headline contract: a zip baked by THIS process is deployed by
    a fresh subprocess ("the restarted server") which serves every
    bucket — and hot-swaps once — with zero XLA traces on the request
    path, pinned by the engine's jit-cache count and the serve
    recompile counter."""
    from deeplearning4j_tpu.train import artifact_store

    width, buckets = 20, (1, 2, 4, 8)
    net = _build_net(width=width)
    zp = str(tmp_path / "model.zip")
    net.save(zp)
    assert artifact_store.ensure_zip_artifacts(zp, net=net,
                                               buckets=buckets) == 4
    result = _run_child(_CHILD_SERVE, zp, json.dumps(list(buckets)),
                        str(width))
    assert result["loaded"] == 4
    assert result["rejects"] == 0
    # zero JIT on the request path, across restart AND hot-swap
    assert result["first"]["compiled_programs"] == 0
    assert result["swap_compiled"] == 0
    assert result["serve_recompiles"] == 0
    assert result["swap_version"] == 2
    assert result["first"]["warm_programs"] == len(buckets)
    assert result["hits"] >= 2 * len(buckets)


# ------------------------------------------------- cross-process warm train
_CHILD_TRAIN = r"""
import json, os, sys
os.environ["DL4J_TPU_COSTMODEL"] = "0"
import numpy as np
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.obs.registry import get_registry
zp, width = sys.argv[1], int(sys.argv[2])
conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1)).list()
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(width)).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
X = rng.normal(size=(64, width)).astype(np.float32)
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
t = Trainer(net)
t.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=3, resume_from=zp)
r = get_registry()
print(json.dumps({
    "recompiles": r.counter("tpudl_train_recompiles_total").value,
    "hits": r.counter("tpudl_compile_artifact_hits_total").value,
    "iteration": net.iteration}))
"""


def test_trainer_resume_warms_train_step_zero_recompiles(tmp_path):
    """A respawned worker's whole fine-tune epoch runs on the
    deserialized train step: tpudl_train_recompiles_total stays at
    exactly zero across the resumed fit (the supervisor-MTTR 'no
    recompile the world' contract), pinned cross-process."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.train.trainer import Trainer

    width = 28
    net = _build_net(width=width)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, width)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    trainer = Trainer(net)
    trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
    # deploy/checkpoint-time bake: capture needs one completed step, so
    # arm the capture and take one more batch through fit_batch
    from deeplearning4j_tpu.config import set_config
    set_config(artifact_bake=True)
    try:
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        from deeplearning4j_tpu.train import artifact_store
        artifact_store.drain_bakes()
        assert trainer.net._artifact_index
        kinds = {ix["kind"] for ix in trainer.net._artifact_index}
        assert kinds == {"train", "eval"}
    finally:
        set_config(artifact_bake=False)
    zp = str(tmp_path / "ck.zip")
    net.save(zp)
    result = _run_child(_CHILD_TRAIN, zp, str(width))
    assert result["recompiles"] == 0
    assert result["hits"] >= 4          # 4 batches of the resumed epoch
    assert result["iteration"] == 12    # 3 epochs total, 4 steps each


_CHILD_TRAIN_DP2 = r"""
import json, os, sys
os.environ["DL4J_TPU_COSTMODEL"] = "0"
import numpy as np
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.obs.registry import get_registry
zp, width = sys.argv[1], int(sys.argv[2])
conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1)).list()
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(width)).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
X = rng.normal(size=(64, width)).astype(np.float32)
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
t = Trainer(net, layout="dp2")
t.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=3, resume_from=zp)
r = get_registry()
print(json.dumps({
    "recompiles": r.counter("tpudl_train_recompiles_total").value,
    "hits": r.counter("tpudl_compile_artifact_hits_total").value,
    "rejects": r.counter("tpudl_compile_artifact_rejects_total").value,
    "iteration": net.iteration}))
# skip interpreter teardown: destroying deserialized SPMD executables
# during the 8-virtual-device CPU client's shutdown segfaults
# intermittently (the XLA:CPU executable-destructor class the pool's
# first-wins insert exists for) — the contract is the line above
sys.stdout.flush()
os._exit(0)
"""


def test_sharded_trainer_resume_warm_zero_recompiles(tmp_path):
    """ISSUE-14 buffer-donation fix-up: the donated AND dp2-sharded
    train step warm-restarts cross-process from the artifact store —
    the layout signature rides the step-cache key into the index, the
    bake lowers against the live call's NamedShardings, and the
    resumed fine-tune's tpudl_train_recompiles_total stays exactly 0
    (4 warm-served batches, no rejects)."""
    from deeplearning4j_tpu.config import set_config
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.train import artifact_store
    from deeplearning4j_tpu.train.trainer import Trainer

    width = 36
    net = _build_net(width=width)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, width)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    trainer = Trainer(net, layout="dp2")
    trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
    set_config(artifact_bake=True)
    try:
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=16), epochs=1)
        artifact_store.drain_bakes()
        assert trainer.net._artifact_index
        # the baked entries carry the layout component in their key
        assert any("layout:dp2" in json.dumps(ix["key"])
                   for ix in trainer.net._artifact_index)
    finally:
        set_config(artifact_bake=False)
    zp = str(tmp_path / "ck.zip")
    net.save(zp)
    result = _run_child(_CHILD_TRAIN_DP2, zp, str(width))
    assert result["recompiles"] == 0
    assert result["rejects"] == 0
    assert result["hits"] >= 4
    assert result["iteration"] == 12


# -------------------------------------------------------- refusal paths
def _rewrite_index(zp, mutate):
    """Rewrite the artifact index through the durable writer (manifest
    stays consistent — this models a STALE artifact, not a torn one)."""
    from deeplearning4j_tpu.resilience.checkpoint import (
        MANIFEST_NAME, write_checkpoint_zip)
    from deeplearning4j_tpu.train import artifact_store
    entries = {}
    with zipfile.ZipFile(zp) as zf:
        for name in zf.namelist():
            if name != MANIFEST_NAME:
                entries[name] = zf.read(name)
    data = json.loads(entries[artifact_store.INDEX_ENTRY].decode())
    for ix in data["programs"]:
        mutate(ix)
    entries[artifact_store.INDEX_ENTRY] = json.dumps(data)
    write_checkpoint_zip(zp, entries)


@pytest.mark.parametrize("mutate,expect", [
    (lambda ix: ix.update(jax="0.0.0"), "jax-version"),
    (lambda ix: ix.update(backend="tpu"), "backend"),
    (lambda ix: ix.update(donation="9,9"), "donation"),
    (lambda ix: ix.update(kind="mystery_kind"), "unknown-kind"),
])
def test_stale_artifact_is_counted_reject_with_live_fallback(
        tmp_path, mutate, expect):
    """A cross-version/cross-backend/cross-donation artifact is refused
    and COUNTED (tpudl_compile_artifact_rejects_total), and the deploy
    falls back to live compilation — it never crashes and never trusts
    the stale executable."""
    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.serve.registry import ModelRegistry
    from deeplearning4j_tpu.train import artifact_store

    # distinct widths per param case → distinct step-cache keys, so no
    # case can be served by a sibling's still-resident programs; bake
    # WITHOUT warming the local pool (warm=False) so this process
    # really is the "restarted server holding only a stale zip"
    width = 30 + 2 * len(expect)
    net = _build_net(width=width)
    zp = str(tmp_path / "model.zip")
    net.save(zp)
    entries, index = artifact_store.bake_serve_artifacts(net, (2,),
                                                         warm=False)
    artifact_store.attach_to_zip(zp, entries, index)
    _rewrite_index(zp, mutate)
    reg = get_registry()
    rejects0 = reg.counter("tpudl_compile_artifact_rejects_total").value
    loaded0 = reg.counter("tpudl_compile_artifacts_loaded_total").value
    registry = ModelRegistry(max_batch=2, buckets=(2,))
    try:
        eng = registry.deploy("m", zp).engine
        out = registry.predict(
            "m", np.random.default_rng(0).normal(size=(2, width))
            .astype(np.float32), timeout_s=60)
        assert out.shape == (2, 4)
        assert reg.counter(
            "tpudl_compile_artifact_rejects_total").value == rejects0 + 1
        assert reg.counter(
            "tpudl_compile_artifacts_loaded_total").value == loaded0
        # ... and the request was served by a LIVE compile
        assert eng.compiled_programs == 1
        assert eng.warm_programs == 0
    finally:
        registry.close()


def test_corrupt_artifact_refused_through_manifest_verify(tmp_path):
    """Bit-rot inside an artifact entry (no index tampering) fails the
    PR-4 manifest verification, so the deploy refuses the WHOLE zip
    with CheckpointCorruptError before anything serves — the artifact
    payload is integrity-checked exactly like the weights."""
    from deeplearning4j_tpu.resilience.checkpoint import (
        CheckpointCorruptError, verify_checkpoint)
    from deeplearning4j_tpu.serve.registry import ModelRegistry
    from deeplearning4j_tpu.train import artifact_store

    net = _build_net(width=26)
    zp = str(tmp_path / "model.zip")
    net.save(zp)
    artifact_store.ensure_zip_artifacts(zp, net=net, buckets=(2,))
    exec_name = artifact_store.read_index(zp)[0]["exec"]
    # flip bytes INSIDE the exec entry, keeping the old manifest: a torn
    # copy / bit-rot model (zipfile rewrite keeps per-entry CRCs of the
    # new bytes, so only the manifest digest catches it — that is the
    # point of the manifest)
    corrupted = str(tmp_path / "corrupt.zip")
    with zipfile.ZipFile(zp) as src, \
            zipfile.ZipFile(corrupted, "w") as dst:
        for name in src.namelist():
            data = src.read(name)
            if name == exec_name:
                data = data[:64] + bytes(32) + data[96:]
            dst.writestr(name, data)
    problems = verify_checkpoint(corrupted)
    assert any(exec_name in p for p in problems)
    registry = ModelRegistry()
    with pytest.raises(CheckpointCorruptError):
        registry.deploy("m", corrupted)


def test_warm_miss_falls_back_to_live_compile_and_counts(tmp_path):
    """A bucket the store never baked live-compiles (counted as an
    artifact miss) while baked buckets keep serving warm — a partial
    store degrades to exactly the old behavior, per bucket."""
    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.serve.registry import ModelRegistry
    from deeplearning4j_tpu.train import artifact_store

    width = 22
    net = _build_net(width=width)
    zp = str(tmp_path / "model.zip")
    net.save(zp)
    artifact_store.ensure_zip_artifacts(zp, net=net, buckets=(2,))
    reg = get_registry()
    misses0 = reg.counter("tpudl_compile_artifact_misses_total").value
    registry = ModelRegistry(max_batch=4, buckets=(2, 4))
    try:
        eng = registry.deploy("m", zp).engine
        rng = np.random.default_rng(0)
        registry.predict("m", rng.normal(size=(2, width))
                         .astype(np.float32), timeout_s=60)
        assert eng.compiled_programs == 0      # warm bucket
        registry.predict("m", rng.normal(size=(4, width))
                         .astype(np.float32), timeout_s=60)
        assert eng.compiled_programs == 1      # live-compiled bucket
        assert reg.counter(
            "tpudl_compile_artifact_misses_total").value > misses0
        assert eng.warm_programs == 1
    finally:
        registry.close()


def test_resume_refuses_corrupt_zip_before_warming_pool(tmp_path):
    """resume_state must verify the checkpoint BEFORE warming: a
    bit-rotted zip is refused whole, and none of its artifacts may
    enter the first-wins pool (a corrupted-but-unpicklable-looking
    executable poisoning every later step would be far worse than the
    recompile it saves)."""
    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.resilience.checkpoint import \
        CheckpointCorruptError
    from deeplearning4j_tpu.train import artifact_store
    from deeplearning4j_tpu.train.trainer import Trainer

    width = 34
    net = _build_net(width=width)
    zp = str(tmp_path / "ck.zip")
    net.save(zp)
    artifact_store.ensure_zip_artifacts(zp, net=net, buckets=(2,))
    exec_name = artifact_store.read_index(zp)[0]["exec"]
    corrupted = str(tmp_path / "rot.zip")
    with zipfile.ZipFile(zp) as src, \
            zipfile.ZipFile(corrupted, "w") as dst:
        for name in src.namelist():
            data = src.read(name)
            if name == exec_name:
                data = data[:64] + bytes(32) + data[96:]
            dst.writestr(name, data)
    reg = get_registry()
    loaded0 = reg.counter("tpudl_compile_artifacts_loaded_total").value
    rejects0 = reg.counter("tpudl_compile_artifact_rejects_total").value
    trainer = Trainer(_build_net(width=width))
    with pytest.raises(CheckpointCorruptError):
        trainer.resume_state(corrupted)
    assert reg.counter(
        "tpudl_compile_artifacts_loaded_total").value == loaded0
    assert reg.counter(
        "tpudl_compile_artifact_rejects_total").value == rejects0


# --------------------------------------------------------- gated pre-bake
def test_gated_deployer_prebakes_candidate_before_flip(tmp_path):
    """A gate-passing candidate's zip carries artifacts BEFORE the
    registry flip (the deploy warms instead of compiling in the swap
    window); a refused candidate is never baked."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.online.gate import EvalGate, GatedDeployer
    from deeplearning4j_tpu.serve.registry import ModelRegistry
    from deeplearning4j_tpu.train import artifact_store

    width = 18
    net = _build_net(width=width)
    rng = np.random.default_rng(3)
    X = rng.normal(size=(32, width)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    holdout = ArrayDataSetIterator(X, Y, batch_size=16)
    candidate = str(tmp_path / "candidate.zip")
    net.save(candidate)
    registry = ModelRegistry(max_batch=4, buckets=(2, 4))
    try:
        deployer = GatedDeployer(registry, EvalGate(holdout,
                                                    metric="accuracy"))
        decision = deployer.deploy_if_better("m", candidate,
                                             prebake_artifacts=True)
        assert decision.deploy
        index = artifact_store.read_index(candidate)
        assert {ix["kind"] for ix in index} == {"serve_forward"}
        assert len(index) == 2                 # buckets (2, 4)
        eng = registry.get("m").engine
        for rows in (2, 4):
            registry.predict("m", rng.normal(size=(rows, width))
                             .astype(np.float32), timeout_s=60)
        # the flip (and the traffic after it) never compiled
        assert eng.compiled_programs == 0
        assert eng.warm_programs == 2
    finally:
        registry.close()
