"""tpudl.obs.report (ISSUE 16): the one-page fleet-health report."""

import json

import pytest

from deeplearning4j_tpu.obs import report, slo
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             install_standard_metrics,
                                             set_registry)


@pytest.fixture
def metrics():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


def test_report_over_the_committed_trajectory(metrics):
    install_standard_metrics(metrics)
    built = report.build_report(registry=metrics)
    rows = {r["record"]: r for r in built["trajectory"]["records"]}
    assert rows["BENCH_r05"]["status"] == "stale"
    assert rows["MULTICHIP_r05"]["status"] == "failed"
    assert built["trajectory"]["regressions"] == []
    assert "r04" in built["trajectory"]["staleness"]["message"]
    # the per-metric delta table covers the real rounds only
    deltas = built["trajectory_deltas"]
    rounds = [row[0] for row in
              deltas["resnet50_train_images_per_sec_per_chip"]]
    assert rounds == [1, 2, 3, 4]
    # honesty counters render as explicit zeros, not absences
    counters = built["health"]["counters"]
    assert counters["tpudl_slo_breaches_total"]["value"] == 0
    assert counters["tpudl_online_rollbacks_total"]["value"] == 0

    text = report.render_markdown(built)
    assert "# Fleet health" in text
    assert "BENCH_r05" in text and "stale" in text
    assert "resnet50_mfu" in text


def test_report_slo_rows_from_a_live_monitor(metrics):
    requests = metrics.labeled_counter("tpudl_serve_requests_total")
    clock_t = [0.0]
    mon = slo.SLOMonitor(
        [slo.AvailabilitySLO(target=0.99)],
        registry=metrics,
        windows=(slo.BurnWindow("fast", 60.0, 300.0, 10.0),),
        clock=lambda: clock_t[0])
    for _ in range(2):
        requests.inc(9, status="error")
        requests.inc(1, status="ok")
        mon.evaluate_once()
        clock_t[0] += 10.0
    built = report.build_report(monitor=mon, registry=metrics)
    (row,) = built["slos"]
    assert row["slo"] == "availability" and row["healthy"] is False
    assert row["burn_rate"] > 10.0
    text = report.render_markdown(built)
    assert "| availability | BREACHED |" in text


def test_report_slo_rows_read_back_from_published_metrics(metrics):
    # the CLI path: no live monitor, just the exported tpudl_slo_* family
    metrics.labeled_gauge("tpudl_slo_healthy",
                          label_names=("slo",)).set(0.0, slo="latency")
    metrics.labeled_gauge("tpudl_slo_burn_rate",
                          label_names=("slo",)).set(22.5, slo="latency")
    metrics.labeled_gauge("tpudl_slo_budget_remaining",
                          label_names=("slo",)).set(0.1, slo="latency")
    built = report.build_report(registry=metrics)
    (row,) = built["slos"]
    assert row["slo"] == "latency"
    assert row["healthy"] is False
    assert row["burn_rate"] == pytest.approx(22.5)


def test_report_cli_json_is_machine_readable(capsys):
    assert report.main(["--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert {"slos", "trajectory", "trajectory_deltas", "health"} \
        <= set(payload)
