"""Multi-slice / DCN tests (VERDICT missing #10): hybrid mesh with a dcn
axis, and the threshold codec plugged into a WORKING cross-slice
allreduce with error feedback.
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.dcn import (
    make_multislice_mesh, InProcessTransport, CompressedAllReducer)


class TestMultisliceMesh:
    def test_axes_and_shape(self):
        mesh = make_multislice_mesh(n_slices=2, data_per_slice=4)
        assert mesh.axis_names == ("dcn", "data", "model")
        assert mesh.shape["dcn"] == 2 and mesh.shape["data"] == 4

    def test_intra_slice_psum_crosses_ici_axis_only(self):
        """Gradient sync within a slice uses 'data'; cross-slice sum uses
        'dcn' — both compile and execute on the hybrid mesh."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from deeplearning4j_tpu.utils.jax_compat import shard_map
        mesh = make_multislice_mesh(n_slices=2, data_per_slice=2, model=2)

        def local(x):
            intra = jax.lax.psum(x, "data")       # ICI collective
            return jax.lax.psum(intra, "dcn")     # DCN collective

        x = jnp.arange(8.0).reshape(2, 2, 2)
        with mesh:
            out = shard_map(local, mesh=mesh,
                            in_specs=P("dcn", "data", "model"),
                            out_specs=P(None, None, "model"))(x)
        np.testing.assert_allclose(np.asarray(out).reshape(-1),
                                   np.asarray(x).sum(axis=(0, 1)))

    def test_too_few_devices_raises(self):
        with pytest.raises(ValueError):
            make_multislice_mesh(n_slices=4, data_per_slice=4)


class TestCompressedAllReduce:
    def _run_ranks(self, reducers, grads, steps=1):
        results = [[None] * len(reducers) for _ in range(steps)]

        def worker(rank):
            for s in range(steps):
                results[s][rank] = reducers[rank].allreduce(grads[s][rank])

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(len(reducers))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def test_all_ranks_agree(self):
        n, size = 3, 512
        transport = InProcessTransport(n)
        reducers = [CompressedAllReducer(r, size, transport) for r in range(n)]
        rng = np.random.default_rng(0)
        grads = [[rng.normal(0, 0.1, size).astype(np.float32) for _ in range(n)]]
        (step,) = self._run_ranks(reducers, grads)
        for r in range(1, n):
            np.testing.assert_array_equal(step[0], step[r])

    def test_error_feedback_converges_to_true_sum(self):
        """Per-step the wire is sparse/approximate; accumulated over steps
        the residual feedback makes the summed updates approach the true
        gradient sum (the reference's convergence property)."""
        n, size, steps = 2, 256, 30
        transport = InProcessTransport(n)
        reducers = [CompressedAllReducer(r, size, transport) for r in range(n)]
        rng = np.random.default_rng(1)
        grads = [[rng.normal(0, 0.05, size).astype(np.float32)
                  for _ in range(n)] for _ in range(steps)]
        results = self._run_ranks(reducers, grads, steps=steps)
        applied = np.sum([results[s][0] for s in range(steps)], axis=0)
        true = np.sum([g for step in grads for g in step], axis=0)
        # residual still holds the un-sent tail; bound it
        leftover = sum(np.abs(r.accumulator.residual).max() for r in reducers)
        np.testing.assert_allclose(applied, true, atol=leftover + 1e-4)
        # and the wire was actually sparse
        msg = reducers[0].accumulator.store_update(grads[0][0])
        stats = reducers[0].wire_stats(msg)
        assert stats["wire_bytes"] < stats["dense_bytes"]

    def test_transport_rounds_never_mix(self):
        """A fast rank entering round 2 must BLOCK for peers' round-2
        posts, not return their stale round-1 messages (review regression)."""
        transport = InProcessTransport(2)
        order = []

        def fast():
            r1 = transport.exchange(0, np.array([10.0]))
            order.append(("fast-r1", float(r1[0][0])))
            r2 = transport.exchange(0, np.array([20.0]))
            order.append(("fast-r2", float(r2[0][0])))

        def slow():
            r1 = transport.exchange(1, np.array([11.0]))
            order.append(("slow-r1", float(r1[0][0])))
            import time
            time.sleep(0.3)              # fast rank reaches round 2 first
            r2 = transport.exchange(1, np.array([21.0]))
            order.append(("slow-r2", float(r2[0][0])))

        t1, t2 = threading.Thread(target=fast), threading.Thread(target=slow)
        t1.start(); t2.start(); t1.join(); t2.join()
        got = dict(order)
        assert got["fast-r1"] == 11.0 and got["slow-r1"] == 10.0
        assert got["fast-r2"] == 21.0      # round-2, never the stale 11.0
        assert got["slow-r2"] == 20.0

    def test_mismatched_size_raises(self):
        transport = InProcessTransport(1)
        red = CompressedAllReducer(0, 16, transport)
        with pytest.raises(ValueError):
            red.allreduce(np.zeros(8, np.float32))

    def test_value_coded_roundtrip_and_residual_tail(self):
        """Top-τ value format: decode is EXACT at transmitted coords, the
        residual holds only the sub-τ tail, and both wire formats
        dispatch through one decoder."""
        from deeplearning4j_tpu.parallel.compression import (
            EncodedGradientsAccumulator, threshold_encode_values,
            threshold_decode)
        rng = np.random.default_rng(4)
        g = rng.normal(0, 0.1, 512).astype(np.float32)
        tau = 0.05
        msg = threshold_encode_values(g, tau)
        dec = np.ravel(threshold_decode(msg, (512,)))
        sent = np.abs(g) >= tau
        np.testing.assert_array_equal(dec[sent], g[sent])   # exact values
        np.testing.assert_array_equal(dec[~sent], 0.0)
        acc = EncodedGradientsAccumulator((512,), value_coded=True)
        acc.store_update(g)
        assert np.abs(acc.residual).max() < acc.algorithm.current() + 1e-7


class TestSocketTransport:
    """VERDICT r2 missing #5: real bytes must cross a process boundary."""

    def test_single_process_loopback(self):
        """Smoke: N thread-ranks around the TCP ring (real sockets,
        one process) agree byte-for-byte with InProcessTransport."""
        from deeplearning4j_tpu.parallel.dcn import SocketTransport
        n, size, steps = 4, 256, 5
        port = 23311
        transports = {}

        def make(rank):
            transports[rank] = SocketTransport(rank, n, port=port)

        # ring handshake: every rank binds + connects concurrently
        threads = [threading.Thread(target=make, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(transports) == list(range(n))
        reducers = [CompressedAllReducer(r, size, transports[r])
                    for r in range(n)]
        ref_transport = InProcessTransport(n)
        ref_reducers = [CompressedAllReducer(r, size, ref_transport)
                        for r in range(n)]
        rng = np.random.default_rng(7)
        grads = [[rng.normal(0, 0.1, size).astype(np.float32)
                  for _ in range(n)] for _ in range(steps)]
        out = [[None] * n for _ in range(steps)]
        ref = [[None] * n for _ in range(steps)]

        def worker(rank, reducer_list, sink):
            for s in range(steps):
                sink[s][rank] = reducer_list[rank].allreduce(grads[s][rank])

        for reducer_list, sink in ((reducers, out), (ref_reducers, ref)):
            threads = [threading.Thread(target=worker,
                                        args=(r, reducer_list, sink))
                       for r in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for s in range(steps):
            for r in range(n):
                np.testing.assert_array_equal(out[s][r], out[s][0])
                np.testing.assert_array_equal(out[s][r], ref[s][r])
        # ring property: every rank moved (n-1) frames each way per
        # exchange — traffic is per-neighbour, not through one relay
        for r in range(n):
            assert transports[r].bytes_sent > 0
            assert transports[r].bytes_received > 0
        total_sent = sum(transports[r].bytes_sent for r in range(n))
        for r in range(n):
            # no rank carries more than ~(2/n) of total traffic
            assert transports[r].bytes_sent < total_sent * 2 / n
        for t in transports.values():
            t.close()

    def test_multiprocess_real_bytes(self):
        """The full thing: N separate PROCESSES exchange compressed
        gradients over loopback TCP; all agree, and the error-feedback
        convergence property holds across the wire."""
        from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster
        from tests.cluster_workers import dcn_socket_allreduce_worker
        n, steps = 4, 8
        results = spawn_local_cluster(dcn_socket_allreduce_worker,
                                      n_processes=n, port=12675)
        assert len(results) == n
        by_pid = {r["pid"]: r for r in results}
        # per-rank bytes-on-wire: every rank sent AND received its
        # (n-1)-hop share; sums agree despite no central relay
        for pid in range(n):
            assert by_pid[pid]["bytes_sent"] > 0
            assert by_pid[pid]["bytes_received"] > 0
        total = sum(by_pid[p]["bytes_sent"] for p in range(n))
        for pid in range(n):
            assert by_pid[pid]["bytes_sent"] < total * 2 / n
        # every rank computed identical sums every step
        for pid in range(1, n):
            np.testing.assert_array_equal(by_pid[pid]["sums"],
                                          by_pid[0]["sums"])
        # error feedback: applied total ≈ true total, residual-bounded
        applied = by_pid[0]["sums"].sum(axis=0)
        true = np.sum([by_pid[p]["grads"].sum(axis=0) for p in range(n)],
                      axis=0)
        leftover = sum(np.abs(by_pid[p]["residual"]).max()
                       for p in range(n))
        np.testing.assert_allclose(applied, true, atol=leftover + 1e-4)


class TestMultiSliceTrainer:
    """VERDICT r3 missing #2: the codec/transport/accumulator must feed an
    end-to-end multi-slice fit() (workload #5 across slices)."""

    def _net(self, seed=77):
        from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.train import Sgd
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Sgd(0.1)).weight_init("xavier").list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    def _data(self, n=64):
        from deeplearning4j_tpu.data.dataset import DataSet
        rng = np.random.default_rng(5)
        x = rng.normal(size=(n, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
        return DataSet(x, y)

    def test_two_slices_times_two_devices_loss_parity(self):
        """2 slices × 2 devices on the CPU mesh: compressed multi-slice
        fit tracks dense single-program DP within error-feedback
        tolerance; slices stay byte-synchronized; wire stats real."""
        import jax
        from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
        from deeplearning4j_tpu.train.trainer import Trainer

        steps = 12
        batch = self._data(64)
        key = jax.random.key(3)

        dense = Trainer(self._net())
        dense_losses = [float(dense.fit_batch(batch, key))
                        for _ in range(steps)]

        from deeplearning4j_tpu.parallel.compression import (
            AdaptiveThresholdAlgorithm)
        trainer = MultiSliceTrainer(
            self._net(), n_slices=2, data_per_slice=2,
            devices=jax.devices()[:4],
            # τ high enough that this small model's gradients actually
            # quantize — the error-feedback loop is then really exercised
            algorithm=AdaptiveThresholdAlgorithm(initial_threshold=3e-2))
        try:
            dcn_losses = [trainer.fit_batch(batch, key)
                          for _ in range(steps)]
            # slices applied identical totals every step → no divergence
            assert trainer.max_param_divergence() == 0.0
            # wire stats: compression happened, residual is carried
            for ws in trainer.last_wire_stats:
                assert ws["wire_bytes"] > 0
                assert ws["wire_bytes"] < ws["dense_bytes"]
                assert ws["compression"] > 1.0
                assert ws["residual_linf"] > 0.0      # quantization carried
            # loss-curve parity: identical data+init; only quantization
            # (error-feedback) separates the curves
            np.testing.assert_allclose(dcn_losses, dense_losses, atol=0.05)
            # training actually progressed
            assert dcn_losses[-1] < dcn_losses[0] - 0.05
            # collect() hands back a usable synchronized net
            net = trainer.collect()
            out = np.asarray(net.output(np.asarray(batch.features[:4])))
            assert out.shape == (4, 3) and np.all(np.isfinite(out))
        finally:
            trainer.close()

    @pytest.mark.slow
    def test_resnet50_multislice_fit(self):
        """BASELINE workload #5 by name: the actual models.resnet50
        training across 2 slices × 2 devices with compressed cross-slice
        gradient exchange — fit() runs end-to-end, slices stay
        synchronized, wire stats show real compression."""
        import jax
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.models import resnet50
        from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer

        from deeplearning4j_tpu.train import Sgd
        net = resnet50(height=32, width=32, num_classes=10,
                       updater=Sgd(0.01))   # gentle lr: 3 steps, batch 16
        net.init()
        rng = np.random.default_rng(11)
        batch = DataSet(
            rng.uniform(0, 1, (16, 32, 32, 3)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)])
        from deeplearning4j_tpu.parallel.compression import (
            AdaptiveThresholdAlgorithm)
        trainer = MultiSliceTrainer(
            net, n_slices=2, data_per_slice=2, devices=jax.devices()[:4],
            # τ sized to resnet's init-gradient scale; the adaptive
            # algorithm would get here on its own over ~50 steps.
            # capacity covers the warm-up transient: at init ~15% of the
            # 23.5M entries exceed τ=0.1 (measured: 3.5M hits) — the
            # steady-state default (4× target sparsity) would truncate
            # 97% of the early signal and this 3-step test would only
            # see the distorted transient
            capacity=4_000_000,
            algorithm=AdaptiveThresholdAlgorithm(initial_threshold=0.1))
        try:
            first = trainer.fit_batch(batch, jax.random.key(2))
            # step 1 (before residual buildup widens the wire): the
            # 25.6M-param gradient must genuinely compress
            for ws in trainer.last_wire_stats:
                assert ws["wire_bytes"] > 0
                assert ws["compression"] > 2.0
            losses = [first] + [trainer.fit_batch(batch, jax.random.key(2))
                                for _ in range(2)]
            assert all(np.isfinite(l) for l in losses)
            assert trainer.max_param_divergence() == 0.0
            # later steps still beat dense f32 on the wire (error
            # feedback widens the message but never to dense size)
            for ws in trainer.last_wire_stats:
                assert ws["wire_bytes"] < ws["dense_bytes"]
            if hasattr(jax, "shard_map"):
                # 3-step loss decrease is numerics-tight: it holds on the
                # rig's jax but not on 0.4.x, where even the single-slice
                # Trainer's loss is non-monotonic over 3 steps at lr 0.01
                assert losses[-1] < losses[0]
        finally:
            trainer.close()

    def test_socket_transport_slices(self):
        """Same trainer over real TCP ring transports (loopback),
        1 device per slice — bytes genuinely leave the slice thread."""
        import jax
        from deeplearning4j_tpu.parallel.dcn import SocketTransport
        from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer

        n = 2
        transports = {}

        def make(rank):
            transports[rank] = SocketTransport(rank, n, port=23511)

        ts = [threading.Thread(target=make, args=(r,)) for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        trainer = MultiSliceTrainer(self._net(), n_slices=n,
                                    data_per_slice=1,
                                    devices=jax.devices()[:n],
                                    transports=[transports[r]
                                                for r in range(n)])
        try:
            batch = self._data(32)
            key = jax.random.key(0)
            losses = [trainer.fit_batch(batch, key) for _ in range(4)]
            assert trainer.max_param_divergence() == 0.0
            assert losses[-1] < losses[0]
            assert all(t.bytes_sent > 0 for t in transports.values())
        finally:
            trainer.close()
            for t in transports.values():
                t.close()


class TestDeviceEncodePath:
    """VERDICT r4 next #1a/#1b: on-device encode (only the message
    crosses D2H) and overlapped exchange."""

    _net = TestMultiSliceTrainer._net
    _data = TestMultiSliceTrainer._data

    def test_device_path_matches_host_codec_path(self):
        """device_encode=True follows the exact host-codec trajectory
        (same wire format, same residual arithmetic): loss curves and
        final params agree to f32 tolerance."""
        import jax
        from deeplearning4j_tpu.parallel.compression import (
            AdaptiveThresholdAlgorithm)
        from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer

        batch = self._data(64)
        key = jax.random.key(3)
        runs = {}
        for dev_enc in (False, True):
            trainer = MultiSliceTrainer(
                self._net(), n_slices=2, data_per_slice=2,
                devices=jax.devices()[:4], device_encode=dev_enc,
                algorithm=AdaptiveThresholdAlgorithm(initial_threshold=3e-2))
            try:
                losses = [trainer.fit_batch(batch, key) for _ in range(8)]
                assert trainer.max_param_divergence() == 0.0
                flat = np.asarray(
                    __import__("jax").flatten_util.ravel_pytree(
                        trainer.slice_params[0])[0])
                runs[dev_enc] = (losses, flat, trainer.last_wire_stats)
            finally:
                trainer.close()
        np.testing.assert_allclose(runs[True][0], runs[False][0], rtol=1e-5)
        np.testing.assert_allclose(runs[True][1], runs[False][1],
                                   rtol=1e-5, atol=1e-7)
        # the point of the device path: D2H is the message, not the grad
        for ws in runs[True][2]:
            assert ws["d2h_bytes"] < ws["dense_bytes"]

    def test_overlap_mode_trains_and_stays_synchronized(self):
        """overlap=True (exchange N rides IO while N+1 computes): loss
        decreases, slices remain byte-identical, finish() drains."""
        import jax
        from deeplearning4j_tpu.parallel.compression import (
            AdaptiveThresholdAlgorithm)
        from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer

        batch = self._data(64)
        key = jax.random.key(3)
        trainer = MultiSliceTrainer(
            self._net(), n_slices=2, data_per_slice=2,
            devices=jax.devices()[:4], device_encode=True, overlap=True,
            algorithm=AdaptiveThresholdAlgorithm(initial_threshold=3e-2))
        try:
            losses = [trainer.fit_batch(batch, key) for _ in range(12)]
            trainer.finish()
            assert trainer.max_param_divergence() == 0.0
            assert losses[-1] < losses[0] - 0.05
            net = trainer.collect()
            out = np.asarray(net.output(np.asarray(batch.features[:4])))
            assert np.all(np.isfinite(out))
        finally:
            trainer.close()
