"""Multi-slice / DCN tests (VERDICT missing #10): hybrid mesh with a dcn
axis, and the threshold codec plugged into a WORKING cross-slice
allreduce with error feedback.
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.dcn import (
    make_multislice_mesh, InProcessTransport, CompressedAllReducer)


class TestMultisliceMesh:
    def test_axes_and_shape(self):
        mesh = make_multislice_mesh(n_slices=2, data_per_slice=4)
        assert mesh.axis_names == ("dcn", "data", "model")
        assert mesh.shape["dcn"] == 2 and mesh.shape["data"] == 4

    def test_intra_slice_psum_crosses_ici_axis_only(self):
        """Gradient sync within a slice uses 'data'; cross-slice sum uses
        'dcn' — both compile and execute on the hybrid mesh."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        mesh = make_multislice_mesh(n_slices=2, data_per_slice=2, model=2)

        def local(x):
            intra = jax.lax.psum(x, "data")       # ICI collective
            return jax.lax.psum(intra, "dcn")     # DCN collective

        x = jnp.arange(8.0).reshape(2, 2, 2)
        with mesh:
            out = jax.shard_map(local, mesh=mesh,
                                in_specs=P("dcn", "data", "model"),
                                out_specs=P(None, None, "model"))(x)
        np.testing.assert_allclose(np.asarray(out).reshape(-1),
                                   np.asarray(x).sum(axis=(0, 1)))

    def test_too_few_devices_raises(self):
        with pytest.raises(ValueError):
            make_multislice_mesh(n_slices=4, data_per_slice=4)


class TestCompressedAllReduce:
    def _run_ranks(self, reducers, grads, steps=1):
        results = [[None] * len(reducers) for _ in range(steps)]

        def worker(rank):
            for s in range(steps):
                results[s][rank] = reducers[rank].allreduce(grads[s][rank])

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(len(reducers))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

    def test_all_ranks_agree(self):
        n, size = 3, 512
        transport = InProcessTransport(n)
        reducers = [CompressedAllReducer(r, size, transport) for r in range(n)]
        rng = np.random.default_rng(0)
        grads = [[rng.normal(0, 0.1, size).astype(np.float32) for _ in range(n)]]
        (step,) = self._run_ranks(reducers, grads)
        for r in range(1, n):
            np.testing.assert_array_equal(step[0], step[r])

    def test_error_feedback_converges_to_true_sum(self):
        """Per-step the wire is sparse/approximate; accumulated over steps
        the residual feedback makes the summed updates approach the true
        gradient sum (the reference's convergence property)."""
        n, size, steps = 2, 256, 30
        transport = InProcessTransport(n)
        reducers = [CompressedAllReducer(r, size, transport) for r in range(n)]
        rng = np.random.default_rng(1)
        grads = [[rng.normal(0, 0.05, size).astype(np.float32)
                  for _ in range(n)] for _ in range(steps)]
        results = self._run_ranks(reducers, grads, steps=steps)
        applied = np.sum([results[s][0] for s in range(steps)], axis=0)
        true = np.sum([g for step in grads for g in step], axis=0)
        # residual still holds the un-sent tail; bound it
        leftover = sum(np.abs(r.accumulator.residual).max() for r in reducers)
        np.testing.assert_allclose(applied, true, atol=leftover + 1e-4)
        # and the wire was actually sparse
        msg = reducers[0].accumulator.store_update(grads[0][0])
        stats = reducers[0].wire_stats(msg)
        assert stats["wire_bytes"] < stats["dense_bytes"]

    def test_transport_rounds_never_mix(self):
        """A fast rank entering round 2 must BLOCK for peers' round-2
        posts, not return their stale round-1 messages (review regression)."""
        transport = InProcessTransport(2)
        order = []

        def fast():
            r1 = transport.exchange(0, np.array([10.0]))
            order.append(("fast-r1", float(r1[0][0])))
            r2 = transport.exchange(0, np.array([20.0]))
            order.append(("fast-r2", float(r2[0][0])))

        def slow():
            r1 = transport.exchange(1, np.array([11.0]))
            order.append(("slow-r1", float(r1[0][0])))
            import time
            time.sleep(0.3)              # fast rank reaches round 2 first
            r2 = transport.exchange(1, np.array([21.0]))
            order.append(("slow-r2", float(r2[0][0])))

        t1, t2 = threading.Thread(target=fast), threading.Thread(target=slow)
        t1.start(); t2.start(); t1.join(); t2.join()
        got = dict(order)
        assert got["fast-r1"] == 11.0 and got["slow-r1"] == 10.0
        assert got["fast-r2"] == 21.0      # round-2, never the stale 11.0
        assert got["slow-r2"] == 20.0

    def test_mismatched_size_raises(self):
        transport = InProcessTransport(1)
        red = CompressedAllReducer(0, 16, transport)
        with pytest.raises(ValueError):
            red.allreduce(np.zeros(8, np.float32))
