"""Pallas flash-attention kernel tests (VERDICT #7).

Runs in interpreter mode on the CPU test rig; the jnp implementations
(_block_attention / reference_attention) are the numerical oracles.
The TPU-compiled path + long-seq microbench live in bench/flash_bench.py
(numbers recorded in bench/PROFILE.md).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas import flash_attention, flash_attention_block
from deeplearning4j_tpu.parallel.unified import (
    _block_attention, reference_attention, ring_attention)
from deeplearning4j_tpu.parallel.mesh import make_mesh


def _qkv(b=2, h=3, t=24, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, h, t, d)).astype(np.float32))
                 for _ in range(3))


class TestFlashBlock:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_jnp_oracle(self, causal):
        q, k, v = _qkv()
        scale = 0.25
        if causal:
            pos = jnp.arange(24)
            mask = pos[:, None] >= pos[None, :]
        else:
            mask = None
        o1, m1, l1 = _block_attention(q, k, v, scale, mask)
        o2, m2, l2 = flash_attention_block(q, k, v, scale=scale,
                                           causal=causal, block_q=8,
                                           block_k=8)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(m2), np.asarray(m1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(l2), np.asarray(l1),
                                   rtol=1e-5, atol=1e-6)

    def test_offsets_and_rectangular_blocks(self):
        """Ring-step shape: Tq != Tk, non-zero global offsets, future block
        fully masked under causal."""
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 2, 20, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 28, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 28, 16)).astype(np.float32))
        qpos, kpos = 40 + jnp.arange(20), 16 + jnp.arange(28)
        mask = qpos[:, None] >= kpos[None, :]
        o1, m1, l1 = _block_attention(q, k, v, 0.25, mask)
        o2, m2, l2 = flash_attention_block(q, k, v, scale=0.25, causal=True,
                                           q_offset=40, k_offset=16,
                                           block_q=8, block_k=8)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-5, atol=1e-5)
        # entirely-future kv block: every row must report nothing visible
        o3, m3, l3 = flash_attention_block(q, k, v, scale=0.25, causal=True,
                                           q_offset=0, k_offset=100,
                                           block_q=8, block_k=8)
        assert np.all(np.asarray(l3) == 0.0)
        assert np.all(np.asarray(m3) <= -1e29)

    def test_padding_of_non_multiple_lengths(self):
        q, k, v = _qkv(t=23)           # 23 % 8 != 0 → padded internally
        o1, m1, l1 = _block_attention(q, k, v, 0.3, None)
        o2, m2, l2 = flash_attention_block(q, k, v, scale=0.3, block_q=8,
                                           block_k=8)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(l2), np.asarray(l1),
                                   rtol=1e-5, atol=1e-6)


class TestFlashFull:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_attention(self, causal):
        rng = np.random.default_rng(2)
        b, t, h, d = 2, 40, 4, 16
        q = jnp.asarray(rng.normal(size=(b, t, h * d)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, t, h * d)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, t, h * d)).astype(np.float32))
        out = flash_attention(q, k, v, n_heads=h, causal=causal,
                              block_q=8, block_k=8)
        ref = reference_attention(q, k, v, n_heads=h, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestFlashBackward:
    """jax.grad through the Pallas kernels vs grad through the jnp
    reference (VERDICT r2 weak #2: the kernel was forward-only)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        rng = np.random.default_rng(7)
        b, t, h, d = 2, 40, 4, 16
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h * d))
                               .astype(np.float32)) for _ in range(3))

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, n_heads=h, causal=causal,
                                  block_q=8, block_k=8)
            return jnp.sum(jnp.sin(out))          # non-uniform cotangent

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(
                reference_attention(q, k, v, n_heads=h, causal=causal)))

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=2e-4, atol=2e-5, err_msg=name)

    def test_grads_non_multiple_length(self):
        # t not a multiple of the block: padded rows must not pollute grads
        rng = np.random.default_rng(8)
        b, t, h, d = 1, 21, 2, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h * d))
                               .astype(np.float32)) for _ in range(3))
        f = lambda *a: jnp.sum(flash_attention(*a, n_heads=h, causal=True,
                                               block_q=8, block_k=8) ** 2)
        r = lambda *a: jnp.sum(reference_attention(*a, n_heads=h,
                                                   causal=True) ** 2)
        g_flash = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_flash, g_ref):
            assert not np.any(np.isnan(np.asarray(gf)))
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=2e-4, atol=2e-5)

    def test_grads_bf16(self):
        rng = np.random.default_rng(9)
        b, t, h, d = 1, 32, 2, 16
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h * d))
                               .astype(np.float32)).astype(jnp.bfloat16)
                   for _ in range(3))
        f = lambda *a: jnp.sum(flash_attention(
            *a, n_heads=h, block_q=16, block_k=16).astype(jnp.float32))
        r = lambda *a: jnp.sum(reference_attention(
            *a, n_heads=h).astype(jnp.float32))
        g_flash = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_flash, g_ref):
            assert gf.dtype == jnp.bfloat16
            np.testing.assert_allclose(np.asarray(gf, dtype=np.float32),
                                       np.asarray(gr, dtype=np.float32),
                                       rtol=0.1, atol=0.1)


class TestFlashMaskAndProduct:
    def test_key_mask_matches_reference(self):
        rng = np.random.default_rng(11)
        b, t, h, d = 2, 24, 2, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h * d))
                               .astype(np.float32)) for _ in range(3))
        mask = jnp.asarray(rng.integers(0, 2, size=(b, t)), jnp.float32)
        mask = mask.at[:, 0].set(1.0)          # keep at least one key alive
        from deeplearning4j_tpu.ops.attention import multi_head_attention

        def loss(fn):
            return lambda *a: jnp.sum(jnp.sin(fn(*a)))

        flash = loss(lambda *a: multi_head_attention(
            *a, n_heads=h, mask=mask, use_flash=True, flash_block=8))
        ref = loss(lambda *a: multi_head_attention(*a, n_heads=h, mask=mask))
        np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                                   np.asarray(ref(q, k, v)),
                                   rtol=2e-5, atol=2e-5)
        gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for a, b2 in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=2e-4, atol=2e-5)

    def test_cross_attention_flash(self):
        """tq != tk with kv_mask (review regression: flash reshaped k/v
        with q's length)."""
        from deeplearning4j_tpu.ops.attention import multi_head_attention
        rng = np.random.default_rng(14)
        q = jnp.asarray(rng.normal(size=(2, 10, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 18, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 18, 16)).astype(np.float32))
        kvm = jnp.ones((2, 18)).at[:, -4:].set(0.0)
        f = lambda *a: jnp.sum(jnp.sin(multi_head_attention(
            *a, n_heads=2, kv_mask=kvm, use_flash=True, flash_block=8)))
        r = lambda *a: jnp.sum(jnp.sin(multi_head_attention(
            *a, n_heads=2, kv_mask=kvm)))
        np.testing.assert_allclose(float(f(q, k, v)), float(r(q, k, v)),
                                   rtol=1e-5)
        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for a, b2 in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=2e-4, atol=2e-5)

    def test_self_attention_layer_flash_trains(self):
        """use_flash on the layer: same forward and grads as the einsum
        path (VERDICT r2: the kernel must be in the product)."""
        from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
        from deeplearning4j_tpu.nn.input_type import InputType
        rng = np.random.default_rng(12)
        x = jnp.asarray(rng.normal(size=(2, 16, 32)).astype(np.float32))
        lay = SelfAttentionLayer(n_heads=4, use_flash=True, flash_block=8)
        ref = SelfAttentionLayer(n_heads=4)
        params = lay.init_params(jax.random.key(0),
                                 InputType.recurrent(32, 16))

        def f(layer):
            def loss(p):
                y, _ = layer.apply(p, {}, x)
                return jnp.sum(y ** 2)
            return loss

        np.testing.assert_allclose(np.asarray(f(lay)(params)),
                                   np.asarray(f(ref)(params)), rtol=1e-5)
        gf = jax.grad(f(lay))(params)
        gr = jax.grad(f(ref))(params)
        for name in gf:
            np.testing.assert_allclose(np.asarray(gf[name]),
                                       np.asarray(gr[name]),
                                       rtol=2e-4, atol=2e-5, err_msg=name)

    @pytest.mark.slow
    def test_bert_flash_step_matches(self):
        """One MLM train step with use_flash on == off (tiny config)."""
        import dataclasses as dc
        from deeplearning4j_tpu.models import bert as bert_mod
        cfg = bert_mod.BertConfig.tiny()
        cfg_flash = dc.replace(cfg, use_flash=True, flash_block=8)
        rng = np.random.default_rng(13)
        b, t = 2, 24
        ids = jnp.asarray(rng.integers(0, 1000, size=(b, t)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 1000, size=(b, t)), jnp.int32)
        weights = jnp.asarray(rng.integers(0, 2, size=(b, t)), jnp.float32)
        amask = jnp.ones((b, t), jnp.float32).at[:, -5:].set(0.0)
        params = bert_mod.init_params(cfg, jax.random.key(1))

        grads = []
        for c in (cfg, cfg_flash):
            def loss(p):
                return bert_mod.mlm_loss(p, c, ids, labels, weights,
                                         attention_mask=amask, train=False)
            l, g = jax.value_and_grad(loss)(params)
            grads.append((l, g))
        np.testing.assert_allclose(np.asarray(grads[0][0]),
                                   np.asarray(grads[1][0]), rtol=1e-5)
        flat0 = jax.tree_util.tree_leaves(grads[0][1])
        flat1 = jax.tree_util.tree_leaves(grads[1][1])
        for a, b2 in zip(flat0, flat1):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=5e-4, atol=5e-5)


class TestRingWithFlash:
    def test_ring_attention_flash_bf16(self):
        """The advertised long-seq dtype must trace through the scan carry
        (review regression: f32 kernel outputs vs bf16 carry)."""
        mesh = make_mesh(data=2, seq=4)
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(2, 32, 32)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        with mesh:
            out = ring_attention(q, q, q, mesh, axis="seq", n_heads=4,
                                 causal=True, use_flash=True, flash_block=8,
                                 data_axis="data")
        assert out.dtype == jnp.bfloat16
        ref = reference_attention(q, q, q, n_heads=4, causal=True)
        np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                                   np.asarray(ref, dtype=np.float32),
                                   rtol=0.1, atol=0.05)   # bf16 tolerance

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_attention_flash_inner_kernel(self, causal):
        """Ring attention with the Pallas inner kernel == jnp ring == full
        reference, on the 8-device mesh."""
        mesh = make_mesh(data=1, seq=8)
        b, t, heads, dh = 2, 32, 4, 8
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
        with mesh:
            out = ring_attention(q, k, v, mesh, axis="seq", n_heads=heads,
                                 causal=causal, use_flash=True, flash_block=8)
        ref = reference_attention(q, k, v, n_heads=heads, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestConv3BnFused:
    """Round-5 measurement artifact (negative result — see
    bench/PROFILE.md): the 3×3 conv+BN kernel must still be CORRECT."""

    def _case(self, N=2, H=8, W=7, C=16):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 1, (N, H, W, C)).astype(np.float32))
        w = jnp.asarray(rng.normal(0, 0.1, (3, 3, C, C)).astype(np.float32))
        a = jnp.asarray(rng.normal(1, 0.1, C).astype(np.float32))
        b = jnp.asarray(rng.normal(0, 0.1, C).astype(np.float32))
        return x, w, a, b

    def test_matches_reference_with_and_without_prologue(self):
        from deeplearning4j_tpu.ops.pallas import conv3_bn as cb
        x, w, a, b = self._case()
        for has_pro in (False, True):
            y, s1, s2 = cb.conv3x3_bn_act(
                x, w, a if has_pro else None, b if has_pro else None,
                interpret=True)
            yr, s1r, s2r = cb._reference(x, w, a, b, has_prologue=has_pro,
                                         relu_in=True)
            np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(np.asarray(s1), np.asarray(s1r),
                                       rtol=1e-4)
            np.testing.assert_allclose(np.asarray(s2), np.asarray(s2r),
                                       rtol=1e-4)

    def test_gradients_flow_through_custom_vjp(self):
        from deeplearning4j_tpu.ops.pallas import conv3_bn as cb
        x, w, a, b = self._case()

        def loss(x, w, a, b):
            y, s1, s2 = cb.conv3x3_bn_act(x, w, a, b, interpret=True)
            return y.sum() + (s1 * s1).sum() + s2.sum()

        def loss_ref(x, w, a, b):
            y, s1, s2 = cb._reference(x, w, a, b, has_prologue=True,
                                      relu_in=True)
            return y.sum() + (s1 * s1).sum() + s2.sum()

        g = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, a, b)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, a, b)
        for gi, gri in zip(g, gr):
            np.testing.assert_allclose(np.asarray(gi), np.asarray(gri),
                                       rtol=1e-4, atol=1e-5)


class TestFlashAutoDefault:
    """ISSUE 11 satellite: the flash kernel is the standard BERT path —
    ``use_flash=None`` auto-enables at seq >= 1024 (explicit False
    still wins), with numeric parity against the einsum path."""

    def test_auto_matches_einsum_at_long_seq(self):
        from deeplearning4j_tpu.ops.attention import multi_head_attention
        rng = np.random.default_rng(21)
        b, t, h, d = 1, 1024, 2, 8
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h * d))
                               .astype(np.float32)) for _ in range(3))
        auto = multi_head_attention(q, k, v, n_heads=h)          # default
        einsum = multi_head_attention(q, k, v, n_heads=h, use_flash=False)
        np.testing.assert_allclose(np.asarray(auto), np.asarray(einsum),
                                   rtol=2e-5, atol=2e-5)

    def test_auto_routing_thresholds(self, monkeypatch):
        """seq >= 1024 routes to the kernel, shorter stays on einsum,
        and an explicit False beats the auto promotion."""
        from deeplearning4j_tpu.ops import pallas as pallas_mod
        from deeplearning4j_tpu.ops.attention import multi_head_attention
        calls = []
        real = pallas_mod.flash_attention

        def spy(*a, **kw):
            calls.append(kw.get("block_q"))
            return real(*a, **kw)

        monkeypatch.setattr(pallas_mod, "flash_attention", spy)
        rng = np.random.default_rng(22)
        short = jnp.asarray(rng.normal(size=(1, 64, 16)).astype(np.float32))
        long = jnp.asarray(rng.normal(size=(1, 1024, 16)).astype(np.float32))
        multi_head_attention(short, short, short, n_heads=2)
        assert calls == []                       # short seq: einsum path
        multi_head_attention(long, long, long, n_heads=2)
        assert len(calls) == 1                   # long seq: promoted
        multi_head_attention(long, long, long, n_heads=2, use_flash=False)
        assert len(calls) == 1                   # explicit False wins

    def test_bert_config_default_is_auto(self):
        from deeplearning4j_tpu.models.bert import BertConfig
        from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
        assert BertConfig().use_flash is None
        assert SelfAttentionLayer().use_flash is None
