"""Pallas flash-attention kernel tests (VERDICT #7).

Runs in interpreter mode on the CPU test rig; the jnp implementations
(_block_attention / reference_attention) are the numerical oracles.
The TPU-compiled path + long-seq microbench live in bench/flash_bench.py
(numbers recorded in bench/PROFILE.md).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas import flash_attention, flash_attention_block
from deeplearning4j_tpu.parallel.context_parallel import (
    _block_attention, reference_attention, ring_attention)
from deeplearning4j_tpu.parallel.mesh import make_mesh


def _qkv(b=2, h=3, t=24, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, h, t, d)).astype(np.float32))
                 for _ in range(3))


class TestFlashBlock:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_jnp_oracle(self, causal):
        q, k, v = _qkv()
        scale = 0.25
        if causal:
            pos = jnp.arange(24)
            mask = pos[:, None] >= pos[None, :]
        else:
            mask = None
        o1, m1, l1 = _block_attention(q, k, v, scale, mask)
        o2, m2, l2 = flash_attention_block(q, k, v, scale=scale,
                                           causal=causal, block_q=8,
                                           block_k=8)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(m2), np.asarray(m1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(l2), np.asarray(l1),
                                   rtol=1e-5, atol=1e-6)

    def test_offsets_and_rectangular_blocks(self):
        """Ring-step shape: Tq != Tk, non-zero global offsets, future block
        fully masked under causal."""
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 2, 20, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 28, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 28, 16)).astype(np.float32))
        qpos, kpos = 40 + jnp.arange(20), 16 + jnp.arange(28)
        mask = qpos[:, None] >= kpos[None, :]
        o1, m1, l1 = _block_attention(q, k, v, 0.25, mask)
        o2, m2, l2 = flash_attention_block(q, k, v, scale=0.25, causal=True,
                                           q_offset=40, k_offset=16,
                                           block_q=8, block_k=8)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-5, atol=1e-5)
        # entirely-future kv block: every row must report nothing visible
        o3, m3, l3 = flash_attention_block(q, k, v, scale=0.25, causal=True,
                                           q_offset=0, k_offset=100,
                                           block_q=8, block_k=8)
        assert np.all(np.asarray(l3) == 0.0)
        assert np.all(np.asarray(m3) <= -1e29)

    def test_padding_of_non_multiple_lengths(self):
        q, k, v = _qkv(t=23)           # 23 % 8 != 0 → padded internally
        o1, m1, l1 = _block_attention(q, k, v, 0.3, None)
        o2, m2, l2 = flash_attention_block(q, k, v, scale=0.3, block_q=8,
                                           block_k=8)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(l2), np.asarray(l1),
                                   rtol=1e-5, atol=1e-6)


class TestFlashFull:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_attention(self, causal):
        rng = np.random.default_rng(2)
        b, t, h, d = 2, 40, 4, 16
        q = jnp.asarray(rng.normal(size=(b, t, h * d)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, t, h * d)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, t, h * d)).astype(np.float32))
        out = flash_attention(q, k, v, n_heads=h, causal=causal,
                              block_q=8, block_k=8)
        ref = reference_attention(q, k, v, n_heads=h, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestRingWithFlash:
    def test_ring_attention_flash_bf16(self):
        """The advertised long-seq dtype must trace through the scan carry
        (review regression: f32 kernel outputs vs bf16 carry)."""
        mesh = make_mesh(data=2, seq=4)
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(2, 32, 32)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        with mesh:
            out = ring_attention(q, q, q, mesh, axis="seq", n_heads=4,
                                 causal=True, use_flash=True, flash_block=8,
                                 data_axis="data")
        assert out.dtype == jnp.bfloat16
        ref = reference_attention(q, q, q, n_heads=4, causal=True)
        np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                                   np.asarray(ref, dtype=np.float32),
                                   rtol=0.1, atol=0.05)   # bf16 tolerance

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_attention_flash_inner_kernel(self, causal):
        """Ring attention with the Pallas inner kernel == jnp ring == full
        reference, on the 8-device mesh."""
        mesh = make_mesh(data=1, seq=8)
        b, t, heads, dh = 2, 32, 4, 8
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
        with mesh:
            out = ring_attention(q, k, v, mesh, axis="seq", n_heads=heads,
                                 causal=causal, use_flash=True, flash_block=8)
        ref = reference_attention(q, k, v, n_heads=heads, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
