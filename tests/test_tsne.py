"""t-SNE tests (reference: deeplearning4j-manifold ``BarnesHutTsne``
tests — embed clustered data, assert cluster structure survives)."""

import numpy as np
import pytest

from deeplearning4j_tpu.manifold import Tsne


def _three_clusters(n_per=30, dim=10, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[8.0] + [0.0] * (dim - 1),
                        [0.0] * (dim - 1) + [8.0],
                        [-8.0] + [0.0] * (dim - 1)])
    x = np.concatenate([c + rng.normal(0, 0.5, (n_per, dim)) for c in centers])
    labels = np.repeat(np.arange(3), n_per)
    return x.astype(np.float32), labels


def test_clusters_stay_separated():
    x, labels = _three_clusters()
    ts = Tsne(perplexity=10.0, n_iter=300, seed=1)
    y = ts.fit_transform(x)
    assert y.shape == (90, 2)
    assert np.all(np.isfinite(y))
    cents = np.stack([y[labels == k].mean(0) for k in range(3)])
    intra = max(np.linalg.norm(y[labels == k] - cents[k], axis=1).mean()
                for k in range(3))
    inter = min(np.linalg.norm(cents[a] - cents[b])
                for a in range(3) for b in range(a + 1, 3))
    assert inter > 2 * intra, (inter, intra)


def test_embedding_centered_and_deterministic():
    x, _ = _three_clusters(n_per=15)
    a = Tsne(perplexity=8.0, n_iter=50, seed=3).fit_transform(x)
    b = Tsne(perplexity=8.0, n_iter=50, seed=3).fit_transform(x)
    np.testing.assert_allclose(a, b, atol=1e-5)
    np.testing.assert_allclose(a.mean(axis=0), 0.0, atol=1e-3)


def test_perplexity_validation():
    x = np.random.default_rng(0).normal(size=(20, 5)).astype(np.float32)
    with pytest.raises(ValueError):
        Tsne(perplexity=30.0).fit_transform(x)
