"""Module-level worker functions for spawn_local_cluster tests.

Lives in a plain module (not a test file) so the spawned processes can
unpickle function references via PYTHONPATH.  Each worker runs under a
REAL multi-process ``jax.distributed`` runtime on CPU loopback — the
DummyTransport translation (SURVEY §4.2-3).
"""

import os

import numpy as np


def _small_net(seed=7):
    from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.train import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(conf).init()


def global_batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


def psum_worker(pid, n):
    """Smoke: a real cross-process collective over the global device mesh."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    got = multihost_utils.process_allgather(jnp.asarray([float(pid + 1)]))
    return {"pid": pid, "n_processes": jax.process_count(),
            "n_devices": len(jax.devices()),
            "allgather_sum": float(np.sum(np.asarray(got)))}


def dp_step_worker(pid, n):
    """One data-parallel step: local grads on this process's shard of the
    global batch, cross-process gradient averaging (the SharedTrainingMaster
    semantic swap: synchronous dense allreduce), one SGD update.  Every
    process must end with identical params equal to the full-batch step."""
    import jax
    from jax.experimental import multihost_utils
    from deeplearning4j_tpu.train.trainer import make_loss_fn
    from deeplearning4j_tpu.utils.pytree import flat_param_vector

    net = _small_net()
    x, y = global_batch()
    shard = slice(pid * len(x) // n, (pid + 1) * len(x) // n)
    loss_fn = make_loss_fn(net)

    def local_loss(params):
        loss, _ = loss_fn(params, net.state_, x[shard], y[shard],
                          None, None, None)
        return loss

    grads = jax.grad(local_loss)(net.params_)
    # gradient sharing: allreduce-mean across processes over loopback
    gathered = multihost_utils.process_allgather(grads)
    grads = jax.tree_util.tree_map(lambda g: np.mean(np.asarray(g), axis=0),
                                   gathered)
    params = jax.tree_util.tree_map(lambda p, g: np.asarray(p) - 0.1 * g,
                                    net.params_, grads)
    return {"pid": pid, "params": np.asarray(flat_param_vector(params))}


def fault_tolerant_train_worker(pid, n, phase="full", workdir="/tmp"):
    """Checkpoint/restart with iterator fast-forward (SURVEY §5.3/§5.4).

    phase="full":   train 6 batches straight through, checkpoint after #3.
    phase="fail":   same, but process 1 dies at batch #5 (fault injection).
    phase="resume": restore the checkpoint + iterator position, finish the
                    remaining batches.
    Each phase ends (if it survives) by allgathering the flat params to
    prove the gang is alive and bitwise-identical across processes.
    """
    import jax
    from jax.experimental import multihost_utils
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator, ResumableIterator
    from deeplearning4j_tpu.io.model_serializer import read_iterator_state
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Trainer
    from deeplearning4j_tpu.utils.pytree import flat_param_vector

    x, y = global_batch(n=24, seed=1)
    batches = [DataSet(x[i:i + 4], y[i:i + 4]) for i in range(0, 24, 4)]
    iterator = ResumableIterator(ListDataSetIterator(batches))
    ckpt = os.path.join(workdir, "cluster_ckpt.zip")

    if phase == "resume":
        net = MultiLayerNetwork.load(ckpt)
        iterator.set_state(read_iterator_state(ckpt))
        start = iterator.batch_index
    else:
        net = _small_net()
        start = 0

    trainer = Trainer(net)
    key = jax.random.key(123)
    for i, batch in enumerate(iterator, start=start):
        key, sub = jax.random.split(key)
        trainer.fit_batch(batch, sub)
        if phase != "resume" and i == 2 and pid == 0:
            net.save(ckpt, iterator_state=iterator.state())
        if phase == "fail" and i == 4 and pid == 1:
            os._exit(3)          # fault injection: hard-kill this process

    flat = np.asarray(flat_param_vector(net.params_))
    gathered = np.asarray(multihost_utils.process_allgather(
        jax.numpy.asarray(flat)))
    return {"pid": pid, "params": flat,
            "all_equal": bool(np.allclose(gathered, gathered[0:1], atol=0)),
            "batches_seen": iterator.batch_index - start}


def dcn_socket_allreduce_worker(pid, n, port=23401, steps=8):
    """Slice-leader role: compressed cross-slice allreduce with REAL
    bytes over the loopback SocketTransport (AeronUdpTransport parity).
    Each rank contributes deterministic per-rank gradients; returns the
    per-step sums so the test can check cross-rank agreement and the
    error-feedback convergence property."""
    import numpy as np
    from deeplearning4j_tpu.parallel.dcn import (CompressedAllReducer,
                                                 SocketTransport)

    size = 384
    transport = SocketTransport(pid, n, port=port)
    reducer = CompressedAllReducer(pid, size, transport)
    rng = np.random.default_rng(100 + pid)
    grads = [rng.normal(0, 0.05, size).astype(np.float32)
             for _ in range(steps)]
    sums = [reducer.allreduce(g) for g in grads]
    stats = {"bytes_sent": transport.bytes_sent,
             "bytes_received": transport.bytes_received}
    transport.close()
    return {"pid": pid,
            "sums": np.stack(sums),
            "grads": np.stack(grads),
            "residual": np.asarray(reducer.accumulator.residual),
            **stats}


def dcn_multislice_fit_worker(pid, n, phase="full", workdir="/tmp",
                              port=23601):
    """Production multi-slice fit: each PROCESS is one slice leader
    running MultiSliceTrainer(world_size=n) over a ring SocketTransport
    with on-device encode + overlapped exchange — the multi-process
    SharedTrainingMaster replacement (VERDICT r4 next #1c).

    phase="full":   6 steps straight through, checkpoint after step #3.
    phase="fail":   same, but process 1 hard-exits at step #5.
    phase="resume": restore net + iterator + codec state, finish.
    """
    import pickle

    import jax
    from jax.experimental import multihost_utils
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                   ResumableIterator)
    from deeplearning4j_tpu.io.model_serializer import read_iterator_state
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.compression import (
        AdaptiveThresholdAlgorithm)
    from deeplearning4j_tpu.parallel.dcn import SocketTransport
    from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
    from deeplearning4j_tpu.utils.pytree import flat_param_vector

    os.makedirs(workdir, exist_ok=True)
    x, y = global_batch(n=48, seed=2)
    # rank-local shard: rank r owns rows [r::n] of each global batch of 8
    batches = [DataSet(x[i:i + 8][pid::n], y[i:i + 8][pid::n])
               for i in range(0, 48, 8)]
    iterator = ResumableIterator(ListDataSetIterator(batches))
    ckpt = os.path.join(workdir, "dcn_ckpt.zip")
    codec_path = os.path.join(workdir, f"dcn_codec_{pid}.pkl")

    if phase == "resume":
        net = MultiLayerNetwork.load(ckpt)
        iterator.set_state(read_iterator_state(ckpt))
        start = iterator.batch_index
    else:
        net = _small_net()
        start = 0

    transport = SocketTransport(pid, n, port=port + {"full": 0, "fail": 10,
                                                     "resume": 20}[phase],
                                timeout=20.0)
    trainer = MultiSliceTrainer(
        net, n_slices=1, world_size=n, rank_offset=pid,
        transports=[transport], device_encode=True, overlap=True,
        devices=jax.local_devices(),   # jax.devices() is GLOBAL here
        algorithm=AdaptiveThresholdAlgorithm(initial_threshold=2e-2))
    if phase == "resume":
        with open(codec_path, "rb") as f:
            trainer.load_codec_state(pickle.load(f))

    key = jax.random.key(123)
    try:
        for i, batch in enumerate(iterator, start=start):
            key, sub = jax.random.split(key)
            trainer.fit_batch(batch, sub)
            if phase != "resume" and i == 2:
                # every rank persists its own codec state; rank 0 owns
                # the model checkpoint (params are identical anyway)
                with open(codec_path, "wb") as f:
                    pickle.dump(trainer.codec_state(), f)
                if pid == 0:
                    trainer.collect()
                    net.save(ckpt, iterator_state=iterator.state())
            if phase == "fail" and i == 4 and pid == 1:
                os._exit(3)      # fault injection: hard-kill this process
        trainer.collect()
    finally:
        trainer.close()
        transport.close()

    flat = np.asarray(flat_param_vector(net.params_))
    gathered = np.asarray(multihost_utils.process_allgather(
        jax.numpy.asarray(flat)))
    return {"pid": pid, "params": flat,
            "all_equal": bool(np.allclose(gathered, gathered[0:1], atol=0)),
            "batches_seen": iterator.batch_index - start,
            "bytes_sent": transport.bytes_sent,
            "dense_bytes_per_step": trainer.grad_size * 4}


def telemetry_train_worker(pid, n, steps=8, straggler_pid=None,
                           delay_s=0.3):
    """Telemetry-federation acceptance rig: train a small net for
    ``steps`` steps; every step stamps onto the coordinator via the
    launcher-injected RemoteStatsRouter (no telemetry code here — the
    Trainer's own notify_step wiring is what is under test).  When this
    process is ``straggler_pid``, a ``delay@trainer.step`` fault makes
    every step slow — the COORDINATOR must flag it as a straggler from
    the federated step times alone."""
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.train.trainer import Trainer

    if straggler_pid is not None and pid == straggler_pid:
        faults.install_fault_plan(faults.FaultPlan.parse(
            f"trainer.step@0:delay:{delay_s}:{steps}"))
    net = _small_net(seed=31 + pid)
    x, y = global_batch(n=16, seed=pid)
    trainer = Trainer(net)
    key = jax.random.key(pid)
    for _ in range(steps):
        key, sub = jax.random.split(key)
        trainer.step_batch(DataSet(x, y), sub)
    return {"pid": pid, "steps": steps}


def hang_worker(pid, n):
    """Fault drill: announce on stderr, then wedge — the launcher's
    timeout path must terminate-then-kill the gang and surface this
    stderr tail in its RuntimeError."""
    import sys
    import time
    print(f"hang_worker {pid} wedged on purpose", file=sys.stderr, flush=True)
    time.sleep(600)
    return {"pid": pid}


def trivial_worker(pid, n):
    """Minimal gang member for launcher startup-retry tests."""
    return {"pid": pid, "n": n}


def stalled_exchange_worker(pid, n):
    """Flight-recorder acceptance rig: one LOCAL MultiSliceTrainer slice
    per process (no cross-process collectives — this jax's CPU backend
    lacks them) whose dcn.exchange is stalled by an injected delay
    (``DL4J_TPU_FAULT_PLAN=dcn.exchange@1:delay:...`` via extra_env).
    Step 0 completes (progress stamps arm the watchdog), step 1 wedges
    in the exchange — the gang-deadline watchdog must dump the black box
    and exit, never return."""
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.dcn import InProcessTransport
    from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer

    net = _small_net(seed=13 + pid)
    x, y = global_batch(n=8, seed=pid)
    # local_devices: under jax.distributed the global device list holds
    # the SIBLING's device too, and CPU lacks multiprocess collectives
    trainer = MultiSliceTrainer(net, n_slices=1, data_per_slice=1,
                                world_size=1,
                                devices=jax.local_devices(),
                                transports=[InProcessTransport(1)])
    key = jax.random.key(0)
    try:
        for _ in range(4):
            trainer.fit_batch(DataSet(x, y), key)
    finally:
        trainer.close()
    return {"pid": pid, "completed": True}


def _supervised_conf(seed):
    """Deterministic net WITH dropout — exact resume must replay the
    RNG trajectory, not just the params."""
    from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
    from deeplearning4j_tpu.nn.layers import (DenseLayer, DropoutLayer,
                                              OutputLayer)
    from deeplearning4j_tpu.train import Adam
    return (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DropoutLayer(dropout=0.8))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())


def supervised_batches(pid, n_batches=6, batch=16):
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.default_rng(11 + pid)
    x = rng.normal(size=(n_batches * batch, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n_batches * batch)]
    return [DataSet(x[i:i + batch], y[i:i + batch])
            for i in range(0, n_batches * batch, batch)]


def run_reference_fit(pid, epochs=2):
    """The uninterrupted single-process run the supervised gang must
    match to 1e-6 — same conf/data/seed as supervised_train_worker."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.obs.listeners import CollectScoresListener
    from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                   ResumableIterator)
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.utils.pytree import flat_param_vector
    net = MultiLayerNetwork(_supervised_conf(42 + pid)).init()
    scores = CollectScoresListener()
    Trainer(net, listeners=[scores]).fit(
        ResumableIterator(ListDataSetIterator(supervised_batches(pid))),
        epochs=epochs)
    return scores.scores, np.asarray(flat_param_vector(net.params_))


def supervised_train_worker(pid, n, workdir=None, epochs=2, kill_at=None,
                            kill_pid=None):
    """THE kill-and-heal acceptance worker: a deterministic fit (dropout
    active) with per-iteration checkpoints; in generation 0,
    ``kill_pid`` SIGKILLs itself before step ``kill_at`` commits.  The
    supervisor respawns the gang; respawned workers resume from their
    own verified checkpoints (``DL4J_TPU_RESUME_FROM``) and report the
    per-step losses they actually ran, so the test can pin the resumed
    tail against the uninterrupted run to 1e-6."""
    from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                   ResumableIterator)
    from deeplearning4j_tpu.io.checkpoint import CheckpointListener
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.obs.listeners import CollectScoresListener
    from deeplearning4j_tpu.resilience import faults, supervisor
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.utils.pytree import flat_param_vector

    generation = int(os.environ.get(supervisor.GENERATION_ENV, "0"))
    if generation == 0 and kill_at is not None and pid == kill_pid:
        faults.install_fault_plan(
            faults.FaultPlan.parse(f"trainer.step@{kill_at}:kill"))
    net = MultiLayerNetwork(_supervised_conf(42 + pid)).init()
    iterator = ResumableIterator(ListDataSetIterator(
        supervised_batches(pid)))
    ckpt_dir = os.path.join(workdir, f"w{pid}")
    ckpt = CheckpointListener(ckpt_dir, save_every_n_iterations=1,
                              keep_last=3, iterator=iterator)
    scores = CollectScoresListener()
    resume = os.environ.get(supervisor.RESUME_ENV)
    Trainer(net, listeners=[scores, ckpt]).fit(
        iterator, epochs=epochs,
        resume_from=(ckpt_dir if resume else None))
    return {"pid": pid, "generation": generation,
            "losses": list(scores.scores),
            "end_iteration": net.iteration,
            "params": np.asarray(flat_param_vector(net.params_))}


def repeatedly_dying_worker(pid, n, die_pid=None, kill_at=2, steps=60):
    """Budget-exhaustion rig: ``die_pid`` SIGKILLs itself EVERY
    generation (installed programmatically, so the supervisor's env
    stripping can't save it); siblings train slowly enough that
    teardown SIGTERMs them mid-fit — their flight-recorder handlers
    write the black boxes the raised error must carry."""
    import jax
    import time as _time
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.train.trainer import Trainer

    if pid == die_pid:
        faults.install_fault_plan(
            faults.FaultPlan.parse(f"trainer.step@{kill_at}:kill"))
    net = _small_net(seed=3 + pid)
    x, y = global_batch(n=16, seed=pid)
    batch = DataSet(x, y)
    trainer = Trainer(net)
    key = jax.random.key(pid)
    for _ in range(steps):
        key, sub = jax.random.split(key)
        trainer.step_batch(batch, sub)
        _time.sleep(0.1)       # stay alive until the supervisor's SIGTERM
    return {"pid": pid, "steps": steps}


def run_elastic_reference(epochs=4):
    """The uninterrupted single-device run an ELASTIC gang must match to
    1e-6 — same conf/data/seed as elastic_train_worker (every worker in
    that gang runs this same trajectory, just laid out dp<width>)."""
    from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                   ResumableIterator)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.obs.listeners import CollectScoresListener
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.utils.pytree import flat_param_vector
    net = MultiLayerNetwork(_supervised_conf(77)).init()
    scores = CollectScoresListener()
    Trainer(net, listeners=[scores]).fit(
        ResumableIterator(ListDataSetIterator(supervised_batches(0))),
        epochs=epochs)
    return scores.scores, np.asarray(flat_param_vector(net.params_))


def elastic_train_worker(pid, n, workdir=None, epochs=4, kill_on_grow=False):
    """THE elastic-gang acceptance worker: a deterministic fit (dropout
    active) laid out ``dp<width>`` over this process's local virtual
    devices, where the width comes from the supervisor's elastic env
    contract (``DL4J_TPU_GANG_WIDTH``) — never hardcoded.  Every worker
    runs the SAME trajectory (same conf/data/seed); slot w0 checkpoints
    every iteration into a SHARED directory, so when the supervisor
    relaunches the gang at a new width, every slot — including brand-new
    ones — resumes from the newest verified checkpoint with params/
    opt-state resharded onto the new-width layout.  PR-14 width
    invariance then makes the post-boundary losses the 1e-6 pin against
    the fixed-width reference.

    ``kill_on_grow``: in a GROW generation (``DL4J_TPU_GANG_GROWN``),
    the new slot w2 installs a ``gang.grow@0:kill`` plan — Trainer fires
    that site right after restoring the checkpoint, so the death lands
    mid-reshard and recovery must ride the normal respawn path."""
    import jax
    from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                   ResumableIterator)
    from deeplearning4j_tpu.io.checkpoint import CheckpointListener
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.obs import remote as obs_remote
    from deeplearning4j_tpu.obs.listeners import CollectScoresListener
    from deeplearning4j_tpu.parallel import mesh as mesh_mod
    from deeplearning4j_tpu.resilience import elastic, faults, supervisor
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.utils.pytree import flat_param_vector

    width = elastic.configured_width(default=n)
    slot = os.environ.get(obs_remote.WORKER_ENV, f"w{pid}")
    if kill_on_grow and elastic.is_grown_child() and slot == "w2":
        faults.install_fault_plan(
            faults.FaultPlan.parse("gang.grow@0:kill"))
    # local devices only: CPU loopback has no cross-process collectives
    # (the established MultiSliceTrainer translation)
    layout = mesh_mod.resolve_layout(
        layout=f"dp{width}", devices=jax.local_devices()[:width])
    net = MultiLayerNetwork(_supervised_conf(77)).init()
    iterator = ResumableIterator(ListDataSetIterator(supervised_batches(0)))
    scores = CollectScoresListener()
    listeners = [scores]
    ckpt_dir = os.path.join(workdir, "shared")
    if slot == "w0":
        listeners.append(CheckpointListener(
            ckpt_dir, save_every_n_iterations=1, keep_last=3,
            iterator=iterator))
    resume = os.environ.get(supervisor.RESUME_ENV)
    Trainer(net, listeners=listeners, layout=layout).fit(
        iterator, epochs=epochs,
        resume_from=(ckpt_dir if resume else None))
    return {"pid": pid, "slot": slot, "width": width,
            "generation": int(os.environ.get(supervisor.GENERATION_ENV,
                                             "0")),
            "grown": elastic.is_grown_child(),
            "losses": list(scores.scores),
            "end_iteration": net.iteration,
            "params": np.asarray(flat_param_vector(net.params_))}


def slot_gated_dying_worker(pid, n, steps=6, workdir=None):
    """Shrink-degradation rig: the worker whose STABLE slot id (the
    supervisor-assigned DL4J_TPU_WORKER_ID, not the process index) is
    ``w1`` SIGKILLs itself every generation; the rest finish quickly.
    Under degradation="shrink" the gang must continue without slot 1."""
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.obs import remote as obs_remote
    from deeplearning4j_tpu.resilience import faults
    from deeplearning4j_tpu.train.trainer import Trainer

    slot = os.environ.get(obs_remote.WORKER_ENV, f"w{pid}")
    if slot == "w1":
        faults.install_fault_plan(
            faults.FaultPlan.parse("trainer.step@2:kill"))
    net = _small_net(seed=5 + pid)
    x, y = global_batch(n=16, seed=pid)
    trainer = Trainer(net)
    key = jax.random.key(pid)
    for _ in range(steps):
        key, sub = jax.random.split(key)
        trainer.step_batch(DataSet(x, y), sub)
    return {"pid": pid, "slot": slot, "steps": steps}
