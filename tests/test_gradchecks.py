"""Per-layer-type numerical gradient checks — the GradientCheckUtil spine.

Parity with deeplearning4j-nn ``gradientcheck/GradientCheckUtil.java`` and
its suites (GradientCheckTests, CNNGradientCheckTest,
LSTMGradientCheckTests): every registered layer type is exercised inside a
small full network and its end-to-end loss gradient is validated against
central differences in float64 on CPU.  Every registered loss function is
checked the same way through an OutputLayer.

Run in x64: central differences in f32 are too noisy for a 1e-3 rel bound.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.gradcheck import check_model_gradients
from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import *  # noqa: F401,F403
from deeplearning4j_tpu.nn.layers.base import layer_registry
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Sgd


_CASE_COUNTER = iter(range(10 ** 9))


@pytest.fixture(autouse=True)
def _periodic_cache_clear():
    """XLA:CPU segfaults inside backend_compile after ~50 accumulated
    f64 compilations in one process (state-dependent compiler bug:
    reproducible at the 48th test of this module under the 8-device CPU
    mesh, passes in isolation).  Dropping the jit caches every few cases
    keeps the compiler out of the poisoned state."""
    yield
    if next(_CASE_COUNTER) % 8 == 7:
        jax.clear_caches()


@pytest.fixture(autouse=True, scope="module")
def _x64():
    jax.config.update("jax_enable_x64", True)
    set_dtype_policy(DTypePolicy(param_dtype=jnp.float64,
                                 compute_dtype=jnp.float64,
                                 output_dtype=jnp.float64))
    yield
    set_dtype_policy(DTypePolicy.f32())
    jax.config.update("jax_enable_x64", False)


def _r():
    return np.random.default_rng(0)


def _ff_batch(n_in, n_out, b=4):
    r = _r()
    x = r.normal(size=(b, n_in))
    y = np.eye(n_out)[r.integers(0, n_out, b)]
    return DataSet(x, y)


def _rnn_batch(n_in, n_out, t=5, b=3):
    r = _r()
    x = r.normal(size=(b, t, n_in))
    y = np.zeros((b, t, n_out))
    y[np.arange(b)[:, None], np.arange(t)[None, :],
      r.integers(0, n_out, (b, t))] = 1.0
    return DataSet(x, y)


def _cnn_batch(h, w, c, n_out, b=2):
    r = _r()
    x = r.normal(size=(b, h, w, c))
    y = np.eye(n_out)[r.integers(0, n_out, b)]
    return DataSet(x, y)


def _cnn3d_batch(d, h, w, c, n_out, b=2):
    r = _r()
    x = r.normal(size=(b, d, h, w, c))
    y = np.eye(n_out)[r.integers(0, n_out, b)]
    return DataSet(x, y)


FF_OUT = lambda n=3: OutputLayer(n_out=n, activation="softmax", loss="mcxent")
RNN_OUT = lambda n=3: RnnOutputLayer(n_out=n, activation="softmax", loss="mcxent")

# type-name → (layers, input_type, batch builder).  Smooth activations
# (tanh/softplus) keep the central difference well-behaved; max-pool /
# relu kinks are measure-zero under the random inputs.
LAYER_CASES = {
    "dense": ([DenseLayer(n_out=6, activation="tanh"), FF_OUT()],
              InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "output": ([FF_OUT()], InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "loss": ([DenseLayer(n_out=3, activation="softmax"), LossLayer(loss="mcxent")],
             InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "activation": ([DenseLayer(n_out=6, activation="identity"),
                    ActivationLayer(activation="softplus"), FF_OUT()],
                   InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "dropout": ([DropoutLayer(dropout=0.5), DenseLayer(n_out=6, activation="tanh"),
                 FF_OUT()],
                InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "embedding": ([EmbeddingLayer(n_in=7, n_out=5), DenseLayer(n_out=6, activation="tanh"),
                   FF_OUT()],
                  InputType.feed_forward(1),
                  lambda: DataSet(_r().integers(0, 7, (4, 1)).astype(np.float64),
                                  np.eye(3)[_r().integers(0, 3, 4)])),
    "embedding_sequence": ([EmbeddingSequenceLayer(n_in=7, n_out=5), RNN_OUT()],
                           InputType.recurrent(1, 5),
                           lambda: DataSet(
                               _r().integers(0, 7, (3, 5, 1)).astype(np.float64),
                               np.eye(3)[_r().integers(0, 3, (3, 5))])),
    "batch_norm": ([DenseLayer(n_out=6, activation="tanh"), BatchNormalization(),
                    FF_OUT()],
                   InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "layer_norm": ([DenseLayer(n_out=6, activation="tanh"), LayerNormalization(),
                    FF_OUT()],
                   InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "prelu": ([DenseLayer(n_out=6, activation="identity"), PReLULayer(), FF_OUT()],
              InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "conv2d": ([ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="tanh"),
                GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
               InputType.convolutional(8, 8, 2), lambda: _cnn_batch(8, 8, 2, 3)),
    "conv1d": ([Convolution1DLayer(n_out=4, kernel_size=3, activation="tanh"), RNN_OUT()],
               InputType.recurrent(2, 6),
               # truncate mode: t 6→4, labels must match the output length
               lambda: DataSet(_r().normal(size=(3, 6, 2)),
                               _rnn_batch(3, 3, t=4).labels)),
    "conv3d": ([Convolution3DLayer(n_out=3, kernel_size=(2, 2, 2), activation="tanh"),
                GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
               InputType.convolutional3d(4, 4, 4, 2),
               lambda: _cnn3d_batch(4, 4, 4, 2, 3)),
    "separable_conv2d": ([SeparableConvolution2D(n_out=4, kernel_size=(3, 3),
                                                 activation="tanh"),
                          GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                         InputType.convolutional(8, 8, 2),
                         lambda: _cnn_batch(8, 8, 2, 3)),
    "depthwise_conv2d": ([DepthwiseConvolution2D(depth_multiplier=2, kernel_size=(3, 3),
                                                 activation="tanh"),
                          GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                         InputType.convolutional(8, 8, 2),
                         lambda: _cnn_batch(8, 8, 2, 3)),
    "deconv2d": ([Deconvolution2D(n_out=4, kernel_size=(3, 3), activation="tanh"),
                  GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                 InputType.convolutional(6, 6, 2), lambda: _cnn_batch(6, 6, 2, 3)),
    "subsampling": ([ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="tanh"),
                     SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                     GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                    InputType.convolutional(8, 8, 2), lambda: _cnn_batch(8, 8, 2, 3)),
    "subsampling1d": ([Convolution1DLayer(n_out=4, kernel_size=3, activation="tanh"),
                       Subsampling1DLayer(kernel_size=2, stride=2), RNN_OUT()],
                      InputType.recurrent(2, 8),
                      # conv t 8→6, pool 6→3
                      lambda: DataSet(_r().normal(size=(3, 8, 2)),
                                      _rnn_batch(3, 3, t=3).labels)),
    "subsampling3d": ([Convolution3DLayer(n_out=3, kernel_size=(2, 2, 2), activation="tanh"),
                       Subsampling3DLayer(kernel_size=(2, 2, 2), stride=(2, 2, 2)),
                       GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                      InputType.convolutional3d(4, 4, 4, 2),
                      lambda: _cnn3d_batch(4, 4, 4, 2, 3)),
    "upsampling2d": ([ConvolutionLayer(n_out=3, kernel_size=(3, 3), activation="tanh"),
                      UpsamplingLayer(size=2),
                      GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                     InputType.convolutional(6, 6, 2), lambda: _cnn_batch(6, 6, 2, 3)),
    "zero_padding": ([ZeroPaddingLayer(padding=(1, 1, 1, 1)),
                      ConvolutionLayer(n_out=3, kernel_size=(3, 3), activation="tanh"),
                      GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                     InputType.convolutional(6, 6, 2), lambda: _cnn_batch(6, 6, 2, 3)),
    "cropping2d": ([CroppingLayer(cropping=(1, 1, 1, 1)),
                    ConvolutionLayer(n_out=3, kernel_size=(3, 3), activation="tanh"),
                    GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                   InputType.convolutional(8, 8, 2), lambda: _cnn_batch(8, 8, 2, 3)),
    "space_to_depth": ([SpaceToDepthLayer(block_size=2),
                        ConvolutionLayer(n_out=3, kernel_size=(1, 1), activation="tanh"),
                        GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                       InputType.convolutional(6, 6, 2), lambda: _cnn_batch(6, 6, 2, 3)),
    "global_pooling": ([ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="tanh"),
                        GlobalPoolingLayer(pooling_type="pnorm"), FF_OUT()],
                       InputType.convolutional(6, 6, 2), lambda: _cnn_batch(6, 6, 2, 3)),
    "lrn": ([ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="tanh"),
             LocalResponseNormalization(),
             GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
            InputType.convolutional(6, 6, 2), lambda: _cnn_batch(6, 6, 2, 3)),
    "lstm": ([LSTM(n_out=5), RNN_OUT()],
             InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "graves_lstm": ([GravesLSTM(n_out=5), RNN_OUT()],
                    InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "simple_rnn": ([SimpleRnn(n_out=5), RNN_OUT()],
                   InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "gru": ([GRU(n_out=5), RNN_OUT()],
            InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "bidirectional": ([Bidirectional(fwd=LSTM(n_out=4), mode="concat"), RNN_OUT()],
                      InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "last_time_step": ([LastTimeStep(underlying=LSTM(n_out=5)), FF_OUT()],
                       InputType.recurrent(3, 5),
                       lambda: DataSet(_r().normal(size=(3, 5, 3)),
                                       np.eye(3)[_r().integers(0, 3, 3)])),
    "time_distributed": ([LSTM(n_out=5),
                          TimeDistributed(underlying=DenseLayer(n_out=4, activation="tanh")),
                          RNN_OUT()],
                         InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "rnn_output": ([SimpleRnn(n_out=5), RNN_OUT()],
                   InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "rnn_loss": ([SimpleRnn(n_out=3, activation="identity"),
                  ActivationLayer(activation="softmax"), RnnLossLayer(loss="mcxent")],
                 InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "self_attention": ([SelfAttentionLayer(n_heads=2), RNN_OUT()],
                       InputType.recurrent(4, 5), lambda: _rnn_batch(4, 3)),
    "learned_self_attention": ([LearnedSelfAttentionLayer(n_heads=2, n_queries=3),
                                GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                               InputType.recurrent(4, 5),
                               lambda: DataSet(_r().normal(size=(3, 5, 4)),
                                               np.eye(3)[_r().integers(0, 3, 3)])),
    # ---- layer-catalog tail (nn/layers/extra.py) -----------------------
    "zero_padding1d": ([ZeroPadding1DLayer(padding=1), RNN_OUT()],
                       InputType.recurrent(3, 5),
                       lambda: DataSet(_r().normal(size=(3, 5, 3)),
                                       _rnn_batch(3, 3, t=7).labels)),
    "cropping1d": ([Cropping1DLayer(cropping=1), RNN_OUT()],
                   InputType.recurrent(3, 5),
                   lambda: DataSet(_r().normal(size=(3, 5, 3)),
                                   _rnn_batch(3, 3, t=3).labels)),
    "upsampling1d": ([Upsampling1DLayer(size=2), RNN_OUT()],
                     InputType.recurrent(3, 4),
                     lambda: DataSet(_r().normal(size=(3, 4, 3)),
                                     _rnn_batch(3, 3, t=8).labels)),
    "zero_padding3d": ([ZeroPadding3DLayer(padding=1),
                        GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                       InputType.convolutional3d(3, 3, 3, 2),
                       lambda: _cnn3d_batch(3, 3, 3, 2, 3)),
    "cropping3d": ([Cropping3DLayer(cropping=1),
                    GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                   InputType.convolutional3d(4, 4, 4, 2),
                   lambda: _cnn3d_batch(4, 4, 4, 2, 3)),
    "upsampling3d": ([Upsampling3DLayer(size=2),
                      GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                     InputType.convolutional3d(2, 2, 2, 2),
                     lambda: _cnn3d_batch(2, 2, 2, 2, 3)),
    "space_to_batch": ([SpaceToBatchLayer(blocks=2),
                        GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                       InputType.convolutional(4, 4, 2),
                       # blocks 2x2 quadruple the batch: labels for 4*B rows
                       lambda: DataSet(_r().normal(size=(2, 4, 4, 2)),
                                       np.eye(3)[_r().integers(0, 3, 8)])),
    "gaussian_dropout": ([GaussianDropoutLayer(rate=0.1),
                          DenseLayer(n_out=5, activation="tanh"), FF_OUT()],
                         InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "gaussian_noise": ([GaussianNoiseLayer(stddev=0.1),
                        DenseLayer(n_out=5, activation="tanh"), FF_OUT()],
                       InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "alpha_dropout": ([AlphaDropoutLayer(p=0.9),
                       DenseLayer(n_out=5, activation="tanh"), FF_OUT()],
                      InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "spatial_dropout": ([SpatialDropoutLayer(p=0.9),
                         ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                          activation="tanh"),
                         GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                        InputType.convolutional(6, 6, 2),
                        lambda: _cnn_batch(6, 6, 2, 3)),
    "locally_connected1d": ([LocallyConnected1D(n_out=4, kernel=3,
                                                activation="tanh"), RNN_OUT()],
                            InputType.recurrent(2, 6),
                            lambda: DataSet(_r().normal(size=(3, 6, 2)),
                                            _rnn_batch(3, 3, t=4).labels)),
    "locally_connected2d": ([LocallyConnected2D(n_out=4, kernel=3,
                                                activation="tanh"),
                             GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                            InputType.convolutional(6, 6, 2),
                            lambda: _cnn_batch(6, 6, 2, 3)),
    "element_wise_mult": ([DenseLayer(n_out=5, activation="tanh"),
                           ElementWiseMultiplicationLayer(n_out=5,
                                                          activation="tanh"),
                           FF_OUT()],
                          InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "repeat_vector": ([DenseLayer(n_out=5, activation="tanh"),
                       RepeatVector(n=4), RNN_OUT()],
                      InputType.feed_forward(4),
                      lambda: DataSet(_r().normal(size=(3, 4)),
                                      _rnn_batch(3, 3, t=4).labels)),
    "mask_zero": ([MaskZeroLayer(underlying=LSTM(n_out=5)), RNN_OUT()],
                  InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    "permute": ([PermuteLayer(dims=(2, 1)), RNN_OUT()],
                InputType.recurrent(3, 4),
                lambda: DataSet(_r().normal(size=(3, 4, 3)),
                                _rnn_batch(3, 3, t=3).labels)),
    "separable_conv1d": ([SeparableConvolution1D(n_out=4, kernel_size=3,
                                                 activation="tanh"),
                          RNN_OUT()],
                         InputType.recurrent(2, 6),
                         lambda: DataSet(_r().normal(size=(3, 6, 2)),
                                         _rnn_batch(3, 3, t=4).labels)),
    "conv_lstm2d": ([ConvLSTM2D(n_out=3, kernel_size=(2, 2),
                                convolution_mode="same"),
                     GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                    InputType.convolutional3d(3, 4, 4, 2),
                    lambda: DataSet(_r().normal(size=(2, 3, 4, 4, 2)),
                                    np.eye(3)[_r().integers(0, 3, 2)])),
    "bidirectional_last": ([BidirectionalLastStep(fwd=LSTM(n_out=4),
                                                  mode="concat"), FF_OUT()],
                           InputType.recurrent(3, 5),
                           lambda: DataSet(_r().normal(size=(3, 5, 3)),
                                           np.eye(3)[_r().integers(0, 3, 3)])),
    "graves_bidirectional_lstm": ([GravesBidirectionalLSTM(n_out=5), RNN_OUT()],
                                  InputType.recurrent(3, 5),
                                  lambda: _rnn_batch(3, 3)),
    "center_loss_output": ([DenseLayer(n_out=6, activation="tanh"),
                            CenterLossOutputLayer(n_out=3, activation="softmax",
                                                  loss="mcxent", lambda_=1e-2)],
                           InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
    "yolo2_output": ([ConvolutionLayer(n_out=14, kernel_size=(1, 1),
                                       activation="identity"),
                      Yolo2OutputLayer(anchors=((1.0, 1.5), (2.0, 1.0)),
                                       num_classes=2)],
                     InputType.convolutional(3, 3, 4),
                     lambda: DataSet(_r().normal(size=(2, 3, 3, 4)),
                                     _yolo_batch(3, 3, 2, 2).labels)),
    "vae": ([VariationalAutoencoder(n_out=3, encoder_layer_sizes=(6,),
                                    decoder_layer_sizes=(6,),
                                    activation="tanh",
                                    reconstruction="gaussian")],
            InputType.feed_forward(4),
            lambda: (lambda x: DataSet(x, x))(_r().normal(size=(3, 4)))),
    "primary_capsules": ([PrimaryCapsules(capsules=2, capsule_dimensions=4,
                                          kernel=3, stride=2),
                          GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                         InputType.convolutional(7, 7, 2),
                         lambda: _cnn_batch(7, 7, 2, 3)),
    "capsules": ([CapsuleLayer(capsules=3, capsule_dimensions=4, routings=2),
                  GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                 InputType.recurrent(4, 6),
                 lambda: DataSet(_r().normal(size=(3, 6, 4)),
                                 np.eye(3)[_r().integers(0, 3, 3)])),
    "capsule_strength": ([CapsuleStrengthLayer(), FF_OUT()],
                         InputType.recurrent(4, 5),
                         lambda: DataSet(_r().normal(size=(3, 5, 4)),
                                         np.eye(3)[_r().integers(0, 3, 3)])),
    "recurrent_attention": ([RecurrentAttentionLayer(n_out=4, activation="tanh"),
                             RNN_OUT()],
                            InputType.recurrent(3, 5), lambda: _rnn_batch(3, 3)),
    # relu kinks are measure-zero under random inputs (as for max-pool);
    # f64 policy routes matmul_bn_act through its exact reference path
    "fused_bottleneck": ([FusedBottleneck(filters=(3, 3, 8), project=True),
                          GlobalPoolingLayer(pooling_type="avg"), FF_OUT()],
                         InputType.convolutional(6, 6, 4),
                         lambda: _cnn_batch(6, 6, 4, 3)),
    # generous capacity: no token drops, so routing is locally constant
    # and the loss is differentiable at the sampled inputs
    "mixture_of_experts": ([MixtureOfExperts(n_experts=3, hidden=6, top_k=2,
                                             capacity_factor=3.0,
                                             activation="tanh"),
                            FF_OUT()],
                           InputType.feed_forward(4), lambda: _ff_batch(4, 3)),
}


def _yolo_batch(h, w, a, c, b=2):
    """Grid labels: per anchor (tx,ty,tw,th,obj,classes) with obj∈{0,1}
    and one-hot classes on object cells."""
    r = _r()
    x = r.normal(size=(b, h, w, a * (5 + c)))
    y = np.zeros((b, h, w, a, 5 + c))
    obj = r.integers(0, 2, (b, h, w, a))
    y[..., 0:2] = r.uniform(0.2, 0.8, (b, h, w, a, 2))
    y[..., 2:4] = r.normal(0, 0.3, (b, h, w, a, 2))
    y[..., 4] = obj
    cls = np.eye(c)[r.integers(0, c, (b, h, w, a))]
    y[..., 5:] = cls * obj[..., None]
    return DataSet(x, y.reshape(b, h, w, a * (5 + c)))


def test_all_registered_layer_types_have_gradcheck_cases():
    """Every type in the registry must appear in LAYER_CASES — adding a
    layer without a gradcheck fails the suite (OpValidation's coverage
    discipline applied to layers)."""
    registered = set(layer_registry())
    missing = registered - set(LAYER_CASES)
    assert not missing, f"layer types without gradcheck cases: {sorted(missing)}"


@pytest.mark.parametrize("type_name", sorted(LAYER_CASES))
def test_layer_gradcheck(type_name):
    layers, itype, batch_fn = LAYER_CASES[type_name]
    builder = NeuralNetConfiguration.builder().seed(12345).updater(Sgd(0.1)).list()
    for layer in layers:
        builder = builder.layer(layer)
    conf = builder.set_input_type(itype).build()
    net = MultiLayerNetwork(conf).init()
    report = check_model_gradients(net, batch_fn(), eps=1e-5,
                                   max_rel_error=1e-3,
                                   max_checks_per_leaf=10)
    assert report["checked"] > 0, f"{type_name}: no gradient entries checked"


SMOOTH_LOSS_DATA = {
    # loss name → (activation, labels builder over (b, n))
    "mcxent": ("softmax", lambda b, n: np.eye(n)[_r().integers(0, n, b)]),
    "sparse_mcxent": ("softmax", lambda b, n: _r().integers(0, n, (b,)).astype(np.float64)),
    "binary_xent": ("sigmoid", lambda b, n: _r().integers(0, 2, (b, n)).astype(np.float64)),
    "mse": ("identity", lambda b, n: _r().normal(size=(b, n))),
    "l2": ("identity", lambda b, n: _r().normal(size=(b, n))),
    "mae": ("identity", lambda b, n: _r().normal(size=(b, n))),
    "l1": ("identity", lambda b, n: _r().normal(size=(b, n))),
    "msle": ("sigmoid", lambda b, n: _r().uniform(0.1, 2.0, (b, n))),
    "mape": ("identity", lambda b, n: _r().uniform(0.5, 2.0, (b, n))),
    "poisson": ("softplus", lambda b, n: _r().uniform(0.1, 3.0, (b, n))),
    "kld": ("softmax", lambda b, n: (lambda p: p / p.sum(-1, keepdims=True))(
        _r().uniform(0.1, 1.0, (b, n)))),
    "kl_divergence": ("softmax", lambda b, n: (lambda p: p / p.sum(-1, keepdims=True))(
        _r().uniform(0.1, 1.0, (b, n)))),
    "cosine_proximity": ("identity", lambda b, n: _r().normal(size=(b, n))),
    "hinge": ("identity", lambda b, n: 2.0 * _r().integers(0, 2, (b, n)) - 1.0),
    "squared_hinge": ("identity", lambda b, n: 2.0 * _r().integers(0, 2, (b, n)) - 1.0),
    "wasserstein": ("identity", lambda b, n: 2.0 * _r().integers(0, 2, (b, n)) - 1.0),
    "fmeasure": ("sigmoid", lambda b, n: _r().integers(0, 2, (b, n)).astype(np.float64)),
    # |err| = delta kink is measure-zero under random labels
    "huber": ("identity", lambda b, n: _r().normal(size=(b, n))),
    "log_poisson": ("identity", lambda b, n: _r().uniform(0.1, 3.0, (b, n))),
    # labels fixed during the check: the labels>1 Stirling gate is constant
    "log_poisson_full": ("identity", lambda b, n: _r().uniform(0.1, 3.0, (b, n))),
    "weighted_cross_entropy_with_logits": (
        "identity", lambda b, n: _r().integers(0, 2, (b, n)).astype(np.float64)),
    "mean_pairwise_squared_error": (
        "identity", lambda b, n: _r().normal(size=(b, n))),
}


def test_all_registered_losses_have_gradcheck_cases():
    """Every DISTINCT loss function (names() includes aliases) must have a
    gradcheck case under at least one of its names."""
    covered_fns = {id(losses_mod.get(n)) for n in SMOOTH_LOSS_DATA}
    missing = [n for n in losses_mod.names()
               if id(losses_mod.get(n)) not in covered_fns]
    assert not missing, f"losses without gradcheck cases: {sorted(missing)}"


@pytest.mark.parametrize("loss_name", sorted(SMOOTH_LOSS_DATA))
def test_loss_gradcheck(loss_name):
    act, label_fn = SMOOTH_LOSS_DATA[loss_name]
    n = 4
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=5, activation="tanh"))
            .layer(OutputLayer(n_out=n, activation=act, loss=loss_name))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    b = 4
    x = _r().normal(size=(b, 3))
    batch = DataSet(x, label_fn(b, n))
    report = check_model_gradients(net, batch, eps=1e-5, max_rel_error=1e-3,
                                   max_checks_per_leaf=10)
    assert report["checked"] > 0
