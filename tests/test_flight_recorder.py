"""Flight recorder: ring semantics, dump contents, watchdog behavior,
and the ISSUE-6 acceptance rig — a fault-injected stalled DCN exchange
under spawn_local_cluster raises within the gang deadline with a
per-child black box (thread stacks + the last N spans) on the error."""

import functools
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import cluster_workers  # noqa: E402

from deeplearning4j_tpu.obs import flight_recorder, tracing  # noqa: E402

_ENV = {"PYTHONPATH": os.path.dirname(__file__) + os.pathsep +
        os.environ.get("PYTHONPATH", "")}


class TestRing:
    def test_ring_is_bounded_and_ordered(self):
        rec = flight_recorder.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("step", iteration=i)
        events = rec.events()
        assert len(events) == 4
        assert [e["iteration"] for e in events] == [6, 7, 8, 9]
        assert all(e["kind"] == "step" for e in events)

    def test_progress_tracks_latest_site(self):
        rec = flight_recorder.FlightRecorder()
        rec.progress("trainer.step")
        time.sleep(0.01)
        rec.progress("dcn.exchange")
        site, stamp, count = rec.last_progress()
        assert site == "dcn.exchange"
        assert count == 2
        assert time.monotonic() - stamp < 5.0

    def test_spans_mirror_into_the_global_ring(self):
        rec = flight_recorder.get_recorder()
        rec.clear()
        with tracing.use_tracer(tracing.Tracer(enabled=True)):
            with tracing.span("fit", model="m"):
                with tracing.span("step", iteration=3):
                    pass
        names = [e["name"] for e in rec.events() if e["kind"] == "span"]
        assert names == ["step", "fit"]      # finish order
        step_ev = next(e for e in rec.events()
                       if e["kind"] == "span" and e["name"] == "step")
        assert step_ev["attributes"]["iteration"] == 3
        assert step_ev["trace_id"]


class TestDump:
    def test_dump_schema(self, tmp_path):
        rec = flight_recorder.FlightRecorder()
        rec.record("step", iteration=7)
        rec.progress("trainer.step")
        path = rec.dump(str(tmp_path / "box.jsonl"), reason="explicit",
                        detail={"why": "test"})
        lines = flight_recorder.read_dump(path)
        by_type = {}
        for line in lines:
            by_type.setdefault(line["type"], []).append(line)
        assert by_type["header"][0]["reason"] == "explicit"
        assert by_type["header"][0]["pid"] == os.getpid()
        assert by_type["header"][0]["detail"] == {"why": "test"}
        assert by_type["liveness"][0]["last_site"] == "trainer.step"
        # every live thread contributes a stack; this test's own frame
        # is in the main thread's stack
        assert len(by_type["thread"]) >= 1
        assert any("test_dump_schema" in "".join(t["stack"])
                   for t in by_type["thread"])
        assert any(e.get("kind") == "step" and e.get("iteration") == 7
                   for e in by_type["event"])
        assert isinstance(by_type["metrics"][0]["values"], dict)
        assert "device" in by_type

    def test_dump_appends_and_tolerates_partial_lines(self, tmp_path):
        rec = flight_recorder.FlightRecorder()
        path = str(tmp_path / "box.jsonl")
        rec.dump(path, reason="first")
        rec.dump(path, reason="second")
        with open(path, "a") as f:
            f.write('{"type": "torn')     # killed mid-write
        lines = flight_recorder.read_dump(path)
        reasons = [l["reason"] for l in lines if l["type"] == "header"]
        assert reasons == ["first", "second"]


class TestWatchdog:
    def test_fires_on_stall_after_arming(self, tmp_path):
        rec = flight_recorder.FlightRecorder()
        fired = []
        wd = flight_recorder.Watchdog(
            0.5, recorder=rec, dump_path=str(tmp_path / "wd.jsonl"),
            on_fire=fired.append, arm_on_first_progress=True, poll_s=0.05)
        try:
            # not armed yet: well past the deadline with no progress
            time.sleep(0.8)
            assert not wd.fired.is_set()
            rec.progress("dcn.exchange")
            time.sleep(1.0)
            assert wd.fired.is_set()
        finally:
            wd.stop()
        assert fired and fired[0]["stalled_site"] == "dcn.exchange"
        lines = flight_recorder.read_dump(str(tmp_path / "wd.jsonl"))
        header = next(l for l in lines if l["type"] == "header")
        assert header["reason"] == "watchdog"

    def test_does_not_fire_while_progress_flows(self, tmp_path):
        rec = flight_recorder.FlightRecorder()
        wd = flight_recorder.Watchdog(
            0.4, recorder=rec, dump_path=str(tmp_path / "wd.jsonl"),
            arm_on_first_progress=False, poll_s=0.05)
        try:
            for _ in range(10):
                rec.progress("trainer.step")
                time.sleep(0.1)
            assert not wd.fired.is_set()
        finally:
            wd.stop()

    def test_grace_fire_re_arms_instead_of_exiting(self, tmp_path):
        """fires_before_exit=2 (the dryrun_multichip setting): one slow
        phase costs a dump and a re-arm, not the process — only two
        consecutive dead deadlines reach the final (exiting) fire."""
        rec = flight_recorder.FlightRecorder()
        fired = []
        wd = flight_recorder.Watchdog(
            0.4, recorder=rec, dump_path=str(tmp_path / "wd.jsonl"),
            on_fire=fired.append, arm_on_first_progress=False,
            poll_s=0.05, fires_before_exit=2)
        try:
            rec.progress("multichip.phase")
            time.sleep(0.7)               # one dead deadline → grace fire
            assert len(fired) == 1
            assert fired[0]["fire"] == 1
            rec.progress("multichip.phase")   # "compile finished"
            time.sleep(0.25)
            assert len(fired) == 1        # progress reset the count
            time.sleep(0.7)               # dead again → fire 1 of 2 again
            time.sleep(0.5)               # still dead → final fire
            assert len(fired) >= 3
            assert any(f["fire"] >= 2 for f in fired)
        finally:
            wd.stop()
        reasons = [l["detail"]["fire"] for l in
                   flight_recorder.read_dump(str(tmp_path / "wd.jsonl"))
                   if l["type"] == "header"]
        assert reasons[0] == 1 and max(reasons) >= 2

    def test_grace_window_aborts_exit_on_late_progress(self, tmp_path,
                                                       monkeypatch):
        """The final fire holds the exit for exit_grace_s (so sibling
        black boxes land first) — real progress inside that window means
        the process is alive and must NOT be reported as a stall."""
        exits = []
        monkeypatch.setattr(flight_recorder.os, "_exit",
                            lambda code: exits.append(code))
        rec = flight_recorder.FlightRecorder()
        wd = flight_recorder.Watchdog(
            0.4, recorder=rec, dump_path=str(tmp_path / "wd.jsonl"),
            exit_code=87, arm_on_first_progress=False, poll_s=0.05,
            exit_grace_s=1.0)
        try:
            assert wd.fired.wait(timeout=5)
            rec.progress("trainer.step")    # lands inside the grace
            time.sleep(1.2)                 # past the grace re-check
            assert exits == []              # late progress: re-armed
        finally:
            wd.stop()
        assert exits == []

    def test_grace_window_aborts_exit_on_clean_stop(self, tmp_path,
                                                    monkeypatch):
        """stop() racing the final fire (a main thread finishing just
        past the deadline) must win over the pending os._exit."""
        exits = []
        monkeypatch.setattr(flight_recorder.os, "_exit",
                            lambda code: exits.append(code))
        rec = flight_recorder.FlightRecorder()
        wd = flight_recorder.Watchdog(
            0.4, recorder=rec, dump_path=str(tmp_path / "wd.jsonl"),
            exit_code=87, arm_on_first_progress=False, poll_s=0.05,
            exit_grace_s=1.0)
        assert wd.fired.wait(timeout=5)
        wd.stop()                           # clean shutdown in the grace
        time.sleep(1.2)                     # past the would-be exit
        assert exits == []


class TestClusterStall:
    def test_stalled_exchange_raises_with_per_child_black_boxes(self):
        """ISSUE 6 acceptance: a faults.py delay at dcn.exchange under
        spawn_local_cluster raises within the gang deadline, and the
        error carries a flight-recorder dump per child with thread
        stacks and the last N spans."""
        from deeplearning4j_tpu.parallel.launcher import (
            ClusterStallError, spawn_local_cluster)
        n = 2
        t0 = time.monotonic()
        with pytest.raises(ClusterStallError) as excinfo:
            spawn_local_cluster(
                cluster_workers.stalled_exchange_worker,
                n_processes=n, port=12741, local_devices=1,
                timeout=120.0, gang_deadline=5.0, startup_retries=0,
                extra_env={**_ENV,
                           "DL4J_TPU_FAULT_PLAN":
                               "dcn.exchange@1:delay:300"})
        elapsed = time.monotonic() - t0
        # the watchdog beat the 120s wall budget by a wide margin
        assert elapsed < 90.0, f"stall took {elapsed:.0f}s to surface"
        err = excinfo.value
        assert "stalled" in str(err)
        assert len(err.flight_dumps) == n, (
            f"expected a black box per child, got "
            f"{sorted(err.flight_dumps)}: {err}")
        for pid, lines in err.flight_dumps.items():
            header = next(l for l in lines if l["type"] == "header")
            assert header["reason"] == "watchdog"
            liveness = next(l for l in lines if l["type"] == "liveness")
            # the stall happened in (or right after entering) the
            # exchange; either way the last stamped site names it
            assert liveness["last_site"] in ("dcn.exchange",
                                             "trainer.step")
            assert liveness["stalled_for_s"] >= 4.0
            stacks = [l for l in lines if l["type"] == "thread"]
            assert stacks, f"child {pid} dump has no thread stacks"
            joined = "".join("".join(t["stack"]) for t in stacks)
            # the wedged exchange thread is visible in the stacks
            assert "_exchange" in joined or "fire" in joined
            # gang mode turns tracing on: the ring carries recent spans
            spans = [l for l in lines
                     if l["type"] == "event" and l.get("kind") == "span"]
            assert spans, f"child {pid} dump has no span events"
            assert any(e["name"] in ("step", "slice", "encode", "exchange")
                       for e in spans)
            # step 0 completed before the injected stall
            steps = [l for l in lines
                     if l["type"] == "event" and l.get("kind") == "step"]
            assert steps
