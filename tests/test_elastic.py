"""Elastic device pool (ISSUE 19): checkpoint-consistent gang
grow/shrink + the serve/train chip arbiter.

Acceptance pins:

- N→M resharding in isolation: ``resize_layout`` derivations (grow
  dp2→4, shrink 4→2) produce exactly the sharding trees a from-scratch
  build at the new width derives; pipeline layouts refuse non-divisible
  widths with a typed :class:`~...parallel.mesh.LayoutResizeError`;
- the 1e-6 contract across a live resize: a Trainer that grows dp2→dp4
  (and shrinks dp4→dp2) at an epoch boundary mid-``fit`` matches a
  fixed-width run's per-step losses AND final params to 1e-6 with
  dropout active;
- fault sites: a crash injected at ``gang.grow`` mid-reshard leaves the
  old layout fully intact (no torn placement) and the same grow
  succeeds afterwards; crashes at ``arbiter.borrow``/``arbiter.return``
  abort the flip with the chip inventory exactly conserved;
- the arbiter: borrow/return cycle under live serve load with zero
  dropped/garbled responses and the gang restored to its original
  width; hysteresis + cooldown + the ``min_train`` floor;
- @slow: a supervised 2-worker gang grows to 4 at a round boundary
  (relaunch + checkpoint reshard) and its post-boundary losses match
  the uninterrupted reference to 1e-6; a ``gang.grow@0:kill`` injected
  into the grown child recovers through the normal respawn path.
"""

import functools
import os
import sys
import threading
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import cluster_workers  # noqa: E402

from jax.sharding import PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,  # noqa: E402
                                             set_registry)
from deeplearning4j_tpu.obs.remote import ClusterStore  # noqa: E402
from deeplearning4j_tpu.parallel import mesh as mesh_mod  # noqa: E402
from deeplearning4j_tpu.parallel.mesh import (AXIS_MODEL, LayoutResizeError,  # noqa: E402
                                              MeshSpec)
from deeplearning4j_tpu.resilience import elastic, faults  # noqa: E402
from deeplearning4j_tpu.resilience.arbiter import (DevicePoolArbiter,  # noqa: E402
                                                   TrainerGang)
from deeplearning4j_tpu.resilience.elastic import ResizeCoordinator  # noqa: E402
from deeplearning4j_tpu.resilience.retry import RetryPolicy  # noqa: E402
from deeplearning4j_tpu.resilience.supervisor import ClusterSupervisor  # noqa: E402
from deeplearning4j_tpu.serve import (AutoscaleConfig, Autoscaler,  # noqa: E402
                                      ModelRegistry, ReplicaRouter)
from deeplearning4j_tpu.train import Sgd  # noqa: E402
from deeplearning4j_tpu.train.trainer import Trainer  # noqa: E402

_ENV = {"PYTHONPATH": os.path.dirname(os.path.abspath(__file__))
        + os.pathsep + os.environ.get("PYTHONPATH", "")}


@pytest.fixture
def registry():
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


@pytest.fixture(autouse=True)
def _no_ambient_fault_plan():
    faults.clear_fault_plan()
    yield
    faults.clear_fault_plan()


def _mlp(seed=11, dropout=True):
    drop = 0.8 if dropout else None
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu", dropout=drop))
            .layer(DenseLayer(n_out=16, activation="tanh", dropout=drop))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf)


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, -1)]
    return x, y


def _elastic_run(start, resize_to=None, boundary=2, epochs=4):
    """One continuous fit (dropout active) that optionally requests an
    elastic resize at an epoch boundary mid-run.  Returns (per-step
    losses, flat final params, the trainer)."""
    x, y = _data()
    net = _mlp()
    trainer = Trainer(net, layout=start)
    losses = []

    class Rec:
        def iteration_done(self, net, it, ep, loss):
            losses.append(float(loss))

        def on_epoch_end(self, net, epoch, info):
            if resize_to is not None and epoch + 1 == boundary:
                trainer.request_resize(resize_to)

    trainer.bus.listeners.append(Rec())
    trainer.fit(ArrayDataSetIterator(x, y, 16, shuffle=False), epochs=epochs)
    return losses, np.asarray(net.params()), trainer


# ===================================== N→M resharding, in isolation
def test_resize_spec_scales_only_the_data_axis():
    assert mesh_mod.resize_spec(MeshSpec.parse("dp2"), 4).describe() == "dp4"
    assert mesh_mod.resize_spec(MeshSpec.parse("dp4"), 2).describe() == "dp2"
    # non-data axes describe how the MODEL is cut: they survive a resize
    assert mesh_mod.resize_spec(MeshSpec.parse("dp2xtp2"), 8).describe() \
        == "dp4xtp2"
    assert mesh_mod.resize_spec(MeshSpec.parse("dp4xpp2"), 4).describe() \
        == "dp2xpp2"


def test_resize_refuses_non_divisible_widths_with_typed_error():
    """A pp3 layout cannot live on 4 devices; the refusal is TYPED so
    elastic callers keep the current width instead of tearing down."""
    with pytest.raises(LayoutResizeError, match="pp3"):
        mesh_mod.resize_spec(MeshSpec.parse("pp3"), 4)
    with pytest.raises(LayoutResizeError, match="non-data degree"):
        mesh_mod.resize_spec(MeshSpec.parse("dp2xtp2"), 5)
    with pytest.raises(LayoutResizeError):
        mesh_mod.resize_spec(MeshSpec.parse("dp4"), 0)
    # LayoutResizeError IS a ValueError: pre-elastic callers that catch
    # ValueError keep working
    assert issubclass(LayoutResizeError, ValueError)
    # ... and the same eager validation runs at Trainer.request_resize,
    # the decision site — not an epoch later inside fit()
    trainer = Trainer(_mlp(), layout="dp2xtp2")
    with pytest.raises(LayoutResizeError):
        trainer.request_resize(5)
    with pytest.raises(ValueError, match="layout"):
        Trainer(_mlp()).request_resize(2)   # single-device: no width


def test_resized_layout_matches_from_scratch_derivation():
    """The reshard primitive: resize_layout(dp2 → 4) derives exactly the
    param/opt-state sharding trees a from-scratch dp4 build derives —
    placing a checkpoint onto them IS the reshard."""
    net = _mlp().init()
    params = net.params_
    leaves = functools.partial(jax.tree_util.tree_leaves)

    base = mesh_mod.resolve_layout(layout="dp2")
    grown = mesh_mod.resize_layout(base, 4)
    scratch = mesh_mod.resolve_layout(layout="dp4")
    assert grown.describe() == "dp4"
    assert grown.spec.sizes() == scratch.spec.sizes()
    assert grown.cache_signature() == scratch.cache_signature()
    assert leaves(grown.param_sharding_tree(params)) \
        == leaves(scratch.param_sharding_tree(params))
    opt_state = {"mu": params, "nu": params, "count": np.zeros(())}
    assert leaves(grown.opt_state_sharding_tree(opt_state, params)) \
        == leaves(scratch.opt_state_sharding_tree(opt_state, params))
    # placed values: numerically identical to the from-scratch placement
    for a, b in zip(leaves(grown.shard_params(params)),
                    leaves(scratch.shard_params(params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # shrink 4→2 is the same derivation in reverse
    shrunk = mesh_mod.resize_layout(scratch, 2)
    again = mesh_mod.resolve_layout(layout="dp2")
    assert shrunk.describe() == "dp2"
    assert leaves(shrunk.param_sharding_tree(params)) \
        == leaves(again.param_sharding_tree(params))

    # TP rules ride along: dp2xtp2 grown to 8 devices keeps its
    # model-axis kernel sharding
    tp8 = mesh_mod.resize_layout(mesh_mod.resolve_layout(layout="dp2xtp2"), 8)
    assert tp8.describe() == "dp4xtp2"
    specs = jax.tree_util.tree_leaves(
        tp8.param_spec_tree(params), is_leaf=lambda s: isinstance(s, P))
    assert any(s == P(None, AXIS_MODEL) for s in specs)


# =============================== the 1e-6 contract across a live flip
def test_grow_mid_run_matches_fixed_width_run(registry):
    """THE tentpole pin: dp2 grows to dp4 at an epoch boundary inside
    one continuous fit; losses and final params match a fixed-dp4 run
    to 1e-6 with dropout ACTIVE (the RNG trajectory is width-invariant,
    so the reshard — not luck — is what keeps the runs identical)."""
    fixed_losses, fixed_params, _ = _elastic_run("dp4")
    losses, params, trainer = _elastic_run("dp2", resize_to=4)
    assert trainer._layout.spec.describe() == "dp4"
    assert len(losses) == len(fixed_losses)
    np.testing.assert_allclose(losses, fixed_losses, rtol=0, atol=1e-6)
    np.testing.assert_allclose(params, fixed_params, rtol=0, atol=1e-6)
    assert registry.counter("tpudl_elastic_grows_total").value == 1
    assert registry.gauge("tpudl_elastic_gang_width").value == 4


def test_shrink_mid_run_matches_fixed_width_run(registry):
    """The reverse direction: dp4 shrinks to dp2 mid-run, same 1e-6
    contract — shrink is no longer a one-way degradation ratchet."""
    fixed_losses, fixed_params, _ = _elastic_run("dp2")
    losses, params, trainer = _elastic_run("dp4", resize_to=2)
    assert trainer._layout.spec.describe() == "dp2"
    np.testing.assert_allclose(losses, fixed_losses, rtol=0, atol=1e-6)
    np.testing.assert_allclose(params, fixed_params, rtol=0, atol=1e-6)
    assert registry.counter("tpudl_elastic_shrinks_total").value == 1
    assert registry.gauge("tpudl_elastic_gang_width").value == 2


def test_crash_injected_mid_grow_leaves_old_layout_intact(registry):
    """The ``gang.grow`` fault site fires before ANY state mutates: an
    injected crash mid-reshard leaves the dp2 trainer fully consistent
    (no torn placement), still trainable, and the same grow succeeds
    once the fault is gone."""
    x, y = _data()
    trainer = Trainer(_mlp(), layout="dp2")
    it = ArrayDataSetIterator(x, y, 16, shuffle=False)
    trainer.fit(it, epochs=1)
    with faults.inject("gang.grow@0:crash"):
        with pytest.raises(faults.InjectedCrash):
            trainer.resize_mesh(4)
    assert trainer._layout.spec.total() == 2
    assert trainer._layout_placed          # nothing was torn down
    trainer.fit(it, epochs=1)              # still trainable at dp2
    assert trainer._layout.spec.total() == 2
    assert trainer.resize_mesh(4) is True  # the grow lands afterwards
    assert trainer._layout.spec.total() == 4
    assert registry.counter("tpudl_elastic_grows_total").value == 1


# =================================================== ResizeCoordinator
def test_resize_coordinator_lifecycle(registry):
    events = []
    rc = ResizeCoordinator(width=2, min_width=1, on_event=events.append)
    with pytest.raises(ValueError):
        rc.request(0)
    with pytest.raises(ValueError, match="training floor"):
        ResizeCoordinator(width=4, min_width=2).request(1)

    d1 = rc.request(4, reason="spike")
    assert d1.kind == "grow" and rc.pending() is d1 and rc.width == 2
    d2 = rc.request(3)                      # latest wins over un-begun
    assert rc.pending() is d2
    begun = rc.begin()
    assert begun is d2 and rc.in_flight() is d2 and rc.pending() is None
    with pytest.raises(ValueError, match="in flight"):
        rc.request(4)                       # one flip at a time
    rc.commit(begun)
    assert rc.width == 3 and begun.outcome == "committed"
    assert begun.flip_s is not None and events[-1] is begun
    assert registry.counter("tpudl_elastic_grows_total").value == 1
    assert registry.gauge("tpudl_elastic_gang_width").value == 3

    noop = rc.request(3)                    # recorded, never queued
    assert noop.outcome == "noop" and rc.pending() is None

    rc.request(2)
    d3 = rc.begin()
    rc.abort(d3, reason="relaunch failed")
    assert rc.width == 3 and d3.outcome == "aborted"   # reversible
    with pytest.raises(ValueError):
        rc.commit(d3)                       # not in flight anymore
    assert [d.outcome for d in rc.history] == ["committed", "noop",
                                               "aborted"]


def test_elastic_env_contract(monkeypatch):
    monkeypatch.delenv(elastic.WIDTH_ENV, raising=False)
    monkeypatch.delenv(elastic.GROWN_ENV, raising=False)
    assert elastic.configured_width() is None
    assert elastic.configured_width(default=3) == 3
    assert not elastic.is_grown_child()
    monkeypatch.setenv(elastic.WIDTH_ENV, "4")
    monkeypatch.setenv(elastic.GROWN_ENV, "1")
    assert elastic.configured_width() == 4
    assert elastic.is_grown_child()


def test_supervisor_resize_request_and_child_env(tmp_path):
    sup = ClusterSupervisor(cluster_workers.trivial_worker, n_processes=2,
                            min_workers=2, checkpoint_dir=str(tmp_path))
    assert sup.width == 2
    with pytest.raises(ValueError, match="training floor"):
        sup.request_resize(1)               # the floor is eager
    sup.request_resize(4, reason="test")
    assert sup._resize.pending().to_width == 4
    # grow generations carry the elastic env contract to every child
    env = sup._child_env(1, [0, 1, 2, 3], None, grown=True)(2)
    assert env[elastic.WIDTH_ENV] == "4"
    assert env[elastic.GROWN_ENV] == "1"
    assert env["DL4J_TPU_WORKER_ID"] == "w2"
    env = sup._child_env(0, [0, 1], None)(0)
    assert env[elastic.WIDTH_ENV] == "2"
    assert env[elastic.GROWN_ENV] == ""


def test_cluster_store_gang_width_and_resize_annotations():
    store = ClusterStore()
    assert store.summary()["gang_width"] is None
    assert "gang width" in store.render_html()
    store.set_gang_width(4)
    store.annotate("resize", "resize#1 grow 2→4 [committed]",
                   direction="grow", from_width=2, to_width=4,
                   outcome="committed")
    summary = store.summary()
    assert summary["gang_width"] == 4
    notes = [a for a in summary["annotations"] if a["kind"] == "resize"]
    assert notes and notes[0]["to_width"] == 4
    assert "[resize]" in store.render_html()


# ================================================== DevicePoolArbiter
class _FakeGang:
    """Minimal gang side: width + request_resize, applied immediately."""

    def __init__(self, width):
        self._width = width
        self.requests = []

    @property
    def width(self):
        return self._width

    def request_resize(self, width, reason=""):
        self.requests.append((int(width), reason))
        self._width = int(width)


def _routed(tmp_path, replicas=2, max_replicas=4):
    net = _mlp(dropout=False).init()
    path = str(tmp_path / "serve.zip")
    net.save(path)
    models = ModelRegistry(max_batch=8, max_latency_ms=2, queue_limit=64)
    models.deploy("m", path)
    router = ReplicaRouter(models, "m", replicas=replicas,
                           max_replicas=max_replicas)
    return models, router, net


def test_arbiter_borrow_return_cycle_conserves_inventory(tmp_path, registry):
    models, router, _ = _routed(tmp_path)
    gang = _FakeGang(4)
    arb = DevicePoolArbiter(router, gang, min_train=2, chips_per_flip=2,
                            cooldown_s=0.0, serve_chips=2)
    assert arb.total() == 6
    assert arb.borrow() is True
    assert arb.snapshot() == {"serve": 4, "train": 2, "borrowed": 2,
                              "total": 6}
    assert gang.width == 2
    assert router.replicas == 4 and router.max_replicas == 6
    # the training floor: the next borrow would cross min_train → refused
    # at the decision site, nothing torn down
    assert arb.borrow() is False
    assert arb.snapshot()["train"] == 2
    assert arb.return_chips() is True
    assert arb.snapshot() == {"serve": 2, "train": 4, "borrowed": 0,
                              "total": 6}
    assert gang.width == 4
    assert router.replicas == 2 and router.max_replicas == 4
    assert registry.counter("tpudl_elastic_borrows_total").value == 1
    assert registry.counter("tpudl_elastic_returns_total").value == 1
    gauge = registry.labeled_gauge("tpudl_elastic_pool_devices",
                                   label_names=("owner",))
    assert gauge.labeled_value(owner="train") == 4
    assert gauge.labeled_value(owner="serve") == 2


def test_arbiter_crash_mid_flip_never_leaks_a_device(tmp_path, registry):
    """Crashes at the ``arbiter.borrow``/``arbiter.return`` sites fire
    at the worst instant (between the gang request and the serve-side
    mutation); the flip aborts with serve + train chip counts, router
    capacity AND the gang width exactly as they were."""
    models, router, _ = _routed(tmp_path)
    gang = _FakeGang(4)
    arb = DevicePoolArbiter(router, gang, min_train=1, chips_per_flip=2,
                            cooldown_s=0.0, serve_chips=2)
    before = arb.snapshot()
    with faults.inject("arbiter.borrow@0:crash"):
        assert arb.borrow() is False
    assert arb.snapshot() == before
    assert router.replicas == 2 and router.max_replicas == 4
    assert gang.width == 4                   # rolled back
    assert gang.requests[-1] == (4, "arbiter rollback")

    assert arb.borrow() is True              # the pool is healthy
    borrowed = arb.snapshot()
    with faults.inject("arbiter.return@0:crash"):
        assert arb.return_chips() is False
    assert arb.snapshot() == borrowed
    assert router.replicas == 4 and router.max_replicas == 6
    assert gang.width == 2
    assert arb.return_chips() is True
    assert arb.snapshot() == before


def test_arbiter_retries_transient_faults(tmp_path, registry):
    """A transient InjectedFault at the borrow site is retried under
    resilience.retry backoff — the flip still lands."""
    models, router, _ = _routed(tmp_path)
    gang = _FakeGang(4)
    arb = DevicePoolArbiter(router, gang, min_train=1, chips_per_flip=1,
                            cooldown_s=0.0, serve_chips=2,
                            policy=RetryPolicy(max_attempts=3,
                                               base_delay_s=0.0, jitter=0.0))
    with faults.inject("arbiter.borrow@0:error"):
        assert arb.borrow() is True
    assert arb.snapshot() == {"serve": 3, "train": 3, "borrowed": 1,
                              "total": 6}


def test_arbiter_hysteresis_and_cooldown(tmp_path, registry):
    models, router, _ = _routed(tmp_path)
    gang = _FakeGang(4)
    arb = DevicePoolArbiter(router, gang, min_train=1, chips_per_flip=1,
                            high_water=0.5, low_water=0.05,
                            sustain_polls=3, cooldown_s=0.0, serve_chips=2)
    # a borrow needs sustain_polls CONSECUTIVE saturated-high polls; a
    # mid-band sample resets the streak
    assert arb.note_pressure(0.9, saturated=True) is None
    assert arb.note_pressure(0.9, saturated=True) is None
    assert arb.note_pressure(0.3) is None                # streak reset
    assert arb.note_pressure(0.9, saturated=True) is None
    assert arb.note_pressure(0.9) is None                # not saturated
    assert arb.note_pressure(0.9, saturated=True) is None
    assert arb.note_pressure(0.9, saturated=True) is None
    assert arb.note_pressure(0.9, saturated=True) == "borrow"
    assert arb.borrowed == 1
    # pressure ebbs: the return needs its own sustained calm window
    assert arb.note_pressure(0.0) is None
    assert arb.note_pressure(0.0) is None
    assert arb.note_pressure(0.0) == "return"
    assert arb.borrowed == 0 and gang.width == 4

    # cooldown separates any two flips
    arb2 = DevicePoolArbiter(router, _FakeGang(4), min_train=1,
                             sustain_polls=1, cooldown_s=3600.0,
                             serve_chips=2)
    assert arb2.borrow() is True
    for _ in range(5):
        assert arb2.note_pressure(0.0) is None   # cooldown gates it
    assert arb2.borrowed == 1


def test_autoscaler_escalates_to_arbiter_on_saturation():
    """The escalation signal: an up-decision that hits max_replicas
    while pressure persists reports ``saturated=True`` to the arbiter —
    replica scaling is spent, only chips will help."""

    class _StubRouter:
        name = "m"
        fill = 0.9

        def heal(self):
            pass

        def queue_fill(self):
            return self.fill

        def add_replica(self):
            return False                     # max_replicas spent

        def retire_replica(self):
            return False

    class _Recorder:
        def __init__(self):
            self.calls = []

        def note_pressure(self, fill, saturated=False):
            self.calls.append((fill, saturated))

    router, rec = _StubRouter(), _Recorder()
    auto = Autoscaler(router, AutoscaleConfig(poll_s=30.0,
                                              up_cooldown_s=0.0, window=1),
                      arbiter=rec)
    try:
        auto.step()
        assert (0.9, True) in rec.calls
        router.fill = 0.0                    # pressure gone
        auto.step()
        assert rec.calls[-1] == (0.0, False)
    finally:
        auto.close()


def test_trainer_gang_requires_a_layout():
    with pytest.raises(ValueError, match="layout"):
        TrainerGang(Trainer(_mlp()))


def test_borrow_return_under_live_serve_load(tmp_path, registry):
    """The acceptance cycle: sustained pressure borrows 2 training chips
    (the dp4 gang shrinks to dp2 at its next round boundary, serve
    replicas rise), pressure ebbs, the chips return and the gang grows
    back to dp4 — all while live serve traffic sees zero dropped or
    garbled responses."""
    models, router, snet = _routed(tmp_path)
    x, y = _data()
    trainer = Trainer(_mlp(), layout="dp4")
    it = ArrayDataSetIterator(x, y, 16, shuffle=False)
    trainer.fit(it, epochs=1)
    arb = DevicePoolArbiter(router, TrainerGang(trainer), min_train=2,
                            chips_per_flip=2, cooldown_s=0.0, serve_chips=2)
    xs = x[:8]
    expected = np.asarray(snet.output(xs))
    stop, errors, served = threading.Event(), [], [0]

    def client():
        while not stop.is_set():
            try:
                out, _ = models.predict_versioned("m", xs, timeout_s=30)
                np.testing.assert_allclose(out, expected, rtol=1e-5,
                                           atol=1e-6)
                served[0] += 1
            except Exception as e:           # noqa: BLE001 — the assertion
                errors.append(repr(e))
                return
    threads = [threading.Thread(target=client) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        assert arb.borrow() is True
        trainer.fit(it, epochs=1)            # shrink applies at the boundary
        assert trainer._layout.spec.total() == 2
        assert arb.return_chips() is True
        trainer.fit(it, epochs=1)            # ... and the grow-back too
        assert trainer._layout.spec.total() == 4
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors[:3]
    assert served[0] > 0
    assert arb.snapshot() == {"serve": 2, "train": 4, "borrowed": 0,
                              "total": 6}
    assert router.replicas == 2 and router.max_replicas == 4


# ================================= supervised gang grow/shrink (e2e)
def _drive_resize(sup, to_width, reason):
    """Run ``sup`` on a thread; once the gang has produced a verified
    checkpoint, request the resize from the main thread (the arbiter's
    seat).  Returns the completed SupervisedRun."""
    result = {}

    def run():
        try:
            result["run"] = sup.run()
        except BaseException as e:           # surfaced by the caller
            result["error"] = e
    thread = threading.Thread(target=run)
    thread.start()
    deadline = time.monotonic() + 150.0
    while time.monotonic() < deadline and sup._latest_checkpoint() is None \
            and thread.is_alive():
        time.sleep(0.02)
    assert sup._latest_checkpoint() is not None, \
        f"no verified checkpoint appeared: {result.get('error')}"
    sup.request_resize(to_width, reason=reason)
    thread.join(timeout=300.0)
    assert not thread.is_alive(), "supervised run did not finish"
    if "error" in result:
        raise result["error"]
    return result["run"]


@pytest.mark.slow
def test_supervised_gang_grows_2_to_4_and_matches_reference(tmp_path,
                                                            registry):
    """THE elastic acceptance e2e: a supervised 2-worker gang is asked
    to grow mid-run; the supervisor tears it down at the round boundary,
    relaunches 4 workers that resume from the shared verified checkpoint
    with params/opt-state resharded onto the dp4 layout, and every
    worker's post-boundary losses + final params match the uninterrupted
    reference to 1e-6 (dropout active)."""
    ref_losses, ref_params = cluster_workers.run_elastic_reference(epochs=4)
    fn = functools.partial(cluster_workers.elastic_train_worker,
                           workdir=str(tmp_path), epochs=4)
    from deeplearning4j_tpu.obs.ui_server import UIServer
    server = UIServer(port=0)
    try:
        sup = ClusterSupervisor(
            fn, n_processes=2, checkpoint_dir=str(tmp_path),
            max_restarts=2, min_workers=1, port=25611, timeout=240.0,
            local_devices=4, remote_ui=server.url,
            cluster_store=server.cluster, extra_env=_ENV,
            backoff=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                jitter=0.0))
        run = _drive_resize(sup, 4, reason="test grow")

        # a planned resize is a round boundary, NOT an incident
        assert run.incidents == []
        assert run.slots == [0, 1, 2, 3]
        assert run.generations >= 2
        assert sup.width == 4
        results = {r["pid"]: r for r in run.results}
        assert sorted(results) == [0, 1, 2, 3]
        for r in results.values():
            assert r["width"] == 4 and r["grown"]
            start = r["end_iteration"] - len(r["losses"])
            # resumed post-boundary tail, not a from-scratch replay
            assert 0 < start and len(r["losses"]) < len(ref_losses)
            np.testing.assert_allclose(r["losses"], ref_losses[start:],
                                       atol=1e-6)
            np.testing.assert_allclose(r["params"], ref_params, atol=1e-6)
        # the flip was committed and annotated
        assert registry.counter("tpudl_elastic_grows_total").value == 1
        assert registry.gauge("tpudl_elastic_gang_width").value == 4
        summary = server.cluster.summary()
        assert summary["gang_width"] == 4
        kinds = [a["kind"] for a in summary["annotations"]]
        assert "resize" in kinds
    finally:
        server.stop()


@pytest.mark.slow
def test_kill_injected_at_gang_grow_recovers_via_respawn(tmp_path,
                                                         registry):
    """Chaos on the grow path: the NEW worker slot SIGKILLs itself at
    the ``gang.grow`` site (right after restoring the checkpoint —
    mid-reshard).  The supervisor treats it like any worker death:
    respawn at the grown width from the still-intact verified
    checkpoint; the run completes at width 4 with the reference params,
    proving no torn checkpoint and no leaked worker slot."""
    ref_losses, ref_params = cluster_workers.run_elastic_reference(epochs=4)
    fn = functools.partial(cluster_workers.elastic_train_worker,
                           workdir=str(tmp_path), epochs=4,
                           kill_on_grow=True)
    sup = ClusterSupervisor(
        fn, n_processes=2, checkpoint_dir=str(tmp_path),
        max_restarts=2, min_workers=1, port=25811, timeout=240.0,
        local_devices=4, extra_env=_ENV,
        backoff=RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0))
    run = _drive_resize(sup, 4, reason="test grow under chaos")

    assert len(run.incidents) == 1
    incident = run.incidents[0]
    assert incident.reason == "killed"
    assert any(slot == 2 and rc is not None and rc < 0
               for slot, rc in incident.exits)
    assert incident.restarted
    # no leaked slot: the gang ends at exactly the requested width
    assert run.slots == [0, 1, 2, 3]
    assert sup.width == 4
    results = {r["pid"]: r for r in run.results}
    assert sorted(results) == [0, 1, 2, 3]
    for r in results.values():
        assert r["width"] == 4
        start = r["end_iteration"] - len(r["losses"])
        np.testing.assert_allclose(r["losses"], ref_losses[start:],
                                   atol=1e-6)
        np.testing.assert_allclose(r["params"], ref_params, atol=1e-6)
    assert registry.counter("tpudl_elastic_grows_total").value == 1
    assert registry.counter(
        "tpudl_resilience_gang_restarts_total").value == 1
