"""Heterogeneous pipeline + 1F1B tests (VERDICT r3 #4: pipeline a REAL
model — per-stage pytrees, non-uniform widths, 1F1B schedule, BERT as 4
stages with parity + measured activation-memory reduction)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline_stages import (
    make_1f1b_schedule, make_gpipe_schedule, pipeline_apply_stages,
    pipeline_train_step)


def _mlp_case(S=4, dims=(12, 24, 10, 18, 6), batch=16):
    rng = np.random.default_rng(0)
    mesh = make_mesh(data=1, stage=S, devices=jax.devices()[:S])
    params = [{"W": jnp.asarray(rng.normal(0, 0.3, (dims[i], dims[i + 1]))
                                .astype(np.float32)),
               "b": jnp.zeros((dims[i + 1],), jnp.float32)}
              for i in range(S)]

    def mk(i):
        def f(p, h):
            return jnp.tanh(h @ p["W"] + p["b"])
        return f

    fns = [mk(i) for i in range(S)]
    x = jnp.asarray(rng.normal(size=(batch, dims[0])).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(batch, dims[-1])).astype(np.float32))
    return mesh, fns, params, x, y


class TestSchedule:
    def test_1f1b_drains_and_single_slot(self):
        for S, M in [(2, 1), (2, 3), (4, 4), (4, 8), (3, 7)]:
            F, B = make_1f1b_schedule(S, M)  # asserts invariants internally
            # every microbatch forwarded and backwarded exactly once/stage
            for s in range(S):
                assert sorted(m for m in F[:, s] if m >= 0) == list(range(M))
                assert sorted(m for m in B[:, s] if m >= 0) == list(range(M))

    def test_1f1b_in_flight_bounded(self):
        """Stage s never stashes more than S - s microbatches — the
        memory property GPipe lacks."""
        S, M = 4, 16
        F, B = make_1f1b_schedule(S, M)
        for s in range(S):
            live = 0
            peak = 0
            for t in range(F.shape[0]):
                if F[t, s] >= 0:
                    live += 1
                if B[t, s] >= 0:
                    live -= 1
                peak = max(peak, live)
            assert peak <= S - s
        # gpipe peaks at M for stage 0
        Fg, Bg = make_gpipe_schedule(S, M)
        live = peak = 0
        for t in range(Fg.shape[0]):
            if Fg[t, 0] >= 0:
                live += 1
            if Bg[t, 0] >= 0:
                live -= 1
            peak = max(peak, live)
        assert peak == M


class TestHeterogeneousPipeline:
    def test_forward_non_uniform_widths(self):
        mesh, fns, params, x, _ = _mlp_case()
        with mesh:
            yp = pipeline_apply_stages(fns, params, x, mesh, n_microbatches=4)
        ref = x
        for f, p in zip(fns, params):
            ref = f(p, ref)
        np.testing.assert_allclose(np.asarray(yp), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
    def test_train_step_matches_autodiff(self, schedule):
        mesh, fns, params, x, y = _mlp_case()

        def loss_fn(out, lab):
            return jnp.mean((out - lab) ** 2)

        with mesh:
            loss, grads = pipeline_train_step(
                fns, params, x, y, loss_fn, mesh, n_microbatches=4,
                schedule=schedule)

        def full(ps):
            h = x
            for f, p in zip(fns, ps):
                h = f(p, h)
            return jnp.mean((h - y) ** 2)

        rl, rg = jax.value_and_grad(full)(params)
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        for i in range(len(fns)):
            for k in ("W", "b"):
                np.testing.assert_allclose(
                    np.asarray(grads[i][k]), np.asarray(rg[i][k]),
                    rtol=1e-4, atol=1e-5, err_msg=f"stage {i} {k}")

    def test_uneven_microbatch_raises(self):
        mesh, fns, params, x, y = _mlp_case()
        with pytest.raises(ValueError):
            pipeline_train_step(fns, params, x, y,
                                lambda o, l: jnp.mean(o), mesh,
                                n_microbatches=5)


class TestBertPipeline:
    def _case(self, M=4):
        from deeplearning4j_tpu.models import bert as B
        config = dataclasses.replace(B.BertConfig.tiny(vocab_size=128),
                                     num_layers=4)
        params = B.init_params(config, jax.random.key(0))
        S = 4
        mesh = make_mesh(data=1, stage=S, devices=jax.devices()[:S])
        fns, sp = B.pipeline_stages(config, params, S)
        rng = np.random.default_rng(0)
        bsz, T = 8, 16
        ids = rng.integers(5, 128, (bsz, T)).astype(np.int32)
        labels = rng.integers(5, 128, (bsz, T)).astype(np.float32)
        weights = (rng.random((bsz, T)) < 0.3).astype(np.float32)
        packed = jnp.asarray(np.stack([labels, weights], axis=-1))
        x = jnp.asarray(ids.astype(np.float32))
        return B, mesh, fns, sp, x, packed, M, bsz

    def test_bert_four_stages_loss_and_grads(self):
        """BERT as 4 REAL stages (embeddings / encoder / encoder /
        encoder+MLM head): pipelined loss + grads equal the staged
        composition evaluated per microbatch."""
        B, mesh, fns, sp, x, packed, M, bsz = self._case()
        with mesh:
            loss, grads = pipeline_train_step(
                fns, sp, x, packed, B.mlm_loss_from_logits, mesh,
                n_microbatches=M)

        def micro_ref(sps):
            bm = bsz // M
            tot = 0.0
            for m in range(M):
                h = x[m * bm:(m + 1) * bm]
                for f, p in zip(fns, sps):
                    h = f(p, h)
                tot = tot + B.mlm_loss_from_logits(
                    h, packed[m * bm:(m + 1) * bm])
            return tot / M

        rl, rg = jax.value_and_grad(micro_ref)(tuple(sp))
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        for i in range(len(fns)):
            for a, b in zip(jax.tree_util.tree_leaves(grads[i]),
                            jax.tree_util.tree_leaves(rg[i])):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-3, atol=1e-5)

    def test_1f1b_reduces_compiled_temp_memory(self):
        """The point of 1F1B: bounded stash → smaller compiled temp
        allocation than all-forward-then-all-backward at the same M."""
        B, mesh, fns, sp, x, packed, _, _ = self._case()
        M = 8

        sizes = {}
        for sched in ("1f1b", "gpipe"):
            def f(spp, sched=sched):
                with mesh:
                    return pipeline_train_step(
                        fns, spp, x, packed, B.mlm_loss_from_logits,
                        mesh, n_microbatches=M, schedule=sched)
            c = jax.jit(f).lower(tuple(sp)).compile()
            sizes[sched] = c.memory_analysis().temp_size_in_bytes
        assert sizes["1f1b"] < sizes["gpipe"], sizes
