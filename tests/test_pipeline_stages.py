"""Heterogeneous pipeline + 1F1B tests (VERDICT r3 #4: pipeline a REAL
model — per-stage pytrees, non-uniform widths, 1F1B schedule, BERT as 4
stages with parity + measured activation-memory reduction)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline_stages import (
    make_1f1b_schedule, make_gpipe_schedule, pipeline_apply_stages,
    pipeline_train_step)


def _mlp_case(S=4, dims=(12, 24, 10, 18, 6), batch=16):
    rng = np.random.default_rng(0)
    mesh = make_mesh(data=1, stage=S, devices=jax.devices()[:S])
    params = [{"W": jnp.asarray(rng.normal(0, 0.3, (dims[i], dims[i + 1]))
                                .astype(np.float32)),
               "b": jnp.zeros((dims[i + 1],), jnp.float32)}
              for i in range(S)]

    def mk(i):
        def f(p, h):
            return jnp.tanh(h @ p["W"] + p["b"])
        return f

    fns = [mk(i) for i in range(S)]
    x = jnp.asarray(rng.normal(size=(batch, dims[0])).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(batch, dims[-1])).astype(np.float32))
    return mesh, fns, params, x, y


class TestSchedule:
    def test_1f1b_drains_and_single_slot(self):
        for S, M in [(2, 1), (2, 3), (4, 4), (4, 8), (3, 7)]:
            F, B = make_1f1b_schedule(S, M)  # asserts invariants internally
            # every microbatch forwarded and backwarded exactly once/stage
            for s in range(S):
                assert sorted(m for m in F[:, s] if m >= 0) == list(range(M))
                assert sorted(m for m in B[:, s] if m >= 0) == list(range(M))

    def test_1f1b_in_flight_bounded(self):
        """Stage s never stashes more than S - s microbatches — the
        memory property GPipe lacks."""
        S, M = 4, 16
        F, B = make_1f1b_schedule(S, M)
        for s in range(S):
            live = 0
            peak = 0
            for t in range(F.shape[0]):
                if F[t, s] >= 0:
                    live += 1
                if B[t, s] >= 0:
                    live -= 1
                peak = max(peak, live)
            assert peak <= S - s
        # gpipe peaks at M for stage 0
        Fg, Bg = make_gpipe_schedule(S, M)
        live = peak = 0
        for t in range(Fg.shape[0]):
            if Fg[t, 0] >= 0:
                live += 1
            if Bg[t, 0] >= 0:
                live -= 1
            peak = max(peak, live)
        assert peak == M


class TestHeterogeneousPipeline:
    def test_forward_non_uniform_widths(self):
        mesh, fns, params, x, _ = _mlp_case()
        with mesh:
            yp = pipeline_apply_stages(fns, params, x, mesh, n_microbatches=4)
        ref = x
        for f, p in zip(fns, params):
            ref = f(p, ref)
        np.testing.assert_allclose(np.asarray(yp), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
    def test_train_step_matches_autodiff(self, schedule):
        mesh, fns, params, x, y = _mlp_case()

        def loss_fn(out, lab):
            return jnp.mean((out - lab) ** 2)

        with mesh:
            loss, grads = pipeline_train_step(
                fns, params, x, y, loss_fn, mesh, n_microbatches=4,
                schedule=schedule)

        def full(ps):
            h = x
            for f, p in zip(fns, ps):
                h = f(p, h)
            return jnp.mean((h - y) ** 2)

        rl, rg = jax.value_and_grad(full)(params)
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        for i in range(len(fns)):
            for k in ("W", "b"):
                np.testing.assert_allclose(
                    np.asarray(grads[i][k]), np.asarray(rg[i][k]),
                    rtol=1e-4, atol=1e-5, err_msg=f"stage {i} {k}")

    def test_uneven_microbatch_raises(self):
        mesh, fns, params, x, y = _mlp_case()
        with pytest.raises(ValueError):
            pipeline_train_step(fns, params, x, y,
                                lambda o, l: jnp.mean(o), mesh,
                                n_microbatches=5)


class TestBertPipeline:
    def _case(self, M=4):
        from deeplearning4j_tpu.models import bert as B
        config = dataclasses.replace(B.BertConfig.tiny(vocab_size=128),
                                     num_layers=4)
        params = B.init_params(config, jax.random.key(0))
        S = 4
        mesh = make_mesh(data=1, stage=S, devices=jax.devices()[:S])
        fns, sp = B.pipeline_stages(config, params, S)
        rng = np.random.default_rng(0)
        bsz, T = 8, 16
        ids = rng.integers(5, 128, (bsz, T)).astype(np.int32)
        labels = rng.integers(5, 128, (bsz, T)).astype(np.float32)
        weights = (rng.random((bsz, T)) < 0.3).astype(np.float32)
        packed = jnp.asarray(np.stack([labels, weights], axis=-1))
        x = jnp.asarray(ids.astype(np.float32))
        return B, mesh, fns, sp, x, packed, M, bsz

    @pytest.mark.slow
    def test_bert_four_stages_loss_and_grads(self):
        """BERT as 4 REAL stages (embeddings / encoder / encoder /
        encoder+MLM head): pipelined loss + grads equal the staged
        composition evaluated per microbatch."""
        B, mesh, fns, sp, x, packed, M, bsz = self._case()
        with mesh:
            loss, grads = pipeline_train_step(
                fns, sp, x, packed, B.mlm_loss_from_logits, mesh,
                n_microbatches=M)

        def micro_ref(sps):
            bm = bsz // M
            tot = 0.0
            for m in range(M):
                h = x[m * bm:(m + 1) * bm]
                for f, p in zip(fns, sps):
                    h = f(p, h)
                tot = tot + B.mlm_loss_from_logits(
                    h, packed[m * bm:(m + 1) * bm])
            return tot / M

        rl, rg = jax.value_and_grad(micro_ref)(tuple(sp))
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        for i in range(len(fns)):
            for a, b in zip(jax.tree_util.tree_leaves(grads[i]),
                            jax.tree_util.tree_leaves(rg[i])):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-3, atol=1e-5)

    @pytest.mark.slow
    def test_tied_embedding_grad_merge(self):
        """merge_tied_embedding_grads re-ties the split embedding grad:
        the merged leaf equals the gradient of a SHARED-table reference,
        and under per-leaf SGD the two copies stay bitwise equal."""
        B, mesh, fns, sp, x, packed, M, bsz = self._case()
        with mesh:
            _, grads = pipeline_train_step(
                fns, sp, x, packed, B.mlm_loss_from_logits, mesh,
                n_microbatches=M)
        merged = B.merge_tied_embedding_grads(grads)
        we = np.asarray(merged[0]["embeddings"]["word_embeddings"])
        de = np.asarray(merged[-1]["decode_embeddings"])
        np.testing.assert_array_equal(we, de)
        np.testing.assert_allclose(
            we,
            np.asarray(grads[0]["embeddings"]["word_embeddings"])
            + np.asarray(grads[-1]["decode_embeddings"]), rtol=1e-6)

        # shared-table reference: stage params rebuilt so decode shares
        # the stage-0 table leaf — its grad must equal the merged total
        def micro_ref_tied(table):
            sps = [dict(p) for p in sp]
            e = dict(sps[0]["embeddings"])
            e["word_embeddings"] = table
            sps[0] = {**sps[0], "embeddings": e}
            sps[-1] = {**sps[-1], "decode_embeddings": table}
            bm = bsz // M
            tot = 0.0
            for m in range(M):
                h = x[m * bm:(m + 1) * bm]
                for f, p in zip(fns, sps):
                    h = f(p, h)
                tot = tot + B.mlm_loss_from_logits(
                    h, packed[m * bm:(m + 1) * bm])
            return tot / M

        ref_g = jax.grad(micro_ref_tied)(
            sp[0]["embeddings"]["word_embeddings"])
        np.testing.assert_allclose(we, np.asarray(ref_g),
                                   rtol=2e-3, atol=1e-5)

        # per-leaf SGD keeps the copies exactly tied after the update
        lr = 0.1
        new0 = np.asarray(sp[0]["embeddings"]["word_embeddings"]) - lr * we
        new3 = np.asarray(sp[-1]["decode_embeddings"]) - lr * de
        np.testing.assert_array_equal(new0, new3)

    @pytest.mark.slow
    def test_1f1b_reduces_compiled_temp_memory(self):
        """The point of 1F1B: bounded stash → smaller compiled temp
        allocation than all-forward-then-all-backward at the same M."""
        B, mesh, fns, sp, x, packed, _, _ = self._case()
        M = 8

        sizes = {}
        for sched in ("1f1b", "gpipe"):
            def f(spp, sched=sched):
                with mesh:
                    return pipeline_train_step(
                        fns, spp, x, packed, B.mlm_loss_from_logits,
                        mesh, n_microbatches=M, schedule=sched)
            c = jax.jit(f).lower(tuple(sp)).compile()
            sizes[sched] = c.memory_analysis().temp_size_in_bytes
        assert sizes["1f1b"] < sizes["gpipe"], sizes


class TestStageLocalOptimizer:
    """VERDICT r4 missing #5 / next #6: grads + updater state stay
    sharded per stage inside the shard_map (no full-tuple psum)."""

    def _setup(self):
        import optax
        mesh, fns, params, x, y = _mlp_case()
        from deeplearning4j_tpu.parallel.pipeline_stages import (
            flatten_stage_params, init_stage_local_opt)
        tx = optax.adam(1e-2)
        flat, unravels, sizes = flatten_stage_params(params)
        from jax.sharding import NamedSharding, PartitionSpec as P
        flat = jax.device_put(flat, NamedSharding(mesh, P("pipe")))
        opt = init_stage_local_opt(tx, flat, mesh)
        return mesh, fns, params, x, y, tx, flat, unravels, sizes, opt

    @pytest.mark.slow
    def test_matches_replicated_pipeline_plus_optimizer(self):
        import optax
        from deeplearning4j_tpu.parallel.pipeline_stages import (
            pipeline_fit_step_local, pipeline_train_step,
            unflatten_stage_params)
        (mesh, fns, params, x, y, tx, flat, unravels, sizes,
         opt) = self._setup()

        def loss_fn(out, lab):
            return jnp.mean((out - lab) ** 2)

        with mesh:
            loss_l, new_flat, new_opt = pipeline_fit_step_local(
                fns, flat, opt, tx, unravels, sizes, x, y, loss_fn,
                mesh, n_microbatches=4)

        # reference: replicated pipeline grads + the same optax update
        # applied per stage on the host
        with mesh:
            loss_r, grads = pipeline_train_step(
                fns, params, x, y, loss_fn, mesh, n_microbatches=4)
        np.testing.assert_allclose(float(loss_l), float(loss_r), rtol=1e-5)
        ref_opt = tx.init(self._flat_unsharded(params))
        updates, _ = tx.update(self._flat_unsharded(grads), ref_opt,
                               self._flat_unsharded(params))
        want = self._flat_unsharded(params) + updates
        got = np.asarray(new_flat)
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4,
                                   atol=1e-6)
        # round-trip back to pytrees works
        back = unflatten_stage_params(new_flat, unravels, sizes)
        assert back[0]["W"].shape == params[0]["W"].shape

    def _flat_unsharded(self, stage_trees):
        from deeplearning4j_tpu.parallel.pipeline_stages import (
            flatten_stage_params)
        return flatten_stage_params(stage_trees)[0]

    def test_params_grads_opt_stay_stage_sharded(self):
        """The memory point: each device holds exactly ONE stage row of
        params and optimizer state (1/S of the model), before AND after
        the step."""
        from deeplearning4j_tpu.parallel.pipeline_stages import (
            pipeline_fit_step_local)
        (mesh, fns, params, x, y, tx, flat, unravels, sizes,
         opt) = self._setup()
        S = flat.shape[0]

        def rows_per_device(arr):
            return {sh.data.shape[0] for sh in arr.addressable_shards}

        assert rows_per_device(flat) == {1}
        with mesh:
            loss, new_flat, new_opt = pipeline_fit_step_local(
                fns, flat, opt, tx, unravels, sizes, x, y,
                lambda o, l: jnp.mean((o - l) ** 2), mesh,
                n_microbatches=4)
        assert rows_per_device(new_flat) == {1}
        for leaf in jax.tree_util.tree_leaves(new_opt):
            if np.ndim(leaf) == 2:
                assert rows_per_device(leaf) == {1}, "opt state gathered!"

    def test_local_step_memory_below_replicated(self):
        """Compiled per-step memory: the stage-local step must allocate
        less than the replicated-grads step + full-tuple psum at the
        same (S, M) — the carry is one [Pmax] row, not the whole tuple."""
        import optax
        from deeplearning4j_tpu.parallel.pipeline_stages import (
            pipeline_fit_step_local, pipeline_train_step)
        (mesh, fns, params, x, y, tx, flat, unravels, sizes,
         opt) = self._setup()

        def loss_fn(out, lab):
            return jnp.mean((out - lab) ** 2)

        def local_step(flat, opt):
            with mesh:
                return pipeline_fit_step_local(
                    fns, flat, opt, tx, unravels, sizes, x, y, loss_fn,
                    mesh, n_microbatches=4)

        def repl_step(ps):
            with mesh:
                return pipeline_train_step(fns, ps, x, y, loss_fn, mesh,
                                           n_microbatches=4)

        m_local = (jax.jit(local_step).lower(flat, opt).compile()
                   .memory_analysis())
        m_repl = jax.jit(repl_step).lower(tuple(params)).compile() \
                    .memory_analysis()
        local_total = m_local.temp_size_in_bytes + m_local.output_size_in_bytes
        repl_total = m_repl.temp_size_in_bytes + m_repl.output_size_in_bytes
        assert local_total < repl_total, (local_total, repl_total)


class TestVmaSwitchRegression:
    def test_switch_on_axis_index_no_cross_leak_checked(self):
        """Minimal form of the pipeline's stage dispatch: lax.switch on
        axis_index inside shard_map with vma checking ON.  Each device's
        branch writes only its own slot; psum must yield the diagonal.
        Documents that in a FRESH CPU process the checked path is sound
        (the r3 cross-leak needed lax.pcast inside a branch).  The
        production code still ships check_vma=False because the checked
        path segfaults XLA:CPU in a BACKEND-SWITCHED process (axon →
        clear_backends → CPU, the driver's dryrun environment) — see the
        comment at pipeline_stages.py's shard_map call."""
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from deeplearning4j_tpu.utils.jax_compat import shard_map

        S = 4
        mesh = make_mesh(data=1, stage=S, devices=jax.devices()[:S])

        def mk(i):
            def run(operand):
                vz = operand * 0.0
                return tuple((jnp.float32(i + 1) + vz) if j == i else vz
                             for j in range(S))
            return run

        branches = [mk(i) for i in range(S)]

        def local(x):
            idx = lax.axis_index("pipe")
            outs = lax.switch(idx, branches, x[0])
            return tuple(lax.psum(o, "pipe") for o in outs)

        y = shard_map(local, mesh=mesh, in_specs=(P("pipe"),),
                      out_specs=tuple(P() for _ in range(S)),
                      check_vma=True)(jnp.arange(S, dtype=jnp.float32))
        np.testing.assert_allclose([float(v) for v in y],
                                   [1.0, 2.0, 3.0, 4.0])
