"""tpudl.serve.router / autoscale — traffic-scale serving (ISSUE 13).

Acceptance: one registry model spread across N replica engines behind a
least-queue-depth router with per-replica health; priority lanes shed
low-priority traffic FIRST and per-tenant token buckets meter noisy
tenants without touching their neighbors; the queue-depth autoscaler
grows/retires replicas within bounds (retiring always drains, never
drops); a fan-out hot-swap flips every replica atomically under
concurrent load with zero dropped or garbled responses while
``ready()`` stays true; rollback returns the WHOLE replica set
together; autoscaling racing a fan-out swap preserves every invariant;
and the engine's continuous-batching staging state is reused across
flushes with per-request outputs exact to 1e-6 on sequence workloads.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import LSTM, DenseLayer, OutputLayer, \
    RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)
from deeplearning4j_tpu.serve import (AdmissionControl, AutoscaleConfig,
                                      Autoscaler, InferenceEngine, Lane,
                                      ModelRegistry, Overloaded,
                                      QuotaExceeded, ReplicaRouter,
                                      RoutedModelError, TenantQuota)
from deeplearning4j_tpu.train import Sgd

N_IN, N_OUT = 8, 4


def _net(seed=11):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Sgd(0.1)).weight_init("xavier").list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()).init()


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, N_IN)).astype(np.float32)


@pytest.fixture
def metrics():
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


def _routed(tmp_path, seed=11, replicas=2, max_replicas=4, admission=None,
            **engine_kw):
    """Deploy one net and attach a router; returns (registry, router,
    net, zip_path)."""
    net = _net(seed)
    path = str(tmp_path / f"v{seed}.zip")
    net.save(path)
    registry = ModelRegistry(max_batch=8, max_latency_ms=2,
                             queue_limit=64, **engine_kw)
    registry.deploy("m", path)
    router = ReplicaRouter(registry, "m", replicas=replicas,
                           max_replicas=max_replicas, admission=admission)
    return registry, router, net, path


# ------------------------------------------------------------- dispatch
def test_routed_predict_and_version_attribution(tmp_path, metrics):
    registry, router, net, _ = _routed(tmp_path)
    x = _data(4, 1)
    out, version = registry.predict_versioned("m", x, timeout_s=30)
    assert version == 1
    np.testing.assert_allclose(out, np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)
    assert router.replicas == 2
    assert metrics.gauge("tpudl_router_replicas").value == 2
    # the registry's own engine was handed over: entry is engine-less,
    # the models() row carries per-replica health instead
    assert registry.get("m").engine is None
    row = next(r for r in registry.models() if r["name"] == "m")
    assert len(row["replicas"]) == 2
    assert all(r["healthy"] and r["ready"] for r in row["replicas"])
    registry.close()


def test_dispatch_spreads_and_skips_unready(tmp_path, metrics):
    registry, router, _, _ = _routed(tmp_path, replicas=2)
    x = _data(2, 2)
    rep0, rep1 = router._replicas
    rep0.ready = False
    for _ in range(6):
        router.predict(x, timeout_s=30)
    dispatch = metrics.labeled_counter("tpudl_router_dispatch_total",
                                       label_names=("replica",))
    assert dispatch.labeled_value(replica=f"r{rep0.id}") == 0
    assert dispatch.labeled_value(replica=f"r{rep1.id}") == 6
    rep0.ready = True
    # both replicas serve once ready again (concurrent closed-loop load
    # so the queues actually interleave)
    def client(cid):
        for _ in range(20):
            router.predict(x, timeout_s=30)
    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert dispatch.labeled_value(replica=f"r{rep0.id}") > 0
    registry.close()


def test_direct_registry_deploy_refused_on_routed_model(tmp_path, metrics):
    registry, router, _, path = _routed(tmp_path)
    with pytest.raises(RoutedModelError):
        registry.deploy("m", path)
    # the fleet is untouched
    assert router.replicas == 2
    assert registry.get("m").version == 1
    registry.close()


# ----------------------------------------------------------- admission
def test_lane_shed_low_priority_first(tmp_path, metrics):
    """A lane past its shed threshold sheds while the high-priority
    lane keeps serving — Overloaded stops being binary."""
    admission = AdmissionControl(
        lanes=[Lane("interactive", 0, shed_at=1.0),
               Lane("batch", 1, shed_at=0.0)],     # sheds at ANY pressure
        default_lane="interactive")
    registry, router, net, _ = _routed(tmp_path, admission=admission)
    x = _data(2, 3)
    with pytest.raises(Overloaded):
        router.predict(x, lane="batch", timeout_s=30)
    out = router.predict(x, lane="interactive", timeout_s=30)
    np.testing.assert_allclose(out, np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)
    shed = metrics.labeled_counter("tpudl_router_shed_total",
                                   label_names=("lane",))
    assert shed.labeled_value(lane="batch") == 1
    assert shed.labeled_value(lane="interactive") == 0
    # unknown lane rides the default (interactive) lane
    assert router.predict(x, lane="nope", timeout_s=30).shape == (2, N_OUT)
    registry.close()


def test_tenant_token_bucket_quota(tmp_path, metrics):
    """A tenant over its rate is shed with QuotaExceeded (→ 429) while
    other tenants — and unmetered traffic — are untouched."""
    admission = AdmissionControl(
        quotas={"noisy": TenantQuota(rate=0.001, burst=2)})
    registry, router, _, _ = _routed(tmp_path, admission=admission)
    x = _data(1, 4)
    router.predict(x, tenant="noisy", timeout_s=30)
    router.predict(x, tenant="noisy", timeout_s=30)
    with pytest.raises(QuotaExceeded):
        router.predict(x, tenant="noisy", timeout_s=30)
    router.predict(x, tenant="polite", timeout_s=30)   # unaffected
    router.predict(x, timeout_s=30)                    # unmetered
    requests = metrics.labeled_counter("tpudl_serve_tenant_requests_total",
                                       label_names=("tenant",))
    shed = metrics.labeled_counter("tpudl_serve_tenant_shed_total",
                                   label_names=("tenant",))
    assert requests.labeled_value(tenant="noisy") == 3
    assert shed.labeled_value(tenant="noisy") == 1
    assert shed.labeled_value(tenant="polite") == 0
    registry.close()


def test_server_tenant_and_lane_headers(tmp_path, metrics):
    """X-Tenant/X-Lane ride the HTTP front door into the router's
    admission control; a quota shed maps to 429 like any Overloaded."""
    import http.client
    import json

    from deeplearning4j_tpu.serve import ModelServer
    admission = AdmissionControl(
        quotas={"noisy": TenantQuota(rate=0.001, burst=1)})
    registry, router, _, _ = _routed(tmp_path, admission=admission)
    server = ModelServer(registry, port=0)
    try:
        def post(tenant):
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            conn.request("POST", "/v1/models/m:predict",
                         json.dumps({"instances": _data(1, 5).tolist()}),
                         {"X-Tenant": tenant, "X-Lane": "interactive"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            return resp.status, body

        status, body = post("noisy")
        assert status == 200 and len(body["predictions"]) == 1
        status, body = post("noisy")        # burst of 1 exhausted
        assert status == 429
        assert "quota" in body["error"]
        status, _ = post("polite")
        assert status == 200
    finally:
        server.stop()
        registry.close()


# ----------------------------------------------------------- autoscale
def test_autoscaler_scales_up_down_and_heals(tmp_path, metrics,
                                             monkeypatch):
    registry, router, _, _ = _routed(tmp_path, replicas=1, max_replicas=3)
    scaler = Autoscaler(router, AutoscaleConfig(
        scale_up_at=0.5, scale_down_at=0.05, poll_s=30.0,
        up_cooldown_s=0.0, down_cooldown_s=0.0, window=1))
    try:
        monkeypatch.setattr(router, "queue_fill", lambda: 0.9)
        scaler.step()
        scaler.step()
        assert router.replicas == 3
        scaler.step()                      # bounded at max_replicas
        assert router.replicas == 3
        assert metrics.counter("tpudl_router_scale_ups_total").value == 2
        monkeypatch.setattr(router, "queue_fill", lambda: 0.0)
        scaler.step()
        scaler.step()
        assert router.replicas == 1
        scaler.step()                      # bounded at min_replicas
        assert router.replicas == 1
        assert metrics.counter("tpudl_router_scale_downs_total").value == 2
        # heal: a replica whose engine died is replaced on the next poll
        sick = router._replicas[0]
        sick.engine.shutdown(drain=True)
        assert not router.ready()
        scaler.step()
        assert router.replicas == 1
        assert router.ready()
        assert router._replicas[0].id != sick.id
    finally:
        scaler.close()
        registry.close()


def test_retire_always_drains_never_drops(tmp_path, metrics):
    """Queued work on a retiring replica completes before its engine
    goes away — scale-down can't fail a request."""
    registry, router, net, _ = _routed(tmp_path, replicas=2)
    x = _data(8, 6)
    expected = np.asarray(net.output(x))
    futures = []
    for i in range(32):      # enough to queue on both replicas
        fut, _ = router.submit(x[i % 8:i % 8 + 1])
        futures.append((i % 8, fut))
    assert router.retire_replica()
    assert router.replicas == 1
    for i, fut in futures:
        np.testing.assert_allclose(fut.result(timeout=30), expected[i:i + 1],
                                   rtol=1e-5, atol=1e-6)
    assert metrics.counter("tpudl_router_scale_downs_total").value == 1
    registry.close()


# ------------------------------------------------------------- fan-out
def test_fan_out_swap_under_concurrent_load(tmp_path, metrics):
    """Deploy v2 through the router while clients hammer the fleet:
    zero dropped, every response a valid output of exactly one version,
    every replica on v2 afterwards — and ready() stays TRUE throughout
    (only the replica mid-flip is ever unready)."""
    registry, router, net1, _ = _routed(tmp_path, replicas=3)
    net2 = _net(12)
    p2 = str(tmp_path / "v2.zip")
    net2.save(p2)
    x = _data(16, 7)
    exp1, exp2 = np.asarray(net1.output(x)), np.asarray(net2.output(x))

    errors, results, ready_samples = [], [], []
    stop = threading.Event()

    def client(cid):
        rng = np.random.default_rng(cid)
        count = 0
        while not (stop.is_set() and count >= 20):
            i = int(rng.integers(0, x.shape[0]))
            try:
                out = registry.predict("m", x[i:i + 1], timeout_s=30)
                results.append((i, np.asarray(out)[0]))
            except BaseException as e:   # noqa: BLE001 — collect all
                errors.append(e)
            count += 1
            if count > 500:
                break

    def ready_sampler():
        while not stop.is_set():
            ready_samples.append((registry.ready(), router.ready()))
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(c,)) for c in range(6)]
    sampler = threading.Thread(target=ready_sampler)
    for t in threads:
        t.start()
    sampler.start()
    time.sleep(0.2)
    entry = router.deploy(p2)            # fan-out hot-swap mid-traffic
    time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    sampler.join(timeout=10)

    assert not errors, errors[:3]
    assert len(results) >= 120
    for i, row in results:
        ok1 = np.allclose(row, exp1[i], rtol=1e-5, atol=1e-5)
        ok2 = np.allclose(row, exp2[i], rtol=1e-5, atol=1e-5)
        assert ok1 or ok2, f"garbled response for row {i}"
    assert entry.version == 2
    assert [r["version"] for r in router.replica_stats()] == [2, 2, 2]
    assert registry.get("m").version == 2
    assert metrics.labeled_gauge(
        "tpudl_serve_model_version").labeled_value(model="m") == 2
    assert metrics.counter("tpudl_router_swaps_total").value == 1
    # the front door never closed: unlike a single-engine swap, the
    # fan-out keeps /healthz green the whole time
    assert ready_samples and all(reg and rt for reg, rt in ready_samples)
    registry.close()


def test_rollback_fans_all_replicas_together(tmp_path, metrics):
    registry, router, net1, _ = _routed(tmp_path, replicas=3)
    net2 = _net(12)
    p2 = str(tmp_path / "v2.zip")
    net2.save(p2)
    router.deploy(p2)
    rolled = registry.rollback("m")      # delegates to the router
    assert rolled.version == 3
    assert [r["version"] for r in router.replica_stats()] == [3, 3, 3]
    x = _data(4, 8)
    out, version = registry.predict_versioned("m", x, timeout_s=30)
    assert version == 3
    np.testing.assert_allclose(out, np.asarray(net1.output(x)),
                               rtol=1e-5, atol=1e-6)
    assert metrics.counter("tpudl_router_swaps_total").value == 2
    registry.close()


def test_swap_zero_recompiles_same_architecture(tmp_path, metrics):
    """All replicas share the step-cached forward; a same-architecture
    fan-out costs zero recompiles — and so does adding a replica."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    registry, router, net, _ = _routed(tmp_path, replicas=2)
    x = _data(8, 9)
    it = ArrayDataSetIterator(_data(32, 10),
                              np.eye(N_OUT, dtype=np.float32)[
                                  np.random.default_rng(0).integers(
                                      0, N_OUT, 32)], 16)
    net.fit(it, epochs=1)                # same config, moved weights
    p2 = str(tmp_path / "v2.zip")
    net.save(p2)
    router.predict(x, timeout_s=30)      # compile bucket 8
    before = metrics.counter("tpudl_serve_recompiles_total").value
    router.deploy(p2)
    router.add_replica()
    out = router.predict(x, timeout_s=30)
    assert metrics.counter("tpudl_serve_recompiles_total").value == before
    np.testing.assert_allclose(
        out, np.asarray(MultiLayerNetwork.load(p2, load_updater=False)
                        .output(x)), rtol=1e-5, atol=1e-6)
    registry.close()


def test_gated_deployer_fans_out_routed_model(tmp_path, metrics):
    """The online gate is the sanctioned door: deploy_if_better on a
    routed name fans a gate-passing candidate across every replica and
    leaves the fleet untouched on refusal."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.online.gate import EvalGate, GatedDeployer
    registry, router, net1, p1 = _routed(tmp_path, replicas=2)
    x = _data(32, 11)
    labels = np.eye(N_OUT, dtype=np.float32)[
        np.argmax(np.asarray(net1.output(x)), axis=1)]
    holdout = [DataSet(x, labels)]
    gate = EvalGate(holdout, metric="accuracy", min_delta=0.0)
    deployer = GatedDeployer(registry, gate)
    # candidate = the incumbent's own weights → ties pass (non-regression)
    decision = deployer.deploy_if_better("m", p1)
    assert decision.deploy
    assert [r["version"] for r in router.replica_stats()] == [2, 2]
    # a garbage candidate is refused and the fleet stays on v2
    net_bad = _net(99)
    p_bad = str(tmp_path / "bad.zip")
    net_bad.save(p_bad)
    decision = deployer.deploy_if_better("m", p_bad)
    assert not decision.deploy
    assert [r["version"] for r in router.replica_stats()] == [2, 2]
    assert metrics.counter("tpudl_online_refusals_total").value == 1
    registry.close()


def test_autoscale_racing_fan_out_swap(tmp_path, metrics):
    """The ISSUE-13 race: scaling (add + retire, via the autoscaler's
    own step loop) races a fan-out hot-swap under client load.  After
    the dust settles every surviving replica is on the new version,
    bounds were respected, nothing was dropped or garbled."""
    registry, router, net1, _ = _routed(tmp_path, replicas=2,
                                        max_replicas=4)
    net2 = _net(12)
    p2 = str(tmp_path / "v2.zip")
    net2.save(p2)
    x = _data(16, 13)
    exp1, exp2 = np.asarray(net1.output(x)), np.asarray(net2.output(x))
    errors, results = [], []
    stop = threading.Event()

    def client(cid):
        rng = np.random.default_rng(cid)
        while not stop.is_set():
            i = int(rng.integers(0, x.shape[0]))
            try:
                out = registry.predict("m", x[i:i + 1], timeout_s=30)
                results.append((i, np.asarray(out)[0]))
            except Overloaded:
                pass                      # admission, not a drop
            except BaseException as e:   # noqa: BLE001
                errors.append(e)

    def churn():
        # alternate pressure/calm so the autoscaler adds AND retires
        # while the fan-out runs
        scaler = Autoscaler(router, AutoscaleConfig(
            scale_up_at=0.5, scale_down_at=0.05, poll_s=30.0,
            up_cooldown_s=0.0, down_cooldown_s=0.0, window=1))
        try:
            for step in range(60):
                if stop.is_set():
                    break
                fill = 0.9 if step % 2 == 0 else 0.0
                try:
                    router.queue_fill = lambda f=fill: f
                    scaler.step()
                finally:
                    del router.queue_fill   # back to the real method
                time.sleep(0.005)
        finally:
            scaler.close()

    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    churner = threading.Thread(target=churn)
    for t in threads:
        t.start()
    churner.start()
    time.sleep(0.1)
    entry = router.deploy(p2)            # fan-out races the churn
    churner.join(timeout=60)
    stop.set()
    for t in threads:
        t.join(timeout=60)

    assert not errors, errors[:3]
    assert len(results) >= 50
    for i, row in results:
        ok1 = np.allclose(row, exp1[i], rtol=1e-5, atol=1e-5)
        ok2 = np.allclose(row, exp2[i], rtol=1e-5, atol=1e-5)
        assert ok1 or ok2, f"garbled response for row {i}"
    # bounds respected, every surviving replica healthy and on v2
    assert 1 <= router.replicas <= 4
    stats = router.replica_stats()
    assert all(r["version"] == entry.version for r in stats)
    assert all(r["healthy"] for r in stats)
    out = router.predict(x[:2], timeout_s=30)
    assert np.allclose(out, exp2[:2], rtol=1e-5, atol=1e-5)
    registry.close()


# ---------------------------------------------- continuous batching (engine)
def _lstm_net(seed=31, t=6, f=5, out=3):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Sgd(0.1)).list()
        .layer(LSTM(n_out=8, activation="tanh"))
        .layer(RnnOutputLayer(n_out=out, activation="softmax",
                              loss="mcxent"))
        .set_input_type(InputType.recurrent(f, t))
        .build()).init()


def test_continuous_batching_state_reuse_sequence_workload(metrics):
    """Sequence requests ([n, T, F], the BERT-MLM/LSTM serving shape)
    ride the persistent per-signature staging buffer: outputs match the
    per-request forward to 1e-6 across many flushes, and reuse (not
    re-allocation) is counted after the first flush."""
    t, f = 6, 5
    net = _lstm_net(t=t, f=f)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, t, f)).astype(np.float32)
    expected = np.asarray(net.output(x))
    with InferenceEngine(net, name="seq", max_batch=8, max_latency_ms=5,
                         queue_limit=64, buckets=(4, 8)) as eng:
        eng.predict(x[:8], timeout_s=120)        # compile bucket 8
        eng.predict(x[:4], timeout_s=120)        # compile bucket 4
        for round_idx in range(6):               # many flushes, one buffer
            futures, offset = [], 0
            sizes = [1, 3, 2, 4, 3, 2]
            for n in sizes:
                futures.append((offset, n,
                                eng.submit(x[offset:offset + n])))
                offset += n
            for off, n, fut in futures:
                np.testing.assert_allclose(
                    fut.result(timeout=60), expected[off:off + n],
                    rtol=1e-6, atol=1e-6)
        assert metrics.counter("tpudl_serve_stage_reuse_total").value > 0
        assert eng.compiled_programs <= 2        # still one per bucket


def test_continuous_batching_masks_and_mixed_signatures(metrics):
    """Masked and maskless sequence requests share one staged batch
    (maskless rows get ones, padding rows zeros); a request with a
    different signature mid-batch falls back to the concat path without
    corrupting anyone's rows."""
    t, f = 6, 5
    net = _lstm_net(seed=32, t=t, f=f)
    rng = np.random.default_rng(4)
    x = rng.normal(size=(8, t, f)).astype(np.float32)
    mask = np.ones((2, t), np.float32)
    mask[:, 4:] = 0.0                            # truncate two sequences
    exp_masked = np.asarray(net.output(x[:2], mask=mask))
    exp_plain = np.asarray(net.output(x[2:5]))
    with InferenceEngine(net, name="mix", max_batch=8, max_latency_ms=20,
                         queue_limit=16) as eng:
        f1 = eng.submit(x[:2], mask=mask)
        f2 = eng.submit(x[2:5])                  # no mask, same flush
        np.testing.assert_allclose(f1.result(timeout=60), exp_masked,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(f2.result(timeout=60), exp_plain,
                                   rtol=1e-5, atol=1e-6)


def test_batch_stage_restage_and_zeroing():
    """_BatchStage unit semantics: stale tail rows re-zero on a smaller
    flush, dead requests compact without allocation, late-arriving
    masks backfill ones for earlier maskless rows."""
    from concurrent.futures import Future

    from deeplearning4j_tpu.serve.engine import _BatchStage, _Request

    def req(rows, mask=None):
        return _Request(np.full((rows, 2), float(rows), np.float32),
                        None if mask is None else mask, Future(), 0.0, None)

    stage = _BatchStage(8, (2,), np.float32)
    stage.begin()
    r1, r2 = req(3), req(2)
    assert stage.put(r1, 0) and stage.put(r2, 3)
    view = stage.view(8, 5)
    assert (view[:3] == 3.0).all() and (view[3:5] == 2.0).all()
    assert (view[5:] == 0.0).all()
    assert stage.mask_view(8, 5) is None
    # smaller next flush: rows 2..5 held stale data and must re-zero
    stage.begin()
    r3 = req(2)
    assert stage.put(r3, 0)
    view = stage.view(4, 2)
    assert (view[:2] == 2.0).all() and (view[2:] == 0.0).all()
    # dead-request compaction: restage only the survivors — and rows
    # the dead request had already staged past the survivors' extent
    # must re-zero on the NEXT flush (put moves the high-water mark at
    # write time, not view time)
    stage.begin()
    a, b, c = req(2), req(1), req(3)
    stage.put(a, 0), stage.put(b, 2), stage.put(c, 3)
    stage.restage([a, c])                        # b expired pre-dispatch
    view = stage.view(8, 5)
    assert (view[:2] == 2.0).all() and (view[2:5] == 3.0).all()
    assert (view[5:] == 0.0).all()
    stage.begin()
    stage.put(req(1), 0)
    view = stage.view(8, 1)
    assert (view[1:] == 0.0).all()               # rows 1..4 re-zeroed
    # late mask: earlier maskless rows backfill with ones
    stage.begin()
    m = np.zeros((2, 3), np.float32)
    stage.put(req(2), 0)
    stage.put(req(2, mask=m), 2)
    mask_view = stage.mask_view(8, 4)
    assert (mask_view[:2] == 1.0).all() and (mask_view[2:4] == 0.0).all()
    assert (mask_view[4:] == 0.0).all()
