"""Telemetry federation: RemoteStatsRouter buffering/backpressure, the
UIServer ingest + /cluster surface, ClusterStore straggler detection,
and the ISSUE-7 acceptance rig — a spawn_local_cluster gang whose every
worker reports in, with a fault-injected straggler flagged on the
coordinator from federated step times alone."""

import functools
import json
import os
import socket
import sys
import time
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import cluster_workers  # noqa: E402

from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)  # noqa: E402
from deeplearning4j_tpu.obs.remote import (ClusterStore,  # noqa: E402
                                           RemoteStatsRouter)
from deeplearning4j_tpu.obs.ui_server import UIServer  # noqa: E402

_ENV = {"PYTHONPATH": os.path.dirname(__file__) + os.pathsep +
        os.environ.get("PYTHONPATH", "")}


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.read().decode()


@pytest.fixture
def registry():
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


# ===================================================== router semantics
class TestRouter:
    def test_loopback_round_trip(self, registry):
        """Records pushed through the router land in the coordinator's
        ClusterStore and on /metrics with a worker label."""
        server = UIServer(port=0)
        router = RemoteStatsRouter(server.url, worker="rt",
                                   flush_interval_s=0.02)
        try:
            for i in range(4):
                router.put_event("step", iteration=i, step_seconds=0.01,
                                 score=0.5)
            router.put({"type": "stats", "iteration": 3,
                        "params": {"0": {"norm": 1.0}}})
            deadline = time.monotonic() + 10
            summary = {}
            while time.monotonic() < deadline:
                summary = json.loads(_get(server.url + "cluster.json"))
                if summary["workers"].get("rt", {}).get("steps") == 4:
                    break
                time.sleep(0.02)
            worker = summary["workers"]["rt"]
            assert worker["steps"] == 4
            assert worker["iteration"] == 3
            assert worker["median_step_ms"] == pytest.approx(10.0)
            assert worker["liveness_age_s"] < 10
            # the full stats record rides along (dashboard replay)
            assert server.cluster.records_for("rt")
            body = _get(server.url + "metrics")
            assert 'tpudl_cluster_worker_iteration{worker="rt"} 3' in body
            assert 'tpudl_cluster_step_seconds_count{worker="rt"} 4' in body
            assert router.dropped == 0
        finally:
            router.close(timeout=2)
            server.stop()

    def test_put_is_nonblocking_and_buffer_bounded(self, registry):
        """With NO coordinator at all, producers never block and the
        buffer stays bounded (drop-oldest, counted)."""
        # a port nothing listens on: connect fails fast
        router = RemoteStatsRouter("http://127.0.0.1:9", worker="nb",
                                   flush_interval_s=10.0, max_buffer=16,
                                   timeout_s=0.2)
        try:
            t0 = time.perf_counter()
            for i in range(5000):
                router.put_event("step", iteration=i)
            elapsed = time.perf_counter() - t0
            assert elapsed < 2.0          # ~µs/append, never a network wait
            assert len(router._buf) <= 16
            # everything beyond the bounded buffer + one in-flight batch
            # is dropped AND counted
            assert router.dropped >= 5000 - 16 - 64
        finally:
            router.close(timeout=5)

    def test_stalled_coordinator_never_blocks_fit(self, registry):
        """THE off-step-path contract: a stalled (non-accepting)
        coordinator leaves fit() step timings unaffected; the worker
        exits cleanly with a bounded drop counter, never an exception."""
        import jax
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.obs import remote
        from deeplearning4j_tpu.train.trainer import Trainer

        # a listener that never accepts: connections sit in the backlog
        # (or hang in SYN) — the worst case for a synchronous pusher
        blocked = socket.create_server(("127.0.0.1", 0), backlog=1)
        port = blocked.getsockname()[1]
        router = remote.install(f"http://127.0.0.1:{port}",
                                worker="stalled", flush_interval_s=0.02,
                                max_buffer=8, timeout_s=0.3)
        try:
            net = cluster_workers._small_net(seed=5)
            trainer = Trainer(net)
            x, y = cluster_workers.global_batch(n=16, seed=0)
            batch = DataSet(x, y)
            key = jax.random.key(0)
            trainer.step_batch(batch, key)    # compile outside the clock
            t0 = time.perf_counter()
            for _ in range(20):
                key, sub = jax.random.split(key)
                trainer.step_batch(batch, sub)
            wall = time.perf_counter() - t0
            # 20 CPU steps are milliseconds; a step path that waited on
            # the stalled socket even once would eat a 0.3s timeout
            assert wall < 3.0, f"steps took {wall:.2f}s with a stalled " \
                               f"coordinator — pushes are ON the step path"
            router.close(timeout=5.0)         # clean exit, no exception
            assert not router._thread.is_alive()
            assert router.dropped > 0         # bounded loss, counted
            assert router.dropped <= 20 + 8 + router.push_failures * 64
        finally:
            remote.close_router()
            blocked.close()

    def test_stats_listener_federates_through_router(self, registry):
        """StatsListener(storage=router): the full stats records (incl.
        the init topology) arrive on the coordinator."""
        import jax
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.obs.stats import StatsListener
        from deeplearning4j_tpu.train.trainer import Trainer

        server = UIServer(port=0)
        router = RemoteStatsRouter(server.url, worker="sl",
                                   flush_interval_s=0.02)
        try:
            net = cluster_workers._small_net(seed=6)
            trainer = Trainer(net, listeners=[StatsListener(router,
                                                            frequency=1)])
            x, y = cluster_workers.global_batch(n=8, seed=1)
            key = jax.random.key(0)
            for _ in range(3):
                key, sub = jax.random.split(key)
                trainer.step_batch(DataSet(x, y), sub)
            deadline = time.monotonic() + 10
            records = []
            while time.monotonic() < deadline:
                records = server.cluster.records_for("sl")
                if sum(1 for r in records if r.get("type") == "stats") >= 3:
                    break
                time.sleep(0.02)
            kinds = [r.get("type") for r in records]
            assert kinds.count("stats") >= 3
            assert "init" in kinds            # topology record federated
            stats = next(r for r in records if r.get("type") == "stats")
            assert "params" in stats and "gradients" in stats
        finally:
            router.close(timeout=2)
            server.stop()


# ================================================ coordinator-side logic
class TestClusterStore:
    def _feed(self, store, worker, step_s, n=6):
        store.ingest(worker, [{"type": "step", "iteration": i,
                               "step_seconds": step_s, "score": 1.0}
                              for i in range(n)])

    def test_straggler_flagged_and_counted(self, registry):
        from deeplearning4j_tpu.obs.registry import install_standard_metrics
        install_standard_metrics()
        store = ClusterStore(straggler_factor=2.0)
        self._feed(store, "w0", 0.01)
        self._feed(store, "w1", 0.011)
        self._feed(store, "w2", 0.009)
        self._feed(store, "w3", 0.05)     # 5x the median
        summary = store.summary()
        assert summary["workers"]["w3"]["straggler"] is True
        assert all(not summary["workers"][w]["straggler"]
                   for w in ("w0", "w1", "w2"))
        assert summary["straggler_skew"] > 2.0
        anomalies = get_registry().labeled_counter(
            "tpudl_health_anomalies_total", label_names=("kind",))
        assert anomalies.labeled_value(kind="straggler") == 1.0
        # an even gang never flags
        even = ClusterStore(straggler_factor=2.0)
        for w in ("a", "b", "c"):
            self._feed(even, w, 0.01)
        assert even.straggler_skew() == pytest.approx(1.0)
        assert not any(w["straggler"]
                       for w in even.summary()["workers"].values())

    def test_steps_per_s_uses_producer_clock(self, registry):
        """A router flush delivers many step records in ONE ingest call;
        the rate must come from the records' own ``time`` stamps, not
        the (near-zero) coordinator receipt span."""
        store = ClusterStore()
        t0 = time.time()
        store.ingest("w", [{"type": "step", "iteration": i,
                            "step_seconds": 0.1, "time": t0 + i * 0.1}
                           for i in range(11)])     # 10 Hz worker
        rate = store.summary()["workers"]["w"]["steps_per_s"]
        assert rate == pytest.approx(10.0, rel=0.01)
        # records without a producer clock fall back to 1/median, never
        # to the inflated receipt-span rate
        bare = ClusterStore()
        bare.ingest("w", [{"type": "step", "iteration": i,
                           "step_seconds": 0.05} for i in range(6)])
        assert bare.summary()["workers"]["w"]["steps_per_s"] \
            == pytest.approx(20.0, rel=0.01)

    def test_ingest_rejects_garbage_payloads(self, registry):
        server = UIServer(port=0)
        try:
            req = urllib.request.Request(
                server.url.rstrip("/") + "/remote/stats",
                data=b"not json",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 400
            # a proper payload on a wrong path 404s
            req = urllib.request.Request(
                server.url.rstrip("/") + "/remote/nope", data=b"{}")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 404
        finally:
            server.stop()

    def test_malformed_record_fields_never_500(self, registry):
        """Structurally-valid JSON whose record FIELDS are garbage (a
        null iteration) must not crash the handler or poison the worker
        state: the bad record is skipped, its siblings land."""
        store = ClusterStore()
        n = store.ingest("w", [
            {"type": "step", "iteration": None},              # skipped
            {"type": "step", "iteration": 0, "step_seconds": 0.01},
            {"type": "step", "iteration": "nope"},            # skipped
            {"type": "step", "iteration": 1, "step_seconds": 0.01},
        ])
        assert n == 2
        w = store.summary()["workers"]["w"]
        assert w["steps"] == 2 and w["iteration"] == 1
        # over HTTP the same payload answers 200 (never a connection
        # reset from an unhandled handler exception)
        server = UIServer(port=0)
        try:
            req = urllib.request.Request(
                server.url.rstrip("/") + "/remote/stats",
                data=json.dumps({"worker": "w", "records": [
                    {"type": "step", "iteration": None},
                    {"type": "step", "iteration": 3},
                ]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert json.loads(resp.read())["ok"] == 1
        finally:
            server.stop()


# ======================================================= the acceptance
class TestClusterFederationE2E:
    def test_four_workers_report_in_and_straggler_is_flagged(self):
        """ISSUE-7 acceptance: 4 workers under spawn_local_cluster →
        the coordinator's /metrics exposes per-worker series with
        ``worker`` labels, /cluster renders per-worker step time +
        liveness, and the delay@-injected worker 0 is flagged as a
        straggler from federated telemetry alone."""
        from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster

        server = UIServer(port=0)
        try:
            fn = functools.partial(cluster_workers.telemetry_train_worker,
                                   steps=8, straggler_pid=0, delay_s=0.25)
            results = spawn_local_cluster(fn, n_processes=4, port=23801,
                                          timeout=240.0, extra_env=_ENV,
                                          remote_ui=server.url)
            assert len(results) == 4
            summary = json.loads(_get(server.url + "cluster.json"))
            workers = summary["workers"]
            assert sorted(workers) == ["w0", "w1", "w2", "w3"]
            for name, w in workers.items():
                assert w["steps"] == 8, (name, w)
                assert w["median_step_ms"] is not None
                assert w["liveness_age_s"] < 120
            # the injected 0.25s delay dwarfs a millisecond CPU step
            assert workers["w0"]["straggler"] is True
            assert not any(workers[w]["straggler"]
                           for w in ("w1", "w2", "w3"))
            assert summary["straggler_skew"] > 2.0
            # federated /metrics: per-worker series under one scrape
            body = _get(server.url + "metrics")
            for w in ("w0", "w1", "w2", "w3"):
                assert f'tpudl_cluster_worker_iteration{{worker="{w}"}} 7' \
                    in body
                assert f'tpudl_cluster_step_seconds_count{{worker="{w}"}}' \
                    in body
            # /cluster renders per-worker step time + liveness + the flag
            html = _get(server.url + "cluster")
            assert "median step ms" in html and "liveness age s" in html
            assert "w3" in html and "straggler" in html
            # the coordinator's health family saw the straggler verdict
            anomalies = get_registry().labeled_counter(
                "tpudl_health_anomalies_total", label_names=("kind",))
            assert anomalies.labeled_value(kind="straggler") >= 1.0
        finally:
            server.stop()


# ============================================== multichip bench record
def test_bench_multichip_record_measures_scaling(tmp_path):
    """The ROADMAP-2 deliverable plus the ISSUE-8 recovery row:
    bench/multichip.py completes on CPU (rc=0 — runs with the tunnel
    down), reports measured per_chip_scaling_efficiency +
    straggler_skew from federated telemetry, and the recovery record
    shows a supervised kill-and-heal with measured mttr_s and
    steps_replayed."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DL4J_TPU_MULTICHIP_WORKERS": "2",
           "DL4J_TPU_MULTICHIP_STEPS": "5",
           "DL4J_TPU_MULTICHIP_RECOVERY_STEPS": "8",
           "DL4J_TPU_MULTICHIP_PORT": "24451"}
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench", "multichip.py")],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    record = json.loads([ln for ln in proc.stdout.splitlines()
                         if ln.startswith("{")][-1])
    assert record["metric"] == "multichip_scaling_efficiency"
    assert record["n_workers"] == 2
    assert record["per_chip_scaling_efficiency"] > 0
    assert record["straggler_skew"] >= 1.0
    workers = record["detail"]["workers"]
    assert sorted(workers) == ["w0", "w1"]
    assert all(w["median_step_ms"] for w in workers.values())
    assert record["detail"]["source"] == "federated_telemetry"
    # the ISSUE-8 recovery record: injected worker kill under the
    # supervisor, measured MTTR + steps replayed, recovered: true
    recovery = record["recovery"]
    assert recovery["recovered"] is True
    assert recovery["restarts"] == 1
    assert recovery["reason"] == "killed"
    assert recovery["mttr_s"] is not None and recovery["mttr_s"] > 0
    assert recovery["steps_replayed"] is not None
    assert recovery["steps_replayed"] >= 0


# ==================================== restart generations (self-healing)
class TestGenerationAwareStore:
    def test_restart_resets_window_and_drops_stale_records(self, registry):
        """A respawned worker re-registers under generation+1: its dead
        predecessor's step window stops feeding straggler math and
        median_step_ms, and the predecessor's late buffered records are
        dropped (counted), never mixed into the new series."""
        from deeplearning4j_tpu.obs.registry import install_standard_metrics
        install_standard_metrics()
        store = ClusterStore(straggler_factor=2.0)
        # generation 0: w1 is pathologically slow → flagged straggler
        for w, dt in (("w0", 0.01), ("w2", 0.01)):
            store.ingest(w, [{"type": "step", "iteration": i,
                              "step_seconds": dt} for i in range(6)])
        store.ingest("w1", [{"type": "step", "iteration": i,
                             "step_seconds": 0.08} for i in range(6)])
        assert store.summary()["workers"]["w1"]["straggler"] is True
        # the supervisor respawns w1; generation 1 is healthy
        store.ingest("w1", [{"type": "resume", "iteration": 4}],
                     generation=1)
        store.ingest("w1", [{"type": "step", "iteration": i,
                             "step_seconds": 0.01} for i in range(4, 10)],
                     generation=1)
        w1 = store.summary()["workers"]["w1"]
        assert w1["generation"] == 1
        assert w1["restarts"] == 1
        assert w1["resumed_iteration"] == 4
        # the pre-crash 80ms window is GONE: median reflects gen 1 only
        assert w1["median_step_ms"] == pytest.approx(10.0)
        assert w1["straggler"] is False
        assert store.straggler_skew() == pytest.approx(1.0)
        # a dying predecessor's buffered telemetry arrives late: dropped
        n = store.ingest("w1", [{"type": "step", "iteration": 99,
                                 "step_seconds": 0.5}], generation=0)
        assert n == 0
        assert store.summary()["workers"]["w1"]["median_step_ms"] \
            == pytest.approx(10.0)
        assert get_registry().counter(
            "tpudl_cluster_stale_records_total").value == 1
        # restart annotation recorded for the /cluster dashboard
        notes = store.summary()["restarts"]
        assert len(notes) == 1
        assert notes[0]["worker"] == "w1"
        assert notes[0]["from_generation"] == 0
        assert notes[0]["to_generation"] == 1
        assert notes[0]["last_iteration"] == 5
        html = store.render_html(refresh_seconds=0)
        assert "generation" in html and "Restarts" in html

    def test_ingest_generation_rides_http_payload(self, registry):
        """The router stamps its generation on every push; the UIServer
        hands it to the store."""
        server = UIServer(port=0)
        router = RemoteStatsRouter(server.url, worker="gw",
                                   flush_interval_s=0.02, generation=3)
        try:
            router.put_event("step", iteration=0, step_seconds=0.01)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                summary = json.loads(_get(server.url + "cluster.json"))
                if summary["workers"].get("gw", {}).get("steps") == 1:
                    break
                time.sleep(0.02)
            assert summary["workers"]["gw"]["generation"] == 3
            body = _get(server.url + "metrics")
            assert 'tpudl_cluster_worker_generation{worker="gw"} 3' in body
        finally:
            router.close(timeout=2)
            server.stop()

    def test_router_generation_defaults_from_env(self, registry, monkeypatch):
        from deeplearning4j_tpu.obs import remote
        monkeypatch.setenv(remote.GENERATION_ENV, "5")
        router = RemoteStatsRouter("http://127.0.0.1:9", worker="ge",
                                   flush_interval_s=10.0)
        try:
            assert router.generation == 5
        finally:
            router.close(timeout=1)
