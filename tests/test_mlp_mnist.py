"""M1 acceptance: MLPMnist end-to-end (BASELINE workload #1).

Mirrors dl4j-examples MLPMnistSingleLayerExample /
MLPMnistTwoLayerExample: Dense+ReLU → OutputLayer(softmax, MCXENT),
Adam — train, evaluate, checkpoint round-trip.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.data import datasets
from deeplearning4j_tpu.obs.listeners import CollectScoresListener


def build_net(seed=123):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(1e-3))
            .weight_init("xavier")
            .l2(1e-4)
            .list()
            .layer(DenseLayer(n_out=128, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    return MultiLayerNetwork(conf).init()


def test_mlp_mnist_trains_and_evaluates(tmp_path):
    net = build_net()
    assert net.num_params() == 784 * 128 + 128 + 128 * 10 + 10

    train_iter = datasets.mnist(batch_size=128, train=True, n_synthetic=4000)
    test_iter = datasets.mnist(batch_size=256, train=False, n_synthetic=4000)

    scores = CollectScoresListener()
    net.fit(train_iter, epochs=3, listeners=[scores])

    # loss must decrease substantially
    assert scores.scores[-1] < scores.scores[0] * 0.7, (
        f"loss did not decrease: {scores.scores[0]} -> {scores.scores[-1]}")

    evaluation = net.evaluate(test_iter)
    assert evaluation.accuracy() > 0.90, evaluation.stats()
    assert 0.0 < evaluation.f1() <= 1.0
    stats = evaluation.stats()
    assert "Accuracy" in stats and "Confusion" in stats


def test_checkpoint_roundtrip_resume_identical(tmp_path):
    """SURVEY §7.3 acceptance: save → load → params identical; training
    continues from the restored updater state."""
    net = build_net()
    train_iter = datasets.mnist(batch_size=64, train=True, n_synthetic=640,
                                shuffle=False)
    net.fit(train_iter, epochs=1)

    path = str(tmp_path / "model.zip")
    net.save(path)
    restored = MultiLayerNetwork.load(path)

    np.testing.assert_array_equal(np.asarray(net.params()), np.asarray(restored.params()))
    assert restored.iteration == net.iteration
    assert restored.epoch == net.epoch

    # outputs identical
    x = np.asarray(datasets.mnist(batch_size=8, train=False, n_synthetic=640).features[:8])
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(restored.output(x)), rtol=1e-6)

    # continued training from restored updater state matches continued
    # training of the original (deterministic resume)
    net.fit(train_iter, epochs=1)
    restored.fit(train_iter, epochs=1)
    np.testing.assert_allclose(np.asarray(net.params()),
                               np.asarray(restored.params()), rtol=1e-5, atol=1e-6)


def test_config_json_roundtrip():
    net = build_net()
    js = net.conf.to_json()
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.to_json() == js
    net2 = MultiLayerNetwork(conf2).init()
    assert net2.num_params() == net.num_params()
