"""Op golden-value tests + coverage ledger — the OpValidation translation.

Reference: nd4j-api ``org/nd4j/autodiff/validation/OpValidation.java`` —
every op test asserts forward values (vs an independent numpy reference)
and differentiable ops get ``jax.test_util.check_grads``; a coverage
ledger tracks which registered namespace ops have coverage and FAILS when
coverage regresses against the committed ``tests/op_coverage.json``.
"""

import math as pymath
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.validation import CoverageLedger, op_inventory
from deeplearning4j_tpu.ops import namespaces as ns

BASELINE = os.path.join(os.path.dirname(__file__), "op_coverage.json")
LEDGER = CoverageLedger(BASELINE)

R = np.random.default_rng(42)
A = R.normal(size=(3, 4)).astype(np.float32)          # symmetric reals
B = R.normal(size=(3, 4)).astype(np.float32)
P = R.uniform(0.5, 2.0, (3, 4)).astype(np.float32)    # strictly positive
U = R.uniform(0.05, 0.95, (3, 4)).astype(np.float32)  # in (0,1)
SQ = R.normal(size=(4, 4)).astype(np.float32)
SPD = (SQ @ SQ.T + 4 * np.eye(4)).astype(np.float32)  # symmetric pos-def
I8 = R.integers(0, 127, (3, 4)).astype(np.int32)
J8 = R.integers(0, 127, (3, 4)).astype(np.int32)
IMG = R.uniform(0, 1, (2, 6, 8, 3)).astype(np.float32)


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# (namespace, op, args, numpy-reference fn | None).  A None reference means
# the case exercises the op and checks finiteness/shape only (still counts
# as executed coverage, e.g. for jax.random samplers where the golden
# property is determinism, tested separately below).
CASES = [
    # ---- math: numpy-named twins
    *[("math", name, (A,), getattr(np, name)) for name in
      ("abs", "ceil", "floor", "exp", "expm1", "square", "sign",
       "sin", "cos", "tan", "sinh", "cosh", "tanh", "cumsum")],
    ("math", "round", (A,), lambda x: np.round(x)),
    *[("math", name, (P,), getattr(np, name)) for name in
      ("log", "log1p", "log2", "log10", "sqrt", "reciprocal", "cumprod")],
    ("math", "rsqrt", (P,), lambda x: 1.0 / np.sqrt(x)),
    ("math", "cube", (A,), lambda x: x ** 3),
    ("math", "pow", (P, 2.5), np.power),
    ("math", "neg", (A,), np.negative),
    ("math", "asin", (U,), np.arcsin),
    ("math", "acos", (U,), np.arccos),
    ("math", "atan", (A,), np.arctan),
    ("math", "atan2", (A, B), np.arctan2),
    ("math", "asinh", (A,), np.arcsinh),
    ("math", "acosh", (1.0 + P,), np.arccosh),
    ("math", "atanh", (U,), np.arctanh),
    ("math", "erf", (A,), None),   # scipy-free: checked vs tanh approx below
    ("math", "erfc", (A,), None),
    ("math", "clip_by_value", (A, -0.5, 0.5), lambda x, lo, hi: np.clip(x, lo, hi)),
    ("math", "clip_by_norm", (A, 1.0),
     lambda x, n: x * min(1.0, n / np.linalg.norm(x))),
    ("math", "add", (A, B), np.add), ("math", "sub", (A, B), np.subtract),
    ("math", "mul", (A, B), np.multiply), ("math", "div", (A, P), np.divide),
    ("math", "floormod", (A, P), np.mod),
    ("math", "floordiv", (A, P), np.floor_divide),
    ("math", "maximum", (A, B), np.maximum),
    ("math", "minimum", (A, B), np.minimum),
    *[("math", name, (A,), getattr(np, name)) for name in
      ("mean", "sum", "prod", "max", "min", "std", "var", "argmax", "argmin")],
    ("math", "norm1", (A,), lambda x: np.sum(np.abs(x))),
    ("math", "norm2", (A,), lambda x: np.sqrt(np.sum(x * x))),
    ("math", "normmax", (A,), lambda x: np.max(np.abs(x))),
    ("math", "iamax", (A,), lambda x: np.argmax(np.abs(x))),
    ("math", "iamin", (A,), lambda x: np.argmin(np.abs(x))),
    ("math", "count_nonzero", (A,), np.count_nonzero),
    ("math", "count_zero", (np.array([0.0, 1.0, 0.0, 2.0]),),
     lambda x: np.sum(x == 0)),
    ("math", "entropy", (U,), lambda x: -np.sum(x * np.log(x))),
    ("math", "log_entropy", (U,), lambda x: np.log(-np.sum(x * np.log(x)))),
    ("math", "shannon_entropy", (U,), lambda x: -np.sum(x * np.log2(x))),
    ("math", "amean", (A,), lambda x: np.mean(np.abs(x))),
    ("math", "amax", (A,), lambda x: np.max(np.abs(x))),
    ("math", "amin", (A,), lambda x: np.min(np.abs(x))),
    ("math", "asum", (A,), lambda x: np.sum(np.abs(x))),
    ("math", "standardize", (A,),
     lambda x: (x - x.mean(-1, keepdims=True)) / x.std(-1, keepdims=True)),
    ("math", "is_nan", (A,), np.isnan),
    ("math", "is_inf", (A,), np.isinf),
    ("math", "is_finite", (A,), np.isfinite),
    ("math", "cosine_similarity", (A, B),
     lambda a, b: np.sum(a * b, -1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))),
    ("math", "cosine_distance", (A, B),
     lambda a, b: 1 - np.sum(a * b, -1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))),
    ("math", "euclidean_distance", (A, B),
     lambda a, b: np.linalg.norm(a - b, axis=-1)),
    ("math", "manhattan_distance", (A, B),
     lambda a, b: np.sum(np.abs(a - b), -1)),
    ("math", "hamming_distance", (I8, J8), lambda a, b: np.sum(a != b, -1)),
    ("math", "jaccard_distance", (P, 2 * P[::-1]),
     lambda a, b: 1 - np.sum(np.minimum(a, b), -1) / np.sum(np.maximum(a, b), -1)),
    # ---- nn
    ("nn", "relu", (A,), lambda x: np.maximum(x, 0)),
    ("nn", "relu6", (A,), lambda x: np.clip(x, 0, 6)),
    ("nn", "elu", (A,), lambda x: np.where(x > 0, x, np.expm1(x))),
    ("nn", "selu", (A,), lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * np.expm1(x))),
    ("nn", "gelu", (A,), None),
    ("nn", "silu", (A,), lambda x: x / (1 + np.exp(-x))),
    ("nn", "swish", (A,), lambda x: x / (1 + np.exp(-x))),
    ("nn", "sigmoid", (A,), lambda x: 1 / (1 + np.exp(-x))),
    ("nn", "hard_sigmoid", (A,), lambda x: np.clip(x / 6 + 0.5, 0, 1)),
    ("nn", "tanh", (A,), np.tanh),
    ("nn", "hard_tanh", (A,), lambda x: np.clip(x, -1, 1)),
    ("nn", "softmax", (A,), _softmax),
    ("nn", "log_softmax", (A,), lambda x: np.log(_softmax(x))),
    ("nn", "softplus", (A,), lambda x: np.log1p(np.exp(x))),
    ("nn", "softsign", (A,), lambda x: x / (1 + np.abs(x))),
    ("nn", "leaky_relu", (A,), lambda x: np.where(x > 0, x, 0.01 * x)),
    ("nn", "log_sigmoid", (A,), lambda x: -np.log1p(np.exp(-x))),
    ("nn", "one_hot", (np.array([0, 2, 1]), 3), lambda i, n: np.eye(n)[i]),
    ("nn", "linear", (A, B.T, np.ones(3, np.float32)),
     lambda x, w, b: x @ w + b),
    ("nn", "layer_norm", (A, np.ones(4, np.float32), np.zeros(4, np.float32)),
     lambda x, g, b: (x - x.mean(-1, keepdims=True))
     / np.sqrt(x.var(-1, keepdims=True) + 1e-5) * g + b),
    ("nn", "batch_norm", (A, A.mean(0), A.var(0), np.ones(4, np.float32),
                          np.zeros(4, np.float32)),
     lambda x, m, v, g, b: (x - m) / np.sqrt(v + 1e-5) * g + b),
    ("nn", "pad", (A, ((1, 1), (0, 0))), np.pad),
    ("nn", "dropout", None, None),  # handled in test_random_ops
    # ---- linalg
    ("linalg", "mmul", (A, B.T), np.matmul),
    ("linalg", "matmul", (A, B.T), np.matmul),
    ("linalg", "gemm", (A, B), lambda a, b: a @ b.T, {"transpose_b": True}),
    ("linalg", "tensormmul", (A, B.T, 1), np.tensordot),
    ("linalg", "dot", (A[0], B[0]), np.dot),
    ("linalg", "vdot", (A, B), np.vdot),
    ("linalg", "outer", (A[0], B[0]), np.outer),
    ("linalg", "einsum", ("ij,kj->ik", A, B), np.einsum),
    ("linalg", "cholesky", (SPD,), np.linalg.cholesky),
    ("linalg", "inv", (SPD,), np.linalg.inv),
    ("linalg", "pinv", (A,), np.linalg.pinv),
    ("linalg", "det", (SPD,), np.linalg.det),
    ("linalg", "slogdet", (SPD,), None),
    ("linalg", "eigh", (SPD,), None),
    ("linalg", "eig", (SPD.astype(np.float64),), None),
    ("linalg", "svd", (A,), None),
    ("linalg", "qr", (A,), None),
    ("linalg", "lstsq", (SPD, R.normal(size=(4, 2)).astype(np.float32)), None),
    ("linalg", "solve", (SPD, R.normal(size=(4, 2)).astype(np.float32)),
     np.linalg.solve),
    ("linalg", "matrix_rank", (SPD,), np.linalg.matrix_rank),
    ("linalg", "norm", (A,), np.linalg.norm),
    ("linalg", "trace", (SQ,), np.trace),
    ("linalg", "diag", (A[0],), np.diag),
    ("linalg", "diag_part", (SQ,), np.diagonal),
    ("linalg", "tri", (4,), np.tri),
    ("linalg", "tril", (SQ,), np.tril),
    ("linalg", "triu", (SQ,), np.triu),
    ("linalg", "cross", (A[:, :3], B[:, :3]), np.cross),
    ("linalg", "kron", (SQ[:2, :2], SQ[2:, 2:]), np.kron),
    ("linalg", "matrix_band_part", (SQ, 1, 1),
     lambda x, lo, hi: np.triu(np.tril(x, hi), -lo)),
    # ---- bitwise
    ("bitwise", "and_", (I8, J8), np.bitwise_and),
    ("bitwise", "or_", (I8, J8), np.bitwise_or),
    ("bitwise", "xor", (I8, J8), np.bitwise_xor),
    ("bitwise", "invert", (I8,), np.bitwise_not),
    ("bitwise", "left_shift", (I8, 2), np.left_shift),
    ("bitwise", "right_shift", (I8, 2), np.right_shift),
    ("bitwise", "bits_hamming_distance", (I8, J8),
     lambda a, b: np.sum(np.unpackbits((a ^ b).view(np.uint8)))),
    # ---- image
    ("image", "flip_left_right", (IMG,), lambda x: x[:, :, ::-1, :]),
    ("image", "flip_up_down", (IMG,), lambda x: x[:, ::-1, :, :]),
    ("image", "rot90", (IMG,), None),
    ("image", "adjust_brightness", (IMG, 0.1), lambda x, d: x + d),
    ("image", "adjust_contrast", (IMG, 1.5),
     lambda x, f: (x - x.mean((-3, -2), keepdims=True)) * f
     + x.mean((-3, -2), keepdims=True)),
    ("image", "crop", (IMG, 1, 2, 3, 4), lambda x, t, l, h, w: x[:, t:t + h, l:l + w, :]),
    ("image", "rgb_to_grayscale", (IMG,),
     lambda x: np.sum(x * np.array([0.2989, 0.5870, 0.1140]), -1, keepdims=True)),
    ("image", "resize_bilinear", (IMG, 12, 16), None),
    ("image", "resize_nearest", (IMG, 12, 16), None),
]


def _naive_max_pool(x, k, s):
    n, h, w, c = x.shape
    oh, ow = (h - k) // s + 1, (w - k) // s + 1
    out = np.zeros((n, oh, ow, c), x.dtype)
    for i in range(oh):
        for j in range(ow):
            out[:, i, j] = x[:, i * s:i * s + k, j * s:j * s + k].max((1, 2))
    return out


def _naive_conv2d(x, w):
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    out = np.zeros((n, h - kh + 1, wd - kw + 1, cout), np.float32)
    for i in range(out.shape[1]):
        for j in range(out.shape[2]):
            patch = x[:, i:i + kh, j:j + kw, :].reshape(n, -1)
            out[:, i, j] = patch @ w.reshape(-1, cout)
    return out


@pytest.mark.parametrize("case", CASES, ids=lambda c: f"{c[0]}.{c[1]}")
def test_op_golden(case):
    space, op, args, ref = case[0], case[1], case[2], case[3]
    kwargs = case[4] if len(case) > 4 else {}
    fn = getattr(getattr(ns, space), op)
    if args is None:
        LEDGER.record(f"{space}.{op}")
        return
    jargs = [jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args]
    got = fn(*jargs, **kwargs)
    if ref is not None:
        want = ref(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
    else:
        for leaf in jax.tree_util.tree_leaves(got):
            arr = np.asarray(leaf)
            assert arr.size > 0
            if np.issubdtype(arr.dtype, np.floating):
                assert np.all(np.isfinite(arr))
    LEDGER.record(f"{space}.{op}")


def test_cnn_ops_golden():
    x = R.normal(size=(2, 6, 6, 3)).astype(np.float32)
    w = R.normal(size=(3, 3, 3, 4)).astype(np.float32)
    got = ns.cnn.conv2d(jnp.asarray(x), jnp.asarray(w), padding="VALID", precision="highest")
    np.testing.assert_allclose(np.asarray(got), _naive_conv2d(x, w),
                               rtol=1e-4, atol=1e-4)
    got = ns.cnn.max_pooling2d(jnp.asarray(x), (2, 2))
    np.testing.assert_allclose(np.asarray(got), _naive_max_pool(x, 2, 2))
    got = ns.cnn.avg_pooling2d(jnp.asarray(x), (2, 2))
    want = x.reshape(2, 3, 2, 3, 2, 3).transpose(0, 1, 3, 2, 4, 5).reshape(
        2, 3, 3, 4, 3).mean(3)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)
    # im2col: the reference conv lowering identity conv == matmul of cols
    cols = np.asarray(ns.cnn.im2col(jnp.asarray(x), 3, 3))
    np.testing.assert_allclose(
        cols.reshape(-1, 27) @ w.reshape(27, 4),
        _naive_conv2d(x, w).reshape(-1, 4), rtol=1e-4, atol=1e-4)
    # space_to_depth/depth_to_space round trip
    std = ns.cnn.space_to_depth(jnp.asarray(x), 2)
    assert std.shape == (2, 3, 3, 12)
    back = ns.cnn.depth_to_space(std, 2)
    np.testing.assert_allclose(np.asarray(back), x)
    up = ns.cnn.upsampling2d(jnp.asarray(x), 2)
    np.testing.assert_allclose(np.asarray(up),
                               x.repeat(2, axis=1).repeat(2, axis=2))
    LEDGER.record("cnn.conv2d", "cnn.max_pooling2d", "cnn.avg_pooling2d",
                  "cnn.im2col", "cnn.space_to_depth", "cnn.depth_to_space",
                  "cnn.upsampling2d")


def _naive_lstm_ifog(x, w, u, b):
    """Hand-rolled IFOG LSTM for weight-layout parity."""
    bt, t, _ = x.shape
    h = u.shape[0]
    hs = np.zeros((bt, h)); cs = np.zeros((bt, h))
    ys = []
    sig = lambda v: 1 / (1 + np.exp(-v))
    for step in range(t):
        z = x[:, step] @ w + hs @ u + b
        i = sig(z[:, 0:h]); f = sig(z[:, h:2 * h])
        o = sig(z[:, 2 * h:3 * h]); g = np.tanh(z[:, 3 * h:4 * h])
        cs = f * cs + i * g
        hs = o * np.tanh(cs)
        ys.append(hs)
    return np.stack(ys, 1), hs, cs


def test_rnn_ops_golden():
    x = R.normal(size=(2, 4, 3)).astype(np.float32)
    w = R.normal(size=(3, 8)).astype(np.float32) * 0.3
    u = R.normal(size=(2, 8)).astype(np.float32) * 0.3
    b = R.normal(size=(8,)).astype(np.float32) * 0.1
    y, (hT, cT) = ns.rnn.lstm_layer(jnp.asarray(x), jnp.asarray(w),
                                    jnp.asarray(u), jnp.asarray(b))
    ys, hs, cs = _naive_lstm_ifog(x, w, u, b)
    np.testing.assert_allclose(np.asarray(y), ys, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hT), hs, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(cT), cs, rtol=1e-4, atol=1e-4)
    # gru_cell: one step vs formulas (r,u,c packed order)
    h0 = R.normal(size=(2, 2)).astype(np.float32)
    wg = R.normal(size=(3, 6)).astype(np.float32) * 0.3
    ug = R.normal(size=(2, 6)).astype(np.float32) * 0.3
    bg = R.normal(size=(6,)).astype(np.float32) * 0.1
    got = np.asarray(ns.rnn.gru_cell(jnp.asarray(x[:, 0]), jnp.asarray(h0),
                                     jnp.asarray(wg), jnp.asarray(ug),
                                     jnp.asarray(bg)))
    sig = lambda v: 1 / (1 + np.exp(-v))
    zx = x[:, 0] @ wg + bg
    zh = h0 @ ug
    r = sig(zx[:, 0:2] + zh[:, 0:2])
    uu = sig(zx[:, 2:4] + zh[:, 2:4])
    c = np.tanh(zx[:, 4:6] + r * zh[:, 4:6])
    want = uu * h0 + (1 - uu) * c
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    LEDGER.record("rnn.lstm_layer", "rnn.gru_cell")


def test_loss_ops_golden():
    y = np.eye(4)[R.integers(0, 4, 5)].astype(np.float32)
    z = R.normal(size=(5, 4)).astype(np.float32)
    # mcxent vs manual cross-entropy
    got = np.asarray(ns.loss.mcxent(jnp.asarray(y), jnp.asarray(z)))
    want = -np.sum(y * np.log(_softmax(z)), -1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    got = np.asarray(ns.loss.mse(jnp.asarray(y), jnp.asarray(z), "identity"))
    np.testing.assert_allclose(got, np.mean((z - y) ** 2, -1), rtol=1e-5, atol=1e-5)
    got = np.asarray(ns.loss.mae(jnp.asarray(y), jnp.asarray(z), "identity"))
    np.testing.assert_allclose(got, np.mean(np.abs(z - y), -1), rtol=1e-5, atol=1e-5)
    yb = R.integers(0, 2, (5, 4)).astype(np.float32)
    got = np.asarray(ns.loss.binary_xent(jnp.asarray(yb), jnp.asarray(z)))
    p = 1 / (1 + np.exp(-z))
    want = -np.sum(yb * np.log(p) + (1 - yb) * np.log(1 - p), -1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    # the remaining losses get gradient coverage in test_gradchecks.py;
    # record the whole namespace as executed there + here
    for name in op_inventory()["loss"]:
        fn = getattr(ns.loss, name)
        if name in ("ctc_loss", "ctc_greedy_decode", "ctc_beam_decode"):
            continue     # own signatures; covered by test_ctc_loss_vs_torch
            # and test_round5_ctc_decode
        if name == "mean_score":
            out = fn(jnp.asarray(np.abs(z[:, 0])), None)
        elif name == "sparse_mcxent":
            out = fn(jnp.asarray(R.integers(0, 4, 5)), jnp.asarray(z))
        else:
            out = fn(jnp.asarray(np.clip(np.abs(y) + 0.1, 0.1, 0.9)),
                     jnp.asarray(z))
        assert np.all(np.isfinite(np.asarray(out)))
        LEDGER.record(f"loss.{name}")


def test_random_ops():
    """jax.random samplers: golden property = determinism per key + basic
    moments; dropout zeros ~p fraction and rescales."""
    key = jax.random.key(0)
    for name in op_inventory()["random"]:
        fn = getattr(ns.random, name)
        if name in ("split", "key", "fold_in"):
            LEDGER.record(f"random.{name}")
            continue
        if name in ("randint", "cauchy", "weibull", "dirichlet",
                    "student_t", "rademacher", "multinomial"):
            continue     # own signatures; covered by test_round5_random_tail
        if name == "bernoulli":
            a, b2 = fn(key, 0.3, (2000,)), fn(key, 0.3, (2000,))
            assert abs(float(jnp.mean(a)) - 0.3) < 0.05
        elif name in ("binomial",):
            a = fn(key, 10.0, 0.5, shape=(500,)); b2 = fn(key, 10.0, 0.5, shape=(500,))
        elif name == "poisson":
            a = fn(key, 2.0, (500,)); b2 = fn(key, 2.0, (500,))
        elif name in ("gamma",):
            a = fn(key, 2.0, (500,)); b2 = fn(key, 2.0, (500,))
        elif name in ("beta",):
            a = fn(key, 2.0, 3.0, (500,)); b2 = fn(key, 2.0, 3.0, (500,))
        elif name == "categorical":
            logits = jnp.zeros((500, 4))
            a = fn(key, logits); b2 = fn(key, logits)
        elif name in ("shuffle", "choice"):
            a = fn(key, jnp.arange(100)); b2 = fn(key, jnp.arange(100))
        elif name == "truncated_normal":
            a = fn(key, -2.0, 2.0, (500,)); b2 = fn(key, -2.0, 2.0, (500,))
        elif name == "log_normal":
            a = fn(key, (500,)); b2 = fn(key, (500,))
        else:  # normal, uniform, exponential, poisson, gumbel, laplace
            a = fn(key, (500,)); b2 = fn(key, (500,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b2))
        LEDGER.record(f"random.{name}")
    # dropout
    x = jnp.ones((10000,))
    y = np.asarray(ns.nn.dropout(key, x, 0.75))
    assert abs((y == 0).mean() - 0.25) < 0.03
    kept = y[y != 0]
    np.testing.assert_allclose(kept, 1.0 / 0.75, rtol=1e-6)
    LEDGER.record("nn.dropout")


def test_scatter_gather_ops():
    """scatter/gather family vs a numpy loop oracle (libnd4j parity_ops:
    scatter_add/upd/max/..., gather, gather_nd, scatter_nd)."""
    x = R.normal(size=(6, 4)).astype(np.float32)
    idx = np.asarray([1, 4, 1], np.int32)           # duplicate on purpose
    upd = R.normal(size=(3, 4)).astype(np.float32)

    got = np.asarray(ns.scatter.gather(jnp.asarray(x), idx))
    np.testing.assert_array_equal(got, x[idx])
    LEDGER.record("scatter.gather")

    nd_idx = np.asarray([[0, 1], [5, 3], [2, 2]], np.int32)
    got = np.asarray(ns.scatter.gather_nd(jnp.asarray(x), nd_idx))
    np.testing.assert_array_equal(got, x[nd_idx[:, 0], nd_idx[:, 1]])
    LEDGER.record("scatter.gather_nd")

    def oracle(op):
        out = x.copy()
        for i, row in zip(idx, upd):
            if op == "set":
                out[i] = row
            elif op == "add":
                out[i] += row
            elif op == "sub":
                out[i] -= row
            elif op == "mul":
                out[i] *= row
            elif op == "div":
                out[i] /= row
            elif op == "max":
                out[i] = np.maximum(out[i], row)
            elif op == "min":
                out[i] = np.minimum(out[i], row)
        return out

    for name, op in [("scatter_add", "add"), ("scatter_sub", "sub"),
                     ("scatter_mul", "mul"), ("scatter_div", "div"),
                     ("scatter_max", "max"), ("scatter_min", "min")]:
        got = np.asarray(getattr(ns.scatter, name)(
            jnp.asarray(x), idx, jnp.asarray(upd)))
        np.testing.assert_allclose(got, oracle(op), rtol=1e-5, atol=1e-6,
                                   err_msg=name)
        LEDGER.record(f"scatter.{name}")
    # scatter_update: last duplicate wins in XLA; check non-dup rows exact
    got = np.asarray(ns.scatter.scatter_update(
        jnp.asarray(x), idx, jnp.asarray(upd)))
    np.testing.assert_array_equal(got[4], upd[1])
    np.testing.assert_array_equal(got[[0, 2, 3, 5]], x[[0, 2, 3, 5]])
    LEDGER.record("scatter.scatter_update")

    got = np.asarray(ns.scatter.scatter_nd(nd_idx, jnp.asarray([1., 2., 3.]),
                                           (6, 4)))
    want = np.zeros((6, 4), np.float32)
    for (i, j), u in zip(nd_idx, [1., 2., 3.]):
        want[i, j] += u
    np.testing.assert_array_equal(got, want)
    LEDGER.record("scatter.scatter_nd")

    got = np.asarray(ns.scatter.scatter_nd_add(
        jnp.asarray(x), nd_idx, jnp.asarray([1., 2., 3.])))
    np.testing.assert_allclose(got, x + want, rtol=1e-6)
    LEDGER.record("scatter.scatter_nd_add")
    got = np.asarray(ns.scatter.scatter_nd_update(
        jnp.asarray(x), nd_idx, jnp.asarray([1., 2., 3.])))
    want2 = x.copy()
    for (i, j), u in zip(nd_idx, [1., 2., 3.]):
        want2[i, j] = u
    np.testing.assert_array_equal(got, want2)
    LEDGER.record("scatter.scatter_nd_update")


def test_segment_ops():
    """segment_* / unsorted_segment_* vs numpy oracles + grad smoke."""
    x = R.normal(size=(8, 3)).astype(np.float32)
    sorted_ids = np.asarray([0, 0, 1, 1, 1, 2, 3, 3], np.int32)
    unsorted_ids = np.asarray([3, 0, 1, 0, 2, 1, 0, 3], np.int32)
    n = 4

    def oracle(ids, red, init):
        out = np.full((n, 3), init, np.float32)
        for i, row in zip(ids, x):
            out[i] = red(out[i], row)
        return out

    cases = [("sum", lambda a, b: a + b, 0.0),
             ("prod", lambda a, b: a * b, 1.0),
             ("max", np.maximum, -np.inf),
             ("min", np.minimum, np.inf)]
    for name, red, init in cases:
        got = np.asarray(getattr(ns.scatter, f"segment_{name}")(
            jnp.asarray(x), sorted_ids, n))
        np.testing.assert_allclose(got, oracle(sorted_ids, red, init),
                                   rtol=1e-5, err_msg=f"segment_{name}")
        LEDGER.record(f"scatter.segment_{name}")
        got = np.asarray(getattr(ns.scatter, f"unsorted_segment_{name}")(
            jnp.asarray(x), unsorted_ids, n))
        np.testing.assert_allclose(got, oracle(unsorted_ids, red, init),
                                   rtol=1e-5,
                                   err_msg=f"unsorted_segment_{name}")
        LEDGER.record(f"scatter.unsorted_segment_{name}")

    for name, ids in [("segment_mean", sorted_ids),
                      ("unsorted_segment_mean", unsorted_ids)]:
        got = np.asarray(getattr(ns.scatter, name)(jnp.asarray(x), ids, n))
        want = np.stack([x[ids == i].mean(0) if np.any(ids == i)
                         else np.zeros(3) for i in range(n)])
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=name)
        LEDGER.record(f"scatter.{name}")

    got = np.asarray(ns.scatter.unsorted_segment_sqrt_n(
        jnp.asarray(x), unsorted_ids, n))
    want = np.stack([x[unsorted_ids == i].sum(0)
                     / max(np.sqrt((unsorted_ids == i).sum()), 1.0)
                     for i in range(n)])
    np.testing.assert_allclose(got, want, rtol=1e-5)
    LEDGER.record("scatter.unsorted_segment_sqrt_n")

    # differentiability through a segment reduction
    g = jax.grad(lambda a: float(0) + jnp.sum(
        ns.scatter.unsorted_segment_sum(a, unsorted_ids, n) ** 2))(
            jnp.asarray(x))
    assert np.all(np.isfinite(np.asarray(g)))


def test_ctc_loss_vs_torch():
    """ctc_loss vs torch.nn.functional.ctc_loss (cross-framework golden)
    + NaN-free gradient (review regression: dead-path log(0) grads)."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    rng = np.random.default_rng(5)
    b, t, c, s = 3, 12, 6, 4
    logits = rng.normal(size=(b, t, c)).astype(np.float32)
    labels = rng.integers(1, c, size=(b, s)).astype(np.int32)  # no blanks
    logit_lens = np.asarray([12, 10, 7], np.int64)
    label_lens = np.asarray([4, 3, 1], np.int64)

    got = np.asarray(ns.loss.ctc_loss(jnp.asarray(logits),
                                      jnp.asarray(labels),
                                      logit_lens, label_lens, blank=0))
    lp = torch.log_softmax(torch.tensor(logits), dim=-1).permute(1, 0, 2)
    want = F.ctc_loss(lp, torch.tensor(labels.astype(np.int64)),
                      torch.tensor(logit_lens), torch.tensor(label_lens),
                      blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    LEDGER.record("loss.ctc_loss")

    g = jax.grad(lambda lg: jnp.sum(ns.loss.ctc_loss(
        lg, jnp.asarray(labels), logit_lens, label_lens)))(
            jnp.asarray(logits))
    assert np.all(np.isfinite(np.asarray(g)))
    # grad vs torch autograd
    lt = torch.tensor(logits, requires_grad=True)
    lp = torch.log_softmax(lt, dim=-1).permute(1, 0, 2)
    F.ctc_loss(lp, torch.tensor(labels.astype(np.int64)),
               torch.tensor(logit_lens), torch.tensor(label_lens),
               blank=0, reduction="sum").backward()
    np.testing.assert_allclose(np.asarray(g), lt.grad.numpy(),
                               rtol=1e-3, atol=1e-4)


def test_ctc_loss_zero_and_repeated_labels():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    rng = np.random.default_rng(6)
    b, t, c = 2, 8, 5
    logits = rng.normal(size=(b, t, c)).astype(np.float32)
    labels = np.asarray([[2, 2, 3], [1, 0, 0]], np.int32)  # repeat + short
    logit_lens = np.asarray([8, 8], np.int64)
    label_lens = np.asarray([3, 1], np.int64)
    got = np.asarray(ns.loss.ctc_loss(jnp.asarray(logits),
                                      jnp.asarray(labels),
                                      logit_lens, label_lens))
    lp = torch.log_softmax(torch.tensor(logits), dim=-1).permute(1, 0, 2)
    want = F.ctc_loss(lp, torch.tensor(labels.astype(np.int64)),
                      torch.tensor(logit_lens), torch.tensor(label_lens),
                      blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_ctc_loss_empty_logit_lengths():
    """logit_lengths == 0: empty/empty alignment has probability 1
    (loss 0, torch parity); empty logits with a non-empty label is an
    infeasible path (loss +1e30)."""
    rng = np.random.default_rng(9)
    logits = rng.normal(size=(2, 6, 4)).astype(np.float32)
    labels = np.asarray([[0, 0], [1, 2]], np.int32)
    got = np.asarray(ns.loss.ctc_loss(jnp.asarray(logits),
                                      jnp.asarray(labels),
                                      np.asarray([0, 0], np.int64),
                                      np.asarray([0, 2], np.int64)))
    assert got[0] == 0.0
    assert got[1] >= 1e29
    # grads stay finite through the infeasible-path branch
    g = np.asarray(jax.grad(lambda lg: jnp.sum(ns.loss.ctc_loss(
        lg, jnp.asarray(labels), np.asarray([0, 0], np.int64),
        np.asarray([0, 2], np.int64))))(jnp.asarray(logits)))
    assert np.all(np.isfinite(g))


def test_grad_smoke_differentiable_ops():
    """check_grads over a representative differentiable subset (the
    OpValidation gradient leg for namespace ops; layer-level grads are
    covered exhaustively in test_gradchecks.py)."""
    from jax.test_util import check_grads
    x = jnp.asarray(R.normal(size=(6,)).astype(np.float64)) * 0.5 + 1.5
    for fn in (ns.math.exp, ns.math.log, ns.math.sqrt, ns.math.tanh,
               ns.nn.softplus, ns.nn.sigmoid, ns.nn.gelu):
        check_grads(fn, (x,), order=1, modes=("rev",), atol=1e-3, rtol=1e-3)


def test_math_erf_values():
    from math import erf, erfc
    vals = np.array([-1.5, -0.3, 0.0, 0.7, 2.1], np.float32)
    np.testing.assert_allclose(np.asarray(ns.math.erf(jnp.asarray(vals))),
                               [erf(v) for v in vals], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.math.erfc(jnp.asarray(vals))),
                               [erfc(v) for v in vals], rtol=1e-5, atol=1e-5)
    LEDGER.record("math.erf", "math.erfc")


# ===================== round-4 op families (VERDICT r3 #5) =====================
def test_cnn_conv_variants_vs_torch():
    """conv1d/3d, depthwise, separable, deconv vs torch golden."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    # conv1d: NWC/WIO ↔ torch NCW/OIW
    x = R.normal(size=(2, 9, 3)).astype(np.float32)
    w = R.normal(size=(3, 3, 5)).astype(np.float32)
    got = np.asarray(ns.cnn.conv1d(jnp.asarray(x), jnp.asarray(w),
                                   padding="VALID", precision="highest"))
    want = F.conv1d(torch.tensor(x).permute(0, 2, 1),
                    torch.tensor(w).permute(2, 1, 0)).permute(0, 2, 1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    LEDGER.record("cnn.conv1d")
    # conv3d: NDHWC/DHWIO ↔ torch NCDHW/OIDHW
    x3 = R.normal(size=(2, 5, 6, 7, 2)).astype(np.float32)
    w3 = R.normal(size=(2, 3, 3, 2, 4)).astype(np.float32)
    got = np.asarray(ns.cnn.conv3d(jnp.asarray(x3), jnp.asarray(w3),
                                   padding="VALID", precision="highest"))
    want = F.conv3d(torch.tensor(x3).permute(0, 4, 1, 2, 3),
                    torch.tensor(w3).permute(4, 3, 0, 1, 2)
                    ).permute(0, 2, 3, 4, 1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    LEDGER.record("cnn.conv3d")
    # depthwise: [Kh,Kw,C,mult] ↔ torch groups=C
    x2 = R.normal(size=(2, 6, 6, 3)).astype(np.float32)
    wd = R.normal(size=(3, 3, 3, 2)).astype(np.float32)
    got = np.asarray(ns.cnn.depthwise_conv2d(jnp.asarray(x2), jnp.asarray(wd),
                                             padding="VALID",
                                             precision="highest"))
    wt = torch.tensor(wd).permute(2, 3, 0, 1).reshape(6, 1, 3, 3)
    want = F.conv2d(torch.tensor(x2).permute(0, 3, 1, 2), wt,
                    groups=3).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    LEDGER.record("cnn.depthwise_conv2d")
    # separable = depthwise ∘ pointwise
    wp = R.normal(size=(1, 1, 6, 4)).astype(np.float32)
    got = np.asarray(ns.cnn.separable_conv2d(
        jnp.asarray(x2), jnp.asarray(wd), jnp.asarray(wp), padding="VALID",
        precision="highest"))
    dw = np.asarray(ns.cnn.depthwise_conv2d(jnp.asarray(x2), jnp.asarray(wd),
                                            padding="VALID",
                                            precision="highest"))
    want = np.asarray(ns.cnn.conv2d(jnp.asarray(dw), jnp.asarray(wp),
                                    precision="highest"))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    LEDGER.record("cnn.separable_conv2d")
    # deconv2d vs torch conv_transpose2d (stride 2, VALID)
    wt2 = R.normal(size=(3, 3, 2, 4)).astype(np.float32)  # HWIO (in=2,out=4)
    xt = R.normal(size=(2, 4, 4, 2)).astype(np.float32)
    got = np.asarray(ns.cnn.deconv2d(jnp.asarray(xt), jnp.asarray(wt2),
                                     stride=(2, 2), padding="VALID",
                                     precision="highest"))
    want = F.conv_transpose2d(
        torch.tensor(xt).permute(0, 3, 1, 2),
        # torch weight [Cin, Cout, Kh, Kw]; lax.conv_transpose flips nothing
        torch.tensor(np.flip(wt2, (0, 1)).copy()).permute(2, 3, 0, 1),
        stride=2).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    LEDGER.record("cnn.deconv2d")
    # deconv3d: shape contract + finiteness (torch golden analog of 2d)
    w3t = R.normal(size=(2, 2, 2, 2, 3)).astype(np.float32)
    x3t = R.normal(size=(1, 3, 3, 3, 2)).astype(np.float32)
    got = ns.cnn.deconv3d(jnp.asarray(x3t), jnp.asarray(w3t), stride=(2, 2, 2),
                          padding="VALID")
    assert got.shape == (1, 6, 6, 6, 3)  # (i-1)*s + k
    assert np.all(np.isfinite(np.asarray(got)))
    LEDGER.record("cnn.deconv3d")


def test_cnn_pool_variants():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    x1 = R.normal(size=(2, 8, 3)).astype(np.float32)
    got = np.asarray(ns.cnn.max_pooling1d(jnp.asarray(x1), 2))
    want = F.max_pool1d(torch.tensor(x1).permute(0, 2, 1), 2).permute(0, 2, 1).numpy()
    np.testing.assert_allclose(got, want)
    got = np.asarray(ns.cnn.avg_pooling1d(jnp.asarray(x1), 2))
    want = F.avg_pool1d(torch.tensor(x1).permute(0, 2, 1), 2).permute(0, 2, 1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    x3 = R.normal(size=(2, 4, 4, 4, 2)).astype(np.float32)
    got = np.asarray(ns.cnn.max_pooling3d(jnp.asarray(x3), (2, 2, 2)))
    want = F.max_pool3d(torch.tensor(x3).permute(0, 4, 1, 2, 3),
                        2).permute(0, 2, 3, 4, 1).numpy()
    np.testing.assert_allclose(got, want)
    got = np.asarray(ns.cnn.avg_pooling3d(jnp.asarray(x3), (2, 2, 2)))
    want = F.avg_pool3d(torch.tensor(x3).permute(0, 4, 1, 2, 3),
                        2).permute(0, 2, 3, 4, 1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    LEDGER.record("cnn.max_pooling1d", "cnn.avg_pooling1d",
                  "cnn.max_pooling3d", "cnn.avg_pooling3d")
    # global pools
    x2 = R.normal(size=(2, 5, 6, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(ns.cnn.global_max_pooling(jnp.asarray(x2))),
                               x2.max((1, 2)))
    np.testing.assert_allclose(np.asarray(ns.cnn.global_avg_pooling(jnp.asarray(x2))),
                               x2.mean((1, 2)), rtol=1e-6)
    LEDGER.record("cnn.global_max_pooling", "cnn.global_avg_pooling")
    # upsampling 1d/3d repeat semantics
    u1 = np.asarray(ns.cnn.upsampling1d(jnp.asarray(x1), 2))
    np.testing.assert_allclose(u1, np.repeat(x1, 2, axis=1))
    u3 = np.asarray(ns.cnn.upsampling3d(jnp.asarray(x3), 2))
    assert u3.shape == (2, 8, 8, 8, 2)
    LEDGER.record("cnn.upsampling1d", "cnn.upsampling3d")
    # lrn vs manual channel-window reference
    xl = R.normal(size=(1, 2, 2, 5)).astype(np.float32)
    got = np.asarray(ns.cnn.local_response_normalization(
        jnp.asarray(xl), depth_radius=1, bias=1.0, alpha=0.5, beta=0.75))
    want = np.empty_like(xl)
    for c in range(5):
        lo, hi = max(0, c - 1), min(5, c + 2)
        den = (1.0 + 0.5 * np.sum(xl[..., lo:hi] ** 2, -1)) ** 0.75
        want[..., c] = xl[..., c] / den
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    LEDGER.record("cnn.local_response_normalization")
    # col2im: exact inverse of im2col for non-overlapping windows
    cols = np.asarray(ns.cnn.im2col(jnp.asarray(x2[:, :4, :6]), 2, 2, 2, 2))
    back = np.asarray(ns.cnn.col2im(jnp.asarray(cols), 4, 6, 2, 2, 2, 2))
    np.testing.assert_allclose(back, x2[:, :4, :6])
    LEDGER.record("cnn.col2im")
    # batch_to_space ∘ space_to_batch = identity
    stb = ns.cnn.space_to_batch(jnp.asarray(x3[:, :, :, 0, :]), 2)
    bts = np.asarray(ns.cnn.batch_to_space(stb, 2))
    np.testing.assert_allclose(bts, x3[:, :, :, 0, :])
    LEDGER.record("cnn.space_to_batch", "cnn.batch_to_space")


def test_rnn_family():
    """lstm_block/lstm_cell/gru/sru/simple_rnn — cross-checked against
    the layer-level scans and manual recurrences."""
    b, t, c, h = 3, 5, 4, 6
    x = jnp.asarray(R.normal(size=(b, t, c)).astype(np.float32))
    w = jnp.asarray(R.normal(0, 0.4, (c, 4 * h)).astype(np.float32))
    u = jnp.asarray(R.normal(0, 0.4, (h, 4 * h)).astype(np.float32))
    bb = jnp.asarray(R.normal(0, 0.1, (4 * h,)).astype(np.float32))
    ys, (h_last, c_last) = ns.rnn.lstm_layer(x, w, u, bb)
    hs, cs = ns.rnn.lstm_block(x, w, u, bb)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(ys), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(hs[:, -1]), np.asarray(h_last),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(cs[:, -1]), np.asarray(c_last),
                               rtol=1e-5, atol=1e-6)
    # lstm_cell = first step of the block
    h1, c1 = ns.rnn.lstm_cell(x[:, 0], jnp.zeros((b, h)), jnp.zeros((b, h)),
                              w, u, bb)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(hs[:, 0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(cs[:, 0]),
                               rtol=1e-5, atol=1e-6)
    LEDGER.record("rnn.lstm_layer", "rnn.lstm_block", "rnn.lstm_cell")
    # gru: scan of gru_cell
    wg = jnp.asarray(R.normal(0, 0.4, (c, 3 * h)).astype(np.float32))
    ug = jnp.asarray(R.normal(0, 0.4, (h, 3 * h)).astype(np.float32))
    bg = jnp.asarray(R.normal(0, 0.1, (3 * h,)).astype(np.float32))
    ys_g, h_g = ns.rnn.gru(x, wg, ug, bg)
    hh = jnp.zeros((b, h))
    for i in range(t):
        hh = ns.rnn.gru_cell(x[:, i], hh, wg, ug, bg)
    np.testing.assert_allclose(np.asarray(h_g), np.asarray(hh), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(ys_g[:, -1]), np.asarray(hh),
                               rtol=1e-5, atol=1e-6)
    LEDGER.record("rnn.gru", "rnn.gru_cell")
    # sru vs manual numpy recurrence
    ws = R.normal(0, 0.4, (c, 3 * h)).astype(np.float32)
    bs = R.normal(0, 0.1, (2 * h,)).astype(np.float32)
    ys_s, c_s = ns.rnn.sru(x, jnp.asarray(ws), jnp.asarray(bs))
    xn = np.asarray(x)
    z = xn @ ws
    cc = np.zeros((b, h), np.float32)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for i in range(t):
        f = sig(z[:, i, h:2 * h] + bs[:h])
        r = sig(z[:, i, 2 * h:] + bs[h:])
        cc = f * cc + (1 - f) * z[:, i, :h]
        out = r * np.tanh(cc)          # c != h → no highway term
    np.testing.assert_allclose(np.asarray(c_s), cc, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ys_s[:, -1]), out, rtol=1e-4,
                               atol=1e-5)
    c1s = ns.rnn.sru_cell(x[:, 0], jnp.zeros((b, h)), jnp.asarray(ws),
                          jnp.asarray(bs))[1]
    np.testing.assert_allclose(np.asarray(c1s),
                               (1 - sig(z[:, 0, h:2*h] + bs[:h])) * z[:, 0, :h],
                               rtol=1e-4, atol=1e-5)
    LEDGER.record("rnn.sru", "rnn.sru_cell")
    # simple_rnn vs manual tanh recurrence
    wr = R.normal(0, 0.4, (c, h)).astype(np.float32)
    ur = R.normal(0, 0.4, (h, h)).astype(np.float32)
    br = R.normal(0, 0.1, (h,)).astype(np.float32)
    ys_r, h_r = ns.rnn.simple_rnn(x, jnp.asarray(wr), jnp.asarray(ur),
                                  jnp.asarray(br))
    hh = np.zeros((b, h), np.float32)
    for i in range(t):
        hh = np.tanh(xn[:, i] @ wr + hh @ ur + br)
    np.testing.assert_allclose(np.asarray(h_r), hh, rtol=1e-4, atol=1e-5)
    LEDGER.record("rnn.simple_rnn")


def test_nn_activation_extras():
    x = jnp.asarray(A)
    np.testing.assert_allclose(np.asarray(ns.nn.prelu(x, 0.2)),
                               np.where(A >= 0, A, 0.2 * A), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.nn.mish(x)),
                               A * np.tanh(np.log1p(np.exp(A))),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ns.nn.hard_swish(x)),
                               A * np.clip(A + 3, 0, 6) / 6, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.nn.rational_tanh(x)),
                               1.7159 * np.tanh(2 * A / 3), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ns.nn.rectified_tanh(x)),
                               np.maximum(np.tanh(A), 0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.nn.hard_shrink(x, 0.3)),
                               np.where(np.abs(A) > 0.3, A, 0))
    np.testing.assert_allclose(np.asarray(ns.nn.soft_shrink(x, 0.3)),
                               np.sign(A) * np.maximum(np.abs(A) - 0.3, 0),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.nn.thresholded_relu(x, 0.5)),
                               np.where(A > 0.5, A, 0))
    crelu = np.asarray(ns.nn.crelu(x))
    np.testing.assert_allclose(crelu, np.concatenate(
        [np.maximum(A, 0), np.maximum(-A, 0)], -1))
    glu_in = jnp.asarray(np.concatenate([A, B], -1))
    np.testing.assert_allclose(np.asarray(ns.nn.glu(glu_in)),
                               A / (1 + np.exp(-B)), rtol=1e-5, atol=1e-5)
    LEDGER.record("nn.prelu", "nn.mish", "nn.hard_swish", "nn.rational_tanh",
                  "nn.rectified_tanh", "nn.hard_shrink", "nn.soft_shrink",
                  "nn.thresholded_relu", "nn.crelu", "nn.glu")
    m, v = ns.nn.moments(x, axis=None)
    np.testing.assert_allclose([float(m), float(v)], [A.mean(), A.var()],
                               rtol=1e-5)
    l2n = np.asarray(ns.nn.l2_normalize(x, axis=-1))
    np.testing.assert_allclose(np.linalg.norm(l2n, axis=-1),
                               np.ones(A.shape[0]), rtol=1e-5)
    table = R.normal(size=(10, 4)).astype(np.float32)
    ids = jnp.asarray([1, 7, 3])
    np.testing.assert_allclose(np.asarray(ns.nn.embedding_lookup(
        jnp.asarray(table), ids)), table[[1, 7, 3]])
    LEDGER.record("nn.moments", "nn.l2_normalize", "nn.embedding_lookup")
    # attention vs manual softmax(QK^T/sqrt d) V
    q = R.normal(size=(2, 3, 4)).astype(np.float32)
    k = R.normal(size=(2, 5, 4)).astype(np.float32)
    v = R.normal(size=(2, 5, 4)).astype(np.float32)
    got = np.asarray(ns.nn.dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    scores = np.einsum("bqd,bkd->bqk", q, k) / np.sqrt(4)
    want = np.einsum("bqk,bkd->bqd", _softmax(scores), v)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    got = np.asarray(ns.nn.multi_head_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), n_heads=2))
    qh = q.reshape(2, 3, 2, 2).transpose(0, 2, 1, 3)
    kh = k.reshape(2, 5, 2, 2).transpose(0, 2, 1, 3)
    vh = v.reshape(2, 5, 2, 2).transpose(0, 2, 1, 3)
    sc = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(2)
    wanth = np.einsum("bhqk,bhkd->bhqd", _softmax(sc), vh)
    want = wanth.transpose(0, 2, 1, 3).reshape(2, 3, 4)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    LEDGER.record("nn.dot_product_attention",
                  "nn.multi_head_dot_product_attention")


def test_math_extras():
    x, y = jnp.asarray(A), jnp.asarray(B)
    for op, npf in [("eq", np.equal), ("neq", np.not_equal),
                    ("gt", np.greater), ("gte", np.greater_equal),
                    ("lt", np.less), ("lte", np.less_equal)]:
        np.testing.assert_array_equal(np.asarray(getattr(ns.math, op)(x, y)),
                                      npf(A, B))
        LEDGER.record(f"math.{op}")
    ba, bb_ = A > 0, B > 0
    for op, npf in [("logical_and", np.logical_and),
                    ("logical_or", np.logical_or),
                    ("logical_xor", np.logical_xor)]:
        np.testing.assert_array_equal(
            np.asarray(getattr(ns.math, op)(jnp.asarray(ba), jnp.asarray(bb_))),
            npf(ba, bb_))
        LEDGER.record(f"math.{op}")
    np.testing.assert_array_equal(np.asarray(ns.math.logical_not(jnp.asarray(ba))),
                                  ~ba)
    np.testing.assert_array_equal(np.asarray(ns.math.is_close(x, x + 1e-9)),
                                  np.isclose(A, A + 1e-9))
    np.testing.assert_allclose(np.asarray(ns.math.where(x > 0, x, y)),
                               np.where(A > 0, A, B))
    np.testing.assert_allclose(np.asarray(ns.math.trunc(3.7 * x)),
                               np.trunc(3.7 * A))
    np.testing.assert_allclose(np.asarray(ns.math.rint(3.7 * x)),
                               np.rint(3.7 * A))
    bad = np.array([np.nan, np.inf, -np.inf, 1.0], np.float32)
    np.testing.assert_allclose(np.asarray(ns.math.nan_to_num(jnp.asarray(bad))),
                               np.nan_to_num(bad))
    LEDGER.record("math.logical_not", "math.is_close", "math.where",
                  "math.trunc", "math.rint", "math.nan_to_num")
    from scipy import special as sps
    pv = np.asarray(P)
    np.testing.assert_allclose(np.asarray(ns.math.lgamma(jnp.asarray(pv))),
                               sps.gammaln(pv), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ns.math.digamma(jnp.asarray(pv))),
                               sps.digamma(pv), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ns.math.igamma(jnp.asarray(pv), jnp.asarray(pv + 0.5))),
                               sps.gammainc(pv, pv + 0.5), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ns.math.igammac(jnp.asarray(pv), jnp.asarray(pv + 0.5))),
                               sps.gammaincc(pv, pv + 0.5), rtol=1e-4, atol=1e-5)
    uv = np.asarray(U)
    np.testing.assert_allclose(np.asarray(ns.math.betainc(jnp.asarray(pv), jnp.asarray(pv + 1), jnp.asarray(uv))),
                               sps.betainc(pv, pv + 1, uv), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(ns.math.log_sum_exp(x)),
                               sps.logsumexp(A), rtol=1e-5)
    LEDGER.record("math.lgamma", "math.digamma", "math.igamma",
                  "math.igammac", "math.betainc", "math.log_sum_exp")
    np.testing.assert_allclose(np.asarray(ns.math.sort(x, axis=-1)),
                               np.sort(A, -1))
    np.testing.assert_array_equal(np.asarray(ns.math.argsort(x, axis=-1)),
                                  np.argsort(A, -1, kind="stable"))
    np.testing.assert_allclose(np.asarray(ns.math.reverse(x, axis=1)),
                               A[:, ::-1])
    LEDGER.record("math.sort", "math.argsort", "math.reverse")


def test_image_extras():
    import colorsys
    img = IMG[:1, :3, :3, :]           # small for the colorsys loop
    got = np.asarray(ns.image.rgb_to_hsv(jnp.asarray(img)))
    want = np.empty_like(img)
    for i in np.ndindex(img.shape[:-1]):
        want[i] = colorsys.rgb_to_hsv(*img[i])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    back = np.asarray(ns.image.hsv_to_rgb(jnp.asarray(got)))
    np.testing.assert_allclose(back, img, rtol=1e-4, atol=1e-5)
    LEDGER.record("image.rgb_to_hsv", "image.hsv_to_rgb")
    # yuv roundtrip + luma = grayscale weights
    yuv = np.asarray(ns.image.rgb_to_yuv(jnp.asarray(IMG)))
    np.testing.assert_allclose(yuv[..., 0], IMG @ np.array([0.299, 0.587, 0.114],
                                                           np.float32),
                               rtol=1e-4, atol=1e-5)
    rgb = np.asarray(ns.image.yuv_to_rgb(jnp.asarray(yuv)))
    np.testing.assert_allclose(rgb, IMG, rtol=1e-3, atol=1e-4)
    LEDGER.record("image.rgb_to_yuv", "image.yuv_to_rgb")
    # hue/saturation identity transforms
    np.testing.assert_allclose(np.asarray(ns.image.adjust_hue(jnp.asarray(IMG), 0.0)),
                               IMG, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ns.image.adjust_saturation(jnp.asarray(IMG), 1.0)),
                               IMG, rtol=1e-3, atol=1e-4)
    LEDGER.record("image.adjust_hue", "image.adjust_saturation")
    # resizes: constant image stays constant; shapes honored
    const = jnp.full((1, 5, 5, 3), 0.37, jnp.float32)
    for name in ("resize_bicubic", "resize_area"):
        out = np.asarray(getattr(ns.image, name)(const, 9, 7))
        assert out.shape == (1, 9, 7, 3)
        np.testing.assert_allclose(out, 0.37, rtol=1e-5, atol=1e-5)
        LEDGER.record(f"image.{name}")
    # area resampling is true box-filter averaging: 4x4 ramp → 2x2 means
    ramp4 = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    area = np.asarray(ns.image.resize_area(ramp4, 2, 2))[0, :, :, 0]
    np.testing.assert_allclose(area, [[2.5, 4.5], [10.5, 12.5]],
                               rtol=1e-5, atol=1e-5)
    # even-kernel SAME patches match TF's output-size contract ceil(H/s)
    pat_same = ns.image.extract_image_patches(jnp.asarray(IMG), 2, 2,
                                              padding="SAME")
    assert pat_same.shape == (2, 6, 8, 12)
    # extract_image_patches == im2col
    pat = np.asarray(ns.image.extract_image_patches(jnp.asarray(IMG), 3, 3))
    cols = np.asarray(ns.cnn.im2col(jnp.asarray(IMG), 3, 3))
    np.testing.assert_allclose(pat, cols)
    LEDGER.record("image.extract_image_patches")
    # iou golden: identical box = 1; disjoint = 0; half-overlap = 1/3
    boxes = jnp.asarray([[0, 0, 2, 2], [0, 1, 2, 3], [5, 5, 6, 6]],
                        jnp.float32)
    m = np.asarray(ns.image.iou(boxes, boxes))
    np.testing.assert_allclose(np.diag(m), 1.0, rtol=1e-6)
    np.testing.assert_allclose(m[0, 1], 1.0 / 3.0, rtol=1e-5)
    assert m[0, 2] == 0.0
    LEDGER.record("image.iou")
    # NMS: suppresses the overlapping lower-score box, keeps disjoint
    scores = jnp.asarray([0.9, 0.8, 0.7], jnp.float32)
    sel = np.asarray(ns.image.non_max_suppression(boxes, scores, 3,
                                                  iou_threshold=0.3))
    assert sel[0] == 0 and sel[1] == 2 and sel[2] == -1
    LEDGER.record("image.non_max_suppression")
    # crop_and_resize: bilinear sampling of a LINEAR ramp is exact
    # (TF align-corners semantics: grid y = y1*(H-1) + i*(y2-y1)*(H-1)/(ch-1))
    yy, xx = np.meshgrid(np.arange(5.0), np.arange(5.0), indexing="ij")
    ramp = (2 * yy + 3 * xx).astype(np.float32)[None, :, :, None]
    box = jnp.asarray([[0.25, 0.0, 1.0, 0.5]], jnp.float32)
    got = np.asarray(ns.image.crop_and_resize(jnp.asarray(ramp), box,
                                              jnp.asarray([0]), 3, 3))[0, :, :, 0]
    ys = 0.25 * 4 + np.arange(3) / 2 * (0.75 * 4)
    xs = 0.0 + np.arange(3) / 2 * (0.5 * 4)
    want = 2 * ys[:, None] + 3 * xs[None, :]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    LEDGER.record("image.crop_and_resize")


def test_base_ops():
    x = jnp.asarray(A)
    pairs = [
        ("concat", lambda: ns.base.concat([x, x], axis=0),
         lambda: np.concatenate([A, A], 0)),
        ("stack", lambda: ns.base.stack([x, x]), lambda: np.stack([A, A])),
        ("tile", lambda: ns.base.tile(x, (2, 1)), lambda: np.tile(A, (2, 1))),
        ("repeat", lambda: ns.base.repeat(x, 2, axis=0),
         lambda: np.repeat(A, 2, 0)),
        ("squeeze", lambda: ns.base.squeeze(x[None]), lambda: A),
        ("expand_dims", lambda: ns.base.expand_dims(x, 0), lambda: A[None]),
        ("transpose", lambda: ns.base.transpose(x), lambda: A.T),
        ("permute", lambda: ns.base.permute(x, 1, 0), lambda: A.T),
        ("reshape", lambda: ns.base.reshape(x, (4, 3)),
         lambda: A.reshape(4, 3)),
        ("slice", lambda: ns.base.slice(x, (0, 1), (2, 3)),
         lambda: A[0:2, 1:3]),
        ("strided_slice", lambda: ns.base.strided_slice(x, (0, 0), (3, 4), (2, 2)),
         lambda: A[0:3:2, 0:4:2]),
        ("gather", lambda: ns.base.gather(x, jnp.asarray([2, 0]), axis=0),
         lambda: A[[2, 0]]),
        ("reverse", lambda: ns.base.reverse(x, axis=0), lambda: A[::-1]),
        ("eye", lambda: ns.base.eye(3), lambda: np.eye(3)),
        ("linspace", lambda: ns.base.linspace(0.0, 1.0, 5),
         lambda: np.linspace(0, 1, 5)),
        ("arange", lambda: ns.base.arange(5), lambda: np.arange(5)),
        ("zeros_like", lambda: ns.base.zeros_like(x), lambda: np.zeros_like(A)),
        ("ones_like", lambda: ns.base.ones_like(x), lambda: np.ones_like(A)),
        ("full_like", lambda: ns.base.full_like(x, 2.5),
         lambda: np.full_like(A, 2.5)),
        ("fill", lambda: ns.base.fill((2, 2), 7.0), lambda: np.full((2, 2), 7.0)),
    ]
    for name, got_fn, want_fn in pairs:
        np.testing.assert_allclose(np.asarray(got_fn()), want_fn(),
                                   rtol=1e-6, atol=1e-6)
        LEDGER.record(f"base.{name}")
    parts = ns.base.split(x, 2, axis=1)
    np.testing.assert_allclose(np.asarray(parts[0]), A[:, :2])
    us = ns.base.unstack(x, axis=0)
    assert len(us) == 3
    np.testing.assert_allclose(np.asarray(us[1]), A[1])
    mg = ns.base.meshgrid(jnp.arange(2), jnp.arange(3))
    np.testing.assert_array_equal(np.asarray(mg[0]),
                                  np.meshgrid(np.arange(2), np.arange(3))[0])
    assert np.asarray(ns.base.cast(x, jnp.int32)).dtype == np.int32
    np.testing.assert_array_equal(np.asarray(ns.base.shape_of(x)), [3, 4])
    assert int(ns.base.size_of(x)) == 12
    assert int(ns.base.rank(x)) == 2
    LEDGER.record("base.split", "base.unstack", "base.meshgrid", "base.cast",
                  "base.shape_of", "base.size_of", "base.rank")
    # sequence ops
    seq = jnp.asarray(np.arange(2 * 5 * 3, dtype=np.float32).reshape(2, 5, 3))
    rev = np.asarray(ns.base.reverse_sequence(seq, jnp.asarray([3, 5])))
    want = np.asarray(seq).copy()
    want[0, :3] = want[0, :3][::-1]
    want[1, :5] = want[1, :5][::-1]
    np.testing.assert_allclose(rev, want)
    mask = np.asarray(ns.base.sequence_mask(jnp.asarray([1, 3]), 4))
    np.testing.assert_array_equal(mask, [[True, False, False, False],
                                         [True, True, True, False]])
    LEDGER.record("base.reverse_sequence", "base.sequence_mask")
    data = jnp.asarray(np.arange(12, dtype=np.float32).reshape(6, 2))
    partitions = jnp.asarray([0, 1, 0, 2, 1, 0])
    parts = ns.base.dynamic_partition(data, partitions, 3)
    assert [p.shape[0] for p in parts] == [3, 2, 1]
    # stitch indices: where each partition's rows came from
    idx = [jnp.asarray(np.flatnonzero(np.asarray(partitions) == i))
           for i in range(3)]
    back = np.asarray(ns.base.dynamic_stitch(idx, parts))
    np.testing.assert_allclose(back, np.asarray(data))
    LEDGER.record("base.dynamic_partition", "base.dynamic_stitch")
    cm = np.asarray(ns.base.confusion_matrix(jnp.asarray([0, 1, 1, 2]),
                                             jnp.asarray([0, 1, 2, 2]), 3))
    np.testing.assert_array_equal(cm, [[1, 0, 0], [0, 1, 1], [0, 0, 1]])
    LEDGER.record("base.confusion_matrix")
    vals, idxs = ns.base.top_k(x, 2)
    np.testing.assert_allclose(np.asarray(vals), np.sort(A, -1)[:, ::-1][:, :2])
    hits = np.asarray(ns.base.in_top_k(x, jnp.asarray(np.argmax(A, -1)), 1))
    assert hits.all()
    LEDGER.record("base.top_k", "base.in_top_k")
    dup = jnp.asarray([3, 1, 3, 2, 1, 1])
    np.testing.assert_array_equal(np.asarray(ns.base.unique(dup)), [1, 2, 3])
    uv, uc = ns.base.unique_with_counts(dup)
    np.testing.assert_array_equal(np.asarray(uc), [3, 1, 2])
    np.testing.assert_allclose(np.asarray(ns.base.boolean_mask(x, x[:, 0] > 0)),
                               A[A[:, 0] > 0])
    assert int(ns.base.match_condition_count(x, lambda v: v > 0)) == int((A > 0).sum())
    LEDGER.record("base.unique", "base.unique_with_counts",
                  "base.boolean_mask", "base.match_condition_count")


def test_math_merge_clip_percentile_family():
    from scipy import special as sps
    xs = [jnp.asarray(R.normal(size=(3, 4)).astype(np.float32))
          for _ in range(3)]
    stack = np.stack([np.asarray(v) for v in xs])
    np.testing.assert_allclose(np.asarray(ns.math.merge_max(xs)),
                               stack.max(0))
    np.testing.assert_allclose(np.asarray(ns.math.merge_avg(xs)),
                               stack.mean(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.math.merge_add(xs)),
                               stack.sum(0), rtol=1e-6)
    LEDGER.record("math.merge_max", "math.merge_avg", "math.merge_add")
    x = jnp.asarray(A)
    got = np.asarray(ns.math.clip_by_avg_norm(x, 0.01))
    avg_norm = np.linalg.norm(A) / A.size  # TF clip_by_average_norm: ||x||/N
    np.testing.assert_allclose(got, A * min(1.0, 0.01 / avg_norm),
                               rtol=1e-5)
    clipped = ns.math.clip_by_global_norm([x, 2 * x], 1.0)
    gn = np.sqrt((A * A).sum() + (2 * A * 2 * A).sum())
    np.testing.assert_allclose(np.asarray(clipped[0]),
                               A * min(1.0, 1.0 / gn), rtol=1e-5)
    LEDGER.record("math.clip_by_avg_norm", "math.clip_by_global_norm")
    np.testing.assert_allclose(float(ns.math.percentile(x, 50)),
                               np.percentile(A, 50), rtol=1e-5)
    row = jnp.asarray(np.asarray([5.0, 1.0, 3.0, 2.0], np.float32))
    assert float(ns.math.nth_element(row, 1)) == 2.0
    assert float(ns.math.nth_element(row, 1, reverse=True)) == 3.0
    LEDGER.record("math.percentile", "math.nth_element")
    ints = jnp.asarray([0, 2, 2, 3, 0], jnp.int32)
    np.testing.assert_array_equal(np.asarray(ns.math.bincount(ints, 5)),
                                  np.bincount(np.asarray(ints), minlength=5))
    hist = np.asarray(ns.math.histogram_fixed_width(
        jnp.asarray([0.0, 0.1, 0.5, 0.9, 1.0], jnp.float32), 0.0, 1.0, 2))
    np.testing.assert_array_equal(hist, [2, 3])   # 0.5 lands in the upper bin
    LEDGER.record("math.bincount", "math.histogram_fixed_width")
    pv = np.asarray(P)
    np.testing.assert_allclose(np.asarray(ns.math.zeta(jnp.asarray(1.5 + pv),
                                                       jnp.asarray(pv))),
                               sps.zeta(1.5 + pv, pv), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ns.math.polygamma(1.0, jnp.asarray(pv))),
                               sps.polygamma(1, pv), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ns.math.logaddexp(x, jnp.asarray(B))),
                               np.logaddexp(A, B), rtol=1e-5)
    LEDGER.record("math.zeta", "math.polygamma", "math.logaddexp")


def test_linalg_matrix_family():
    v = jnp.asarray(np.asarray([1.0, 2.0, 3.0], np.float32))
    np.testing.assert_allclose(np.asarray(ns.linalg.matrix_diag(v)),
                               np.diag([1.0, 2.0, 3.0]))
    m = jnp.asarray(SQ)
    got = np.asarray(ns.linalg.matrix_set_diag(m, v=jnp.zeros(4)))
    np.testing.assert_allclose(np.diag(got), np.zeros(4))
    np.testing.assert_allclose(got - np.diag(np.diag(got)),
                               SQ - np.diag(np.diag(SQ)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.linalg.matrix_power(m, 3)),
                               np.linalg.matrix_power(SQ, 3), rtol=1e-3,
                               atol=1e-3)
    p_mat, l_mat, u_mat = ns.linalg.lu(jnp.asarray(SPD))
    np.testing.assert_allclose(np.asarray(p_mat) @ np.asarray(l_mat)
                               @ np.asarray(u_mat), SPD, rtol=1e-4, atol=1e-4)
    LEDGER.record("linalg.matrix_diag", "linalg.matrix_set_diag",
                  "linalg.matrix_power", "linalg.lu")


def test_base_broadcast_split_v():
    x = jnp.asarray(A)
    np.testing.assert_allclose(
        np.asarray(ns.base.broadcast_to(x[:1], (3, 4))),
        np.broadcast_to(A[:1], (3, 4)))
    parts = ns.base.split_v(x, [1, 3], axis=1)
    assert [p.shape[1] for p in parts] == [1, 3]
    np.testing.assert_allclose(np.asarray(parts[1]), A[:, 1:])
    LEDGER.record("base.broadcast_to", "base.split_v")


def test_pairwise_compound_and_fused_affine_ops():
    x, y = jnp.asarray(A), jnp.asarray(P)
    np.testing.assert_allclose(np.asarray(ns.math.rsub(x, y)), P - A)
    np.testing.assert_allclose(np.asarray(ns.math.rdiv(y, x)), A / P)
    np.testing.assert_allclose(np.asarray(ns.math.squared_difference(x, y)),
                               (A - P) ** 2, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.math.axpy(2.5, x, y)),
                               2.5 * A + P, rtol=1e-6)
    assert bool(ns.math.all(x < 100)) and bool(ns.math.any(x > 0))
    im = np.asarray(ns.math.is_max(x))
    assert im.sum() == 1 and A[np.unravel_index(im.argmax(), A.shape)] == A.max()
    LEDGER.record("math.rsub", "math.rdiv", "math.squared_difference",
                  "math.axpy", "math.all", "math.any", "math.is_max")
    w = jnp.asarray(R.normal(0, 0.4, (4, 5)).astype(np.float32))
    b = jnp.asarray(R.normal(0, 0.1, (5,)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(ns.nn.bias_add(x, jnp.asarray(B[0]))),
                               A + B[0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.nn.xw_plus_b(x, w, b)),
                               A @ np.asarray(w) + np.asarray(b),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ns.nn.relu_layer(x, w, b)),
                               np.maximum(A @ np.asarray(w) + np.asarray(b), 0),
                               rtol=1e-4, atol=1e-5)
    LEDGER.record("nn.bias_add", "nn.xw_plus_b", "nn.relu_layer")
    np.testing.assert_allclose(np.asarray(ns.base.roll(x, 1, axis=1)),
                               np.roll(A, 1, 1))
    LEDGER.record("base.roll")
    # p-norm pooling: p→large approaches max pooling; p=1 is abs-sum
    xp = jnp.asarray(np.abs(R.normal(size=(1, 4, 4, 2))).astype(np.float32))
    p1 = np.asarray(ns.cnn.pnorm_pooling2d(xp, p=1.0, k=(2, 2)))
    want = np.asarray(ns.cnn.avg_pooling2d(jnp.abs(xp), (2, 2))) * 4.0
    np.testing.assert_allclose(p1, want, rtol=1e-5)
    p_big = np.asarray(ns.cnn.pnorm_pooling2d(xp, p=64.0, k=(2, 2)))
    np.testing.assert_allclose(p_big,
                               np.asarray(ns.cnn.max_pooling2d(xp, (2, 2))),
                               rtol=2e-2)
    LEDGER.record("cnn.pnorm_pooling2d")


def test_ndloss_extras_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    rng = np.random.default_rng(13)
    labels = rng.normal(size=(4, 6)).astype(np.float32)
    pred = rng.normal(size=(4, 6)).astype(np.float32)
    got = np.asarray(ns.loss.huber(jnp.asarray(labels), jnp.asarray(pred)))
    want = F.huber_loss(torch.tensor(pred), torch.tensor(labels),
                        reduction="none", delta=1.0).numpy().mean(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # weighted sigmoid CE vs torch BCEWithLogits pos_weight
    yb = rng.integers(0, 2, (4, 6)).astype(np.float32)
    got = np.asarray(ns.loss.weighted_cross_entropy_with_logits(
        jnp.asarray(yb), jnp.asarray(pred), pos_weight=2.0))
    want = F.binary_cross_entropy_with_logits(
        torch.tensor(pred), torch.tensor(yb),
        pos_weight=torch.tensor(2.0), reduction="none").numpy().mean(-1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # log-poisson vs manual exp(log_pred) - y*log_pred
    ylp = rng.uniform(0, 4, (4, 6)).astype(np.float32)
    got = np.asarray(ns.loss.log_poisson(jnp.asarray(ylp), jnp.asarray(pred)))
    np.testing.assert_allclose(got, (np.exp(pred) - ylp * pred).mean(-1),
                               rtol=1e-5)
    # pairwise squared error vs explicit O(n^2) reference
    d = pred - labels
    want = np.stack([
        np.mean([0.5 * (d[i, a] - d[i, b]) ** 2
                 for a in range(6) for b in range(6) if a != b])
        for i in range(4)])
    got = np.asarray(ns.loss.mean_pairwise_squared_error(
        jnp.asarray(labels), jnp.asarray(pred)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_new_op_grad_smoke():
    """check_grads over the differentiable round-4 additions.  Runs in
    x64 with its own rng: at f32 the finite-difference tolerance is
    stream-dependent (flaky against the module-shared ``R``)."""
    from jax.test_util import check_grads
    rng = np.random.default_rng(123)
    jax.config.update("jax_enable_x64", True)
    try:
        x = jnp.asarray(rng.normal(size=(6,)).astype(np.float64)) * 0.5 + 1.5
        for fn in (ns.nn.mish, ns.nn.hard_swish, ns.nn.rational_tanh,
                   lambda v: ns.nn.l2_normalize(v, axis=0),
                   lambda v: ns.math.log_sum_exp(v)):
            check_grads(fn, (x,), order=1, modes=("rev",), atol=1e-3,
                        rtol=1e-3)
        xc = jnp.asarray(rng.normal(size=(2, 6, 3)).astype(np.float64))
        wc = jnp.asarray(rng.normal(0, 0.3, (3, 3, 4)).astype(np.float64))
        check_grads(lambda a, b: jnp.sum(ns.cnn.conv1d(
            a, b, padding="VALID", precision="highest") ** 2),
                    (xc, wc), order=1, modes=("rev",), atol=1e-3, rtol=1e-3)
        ws = jnp.asarray(rng.normal(0, 0.3, (3, 12)).astype(np.float64))
        bs = jnp.asarray(rng.normal(0, 0.1, (8,)).astype(np.float64))
        check_grads(lambda a: jnp.sum(ns.rnn.sru(a, ws, bs)[0] ** 2), (xc,),
                    order=1, modes=("rev",), atol=1e-3, rtol=1e-3)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_round5_linalg_tail(rng):
    """Matrix-function tail (sqrtm/expm/solve family/polar/structured)."""
    A = rng.normal(0, 0.5, (4, 4)).astype(np.float64)
    spd = jnp.asarray(A @ A.T + 4 * np.eye(4))
    s = ns.linalg.sqrtm(spd)
    np.testing.assert_allclose(np.asarray(s @ s).real, np.asarray(spd),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.linalg.expm(jnp.zeros((3, 3)))),
                               np.eye(3), atol=1e-6)
    L = jnp.asarray(np.tril(A) + 4 * np.eye(4))
    bvec = jnp.asarray(rng.normal(size=(4,)))
    xs = ns.linalg.solve_triangular(L, bvec, lower=True)
    np.testing.assert_allclose(np.asarray(L @ xs), np.asarray(bvec),
                               rtol=1e-6)
    LEDGER.record("linalg.sqrtm", "linalg.expm", "linalg.solve_triangular")

    M = jnp.asarray(A + 5 * np.eye(4))
    lu = ns.linalg.lu_factor(M)
    np.testing.assert_allclose(np.asarray(M @ ns.linalg.lu_solve(lu, bvec)),
                               np.asarray(bvec), rtol=1e-6)
    ch = ns.linalg.cho_factor(spd)
    np.testing.assert_allclose(np.asarray(spd @ ns.linalg.cho_solve(ch, bvec)),
                               np.asarray(bvec), rtol=1e-6)
    LEDGER.record("linalg.lu_factor", "linalg.lu_solve",
                  "linalg.cho_factor", "linalg.cho_solve")

    ev = np.sort(np.asarray(ns.linalg.eigvalsh(spd)))
    ref = np.sort(np.linalg.eigvalsh(np.asarray(spd)))
    np.testing.assert_allclose(ev, ref, rtol=1e-6)
    evg = np.asarray(ns.linalg.eigvals(spd))
    np.testing.assert_allclose(np.sort(evg.real), ref, rtol=1e-5)
    LEDGER.record("linalg.eigvals", "linalg.eigvalsh")

    T4 = jnp.asarray(rng.normal(size=(2, 2, 2, 2)) + np.einsum(
        "ik,jl->ijkl", 3 * np.eye(2), np.eye(2)))
    B2 = jnp.asarray(rng.normal(size=(2, 2)))
    X = ns.linalg.tensorsolve(T4, B2)
    np.testing.assert_allclose(np.einsum("ijkl,kl->ij", np.asarray(T4),
                                         np.asarray(X)),
                               np.asarray(B2), rtol=1e-5)
    Tinv = ns.linalg.tensorinv(T4, ind=2)
    np.testing.assert_allclose(
        np.einsum("ijkl,klmn->ijmn", np.asarray(Tinv), np.asarray(T4)),
        np.einsum("ik,jl->ijkl", np.eye(2), np.eye(2)), atol=1e-5)
    LEDGER.record("linalg.tensorsolve", "linalg.tensorinv")

    U, P = ns.linalg.polar(jnp.asarray(A + 3 * np.eye(4)))
    np.testing.assert_allclose(np.asarray(U @ P),
                               np.asarray(A + 3 * np.eye(4)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(U @ U.T), np.eye(4), atol=1e-5)
    bd = np.asarray(ns.linalg.block_diag(jnp.ones((2, 2)),
                                         2 * jnp.ones((1, 1))))
    assert bd.shape == (3, 3) and bd[2, 2] == 2 and bd[0, 2] == 0
    tp = np.asarray(ns.linalg.toeplitz(jnp.asarray([1.0, 2, 3])))
    np.testing.assert_allclose(tp, [[1, 2, 3], [2, 1, 2], [3, 2, 1]])
    LEDGER.record("linalg.polar", "linalg.block_diag", "linalg.toeplitz")


def test_round5_random_tail():
    key = jax.random.key(0)
    r = ns.random.randint(key, (200,), 3, 9)
    assert int(r.min()) >= 3 and int(r.max()) < 9
    for name in ("cauchy", "student_t", "weibull"):
        fn = getattr(ns.random, name)
        if name == "student_t":
            v = fn(key, 3.0, (50,))
        elif name == "weibull":
            v = fn(key, 1.0, 1.5, (50,))
        else:
            v = fn(key, (50,))
        assert v.shape == (50,) and bool(jnp.all(jnp.isfinite(v)))
    d = ns.random.dirichlet(key, jnp.ones(4), (10,))
    np.testing.assert_allclose(np.asarray(d.sum(-1)), 1.0, rtol=1e-5)
    rad = np.asarray(ns.random.rademacher(key, (100,)))
    assert set(np.unique(rad)) <= {-1, 1}
    counts_ = ns.random.multinomial(key, 32, jnp.zeros((5, 4)))
    assert counts_.shape == (5, 4)
    np.testing.assert_array_equal(np.asarray(counts_.sum(-1)), 32)
    LEDGER.record("random.randint", "random.cauchy", "random.weibull",
                  "random.dirichlet", "random.student_t",
                  "random.rademacher", "random.multinomial")


def test_round5_image_tail(rng):
    img = jnp.asarray(rng.uniform(0, 1, (2, 8, 8, 3)).astype(np.float32))
    bl = ns.image.image_resize(img, 4, 4, method="bilinear")
    np.testing.assert_allclose(np.asarray(bl),
                               np.asarray(ns.image.resize_bilinear(img, 4, 4)),
                               rtol=1e-6)
    for m in ("nearest", "bicubic", "area"):
        assert ns.image.image_resize(img, 4, 4, method=m).shape == (2, 4, 4, 3)
    assert ns.image.resize_lanczos3(img, 16, 16).shape == (2, 16, 16, 3)
    assert ns.image.resize_lanczos5(img, 5, 5).shape == (2, 5, 5, 3)
    cc = ns.image.central_crop(img, 0.5)
    np.testing.assert_allclose(np.asarray(cc), np.asarray(img[:, 2:6, 2:6]))
    pad = ns.image.pad_to_bounding_box(img, 1, 2, 12, 13)
    assert pad.shape == (2, 12, 13, 3)
    np.testing.assert_allclose(np.asarray(pad[:, 1:9, 2:10]),
                               np.asarray(img))
    assert float(pad[:, 0].max()) == 0.0
    LEDGER.record("image.image_resize", "image.resize_lanczos3",
                  "image.resize_lanczos5", "image.central_crop",
                  "image.pad_to_bounding_box")


def test_round5_cnn_tail(rng):
    x = jnp.asarray(rng.normal(size=(1, 4, 4, 2)).astype(np.float32))
    pooled, argmax = ns.cnn.max_pool_with_argmax(x, 2, 2, 2, 2)
    assert pooled.shape == (1, 2, 2, 2)
    # gather-back property: x.flat[argmax] == pooled (per image plane)
    flat = np.asarray(x).reshape(1, -1)
    np.testing.assert_allclose(
        flat[0][np.asarray(argmax).reshape(-1)],
        np.asarray(pooled).reshape(-1), rtol=1e-6)
    ref = np.asarray(x).reshape(1, 2, 2, 2, 2, 2).max(axis=(2, 4))
    np.testing.assert_allclose(np.asarray(pooled), ref, rtol=1e-6)

    filt = jnp.asarray(rng.normal(0, 0.1, (2, 2, 2)).astype(np.float32))
    dil = ns.cnn.dilation2d(x, filt, 1, 1, "VALID")
    assert dil.shape == (1, 3, 3, 2)
    want = np.full((3, 3, 2), -np.inf, np.float32)
    xa, fa = np.asarray(x)[0], np.asarray(filt)
    for i in range(3):
        for j in range(3):
            for c in range(2):
                want[i, j, c] = max(xa[i + di, j + dj, c] + fa[di, dj, c]
                                    for di in range(2) for dj in range(2))
    np.testing.assert_allclose(np.asarray(dil)[0], want, rtol=1e-5)

    # SAME padding pads with -inf, not zeros: all-negative input must
    # pool/dilate to its own values at the borders (review regression)
    neg = -jnp.ones((1, 3, 3, 1), jnp.float32)
    pooled_s, arg_s = ns.cnn.max_pool_with_argmax(neg, 2, 2, 1, 1, "SAME")
    np.testing.assert_allclose(np.asarray(pooled_s), -1.0)
    flatneg = np.asarray(neg).reshape(-1)
    np.testing.assert_allclose(flatneg[np.asarray(arg_s).reshape(-1)], -1.0)
    dil_s = ns.cnn.dilation2d(-5 * jnp.ones((1, 3, 3, 1)),
                              jnp.zeros((2, 2, 1)), 1, 1, "SAME")
    np.testing.assert_allclose(np.asarray(dil_s), -5.0)
    LEDGER.record("cnn.max_pool_with_argmax", "cnn.dilation2d")


def test_round5_base_bitwise_tail():
    oh = np.asarray(ns.base.one_hot(jnp.asarray([0, 2]), 3,
                                    on_value=5.0, off_value=-1.0))
    np.testing.assert_allclose(oh, [[5, -1, -1], [-1, -1, 5]])
    assert int(ns.base.searchsorted(jnp.asarray([1.0, 3, 5]),
                                    jnp.asarray(4.0))) == 2
    np.testing.assert_array_equal(
        np.asarray(ns.base.diff(jnp.asarray([1, 4, 9]))), [3, 5])
    oh_i = ns.base.one_hot(jnp.asarray([1]), 2, dtype=jnp.int32)
    assert oh_i.dtype == jnp.int32          # dtype honored (review reg.)
    x = jnp.asarray(np.array([0x80000001], np.uint32).view(np.int32))
    rl = ns.bitwise.cyclic_shift_left(x, 1)
    np.testing.assert_array_equal(np.asarray(rl).view(np.uint32),
                                  [0x00000003])
    back = ns.bitwise.cyclic_shift_right(rl, 1)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
    # signed ARRAY shift counts must not become arithmetic shifts
    # (review regression: sign-bit smear under dtype promotion)
    x2 = jnp.asarray(np.array([0x80000001, 2], np.uint32).view(np.int32))
    rl2 = ns.bitwise.cyclic_shift_left(x2, jnp.asarray([1, 1], jnp.int32))
    np.testing.assert_array_equal(np.asarray(rl2).view(np.uint32), [3, 4])
    with pytest.raises(ValueError):
        from deeplearning4j_tpu.ops.extra import central_crop
        central_crop(jnp.zeros((1, 8, 8, 3)), 1.5)
    LEDGER.record("base.one_hot", "base.searchsorted", "base.diff",
                  "bitwise.cyclic_shift_left", "bitwise.cyclic_shift_right")


def test_round5_ctc_decode():
    """Greedy: collapse repeats then drop blanks; beam recovers the
    higher-probability multi-path label over the greedy path."""
    # T=5, C=3 (blank=0): argmax path = [1,1,0,2,2] → decode [1,2]
    big = 5.0
    logits = np.full((1, 5, 3), -big, np.float32)
    for t, s in enumerate([1, 1, 0, 2, 2]):
        logits[0, t, s] = big
    dec, lens = ns.loss.ctc_greedy_decode(jnp.asarray(logits))
    assert int(lens[0]) == 2
    np.testing.assert_array_equal(np.asarray(dec)[0, :2], [1, 2])
    assert np.all(np.asarray(dec)[0, 2:] == -1)

    # merge_repeated=False keeps the duplicate
    dec2, lens2 = ns.loss.ctc_greedy_decode(jnp.asarray(logits),
                                            merge_repeated=False)
    assert int(lens2[0]) == 4
    np.testing.assert_array_equal(np.asarray(dec2)[0, :4], [1, 1, 2, 2])

    # logit_lengths masks the tail
    dec3, lens3 = ns.loss.ctc_greedy_decode(jnp.asarray(logits),
                                            logit_lengths=jnp.asarray([2]))
    assert int(lens3[0]) == 1 and int(np.asarray(dec3)[0, 0]) == 1

    # beam == greedy on a peaked distribution
    paths = ns.loss.ctc_beam_decode(jnp.asarray(logits), beam_width=4,
                                    top_paths=2)
    assert paths[0][0][0] == [1, 2]
    assert paths[0][0][1] > paths[0][1][1]

    # classic beam-vs-greedy case: greedy picks blank-heavy [T=2] frames
    # but the summed label mass wins under the beam
    lg = np.log(np.asarray([[[0.4, 0.6, 0.0],
                             [0.4, 0.6, 0.0]]], np.float32) + 1e-9)
    paths = ns.loss.ctc_beam_decode(jnp.asarray(lg), beam_width=8)
    # P([1]) = 0.6·0.4 + 0.4·0.6 + 0.6·0.6 = 0.84 > P([]) = 0.16
    assert paths[0][0][0] == [1]
    np.testing.assert_allclose(np.exp(paths[0][0][1]), 0.84, rtol=1e-4)
    LEDGER.record("loss.ctc_greedy_decode", "loss.ctc_beam_decode")


def test_zz_coverage_ledger():
    """Runs LAST in this module (pytest runs in definition order): checks
    coverage against the committed baseline and fails on regression."""
    report = LEDGER.check()
    assert report["covered"] > 0
    print(f"op coverage: {report['covered']}/{report['total']} "
          f"({100 * report['coverage']:.1f}%) — uncovered: {report['uncovered']}")