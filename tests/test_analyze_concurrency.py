"""Tier-1 gate + seed tests for the TPU4xx concurrency analyzer.

Mirrors ``test_analyze_self.py``'s contract for the new family: the
framework tree must be free of unsuppressed concurrency findings, every
suppression must carry a written reason (a bare pragma is a TPU400
error and fails this gate), and each rule has positive / negative /
pragma-suppressed seed fixtures under ``tests/fixtures/concurrency/``.
"""

import json
import os

import pytest

import deeplearning4j_tpu
from deeplearning4j_tpu.analyze import source as source_cache
from deeplearning4j_tpu.analyze.__main__ import main as analyze_main
from deeplearning4j_tpu.analyze.concurrency import (
    CONCURRENCY_RULES, analyze_concurrency_package,
    analyze_concurrency_paths, build_model, register_concurrency_rule)
from deeplearning4j_tpu.analyze.diagnostics import Diagnostic, rule_family
from deeplearning4j_tpu.analyze.lint import lint_paths

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "concurrency")
PACKAGE_DIR = os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__))


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def run_on(name: str):
    return analyze_concurrency_paths([fixture(name)])


# ------------------------------------------------------------ tier-1 gates
def test_framework_tree_is_concurrency_clean():
    """The whole-package self-analysis: zero unsuppressed TPU4xx
    findings, and zero TPU400 (so every suppression carries a reason)."""
    report = analyze_concurrency_package()
    errors = report.errors()
    assert errors == [], "concurrency findings in the tree:\n" + "\n".join(
        d.render() for d in errors)
    assert report.context["files_analyzed"] > 100
    # the framework genuinely spawns threads — entry-point discovery
    # finding none would mean the model silently went blind
    assert report.context["entry_points"] >= 10


def test_self_cli_with_concurrency_exits_zero():
    assert analyze_main(["--concurrency", "--self"]) == 0


def test_suppressions_in_tree_are_reasoned():
    """Anything the tree suppresses is still visible in the report, and
    none of it is reason-less (that would be a TPU400 error)."""
    report = analyze_concurrency_package()
    assert not [d for d in report.diagnostics if d.rule == "TPU400"]
    for d in report.suppressed:
        assert d.rule.startswith("TPU4")


# ------------------------------------------------------- TPU401 acceptance
def test_tpu401_inversion_cycle_names_both_locks_and_paths():
    report = run_on("tpu401_inversion.py")
    findings = report.by_rule("TPU401")
    assert len(findings) == 2, "\n".join(d.render() for d in findings)
    direct = next(d for d in findings if "Inverted._lock_a" in d.message)
    # the cycle names BOTH locks and BOTH code paths, with lines
    assert "Inverted._lock_b" in direct.message
    assert "Inverted._worker" in direct.message
    assert "Inverted.poke" in direct.message
    assert "line" in direct.message
    # the indirect cycle required following a call edge
    indirect = next(d for d in findings if "IndirectInversion" in d.message)
    assert "IndirectInversion._commit" in indirect.message
    assert "IndirectInversion.refresh" in indirect.message


def test_tpu401_consistent_order_is_clean():
    assert run_on("tpu401_clean.py").errors() == []


def test_tpu401_reentry_suppressed_with_reason():
    report = run_on("tpu401_suppressed.py")
    assert report.errors() == []
    assert [d.rule for d in report.suppressed] == ["TPU401"]


# ------------------------------------------------ per-rule seed fixtures
@pytest.mark.parametrize("rule,pos,neg,sup", [
    ("TPU402", "tpu402_race.py", "tpu402_clean.py", "tpu402_suppressed.py"),
    ("TPU403", "tpu403_handler.py", "tpu403_clean.py",
     "tpu403_suppressed.py"),
    ("TPU404", "tpu404_blocking.py", "tpu404_clean.py",
     "tpu404_suppressed.py"),
    ("TPU405", "tpu405_leak.py", "tpu405_clean.py", "tpu405_suppressed.py"),
    ("TPU406", "tpu406_futures.py", "tpu406_clean.py",
     "tpu406_suppressed.py"),
])
def test_rule_seed_fixtures(rule, pos, neg, sup):
    positive = run_on(pos)
    assert {d.rule for d in positive.errors()} == {rule}, "\n".join(
        d.render() for d in positive.diagnostics)
    negative = run_on(neg)
    assert negative.errors() == [], "\n".join(
        d.render() for d in negative.errors())
    suppressed = run_on(sup)
    assert suppressed.errors() == []
    assert [d.rule for d in suppressed.suppressed] == [rule]


def test_tpu402_message_names_both_entry_points():
    report = run_on("tpu402_race.py")
    (finding,) = report.by_rule("TPU402")
    assert "thread:Racy._run" in finding.message
    assert "caller API" in finding.message
    assert "_count" in finding.message


def test_tpu404_direct_and_through_a_call():
    report = run_on("tpu404_blocking.py")
    findings = report.by_rule("TPU404")
    assert len(findings) == 2
    assert any("queue .get()" in d.message for d in findings)
    # the join is flagged in _finish but the lock came from stop()
    join = next(d for d in findings if ".join()" in d.message)
    assert "Wedge._finish" in join.message
    assert "Wedge._lock" in join.message


# ------------------------------------------------------------ pragmas
def test_tpu400_bad_pragma_shapes():
    report = run_on("tpu400_pragmas.py")
    messages = [d.message for d in report.by_rule("TPU400")]
    assert len(messages) == 3
    assert any("bare suppression" in m for m in messages)
    assert any("TPU999" in m for m in messages)
    assert any("TPU105" in m for m in messages)
    # the bare pragma STILL suppresses — the TPU400 is what keeps the
    # gate red, not a duplicate of the silenced finding
    assert [d.rule for d in report.suppressed] == ["TPU402"]
    assert not report.by_rule("TPU402")


def test_pragma_cannot_suppress_tpu400(tmp_path):
    """Naming TPU400 in a pragma is itself a TPU400 — a pragma problem
    is fixed by fixing the pragma, never by stacking another one."""
    path = tmp_path / "meta.py"
    path.write_text(
        "def helper():\n"
        "    # tpudl: ok(TPU400) — trying to silence the pragma police\n"
        "    pass\n")
    report = analyze_concurrency_paths([str(path)])
    (finding,) = report.errors()
    assert finding.rule == "TPU400"
    assert "cannot be suppressed" in finding.message
    assert report.suppressed == []


def test_overlapping_paths_analyze_each_file_once(tmp_path):
    """`--concurrency pkg pkg/sub` must not double findings or counts."""
    (tmp_path / "m.py").write_text(
        "import sys\n\n\n"
        "def helper():\n"
        "    sys.exit(1)\n")
    report = lint_paths([str(tmp_path / "m.py"), str(tmp_path)])
    assert report.context["files_linted"] == 1
    assert len(report.errors()) == 1


def test_pragma_honored_by_lint_family_too(tmp_path):
    """One pragma grammar across families: a TPU3xx lint finding is
    suppressible the same way (and a reason is still mandatory)."""
    good = tmp_path / "good.py"
    good.write_text(
        "import sys\n\n\n"
        "def helper():\n"
        "    # tpudl: ok(TPU312) — test fixture: suppression plumbing\n"
        "    sys.exit(1)\n")
    report = lint_paths([str(good)])
    assert report.errors() == []
    assert [d.rule for d in report.suppressed] == ["TPU312"]

    bare = tmp_path / "bare.py"
    bare.write_text(
        "import sys\n\n\n"
        "def helper():\n"
        "    # tpudl: ok(TPU312)\n"
        "    sys.exit(1)\n")
    report = lint_paths([str(bare)])
    assert [d.rule for d in report.errors()] == ["TPU400"]


def test_pragma_in_string_literal_does_not_suppress(tmp_path):
    """Only COMMENT tokens carry pragmas — a docstring mentioning the
    grammar must not silence anything."""
    path = tmp_path / "strung.py"
    path.write_text(
        '"""Docs: write `# tpudl: ok(TPU402) — why` above the line."""\n'
        "import threading\n\n\n"
        "class Racy:\n"
        "    def __init__(self):\n"
        "        self._n = 0\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n\n"
        "    def _run(self):\n"
        "        self._n += 1\n\n"
        "    def reset(self):\n"
        "        self._n = 0\n\n"
        "    def close(self):\n"
        "        self._t.join(1.0)\n")
    report = analyze_concurrency_paths([str(path)])
    assert [d.rule for d in report.errors()] == ["TPU402"]
    assert report.suppressed == []


# ------------------------------------------------------- shared AST cache
def test_families_share_one_parse_per_file():
    """--self --lint --concurrency must parse each module once: the
    second family over the same tree is all cache hits."""
    source_cache.clear_cache()
    lint_report = lint_paths([FIXTURES])
    parses_after_lint = source_cache.cache_stats()["parses"]
    assert parses_after_lint == lint_report.context["files_linted"]
    conc_report = analyze_concurrency_paths([FIXTURES])
    stats = source_cache.cache_stats()
    assert stats["parses"] == parses_after_lint, \
        "concurrency pass re-parsed files the lint pass already parsed"
    assert stats["hits"] >= conc_report.context["files_analyzed"]


# ------------------------------------------------------------ JSON output
def test_json_finding_schema_shared_across_families(capsys):
    rc = analyze_main(["--concurrency", fixture("tpu402_race.py"),
                       "--lint", fixture("tpu402_race.py"),
                       "--format", "json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["exit_code"] == 1
    assert "suppressed" in doc
    (finding,) = doc["diagnostics"]
    assert set(finding) == {"rule", "slug", "family", "severity", "path",
                            "message", "hint"}
    assert finding["rule"] == "TPU402"
    assert finding["family"] == "concurrency"
    assert finding["slug"] == "unlocked-shared-write"


def test_json_carries_suppressed_findings(capsys):
    rc = analyze_main(["--concurrency", fixture("tpu402_suppressed.py"),
                       "--format", "json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["diagnostics"] == []
    (sup,) = doc["suppressed"]
    assert sup["rule"] == "TPU402"
    assert sup["family"] == "concurrency"


def test_rule_family_mapping():
    assert rule_family("TPU101") == "model"
    assert rule_family("TPU201") == "sharding"
    assert rule_family("TPU301") == "lint"
    assert rule_family("TPU402") == "concurrency"


# --------------------------------------------------------- extensibility
def test_register_concurrency_rule_pluggable():
    @register_concurrency_rule("TPU499")
    def _count_classes(model):
        return [Diagnostic("TPU499", f"classes={len(model.classes)}",
                           path=model.path)]
    try:
        report = analyze_concurrency_paths(
            [fixture("tpu402_race.py")],
            rules={"TPU499": CONCURRENCY_RULES["TPU499"]})
        (finding,) = report.diagnostics
        assert finding.rule == "TPU499"
        assert finding.message == "classes=1"
    finally:
        CONCURRENCY_RULES.pop("TPU499")


def test_tpu405_os_path_join_is_not_cleanup(tmp_path):
    """Only thread/queue/process-shaped receivers count as joins —
    os.path.join in a close() must not exempt a leaked thread."""
    path = tmp_path / "pathjoin.py"
    path.write_text(
        "import os\n"
        "import threading\n\n\n"
        "class Leaky:\n"
        "    def __init__(self):\n"
        "        self._thread = threading.Thread(target=self._run)\n"
        "        self._thread.start()\n\n"
        "    def _run(self):\n"
        "        return\n\n"
        "    def close(self):\n"
        "        return os.path.join('/tmp', 'x')\n")
    report = analyze_concurrency_paths([str(path)])
    assert [d.rule for d in report.errors()] == ["TPU405"]


def test_tpu402_sees_workers_nested_in_init(tmp_path):
    """A worker closure defined inside __init__ runs AFTER the thread
    starts — only __init__ itself is construction-time-exempt."""
    path = tmp_path / "nested.py"
    path.write_text(
        "import threading\n\n\n"
        "class Racy:\n"
        "    def __init__(self):\n"
        "        self._n = 0\n\n"
        "        def worker():\n"
        "            self._n += 1\n\n"
        "        self._thread = threading.Thread(target=worker)\n"
        "        self._thread.start()\n\n"
        "    def reset(self):\n"
        "        self._n = 0\n\n"
        "    def close(self):\n"
        "        self._thread.join(1.0)\n")
    report = analyze_concurrency_paths([str(path)])
    assert [d.rule for d in report.errors()] == ["TPU402"]


def test_anchors_keep_caller_given_paths(tmp_path, monkeypatch):
    """Findings anchor to the path AS GIVEN (relative stays relative) so
    JSON diffs don't turn machine-specific — suppression matching still
    works because it abspath-normalizes both sides."""
    pkg = tmp_path / "relcheck"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "import sys\n\n\n"
        "def helper():\n"
        "    sys.exit(1)\n")
    monkeypatch.chdir(tmp_path)
    report = lint_paths(["relcheck"])
    (finding,) = report.errors()
    assert finding.path.startswith("relcheck/"), finding.path
    conc = analyze_concurrency_paths(["relcheck"])
    assert conc.context["files_analyzed"] == 1


def test_build_model_exposes_entries_and_lock_graph():
    model = build_model(fixture("tpu401_inversion.py"))
    labels = {e.label for e in model.entries}
    assert "thread:Inverted._worker" in labels
    assert "caller API" in labels
    assert ("Inverted._lock_a", "Inverted._lock_b") in model.lock_edges
    assert ("Inverted._lock_b", "Inverted._lock_a") in model.lock_edges


def test_combined_cli_merges_and_dedups(capsys):
    """--self --lint --concurrency over one file: TPU400 pragma findings
    come from the shared scan and must not double-report."""
    rc = analyze_main(["--concurrency", fixture("tpu400_pragmas.py"),
                       "--lint", fixture("tpu400_pragmas.py"),
                       "--format", "json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    tpu400 = [d for d in doc["diagnostics"] if d["rule"] == "TPU400"]
    assert len(tpu400) == 3        # bare + unknown + non-AST, once each
