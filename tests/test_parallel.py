"""Distributed/parallelism tests on the 8-device virtual CPU mesh
(multi-host-without-a-cluster, SURVEY.md §4.2 #3)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.parallel import (
    make_mesh, ParallelWrapper, threshold_encode, threshold_decode,
    bitmap_encode, bitmap_decode, EncodedGradientsAccumulator,
    ParallelInference,
)
from deeplearning4j_tpu.parallel.unified import ring_attention, reference_attention
from deeplearning4j_tpu.parallel.pipeline import pipeline_apply
from deeplearning4j_tpu.parallel import mesh as tp
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.train.trainer import Trainer


def _mlp(seed=11):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Sgd(0.1)).weight_init("xavier").list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(8))
        .build())


def _toy_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, -1)]
    return x, y


def test_make_mesh_axes():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8
    mesh2 = make_mesh(data=2, model=2, seq=2)
    assert mesh2.shape == {"pipe": 1, "data": 2, "seq": 2, "expert": 1,
                           "model": 2}
    with pytest.raises(ValueError):
        make_mesh(data=3, model=3)


def test_data_parallel_matches_single_device():
    """DP over 8 shards must produce the same params as single-device
    training on the same batches (sync dense allreduce == exact)."""
    x, y = _toy_data(64)
    it = lambda: ArrayDataSetIterator(x, y, 32)  # noqa: E731

    net_a = _mlp()
    Trainer(net_a).fit(it(), epochs=3)

    net_b = _mlp()
    ParallelWrapper(net_b, mesh=make_mesh(data=8)).fit(it(), epochs=3)

    np.testing.assert_allclose(np.asarray(net_a.params()),
                               np.asarray(net_b.params()), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(data=1, seq=8)
    b, t, heads, dh = 2, 32, 4, 8
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    with mesh:
        out = ring_attention(q, k, v, mesh, axis="seq", n_heads=heads, causal=causal)
    ref = reference_attention(q, k, v, n_heads=heads, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zero_optimizer_sharding_matches_and_shards():
    """ZeRO-1: training with sharded updater state must equal plain DP
    bit-for-bit in results, while each device holds only 1/n of the
    Adam moments."""
    from deeplearning4j_tpu.train import Adam, Trainer

    def _net():
        conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    def it():
        x, y = _toy_data()
        return ArrayDataSetIterator(x, y, 32, shuffle=False)

    net_a = _net()
    ParallelWrapper(net_a, mesh=make_mesh(data=8)).fit(it(), epochs=2)

    net_b = _net()
    ParallelWrapper(net_b, mesh=make_mesh(data=8),
                    zero_optimizer_sharding=True).fit(it(), epochs=2)
    np.testing.assert_allclose(np.asarray(net_a.params()),
                               np.asarray(net_b.params()),
                               rtol=1e-5, atol=1e-6)

    # the Adam moment for the [16-wide] dense W must be sharded: each
    # device's addressable shard is 1/8 of the full tensor
    leaves = [l for l in jax.tree_util.tree_leaves(net_b.opt_state)
              if hasattr(l, "shape") and l.ndim == 2 and l.shape == (8, 16)]
    assert leaves, "expected Adam moments of the first Dense W"
    for leaf in leaves:
        shard = leaf.addressable_shards[0]
        assert shard.data.size == leaf.size // 8, (
            f"opt leaf not ZeRO-sharded: shard {shard.data.shape} "
            f"of {leaf.shape}")


def test_zero_sharding_rejects_averaging_mode():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Adam
    net_conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
                .list()
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
    with pytest.raises(ValueError, match="zero_optimizer_sharding"):
        ParallelWrapper(MultiLayerNetwork(net_conf).init(),
                        mesh=make_mesh(data=8),
                        averaging_frequency=4, zero_optimizer_sharding=True)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    from deeplearning4j_tpu.parallel.unified import ulysses_attention
    mesh = make_mesh(data=1, seq=4, devices=jax.devices()[:4])
    b, t, heads, dh = 2, 32, 8, 8    # heads % seq-axis == 0
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    with mesh:
        out = ulysses_attention(q, k, v, mesh, axis="seq", n_heads=heads,
                                causal=causal)
    ref = reference_attention(q, k, v, n_heads=heads, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_dp_combo_and_validation():
    from deeplearning4j_tpu.parallel.unified import ulysses_attention
    mesh = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    b, t, heads, dh = 4, 16, 4, 4
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    with mesh:
        out = ulysses_attention(q, k, v, mesh, axis="seq", n_heads=heads,
                                data_axis="data", causal=True)
    ref = reference_attention(q, k, v, n_heads=heads, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # grads flow through both all_to_alls
    def loss(q):
        with mesh:
            y = ulysses_attention(q, k, v, mesh, axis="seq", n_heads=heads,
                                  data_axis="data")
        return jnp.mean(y * y)
    g = jax.grad(loss)(q)
    assert np.all(np.isfinite(np.asarray(g)))
    # heads not divisible by axis size → loud error
    with pytest.raises(ValueError, match="divisible"):
        with mesh:
            ulysses_attention(q, k, v, mesh, axis="seq", n_heads=6)


def test_pipeline_matches_sequential():
    mesh = make_mesh(data=1, stage=8)
    n_stages, width, batch, micro = 8, 16, 32, 4
    rng = np.random.default_rng(5)
    stage_w = jnp.asarray(rng.normal(0, 0.3, size=(n_stages, width, width)).astype(np.float32))

    def stage_fn(params, x):
        return jnp.tanh(x @ params)

    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))
    with mesh:
        y = pipeline_apply(stage_fn, stage_w, x, mesh, n_microbatches=micro)
    ref = x
    for s in range(n_stages):
        ref = jnp.tanh(ref @ stage_w[s])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_pipeline_with_data_axis_matches_sequential():
    """dp×pp: two data-parallel pipeline replicas of 4 stages each."""
    mesh = make_mesh(data=2, stage=4)
    n_stages, width, batch, micro = 4, 16, 16, 2
    rng = np.random.default_rng(6)
    stage_w = jnp.asarray(rng.normal(0, 0.3, size=(n_stages, width, width)).astype(np.float32))

    def stage_fn(params, x):
        return jnp.tanh(x @ params)

    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))
    with mesh:
        y = pipeline_apply(stage_fn, stage_w, x, mesh, n_microbatches=micro,
                           data_axis="data")
        # backward through the combined schedule
        g = jax.grad(lambda w: jnp.mean(pipeline_apply(
            stage_fn, w, x, mesh, n_microbatches=micro, data_axis="data") ** 2)
        )(stage_w)
    ref = x
    for s in range(n_stages):
        ref = jnp.tanh(ref @ stage_w[s])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-4, atol=2e-5)
    assert np.all(np.isfinite(np.asarray(g)))


@pytest.mark.parametrize("combo", ["dp_sp", "tp_sp"])
def test_ring_attention_composed_axes(combo):
    """Ring attention with the seq ring composed against a data axis
    (dp×sp) or a head-sharding tensor axis (tp×sp)."""
    if combo == "dp_sp":
        mesh = make_mesh(data=2, seq=4)
        kw = {"data_axis": "data"}
    else:
        mesh = make_mesh(data=1, model=2, seq=4)
        kw = {"head_axis": "model"}
    b, t, heads, dh = 2, 16, 4, 8
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, t, heads * dh)).astype(np.float32))
    with mesh:
        out = ring_attention(q, k, v, mesh, axis="seq", n_heads=heads,
                             causal=True, **kw)
    ref = reference_attention(q, k, v, n_heads=heads, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_tensor_parallel_bert_layer():
    """TP-sharded tiny BERT forward == replicated forward."""
    from deeplearning4j_tpu.models import bert
    config = bert.BertConfig.tiny()
    params = bert.init_params(config, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1000, (2, 16)).astype(np.int32))

    ref = bert.encode(params, config, ids)

    mesh = make_mesh(data=1, model=8)
    sharded = tp.shard_params(params, mesh)
    out = jax.jit(lambda p, i: bert.encode(p, config, i))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
    # verify something actually sharded
    qk = sharded["encoder"]["layer_0"]["attention"]["query"]["kernel"]
    assert len(qk.sharding.spec) >= 2 and qk.sharding.spec[1] == "model"


# ------------------------------------------------------------------ codec
def test_threshold_codec_roundtrip():
    rng = np.random.default_rng(0)
    grad = rng.normal(0, 1e-3, size=10000).astype(np.float32)
    grad[rng.choice(10000, 50, replace=False)] = rng.normal(0, 1.0, 50)
    msg = threshold_encode(grad, 0.1)
    decoded = threshold_decode(msg, grad.shape)
    # decoded has ±0.1 exactly at |grad|>=0.1 positions
    hits = np.abs(grad) >= 0.1
    assert int(msg[0]) == hits.sum()
    np.testing.assert_array_equal(decoded != 0, hits)
    np.testing.assert_allclose(np.abs(decoded[hits]), 0.1, rtol=1e-6)
    np.testing.assert_array_equal(np.sign(decoded[hits]), np.sign(grad[hits]))


def test_bitmap_codec_roundtrip():
    rng = np.random.default_rng(1)
    grad = rng.normal(0, 0.5, size=1001).astype(np.float32)
    packed, header = bitmap_encode(grad, 0.3)
    decoded = bitmap_decode(packed, header)
    expect = np.where(grad >= 0.3, 0.3, np.where(grad <= -0.3, -0.3, 0.0)).astype(np.float32)
    np.testing.assert_allclose(decoded, expect, rtol=1e-6)


def test_accumulator_error_feedback():
    """Residual carries quantization error: summed decoded messages converge
    to the true gradient sum (the error-feedback property)."""
    rng = np.random.default_rng(2)
    n = 500
    acc = EncodedGradientsAccumulator((n,), use_native=False)
    true_sum = np.zeros(n, dtype=np.float32)
    decoded_sum = np.zeros(n, dtype=np.float32)
    for step in range(50):
        g = rng.normal(0, 0.01, n).astype(np.float32)
        true_sum += g
        msg = acc.store_update(g)
        decoded_sum = acc.apply_update(msg, decoded_sum)
    # residual bounds the difference
    np.testing.assert_allclose(decoded_sum + acc.residual, true_sum, atol=1e-4)


def test_native_codec_matches_numpy():
    from deeplearning4j_tpu.native import codec
    if not codec.available():
        pytest.skip("no g++ available")
    rng = np.random.default_rng(3)
    grad = rng.normal(0, 0.2, size=4097).astype(np.float32)
    msg_native = codec.threshold_encode(grad, 0.25)
    msg_numpy = threshold_encode(grad, 0.25)
    np.testing.assert_array_equal(msg_native, msg_numpy)
    np.testing.assert_allclose(codec.threshold_decode(msg_native, grad.shape),
                               threshold_decode(msg_numpy, grad.shape), rtol=1e-6)
    assert codec.threshold_count(grad, 0.25) == int(msg_numpy[0])
    packed_n, header_n = codec.bitmap_encode(grad, 0.25)
    packed_p, header_p = bitmap_encode(grad, 0.25)
    np.testing.assert_array_equal(packed_n, packed_p)
    np.testing.assert_allclose(codec.bitmap_decode(packed_n, header_n),
                               bitmap_decode(packed_p, header_p), rtol=1e-6)


def test_parallel_inference_batching():
    net = _mlp()
    net.init()
    x, _ = _toy_data(16)
    expected = np.asarray(net.output(x))
    with ParallelInference(net, batch_limit=8) as pi:
        futures = [pi.output_async(x[i:i + 1]) for i in range(16)]
        results = [f.result(timeout=30) for f in futures]
    got = np.concatenate(results, axis=0)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
