"""Device-side (jit) codec twins vs the numpy/C++ oracle
(reference: libnd4j encodeThreshold/encodeBitmap — SURVEY §2.1; the
device twins let the DCN path encode before leaving the chip)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.compression import (
    bitmap_decode, bitmap_decode_device, bitmap_encode, bitmap_encode_device,
    threshold_decode, threshold_decode_device, threshold_encode,
    threshold_encode_device)


def _grad(n=512, seed=0):
    return np.random.default_rng(seed).normal(0, 0.02, n).astype(np.float32)


def test_threshold_encode_matches_numpy_oracle():
    g, tau = _grad(), 0.03
    ref = threshold_encode(g, tau)
    dev = np.asarray(threshold_encode_device(jnp.asarray(g), tau, capacity=128))
    count = ref[0]
    assert dev[0] == count
    np.testing.assert_array_equal(dev[2], ref[2])           # τ bits
    np.testing.assert_array_equal(dev[3:3 + count], ref[3:3 + count])
    assert np.all(dev[3 + count:] == 0)                     # padding


def test_threshold_device_roundtrip_and_interop():
    g, tau = _grad(seed=1), 0.025
    msg_dev = threshold_encode_device(jnp.asarray(g), tau, capacity=256)
    # device decode of device message
    dec_dev = np.asarray(threshold_decode_device(msg_dev, g.size))
    # numpy decode of the device message (wire interop)
    dec_np = threshold_decode(np.asarray(msg_dev), (g.size,))
    np.testing.assert_allclose(dec_dev, dec_np, atol=0)
    # ±τ exactly at the hit positions
    hits = np.abs(g) >= tau
    np.testing.assert_allclose(dec_dev[hits], np.sign(g[hits]) * tau,
                               atol=1e-7)
    assert np.all(dec_dev[~hits] == 0)


def test_threshold_capacity_truncates():
    g = np.ones(64, np.float32)
    msg = np.asarray(threshold_encode_device(jnp.asarray(g), 0.5, capacity=10))
    assert msg[0] == 10
    assert np.count_nonzero(msg[3:]) == 10


def test_threshold_encode_decode_jit_fused():
    g, tau = _grad(seed=2), 0.03

    @jax.jit
    def wire(g):
        msg = threshold_encode_device(g, tau, capacity=128)
        return threshold_decode_device(msg, g.size)

    dec = np.asarray(wire(jnp.asarray(g)))
    ref = threshold_decode(threshold_encode(g, tau), (g.size,))
    np.testing.assert_allclose(dec, ref, atol=1e-7)


def test_threshold_decode_accumulates_into_out():
    g, tau = _grad(seed=3), 0.03
    msg = threshold_encode_device(jnp.asarray(g), tau, capacity=128)
    base = jnp.ones((g.size,), jnp.float32)
    acc = np.asarray(threshold_decode_device(msg, g.size, out=base))
    ref = 1.0 + threshold_decode(np.asarray(msg), (g.size,))
    np.testing.assert_allclose(acc, ref, atol=1e-7)


def test_bitmap_device_matches_numpy():
    g, tau = _grad(seed=4), 0.02
    p_ref, h_ref = bitmap_encode(g, tau)
    p_dev, h_dev = bitmap_encode_device(jnp.asarray(g), tau)
    np.testing.assert_array_equal(np.asarray(p_dev), p_ref)
    np.testing.assert_array_equal(np.asarray(h_dev), h_ref)
    dec_dev = np.asarray(bitmap_decode_device(p_dev, h_dev, g.size))
    dec_ref = bitmap_decode(p_ref, h_ref)
    np.testing.assert_allclose(dec_dev, dec_ref, atol=0)


def test_bitmap_jit_roundtrip_unaligned_size():
    g = _grad(n=509, seed=5)   # not a multiple of 4 — padding path
    tau = 0.02

    @jax.jit
    def wire(g):
        p, h = bitmap_encode_device(g, tau)
        return bitmap_decode_device(p, h, g.size)

    dec = np.asarray(wire(jnp.asarray(g)))
    hits_pos = g >= tau
    hits_neg = g <= -tau
    np.testing.assert_allclose(dec[hits_pos], tau, atol=1e-7)
    np.testing.assert_allclose(dec[hits_neg], -tau, atol=1e-7)
    assert np.all(dec[~(hits_pos | hits_neg)] == 0)


def test_overflow_topk_parity_all_twins():
    """Capacity overflow keeps the LARGEST |values| (ties -> lower index),
    bitwise-identically in numpy, C++, and device twins — mixed-host
    slices must produce identical wire messages."""
    rng = np.random.default_rng(7)
    g = rng.normal(0, 1.0, 300).astype(np.float32)
    g[10] = 5.0
    g[250] = -5.0          # big entries at both ends
    g[20] = g[30] = 2.5    # exact tie -> index 20 wins over 30 at the cap
    tau, cap = 0.5, 16
    ref = threshold_encode(g, tau, max_elements=cap)
    assert ref[0] == cap
    body = ref[3:3 + cap]
    idx = np.abs(body) - 1
    # the two largest magnitudes survived the cap and indices are ascending
    assert 10 in idx and 250 in idx
    assert np.all(np.diff(idx) > 0)
    # kept set = top-cap by (|value| desc, index asc)
    hits = np.nonzero(np.abs(g) >= tau)[0]
    order = np.lexsort((hits, -np.abs(g[hits])))
    np.testing.assert_array_equal(np.sort(hits[order[:cap]]), idx)

    dev = np.asarray(threshold_encode_device(jnp.asarray(g), tau,
                                             capacity=cap))
    np.testing.assert_array_equal(dev[:3 + cap], ref[:3 + cap])

    from deeplearning4j_tpu.native import codec as native_codec
    if native_codec.available():
        nat = native_codec.threshold_encode(g, tau, max_elements=cap)
        np.testing.assert_array_equal(nat, ref)
