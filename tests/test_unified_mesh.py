"""Unified device mesh (ISSUE 14): composable DP×TP×PP layouts behind
ONE Trainer flag.

The load-bearing contract: every layout reproduces the single-device
run — per-step losses and final params to 1e-6, dropout ACTIVE — with
exactly one compiled step per layout, and the layout is a first-class
part of the program's identity (step-cache key, artifact store,
tpudl_mesh_* gauges).  The deprecated per-mode entry points warn once
and route here.
"""

import importlib
import json
import sys
import warnings

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import get_registry
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.mesh import (
    AXIS_DATA, AXIS_MODEL, AXIS_PIPE, MESH_AXES, MeshSpec, make_mesh,
    resolve_layout)
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.train.trainer import Trainer


def _mlp(seed=11, dropout=True):
    drop = 0.8 if dropout else None
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu", dropout=drop))
            .layer(DenseLayer(n_out=16, activation="tanh", dropout=drop))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf)


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, -1)]
    return x, y


def _run(layout=None, dropout=True, n_microbatches=1, epochs=2):
    """Fit and return (per-step losses, flat final params, retraces)."""
    x, y = _data()
    net = _mlp(dropout=dropout)
    trainer = Trainer(net, layout=layout, n_microbatches=n_microbatches)
    losses = []

    class Rec:
        def iteration_done(self, net, it, ep, loss):
            losses.append(float(loss))

    trainer.bus.listeners.append(Rec())
    reg = get_registry()
    before = reg.counter("tpudl_train_recompiles_total").value
    trainer.fit(ArrayDataSetIterator(x, y, 16, shuffle=False), epochs=epochs)
    retraced = reg.counter("tpudl_train_recompiles_total").value - before
    return losses, np.asarray(net.params()), retraced


# one baseline per module — every layout case compares against it
_BASELINE = {}


def _baseline(dropout):
    if dropout not in _BASELINE:
        _BASELINE[dropout] = _run(None, dropout=dropout)
    return _BASELINE[dropout]


@pytest.mark.parametrize("layout", ["dp2", "tp2", "dp2xtp2", "pp2"])
def test_layout_matches_single_device_with_dropout(layout):
    """The satellite contract: DP=2, TP=2, DP×TP=2×2 and PP=2 layouts
    all reproduce the single-device per-step losses and final params to
    1e-6 with dropout active, one compile per layout."""
    base_losses, base_params, _ = _baseline(True)
    losses, params, retraced = _run(layout)
    assert len(losses) == len(base_losses)
    np.testing.assert_allclose(losses, base_losses, rtol=0, atol=1e-6)
    np.testing.assert_allclose(params, base_params, rtol=0, atol=1e-6)
    # one compiled step per layout: the first step traced, nothing after
    assert retraced == 1, f"{layout} retraced {retraced} times"


@pytest.mark.parametrize("layout,mb", [
    pytest.param("dp2xpp2", 2, marks=pytest.mark.slow),
    ("dp2xtp2xpp2", 2),
])
def test_composed_pipe_layouts_match_single_device(layout, mb):
    """DP×PP and the full DP×TP×PP composition on one 8-device mesh:
    real 1F1B microbatching (M=2) + batch shards + model-axis param
    shards, still 1e-6 against single-device (dropout off — per-layer
    masks regenerate per microbatch shape at M>1, documented).  The
    full composition runs tier-1 (it exercises every axis at once);
    the DP×PP-only case is @slow (suite-wall budget)."""
    base_losses, base_params, _ = _baseline(False)
    losses, params, retraced = _run(layout, dropout=False, n_microbatches=mb)
    np.testing.assert_allclose(losses, base_losses, rtol=0, atol=1e-6)
    np.testing.assert_allclose(params, base_params, rtol=0, atol=1e-6)
    assert retraced == 1


def test_pp_params_actually_shard_and_metrics_publish():
    """dp2xtp2xpp2 is real parallelism, not a relabeling: model-axis
    leaves live sharded (each device holds 1/tp of dim 0), and the
    tpudl_mesh_* gauges describe the active layout."""
    x, y = _data()
    net = _mlp(dropout=False)
    trainer = Trainer(net, layout="dp2xtp2xpp2", n_microbatches=2)
    trainer.fit(ArrayDataSetIterator(x, y, 16, shuffle=False), epochs=1)
    w0 = net.params_[0]["W"]          # [8, 16] — dim0 divisible by tp=2
    assert str(w0.sharding.spec) == str(jax.sharding.PartitionSpec("model"))
    shard = w0.addressable_shards[0]
    assert shard.data.shape[0] == w0.shape[0] // 2
    reg = get_registry()
    assert reg.gauge("tpudl_mesh_devices").value == 8
    axis = reg.labeled_gauge("tpudl_mesh_axis_size", label_names=("axis",))
    assert axis.labeled_value(axis=AXIS_DATA) == 2
    assert axis.labeled_value(axis=AXIS_MODEL) == 2
    assert axis.labeled_value(axis=AXIS_PIPE) == 2
    layout_g = reg.labeled_gauge("tpudl_mesh_layout_active",
                                 label_names=("layout",))
    assert layout_g.labeled_value(layout="dp2xtp2xpp2") == 1
    assert reg.gauge("tpudl_mesh_collective_bytes").value > 0


def test_layout_signature_separates_step_cache_keys():
    """A sharded layout's step is a different program: its step-cache
    key (and therefore its artifact-store identity) must differ from
    the single-device sibling AND from a different layout."""
    net = _mlp()
    keys = set()
    for layout in (None, "dp2", "dp2xtp2"):
        t = Trainer(net, layout=layout)
        keys.add(t._step_key("train"))
    assert len(keys) == 3


# ------------------------------------------------------------ MeshSpec
def test_meshspec_parse_roundtrip_and_errors():
    spec = MeshSpec.parse("dp2xtp2xpp2")
    assert spec.sizes() == {"data": 2, "model": 2, "pipe": 2, "seq": 1,
                            "expert": 1}
    assert spec.describe() == "dp2xtp2xpp2"
    assert MeshSpec.parse("data4_model2").describe() == "dp4xtp2"
    assert MeshSpec().describe() == "single"
    for bad in ("bogus3", "dp2xdp4", "xx", ""):
        with pytest.raises(ValueError):
            MeshSpec.parse(bad)


def test_make_mesh_pipe_axis_and_stage_alias():
    mesh = make_mesh(data=2, pipe=2, devices=jax.devices()[:4])
    assert mesh.shape[AXIS_PIPE] == 2
    legacy = make_mesh(data=2, stage=2, devices=jax.devices()[:4])
    assert legacy.shape == mesh.shape
    assert tuple(mesh.axis_names) == MESH_AXES
    with pytest.raises(ValueError):
        make_mesh(data=2, pipe=3, stage=2, devices=jax.devices()[:4])


def test_resolve_layout_rules():
    from deeplearning4j_tpu.parallel.mesh import MeshLayout
    assert resolve_layout() is None
    assert resolve_layout(layout="dp1") is None      # trivial → single path
    # the trivial→None contract holds for a pre-resolved MeshLayout too
    # (a 1-device layout must not grow a distinct cache signature)
    trivial = MeshLayout(MeshSpec(), devices=jax.devices()[:1])
    assert resolve_layout(layout=trivial) is None
    # a typo'd TP family raises instead of silently replicating
    with pytest.raises(ValueError, match="unknown TP rule family"):
        MeshLayout(MeshSpec(model=2), tp_family="brt",
                   devices=jax.devices()[:2])
    lay = resolve_layout(layout="dp2")
    assert lay.data == 2 and lay.describe() == "dp2"
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    assert resolve_layout(mesh=mesh).data == 4
    with pytest.raises(ValueError, match="disagrees"):
        resolve_layout(mesh=mesh, layout="dp2")
    with pytest.raises(ValueError, match="needs"):
        resolve_layout(layout="dp64")


def test_pp_layout_rejects_unsupported_nets():
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(4)).build())
    net = MultiLayerNetwork(conf)
    x = np.zeros((4, 6, 4), np.float32)
    y = np.zeros((4, 6, 2), np.float32)
    trainer = Trainer(net, layout="pp2")
    with pytest.raises(ValueError, match="recurrent"):
        trainer.fit_batch(__import__(
            "deeplearning4j_tpu.data.dataset",
            fromlist=["DataSet"]).DataSet(x, y), jax.random.key(0))


# ------------------------------------------------------- analyze layouts
def test_check_layout_static_validation():
    from deeplearning4j_tpu.analyze.sharding import check_layout
    assert check_layout("dp2xtp2xpp2", n_devices=8).exit_code() == 0
    report = check_layout("dp64", n_devices=8)
    assert report.by_rule("TPU201")
    report = check_layout("nope2", n_devices=8)
    assert report.by_rule("TPU201")
    report = check_layout("tp2", tp_family="mystery", n_devices=8)
    assert report.by_rule("TPU203")
    # a model axis whose family never shards over it = silent replication
    mesh_mod.TP_RULE_FAMILIES["_norule"] = [
        (r"nothing$", jax.sharding.PartitionSpec())]
    try:
        report = check_layout("tp2", tp_family="_norule", n_devices=8)
        assert report.by_rule("TPU202")
    finally:
        del mesh_mod.TP_RULE_FAMILIES["_norule"]


def test_analyze_cli_model_plus_layout(tmp_path):
    """`analyze --model <conf> --layout dp2xtp2` gates a model and its
    layout together — zero TPU2xx on the shipped configuration."""
    from deeplearning4j_tpu.analyze.__main__ import main as analyze_main
    conf = _mlp().conf
    path = tmp_path / "conf.json"
    path.write_text(conf.to_json())
    assert analyze_main(["--model", str(path), "--layout", "dp2xtp2",
                         "--devices", "8"]) == 0
    assert analyze_main(["--layout", "dp64", "--devices", "8"]) == 1


# ------------------------------------------------------- deprecation shims
@pytest.mark.parametrize("module,names", [
    ("tensor_parallel", ("BERT_TP_RULES", "shard_params",
                         "tp_sharding_tree", "rule_axes", "tp_jit")),
    ("context_parallel", ("ring_attention", "ulysses_attention",
                          "reference_attention")),
    ("expert_parallel", ("moe_ffn", "moe_ffn_dense", "init_moe_params",
                         "shard_moe_params")),
    ("data_parallel", ("ParallelWrapper", "DATA_AXES")),
])
def test_deprecated_entry_points_warn_once_and_route(module, names):
    """The shim contract: importing an old per-mode module raises ONE
    DeprecationWarning and every public name still works, routed to the
    unified implementations."""
    modname = f"deeplearning4j_tpu.parallel.{module}"
    sys.modules.pop(modname, None)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        mod = importlib.import_module(modname)
    dep = [w for w in caught if issubclass(w.category, DeprecationWarning)
           and "deprecated" in str(w.message)]
    assert len(dep) == 1, f"{module}: expected exactly one warning"
    for name in names:
        assert hasattr(mod, name), f"{module}.{name} missing from shim"
    # routed, not copied: the shim's callables ARE the unified ones
    from deeplearning4j_tpu.parallel import mesh, unified
    if module == "tensor_parallel":
        assert mod.shard_params is mesh.shard_params
        assert mod.tp_jit is unified.tp_jit
    if module == "context_parallel":
        assert mod.ring_attention is unified.ring_attention
    if module == "expert_parallel":
        assert mod.moe_ffn is unified.moe_ffn


def test_parallel_package_reexports_without_warning():
    import subprocess
    code = ("import warnings; warnings.simplefilter('error', "
            "DeprecationWarning); import deeplearning4j_tpu.parallel as p; "
            "print(p.ring_attention.__module__, p.moe_ffn.__module__)")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "unified" in proc.stdout


# ------------------------------------------------------------ mesh sweep
def test_mesh_sweep_reports_per_layout_rows(monkeypatch, capsys):
    """The bench/multichip.py mesh_sweep record: same model under
    multiple layouts, steps/s + collective-bytes estimate + per-layout
    arith intensity from the cost model."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "multichip_sweep",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench", "multichip.py"))
    mc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mc)
    monkeypatch.setenv("DL4J_TPU_MESH_SWEEP_LAYOUTS", "dp2")
    monkeypatch.setenv("DL4J_TPU_MESH_SWEEP_STEPS", "2")
    from deeplearning4j_tpu.config import set_config
    try:
        assert mc.mesh_sweep_main() == 0
    finally:
        set_config(device_feed=True)
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["metric"] == "mesh_layout_sweep"
    assert set(record["layouts"]) == {"dp2"}
    for name, row in record["layouts"].items():
        assert row.get("steps_per_s", 0) > 0, row
        assert row["collective_bytes_per_step"] > 0
        assert row["layout"] == name
    assert record["single_device"]["steps_per_s"] > 0
    # the cost model stamped at least the arith intensity per layout
    assert any("arith_intensity" in r for r in record["layouts"].values())
