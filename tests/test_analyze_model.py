"""tpudl.analyze — model/graph static validation.

Acceptance (ISSUE 2): one seeded defect per graph-family rule — dead
vertex (TPU101), dtype clash at a join (TPU102), preprocessor gap
(TPU103), missing input_type (TPU106), dangling edge (TPU107),
HBM-budget breach (TPU105), unresolvable PartitionSpec (TPU201), DP/TP
axis conflict (TPU202) — each reported with its rule ID and a non-zero
exit, while a clean zoo model exits 0.  Negative-path shape inference
carries the layer path in the message, not a bare KeyError.
"""

import json

import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.analyze import analyze_model, check_sharding, load_model_conf
from deeplearning4j_tpu.analyze.__main__ import main as analyze_main
from deeplearning4j_tpu.analyze.model_checks import parse_byte_size, zoo_factories
from deeplearning4j_tpu.models import mlp_mnist
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, ShapeInferenceError
from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration, VertexSpec
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import ConvolutionLayer, DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.vertices import ElementWiseVertex


def _graph_builder():
    return NeuralNetConfiguration.builder().seed(0).graph()


# ------------------------------------------------------------- clean paths
def test_clean_zoo_model_exits_zero():
    report = analyze_model(mlp_mnist())
    assert report.errors() == []
    assert report.exit_code() == 0
    assert report.context["param_count"] == 443610


def test_cli_zoo_model_and_json_roundtrip(tmp_path, capsys):
    assert analyze_main(["--model", "mlp_mnist"]) == 0
    capsys.readouterr()  # drop the text-format output of the first run
    path = tmp_path / "conf.json"
    path.write_text(mlp_mnist().conf.to_json())
    assert analyze_main(["--model", str(path), "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["exit_code"] == 0
    assert out["context"]["model_kind"] == "MultiLayerConfiguration"


def test_cli_unknown_model_is_usage_error(capsys):
    assert analyze_main(["--model", "definitely_not_a_model"]) == 2
    assert "zoo model" in capsys.readouterr().err


def test_cli_bad_hbm_budget_is_usage_error(capsys):
    assert analyze_main(["--model", "mlp_mnist",
                         "--hbm-budget", "sixteen"]) == 2
    assert "unparseable" in capsys.readouterr().err


def test_zoo_factories_cover_resnet50():
    assert "resnet50" in zoo_factories()


# --------------------------------------------------------- seeded defects
def test_dead_vertex_reported_with_name():
    gb = (_graph_builder()
          .add_inputs("in")
          .set_input_types(InputType.feed_forward(16)))
    gb.add_layer("h", DenseLayer(n_out=8, activation="relu"), "in")
    gb.add_layer("orphan", DenseLayer(n_out=4, activation="relu"), "in")
    gb.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                    loss="mcxent"), "h")
    gb.set_outputs("out")
    report = analyze_model(gb.build())
    dead = report.by_rule("TPU101")
    assert len(dead) == 1 and "orphan" in dead[0].message
    assert report.exit_code() == 1


def test_dtype_clash_at_vertex_join():
    gb = (_graph_builder()
          .add_inputs("a", "b")
          .set_input_types(InputType.feed_forward(8, dtype="float32"),
                           InputType.feed_forward(8, dtype="bfloat16")))
    gb.add_vertex("join", ElementWiseVertex(op="add"), "a", "b")
    gb.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                    loss="mcxent"), "join")
    gb.set_outputs("out")
    report = analyze_model(gb.build())
    clash = report.by_rule("TPU102")
    assert len(clash) == 1
    assert "join" in clash[0].path and "bfloat16" in clash[0].message
    assert report.exit_code() == 1


def test_network_vs_input_dtype_drift():
    conf = (NeuralNetConfiguration.builder().dtype("float32").list()
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4, dtype="bfloat16"))
            .build())
    report = analyze_model(conf)
    assert report.by_rule("TPU102")
    assert report.exit_code() == 1


def test_missing_input_type():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_out=4))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    report = analyze_model(conf)
    missing = report.by_rule("TPU106")
    assert len(missing) == 1 and "set_input_type" in missing[0].message
    assert report.exit_code() == 1


def test_preprocessor_gap_carries_layer_path():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3)))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(64))
            .build())
    report = analyze_model(conf)
    gap = report.by_rule("TPU103")
    assert len(gap) == 1
    assert "layers[0]" in gap[0].path and "ConvolutionLayer" in gap[0].path
    assert report.exit_code() == 1


def test_dangling_edge_reported_not_crash():
    conf = ComputationGraphConfiguration(
        inputs=["in"], outputs=["out"],
        vertices=[VertexSpec("out", "layer",
                             OutputLayer(n_out=2, activation="softmax",
                                         loss="mcxent"),
                             ["nonexistent"])],
        input_types=[InputType.feed_forward(4)])
    report = analyze_model(conf)
    dangling = report.by_rule("TPU107")
    assert len(dangling) == 1 and "nonexistent" in dangling[0].message
    assert report.exit_code() == 1


def test_hbm_budget_breach():
    report = analyze_model(mlp_mnist(), hbm_budget=parse_byte_size("1MiB"))
    over = report.by_rule("TPU105")
    assert len(over) == 1 and "exceeds" in over[0].message
    assert report.exit_code() == 1
    # a budget the model fits passes
    assert analyze_model(mlp_mnist(),
                         hbm_budget=parse_byte_size("16GiB")).exit_code() == 0


# ------------------------------------------------------- sharding family
def test_shipped_sharding_config_is_clean():
    assert check_sharding().exit_code() == 0


def test_unresolvable_partition_axis():
    report = check_sharding(
        tp_rules=[(r"kernel$", P(None, "tensor"))])
    bad = report.by_rule("TPU201")
    assert len(bad) == 1 and "'tensor'" in bad[0].message
    assert report.exit_code() == 1


def test_dp_tp_axis_conflict():
    report = check_sharding(tp_rules=[(r"kernel$", P(None, "data"))])
    conflict = report.by_rule("TPU202")
    assert len(conflict) == 1 and "'data'" in conflict[0].message
    assert report.exit_code() == 1


def test_bad_rule_regex():
    report = check_sharding(tp_rules=[(r"(unclosed", P(None, "model"))])
    assert report.by_rule("TPU203")
    assert report.exit_code() == 1


# ------------------------------------------- negative-path shape inference
def test_shape_inference_error_names_layer_not_keyerror():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3)))
            .set_input_type(InputType.feed_forward(32))
            .build())
    with pytest.raises(ShapeInferenceError) as excinfo:
        conf.input_types()
    msg = str(excinfo.value)
    assert "layers[1]" in msg and "ConvolutionLayer" in msg
    assert not isinstance(excinfo.value, KeyError)


def test_graph_output_types_anchored_and_guarded():
    gb = (_graph_builder()
          .add_inputs("a", "b")
          .set_input_types(InputType.feed_forward(4)))  # one type short
    gb.add_vertex("join", ElementWiseVertex(op="add"), "a", "b")
    gb.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                    loss="mcxent"), "join")
    gb.set_outputs("out")
    conf = gb.build()
    with pytest.raises(ValueError, match="one InputType per graph input"):
        conf.output_types()
    # an inference failure inside the walk carries the vertex anchor
    gb2 = (_graph_builder()
           .add_inputs("in")
           .set_input_types(InputType.feed_forward(32)))
    gb2.add_layer("conv", ConvolutionLayer(n_out=8, kernel_size=(3, 3)), "in")
    gb2.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                     loss="mcxent"), "conv")
    gb2.set_outputs("out")
    with pytest.raises(ShapeInferenceError, match="vertex 'conv'"):
        gb2.build().output_types()


def test_parse_byte_size():
    assert parse_byte_size("16GiB") == 16 * 2**30
    assert parse_byte_size("512MiB") == 512 * 2**20
    assert parse_byte_size("2KB") == 2048
    assert parse_byte_size("1048576") == 1048576
    with pytest.raises(ValueError):
        parse_byte_size("sixteen gigs")
