"""ASan/UBSan lane for the native code (SURVEY §5.2; VERDICT r2 weak #5:
the sanitizer claim in native/codec.py must be an executed check, not a
docstring).

Compiles ``sanitize_main.cpp`` + both native sources into a standalone
binary with ``-fsanitize=address,undefined`` and runs it: ASan aborts
non-zero on any heap error, UBSan on any undefined behavior, and the
driver itself asserts the codec/CSV round-trip values.  A standalone
binary sidesteps the LD_PRELOAD requirements of loading an ASan .so
into the (non-ASan) python process.
"""

import os
import shutil
import subprocess
import sys

import pytest

SRC_DIR = os.path.join(os.path.dirname(__file__), os.pardir,
                       "deeplearning4j_tpu", "native", "src")
SOURCES = ["sanitize_main.cpp", "threshold_codec.cpp", "fast_io.cpp"]

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="g++ unavailable")


@needs_gxx
def test_native_code_clean_under_asan_ubsan(tmp_path):
    binary = str(tmp_path / "sanitize_exercise")
    build = subprocess.run(
        ["g++", "-O1", "-std=c++17", "-g", "-fno-omit-frame-pointer",
         "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
         "-o", binary] + [os.path.join(SRC_DIR, s) for s in SOURCES],
        capture_output=True, text=True, timeout=180)
    assert build.returncode == 0, f"ASan build failed:\n{build.stderr[-1500:]}"

    run = subprocess.run([binary], capture_output=True, text=True,
                         timeout=120)
    assert run.returncode == 0, (
        f"sanitizer reported (rc={run.returncode}):\n"
        f"{run.stdout[-500:]}\n{run.stderr[-2000:]}")
    assert "sanitize-exercise OK" in run.stdout


@needs_gxx
def test_sanitized_shared_lib_builds():
    """The DL4J_TPU_NATIVE_SANITIZE=1 build path itself (codec.py's
    documented flag) must produce a loadable-by-ASan .so without errors —
    built in a subprocess so this process's cached non-ASan lib and the
    on-disk artifacts are untouched."""
    code = (
        "import os, tempfile, shutil\n"
        "os.environ['DL4J_TPU_NATIVE_SANITIZE'] = '1'\n"
        "import deeplearning4j_tpu.native.codec as codec\n"
        "tmp = tempfile.mkdtemp()\n"
        "src_dir = os.path.dirname(codec._SRC)\n"
        "codec._BUILD_DIR = os.path.join(tmp, 'build')\n"
        "codec._LIB = os.path.join(codec._BUILD_DIR, 'lib.so')\n"
        "codec._HASH_FILE = codec._LIB + '.srchash'\n"
        "ok = codec._build()\n"
        "assert ok, 'sanitized build failed'\n"
        "assert os.path.exists(codec._LIB)\n"
        "shutil.rmtree(tmp)\n"
        "print('SANITIZED_BUILD_OK')\n"
    )
    run = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=180)
    assert run.returncode == 0, run.stderr[-1500:]
    assert "SANITIZED_BUILD_OK" in run.stdout
