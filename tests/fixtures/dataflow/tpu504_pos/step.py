"""Defect site: a jit step derives the shape from ``len(batch)``."""
import jax

from alloc import zero_state


@jax.jit
def train_step(params, batch):
    state = zero_state(len(batch), 4)
    return state + batch
