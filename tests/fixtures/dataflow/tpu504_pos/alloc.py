"""Detection site: allocator helper shapes its output from a Python int."""
import jax.numpy as jnp


def zero_state(n, width):
    return jnp.zeros((n, width))
