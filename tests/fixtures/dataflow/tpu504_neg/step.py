"""Clean caller: the allocation shape is a bucketed constant, not data-derived."""
import jax

from alloc import zero_state

BUCKET = 128


@jax.jit
def train_step(params, batch):
    state = zero_state(BUCKET, 4)
    return state + batch
