"""Same allocator helper as the positive case."""
import jax.numpy as jnp


def zero_state(n, width):
    return jnp.zeros((n, width))
