"""Host-sink helper with the sink suppressed in-line."""


def emit(value):
    print(value)  # tpudl: ok(TPU502) — fixture: debug print accepts the sync
