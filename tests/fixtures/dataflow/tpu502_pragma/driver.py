"""Same unfenced flow as the positive case; suppression lives at the sink."""
from model import forward
from report import emit


def run(x):
    y = forward(x)
    emit(y)
