"""Traced-value origin: a jit'd forward pass."""
import jax
import jax.numpy as jnp


@jax.jit
def forward(x):
    return jnp.tanh(x) * 2.0
