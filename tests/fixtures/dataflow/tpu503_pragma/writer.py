"""Setter side, with the orphaned set suppressed in-line."""
import os
import subprocess

GANG_TOKEN_ENV = "DL4J_TPU_GANG_TOKEN"


def spawn(cmd):
    env = dict(os.environ)
    env[GANG_TOKEN_ENV] = "tok"  # tpudl: ok(TPU503) — fixture: consumed by an external tool
    return subprocess.Popen(cmd, env=env)
