"""Reader side, with the orphaned read suppressed in-line."""
import os


def token():
    return os.environ.get("DL4J_TPU_GANG_TOKEN_ID")  # tpudl: ok(TPU503) — fixture: set by the deploy wrapper
