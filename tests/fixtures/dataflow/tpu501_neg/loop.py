"""Clean caller: rebinds the donated names to the step's outputs."""
from steps import train_step


def run_epoch(params, opt_state, batches):
    for batch in batches:
        params, opt_state = train_step(params, opt_state, batch)
    return params
