"""Same host-sink helper as the positive case."""


def emit(value):
    print(value)
