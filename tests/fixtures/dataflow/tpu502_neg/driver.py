"""Clean caller: the jit output is fenced before it reaches the sink."""
import jax

from model import forward
from report import emit


def run(x):
    y = forward(x)
    y = jax.block_until_ready(y)
    emit(y)
