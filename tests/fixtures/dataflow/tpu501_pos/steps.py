"""Defect site: the jit step donates its first two buffers."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0, 1))
def train_step(params, opt_state, batch):
    grads = jax.tree_util.tree_map(jnp.sign, params)
    new_params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
    return new_params, opt_state
