"""Detection site: reads ``params`` after the callee donated it."""
from steps import train_step


def run_epoch(params, opt_state, batches):
    for batch in batches:
        train_step(params, opt_state, batch)
    return params["w"].sum()
