"""Defect site: a jit output flows into the host-sink helper unfenced."""
from model import forward
from report import emit


def run(x):
    y = forward(x)
    emit(y)
