"""Detection site: this helper pushes its argument to the host."""


def emit(value):
    print(value)
