"""Declared user-facing knob: read-only is fine once declared."""
import os

FIXTURE_KNOBS: dict[str, str] = {
    "DL4J_TPU_FIXTURE_DEBUG": "user-set debug toggle; never set by the framework",
}


def debug_enabled():
    return bool(os.environ.get("DL4J_TPU_FIXTURE_DEBUG"))
