"""Setter side of the env contract: exports a token for child processes."""
import os
import subprocess

GANG_TOKEN_ENV = "DL4J_TPU_GANG_TOKEN"


def spawn(cmd):
    env = dict(os.environ)
    env[GANG_TOKEN_ENV] = "tok"
    return subprocess.Popen(cmd, env=env)
