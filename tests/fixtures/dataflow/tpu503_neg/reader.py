"""Reader side: imports the setter's constant, so the spelling cannot drift."""
import os

from writer import GANG_TOKEN_ENV


def token():
    return os.environ.get(GANG_TOKEN_ENV)
