"""Same data-derived shape flow as the positive case."""
import jax

from alloc import zero_state


@jax.jit
def train_step(params, batch):
    state = zero_state(len(batch), 4)
    return state + batch
