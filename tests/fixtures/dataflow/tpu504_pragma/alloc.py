"""Allocator helper with the shape sink suppressed in-line."""
import jax.numpy as jnp


def zero_state(n, width):
    return jnp.zeros((n, width))  # tpudl: ok(TPU504) — fixture: callers bucket n upstream
