"""Reader side — but the spelling drifted from the setter's."""
import os


def token():
    return os.environ.get("DL4J_TPU_GANG_TOKEN_ID")
