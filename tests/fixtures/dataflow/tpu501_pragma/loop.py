"""Positive case with the read suppressed in-line."""
from steps import train_step


def run_epoch(params, opt_state, batches):
    for batch in batches:
        train_step(params, opt_state, batch)
    return params["w"].sum()  # tpudl: ok(TPU501) — fixture: post-donation read is the point
