"""TPU402 pragma-suppressed: same race as tpu402_race.py, vouched for."""

import threading


class RacyButFine:
    def __init__(self):
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            # tpudl: ok(TPU402) — fixture: approximate counter, torn increments acceptable
            self._count += 1

    def reset(self):
        self._count = 0

    def close(self):
        self._thread.join(1.0)
