"""TPU401 positive: a deliberate two-lock order inversion.

``Inverted._worker`` (the thread) takes ``_lock_a`` then ``_lock_b``;
``Inverted.poke`` (a caller) takes ``_lock_b`` then ``_lock_a``.  Two
threads interleaving those paths deadlock.  ``IndirectInversion`` hides
one leg behind a method call — the acquisition graph must follow calls.
"""

import threading


class Inverted:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._items = []
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            with self._lock_a:
                with self._lock_b:
                    break

    def poke(self):
        with self._lock_b:
            with self._lock_a:
                return len(self._items)

    def close(self):
        self._thread.join(1.0)


class IndirectInversion:
    """front→back on one path, back→front on the other — the second
    acquisition happens inside a callee."""

    def __init__(self):
        self._front = threading.Lock()
        self._back = threading.Lock()

    def publish(self):
        with self._front:
            self._commit()

    def _commit(self):
        with self._back:
            pass

    def refresh(self):
        with self._back:
            with self._front:
                pass
