"""TPU403 positive: a signal handler path acquires a non-reentrant
``threading.Lock`` — the handler can interrupt the lock's owner and
self-deadlock."""

import signal
import threading

_LOCK = threading.Lock()
_EVENTS = []


def _record(what):
    with _LOCK:
        _EVENTS.append(what)


def _on_term(signum, frame):
    _record(("sigterm", signum))


def install():
    signal.signal(signal.SIGTERM, _on_term)
