"""TPU403 negative: the handler-reachable lock is an RLock — re-entry
from an interrupting handler cannot self-deadlock."""

import signal
import threading

_LOCK = threading.RLock()
_EVENTS = []


def _on_term(signum, frame):
    with _LOCK:
        _EVENTS.append(("sigterm", signum))


def install():
    signal.signal(signal.SIGTERM, _on_term)
