"""TPU404 positives: indefinite blocking calls while holding a lock —
one direct (queue.get under the lock), one through a call (join inside
a method invoked with the lock held)."""

import queue
import threading


class Wedge:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = queue.Queue()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            break

    def drain(self):
        with self._lock:
            return self._queue.get()   # blocks every other acquirer

    def stop(self):
        with self._lock:
            self._finish()

    def _finish(self):
        self._worker.join()            # lock held by the caller
