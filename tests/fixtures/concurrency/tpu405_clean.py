"""TPU405 negatives: a proper close() that signals and joins; a
fork/join thread scoped to one method; cleanup that joins via a helper
call."""

import threading


class Tidy:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            break

    def close(self):
        self._stop.set()
        self._thread.join(5.0)


class Scoped:
    def compute(self, fn):
        out = []
        thread = threading.Thread(target=lambda: out.append(fn()))
        thread.start()
        thread.join()
        return out


class Delegating:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        return

    def _teardown(self):
        self._thread.join(5.0)

    def shutdown(self):
        self._teardown()
