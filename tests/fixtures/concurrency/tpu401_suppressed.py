"""TPU401 pragma-suppressed: one-lock re-entry (self-deadlock shape),
vouched for by a reasoned suppression."""

import threading


class Reentry:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        # tpudl: ok(TPU401) — fixture: demonstrates a reasoned suppression
        with self._lock:
            pass
