"""TPU406 pragma-suppressed."""

import queue
import threading


class UnresolvedButFine:
    def __init__(self):
        self._jobs = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            fut, fn = self._jobs.get()
            # tpudl: ok(TPU406) — fixture: fn is a pre-validated pure lambda
            fut.set_result(fn())

    def close(self):
        self._thread.join(1.0)
