"""TPU402 positive: ``_count`` is written by the worker thread AND the
caller API with no lock anywhere."""

import threading


class Racy:
    def __init__(self):
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self._count += 1

    def reset(self):
        self._count = 0

    def close(self):
        self._thread.join(1.0)
