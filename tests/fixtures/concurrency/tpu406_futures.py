"""TPU406 positive: a worker loop resolves Futures with set_result but
has no set_exception path — one exception strands every waiter."""

import queue
import threading


class Unresolved:
    def __init__(self):
        self._jobs = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            fut, fn = self._jobs.get()
            fut.set_result(fn())       # fn() raising strands fut forever

    def close(self):
        self._thread.join(1.0)
