"""TPU405 pragma-suppressed: a deliberate process-lifetime thread."""

import threading


class Daemonic:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        # tpudl: ok(TPU405) — fixture: process-lifetime daemon by design
        self._thread.start()

    def _run(self):
        return
