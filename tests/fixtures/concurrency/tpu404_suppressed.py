"""TPU404 pragma-suppressed: a blocking get under the lock, vouched."""

import queue
import threading


class WedgeButFine:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = queue.Queue()

    def drain(self):
        with self._lock:
            # tpudl: ok(TPU404) — fixture: single-threaded test harness, no second acquirer
            return self._queue.get()
