"""TPU405 positive: a class starts a long-lived thread and has no
close()/shutdown()/stop() that joins anything — the thread outlives
the object."""

import threading


class Leaky:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            break
