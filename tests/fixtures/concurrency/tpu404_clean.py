"""TPU404 negatives: bounded waits under a lock are fine, indefinite
waits OUTSIDE the lock are fine, and Condition.wait on the condition's
own lock releases it."""

import queue
import threading


class Bounded:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._queue = queue.Queue()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            break

    def drain(self):
        with self._lock:
            return self._queue.get(timeout=0.5)   # bounded

    def take(self):
        return self._queue.get()                  # no lock held

    def park(self):
        with self._cond:
            self._cond.wait()                     # releases _cond itself

    def stop(self):
        self._worker.join()
