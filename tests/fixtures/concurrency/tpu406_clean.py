"""TPU406 negative: every future resolves on both paths."""

import queue
import threading


class Resolved:
    def __init__(self):
        self._jobs = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            fut, fn = self._jobs.get()
            try:
                fut.set_result(fn())
            except BaseException as e:
                fut.set_exception(e)

    def close(self):
        self._thread.join(1.0)
