"""TPU403 pragma-suppressed: Lock in an atexit path, vouched for."""

import atexit
import threading

_LOCK = threading.Lock()
_STATE = []


def _flush():
    # tpudl: ok(TPU403) — fixture: atexit runs after all other threads joined
    with _LOCK:
        _STATE.clear()


def install():
    atexit.register(_flush)
