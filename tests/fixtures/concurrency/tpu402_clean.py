"""TPU402 negatives: every shared write happens under one common lock;
thread-safe attributes (events/queues) and single-writer attributes
don't flag either."""

import queue
import threading


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self._count += 1

    def reset(self):
        with self._lock:
            self._count = 0

    def close(self):
        self._thread.join(1.0)


class SingleWriter:
    """The thread owns ``_progress``; callers only read it."""

    def __init__(self):
        self._progress = 0
        self._inbox = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            self._progress += 1

    def progress(self):
        return self._progress

    def close(self):
        self._stop.set()
        self._thread.join(1.0)
