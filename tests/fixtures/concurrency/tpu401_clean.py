"""TPU401 negative: two locks, always acquired in the same order."""

import threading


class Ordered:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._items = []
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            with self._lock_a:
                with self._lock_b:
                    break

    def poke(self):
        with self._lock_a:
            with self._lock_b:
                return len(self._items)

    def close(self):
        self._thread.join(1.0)
