"""TPU400 fixture: suppression pragmas that are themselves findings —
bare (no reason), unknown rule ID, non-AST-family rule.  The bare
pragma still suppresses its TPU402 finding; the TPU400 errors keep the
gate red until reasons are written."""

import threading


class Racy:
    def __init__(self):
        self._n = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            # tpudl: ok(TPU402)
            self._n += 1

    def reset(self):
        self._n = 0

    def close(self):
        self._thread.join(1.0)


def helper():
    # tpudl: ok(TPU999) — no such rule exists
    pass


def other():
    # tpudl: ok(TPU105) — model-family rules have no source line to excuse
    pass
