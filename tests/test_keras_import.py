"""Keras .h5 import golden tests — REAL cross-framework fixtures
(SURVEY §4.2-2: import the graph, assert numerical equality against the
source framework's own outputs).

tf.keras builds, saves, and predicts in a SUBPROCESS (TF and JAX share
fragile native deps — loading TF into the pytest process segfaults);
the pytest process then imports the .h5 with OUR importer and must
reproduce Keras's recorded activations.  Skips when tensorflow is absent.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.importers.keras import import_keras_model_and_weights

_GEN = r"""
import json, sys
import numpy as np
import tensorflow as tf
spec = json.loads(sys.argv[1])
keras = tf.keras
layers = []
for l in spec["layers"]:
    kind = l.pop("kind")
    if kind == "input":
        layers.append(keras.layers.Input(shape=tuple(l["shape"])))
    elif kind == "dense":
        layers.append(keras.layers.Dense(l["units"], activation=l["act"], name=l["name"]))
    elif kind == "conv2d":
        layers.append(keras.layers.Conv2D(l["filters"], l["kernel"], activation=l["act"],
                                          padding=l["padding"], name=l["name"]))
    elif kind == "maxpool":
        layers.append(keras.layers.MaxPooling2D(l["pool"], name=l["name"]))
    elif kind == "flatten":
        layers.append(keras.layers.Flatten(name=l["name"]))
    elif kind == "lstm":
        layers.append(keras.layers.LSTM(l["units"], return_sequences=l.get("seq", False),
                                        name=l["name"]))
    elif kind == "bidi_lstm":
        layers.append(keras.layers.Bidirectional(keras.layers.LSTM(l["units"]),
                                                 name=l["name"]))
    elif kind == "sepconv2d":
        layers.append(keras.layers.SeparableConv2D(l["filters"], l["kernel"],
                       activation=l["act"], padding=l["padding"], name=l["name"]))
    elif kind == "dwconv2d":
        layers.append(keras.layers.DepthwiseConv2D(l["kernel"], activation=l["act"],
                       padding=l["padding"], name=l["name"]))
    elif kind == "gru":
        layers.append(keras.layers.GRU(l["units"], return_sequences=l.get("seq", False),
                                       name=l["name"]))
    elif kind == "simplernn":
        layers.append(keras.layers.SimpleRNN(l["units"],
                       return_sequences=l.get("seq", False), name=l["name"]))
    elif kind == "conv1d":
        layers.append(keras.layers.Conv1D(l["filters"], l["kernel"],
                       activation=l["act"], padding=l["padding"], name=l["name"]))
    elif kind == "maxpool1d":
        layers.append(keras.layers.MaxPooling1D(l["pool"], name=l["name"]))
    elif kind == "layernorm":
        layers.append(keras.layers.LayerNormalization(name=l["name"]))
    elif kind == "gap1d":
        layers.append(keras.layers.GlobalAveragePooling1D(name=l["name"]))
    elif kind == "upsampling":
        layers.append(keras.layers.UpSampling2D(l["size"], name=l["name"]))
    elif kind == "zeropad":
        layers.append(keras.layers.ZeroPadding2D(tuple(l["pad"]), name=l["name"]))
    elif kind == "cropping":
        layers.append(keras.layers.Cropping2D(tuple(l["crop"]), name=l["name"]))
    elif kind == "conv2dtranspose":
        layers.append(keras.layers.Conv2DTranspose(
            l["filters"], l["kernel"], strides=l.get("strides", 1),
            activation=l["act"], padding=l["padding"], name=l["name"]))
    elif kind == "conv3d":
        layers.append(keras.layers.Conv3D(l["filters"], l["kernel"],
                       activation=l["act"], padding=l["padding"], name=l["name"]))
    elif kind == "maxpool3d":
        layers.append(keras.layers.MaxPooling3D(l["pool"], name=l["name"]))
    elif kind == "zeropad1d":
        layers.append(keras.layers.ZeroPadding1D(l["pad"], name=l["name"]))
    elif kind == "cropping1d":
        layers.append(keras.layers.Cropping1D(l["crop"], name=l["name"]))
    elif kind == "upsampling1d":
        layers.append(keras.layers.UpSampling1D(l["size"], name=l["name"]))
    elif kind == "repeatvector":
        layers.append(keras.layers.RepeatVector(l["n"], name=l["name"]))
    elif kind == "timedist_dense":
        layers.append(keras.layers.TimeDistributed(
            keras.layers.Dense(l["units"], activation=l["act"]), name=l["name"]))
    elif kind == "relu_layer":
        layers.append(keras.layers.ReLU(negative_slope=l.get("slope", 0.0),
                                        name=l["name"]))
    elif kind == "softmax_layer":
        layers.append(keras.layers.Softmax(name=l["name"]))
    elif kind == "lambda_double":
        layers.append(keras.layers.Lambda(lambda t: t * 2.0, name=l["name"]))
    elif kind == "convlstm2d":
        layers.append(keras.layers.ConvLSTM2D(
            l["filters"], l["kernel"], padding=l["padding"],
            return_sequences=l.get("seq", False), name=l["name"]))
    elif kind == "sepconv1d":
        layers.append(keras.layers.SeparableConv1D(
            l["filters"], l["kernel"], activation=l["act"],
            padding=l["padding"], name=l["name"]))
    elif kind == "masking":
        layers.append(keras.layers.Masking(mask_value=l.get("value", 0.0),
                                           name=l["name"]))
    elif kind == "permute":
        layers.append(keras.layers.Permute(tuple(l["dims"]), name=l["name"]))
    elif kind == "bidi_gru":
        layers.append(keras.layers.Bidirectional(
            keras.layers.GRU(l["units"],
                             return_sequences=l.get("seq", False)),
            merge_mode=l.get("mode", "concat"), name=l["name"]))
    elif kind == "bidi_rnn":
        layers.append(keras.layers.Bidirectional(
            keras.layers.SimpleRNN(l["units"],
                                   return_sequences=l.get("seq", False)),
            merge_mode=l.get("mode", "concat"), name=l["name"]))
    elif kind == "thresholded_relu":
        layers.append(keras.layers.ThresholdedReLU(theta=l.get("theta", 1.0),
                                                   name=l["name"]))
    elif kind == "gap3d":
        layers.append(keras.layers.GlobalAveragePooling3D(name=l["name"]))
if spec.get("functional") == "conv_branches":
    # two conv branches, explicit Flatten per branch, Concatenate, head
    inp = keras.layers.Input(shape=(6, 6, 2))
    a = keras.layers.Conv2D(3, 3, activation="relu", padding="same",
                            name="ca")(inp)
    fa = keras.layers.Flatten(name="fla")(a)
    b = keras.layers.Conv2D(4, 3, activation="tanh", padding="valid",
                            name="cb")(inp)
    fb = keras.layers.Flatten(name="flb")(b)
    cat = keras.layers.Concatenate(name="fcat")([fa, fb])
    lr = keras.layers.LeakyReLU(name="lre")(cat)   # default alpha 0.3
    out = keras.layers.Dense(3, activation="softmax", name="fout")(lr)
    model = keras.Model(inputs=inp, outputs=out)
elif spec.get("functional") == "mha":
    inp = keras.layers.Input(shape=(6, 8))
    att = keras.layers.MultiHeadAttention(num_heads=2, key_dim=4,
                                          name="mha")(inp, inp)
    gp = keras.layers.GlobalAveragePooling1D(name="gp")(att)
    out = keras.layers.Dense(3, activation="softmax", name="fout")(gp)
    model = keras.Model(inputs=inp, outputs=out)
elif spec.get("functional") == "two_inputs_reordered":
    # inputs declared in REVERSE creation order: binds must follow
    # config['input_layers'], not the layers list
    ia = keras.layers.Input(shape=(5,), name="in_a")
    ib = keras.layers.Input(shape=(7,), name="in_b")
    da = keras.layers.Dense(4, activation="relu", name="da")(ia)
    db = keras.layers.Dense(4, activation="tanh", name="db")(ib)
    cat = keras.layers.Concatenate(name="cat")([da, db])
    out = keras.layers.Dense(2, activation="softmax", name="fout")(cat)
    model = keras.Model(inputs=[ib, ia], outputs=out)   # b FIRST
    model.save(spec["h5"])
    rng = np.random.default_rng(spec["seed"])
    xb = rng.normal(size=(4, 7)).astype(np.float32)
    xa = rng.normal(size=(4, 5)).astype(np.float32)
    np.savez(spec["npz"], xb=xb, xa=xa,
             golden=model.predict([xb, xa], verbose=0))
    raise SystemExit(0)
elif spec.get("functional"):
    # fixed functional topology: dense branch + skip, concat, head
    inp = keras.layers.Input(shape=tuple(spec["functional"]["shape"]))
    a = keras.layers.Dense(8, activation="relu", name="fa")(inp)
    b = keras.layers.Dense(8, activation="tanh", name="fb")(a)
    add = keras.layers.Add(name="fadd")([a, b])
    c = keras.layers.Dense(6, activation="relu", name="fc")(inp)
    cat = keras.layers.Concatenate(name="fcat")([add, c])
    out = keras.layers.Dense(3, activation="softmax", name="fout")(cat)
    model = keras.Model(inputs=inp, outputs=out)
else:
    model = keras.Sequential(layers)
model.save(spec["h5"])
rng = np.random.default_rng(spec["seed"])
x = rng.normal(size=tuple(spec["x_shape"])).astype(np.float32)
for i, t in enumerate(spec.get("zero_tail") or []):
    x[i, t:] = 0.0          # masked timesteps for Masking goldens
np.savez(spec["npz"], x=x, golden=model.predict(x, verbose=0))
"""


# Committed golden-fixture cache: each spec's .h5 + recorded Keras
# activations live under tests/fixtures/keras_cache keyed by
# sha1(spec + generator script), so the suite replays REAL tf.keras
# outputs without paying a ~10s TF-subprocess import per test (~6 min
# across the module) — and still runs where tensorflow is absent.
# Cache miss (new spec, or a _GEN change rotating every key) falls back
# to live generation and refreshes the cache; delete the directory to
# force regeneration against the installed tensorflow.
_FIXTURE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "fixtures", "keras_cache")


def _make_fixture(tmp_path, spec_layers, x_shape, seed=0, functional=None,
                  zero_tail=None):
    import hashlib
    import shutil
    key_src = json.dumps(
        [spec_layers, list(x_shape), seed, functional, zero_tail, _GEN],
        sort_keys=True, default=str)
    key = hashlib.sha1(key_src.encode()).hexdigest()[:16]
    cached_h5 = os.path.join(_FIXTURE_CACHE, f"{key}.h5")
    cached_npz = os.path.join(_FIXTURE_CACHE, f"{key}.npz")
    if os.path.exists(cached_h5) and os.path.exists(cached_npz):
        data = np.load(cached_npz)
        return cached_h5, data["x"], data["golden"]
    h5 = str(tmp_path / "model.h5")
    npz = str(tmp_path / "golden.npz")
    spec = {"layers": spec_layers, "h5": h5, "npz": npz,
            "x_shape": list(x_shape), "seed": seed, "functional": functional,
            "zero_tail": zero_tail}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""           # TF subprocess: no jax involved
    proc = subprocess.run([sys.executable, "-c", _GEN, json.dumps(spec)],
                          capture_output=True, timeout=300, env=env)
    if proc.returncode != 0:
        if b"No module named 'tensorflow'" in proc.stderr:
            pytest.skip("tensorflow unavailable (and no cached fixture)")
        raise RuntimeError(proc.stderr.decode()[-1500:])
    os.makedirs(_FIXTURE_CACHE, exist_ok=True)
    shutil.copy(h5, cached_h5)
    shutil.copy(npz, cached_npz)
    data = np.load(npz)
    return h5, data["x"], data["golden"]


class TestKerasH5Golden:
    def test_mlp_golden_activations(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [12]},
            {"kind": "dense", "units": 16, "act": "relu", "name": "d1"},
            {"kind": "dense", "units": 8, "act": "tanh", "name": "d2"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (5, 12))
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-5, atol=1e-6)

    def test_cnn_golden_activations(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [12, 12, 3]},
            {"kind": "conv2d", "filters": 4, "kernel": 3, "act": "relu",
             "padding": "same", "name": "c1"},
            {"kind": "maxpool", "pool": 2, "name": "p1"},
            {"kind": "conv2d", "filters": 6, "kernel": 3, "act": "relu",
             "padding": "valid", "name": "c2"},
            {"kind": "flatten", "name": "f"},
            {"kind": "dense", "units": 5, "act": "softmax", "name": "out"},
        ], (3, 12, 12, 3), seed=1)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_golden_activations(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [7, 5]},
            {"kind": "lstm", "units": 6, "name": "lstm"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (4, 7, 5), seed=2)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_bidirectional_lstm_golden_activations(self, tmp_path):
        """Bidirectional(return_sequences=False): last-step wrap goes
        around the merged output (the bwd half's final state lives at
        unflipped position 0) and all 6 weight arrays load."""
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6, 4]},
            {"kind": "bidi_lstm", "units": 5, "name": "bidi"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (4, 6, 4), seed=3)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_separable_depthwise_conv_golden(self, tmp_path):
        """Separable + depthwise convs: the keras (kh,kw,cin,mult)
        depthwise kernel reshapes exactly to our grouped-conv layout."""
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [8, 8, 3]},
            {"kind": "sepconv2d", "filters": 6, "kernel": 3, "act": "relu",
             "padding": "same", "name": "sep"},
            {"kind": "dwconv2d", "kernel": 3, "act": "linear",
             "padding": "valid", "name": "dw"},
            {"kind": "flatten", "name": "fl"},
            {"kind": "dense", "units": 4, "act": "softmax", "name": "out"},
        ], (3, 8, 8, 3), seed=5)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_gru_simplernn_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6, 4]},
            {"kind": "gru", "units": 5, "seq": True, "name": "g"},
            {"kind": "simplernn", "units": 4, "name": "r"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (4, 6, 4), seed=6)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_conv1d_pool1d_gap_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [12, 3]},
            {"kind": "conv1d", "filters": 5, "kernel": 3, "act": "relu",
             "padding": "same", "name": "c1"},
            {"kind": "maxpool1d", "pool": 2, "name": "p1"},
            {"kind": "gap1d", "name": "gap"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (4, 12, 3), seed=7)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_conv2dtranspose_golden(self, tmp_path):
        """Conv2DTranspose: the keras (kh,kw,out,in) gradient-kernel maps
        to our conv_transpose layout by spatial flip + channel swap."""
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [5, 5, 2]},
            {"kind": "conv2dtranspose", "filters": 4, "kernel": 3,
             "strides": 2, "act": "relu", "padding": "same", "name": "dc"},
            {"kind": "flatten", "name": "fl"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (3, 5, 5, 2), seed=11)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_conv3d_pool3d_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6, 6, 6, 2]},
            {"kind": "conv3d", "filters": 3, "kernel": 2, "act": "relu",
             "padding": "valid", "name": "c3"},
            {"kind": "maxpool3d", "pool": 2, "name": "p3"},
            {"kind": "flatten", "name": "fl"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (2, 6, 6, 6, 2), seed=12)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_pad_crop_upsample_1d_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [8, 3]},
            {"kind": "zeropad1d", "pad": 2, "name": "zp"},
            {"kind": "conv1d", "filters": 4, "kernel": 3, "act": "relu",
             "padding": "valid", "name": "c1"},
            {"kind": "cropping1d", "crop": 1, "name": "cr"},
            {"kind": "upsampling1d", "size": 2, "name": "up"},
            {"kind": "gap1d", "name": "gap"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (4, 8, 3), seed=13)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_repeatvector_timedistributed_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [5]},
            {"kind": "dense", "units": 4, "act": "tanh", "name": "d1"},
            {"kind": "repeatvector", "n": 3, "name": "rv"},
            {"kind": "timedist_dense", "units": 2, "act": "linear",
             "name": "td"},
            {"kind": "softmax_layer", "name": "sm"},
        ], (4, 5), seed=14)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_relu_layer_negative_slope_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6]},
            {"kind": "dense", "units": 5, "act": "linear", "name": "d1"},
            {"kind": "relu_layer", "slope": 0.25, "name": "rl"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (4, 6), seed=15)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_multi_head_attention_functional_golden(self, tmp_path):
        """Keras MultiHeadAttention (self-attention) → SelfAttentionLayer
        with per-head q/k/v/o kernels+biases reshaped exactly."""
        h5, x, golden = _make_fixture(tmp_path, [], (4, 6, 8), seed=16,
                                      functional="mha")
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-4)

    def test_lambda_registry_golden(self, tmp_path):
        """Lambda layers import through the registered-layer SPI
        (KerasLambdaLayer parity): unregistered → clear error; registered
        equivalent layer → golden parity."""
        import dataclasses as _dc
        from deeplearning4j_tpu.importers.keras import (
            register_lambda_layer, _LAMBDA_LAYERS)
        from deeplearning4j_tpu.nn.layers.base import Layer, register_layer

        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6]},
            {"kind": "dense", "units": 4, "act": "tanh", "name": "d1"},
            {"kind": "lambda_double", "name": "dbl"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (4, 6), seed=17)
        with pytest.raises(KeyError, match="register_lambda_layer"):
            import_keras_model_and_weights(h5)

        @register_layer("test_times_two")
        @_dc.dataclass
        class TimesTwo(Layer):
            def get_output_type(self, t):
                return t

            def has_params(self):
                return False

            def apply(self, params, state, x, *, train=False, rng=None,
                      mask=None):
                return 2.0 * x, state

        register_lambda_layer("dbl", TimesTwo())
        try:
            net = import_keras_model_and_weights(h5)
            np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                       rtol=1e-4, atol=1e-5)
        finally:
            _LAMBDA_LAYERS.pop("dbl", None)

    def test_custom_converter_registry(self):
        """register_custom_converter takes precedence over built-ins."""
        from deeplearning4j_tpu.importers.keras import (
            _convert_layer, register_custom_converter, _CUSTOM_CONVERTERS)
        from deeplearning4j_tpu.nn.layers import DenseLayer
        marker = DenseLayer(n_out=9, activation="identity")
        register_custom_converter("MyLayer", lambda kcfg: marker)
        try:
            out = _convert_layer({"class_name": "MyLayer", "config": {}})
            assert out is marker
        finally:
            _CUSTOM_CONVERTERS.pop("MyLayer", None)
        with pytest.raises(KeyError, match="register_custom_converter"):
            _convert_layer({"class_name": "NopeLayer", "config": {}})

    def test_layernorm_geometry_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6, 6, 2]},
            {"kind": "zeropad", "pad": [1, 2], "name": "zp"},
            {"kind": "upsampling", "size": 2, "name": "up"},
            {"kind": "cropping", "crop": [2, 3], "name": "cr"},
            {"kind": "flatten", "name": "fl"},
            {"kind": "layernorm", "name": "ln"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (2, 6, 6, 2), seed=8)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_functional_model_golden(self, tmp_path):
        """Functional topology: two dense branches, Add skip, Concatenate,
        dense head → ComputationGraph with vertices; golden activations
        must match tf.keras."""
        h5, x, golden = _make_fixture(tmp_path, [], (4, 12), seed=11,
                                      functional={"shape": [12]})
        net = import_keras_model_and_weights(h5)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        assert isinstance(net, ComputationGraph)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_functional_reordered_inputs_golden(self, tmp_path):
        """keras.Model(inputs=[b, a]) with creation order (a, b): feature
        binding must follow config['input_layers'] order."""
        h5 = str(tmp_path / "model.h5")
        npz = str(tmp_path / "golden.npz")
        spec = {"layers": [], "h5": h5, "npz": npz, "x_shape": [1],
                "seed": 13, "functional": "two_inputs_reordered"}
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = ""
        proc = subprocess.run([sys.executable, "-c", _GEN, json.dumps(spec)],
                              capture_output=True, timeout=300, env=env)
        if proc.returncode != 0:
            if b"No module named 'tensorflow'" in proc.stderr:
                pytest.skip("tensorflow unavailable")
            raise RuntimeError(proc.stderr.decode()[-1500:])
        d = np.load(npz)
        net = import_keras_model_and_weights(h5)
        got = np.asarray(net.output([d["xb"], d["xa"]]))
        np.testing.assert_allclose(got, d["golden"], rtol=1e-4, atol=1e-5)

    def test_functional_conv_flatten_concat_golden(self, tmp_path):
        """Explicit Flatten feeding a Concatenate becomes a real vertex
        (the flattened [N,108]+[N,64] concat, NOT a channel-axis concat
        of 4-D conv maps) and LeakyReLU keeps Keras's alpha=0.3."""
        h5, x, golden = _make_fixture(tmp_path, [], (3, 6, 6, 2), seed=12,
                                      functional="conv_branches")
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_layernorm_prelu_weights_actually_load(self):
        """Untrained goldens mask non-loaded params (gamma=1/beta=0 both
        sides) — assert the arrays land in the param tree."""
        from deeplearning4j_tpu.importers.keras import load_weights
        from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import (DenseLayer,
                                                  LayerNormalization,
                                                  OutputLayer, PReLULayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_out=4, activation="identity", name="d"))
                .layer(LayerNormalization(name="ln"))
                .layer(PReLULayer(name="pr"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent", name="out"))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        gamma, beta = rng.normal(size=4).astype(np.float32), \
            rng.normal(size=4).astype(np.float32)
        alpha = rng.normal(size=4).astype(np.float32)
        load_weights(net, {"ln": [gamma, beta], "pr": [alpha]})
        np.testing.assert_array_equal(np.asarray(net.params_[1]["gamma"]), gamma)
        np.testing.assert_array_equal(np.asarray(net.params_[1]["beta"]), beta)
        np.testing.assert_array_equal(np.asarray(net.params_[2]["alpha"]), alpha)

    def test_gru_recurrent_bias_folds_z_r_exactly(self):
        """z/r recurrent-bias slices fold into the input bias; nonzero
        candidate slice is rejected."""
        from deeplearning4j_tpu.importers.keras import load_weights
        from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import GRU, RnnOutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        h = 3
        conf = (NeuralNetConfiguration.builder().list()
                .layer(GRU(n_out=h, name="g"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent", name="out"))
                .set_input_type(InputType.recurrent(4, 5)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        w = rng.normal(size=(4, 3 * h)).astype(np.float32)
        u = rng.normal(size=(h, 3 * h)).astype(np.float32)
        b = rng.normal(size=(2, 3 * h)).astype(np.float32)
        b[1, 2 * h:] = 0.0      # candidate recurrent bias zero → foldable
        load_weights(net, {"g": [w, u, b]})
        got_b = np.asarray(net.params_[0]["b"])
        # ours is r,u,c order: z/r slices carry the folded recurrent bias
        np.testing.assert_allclose(got_b[0:h], b[0, h:2 * h] + b[1, h:2 * h],
                                   atol=1e-6)   # r gate
        np.testing.assert_allclose(got_b[h:2 * h], b[0, 0:h] + b[1, 0:h],
                                   atol=1e-6)   # u(z) gate
        np.testing.assert_allclose(got_b[2 * h:], b[0, 2 * h:], atol=1e-6)

        b_bad = b.copy()
        b_bad[1, 2 * h:] = 1.0
        with pytest.raises(ValueError, match="candidate"):
            load_weights(net, {"g": [w, u, b_bad]})

    def test_bidirectional_unsupported_inner_rejected(self):
        """Bidirectional over a non-recurrent inner layer must fail
        loudly, not import as LSTM (review regression; GRU/SimpleRNN
        inner cells convert since round 5 — TestRound5BidirectionalTail
        has their goldens)."""
        from deeplearning4j_tpu.importers.keras import import_sequential
        model_json = json.dumps({
            "class_name": "Sequential",
            "config": {"layers": [
                {"class_name": "InputLayer",
                 "config": {"batch_input_shape": [None, 6, 4]}},
                {"class_name": "Bidirectional",
                 "config": {"name": "bidi", "merge_mode": "concat",
                            "layer": {"class_name": "ConvLSTM1D",
                                      "config": {"name": "cl",
                                                 "units": 5}}}},
            ]}})
        with pytest.raises(KeyError):
            import_sequential(model_json)

    def test_missing_model_config_raises(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        path = str(tmp_path / "bare.h5")
        with h5py.File(path, "w") as f:
            f.create_dataset("x", data=np.zeros(3))
        with pytest.raises(ValueError):
            import_keras_model_and_weights(path)


class TestRound5ConverterTail:
    """VERDICT r4 missing #2 / next #5: the last ~15 Keras converters."""

    def test_convlstm2d_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [4, 6, 6, 2]},
            {"kind": "convlstm2d", "filters": 3, "kernel": 3,
             "padding": "same", "name": "cl"},
            {"kind": "flatten", "name": "f"},
            {"kind": "dense", "units": 4, "act": "softmax", "name": "out"},
        ], (2, 4, 6, 6, 2), seed=7)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_convlstm2d_return_sequences_valid_padding(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [3, 7, 7, 1]},
            {"kind": "convlstm2d", "filters": 2, "kernel": 3,
             "padding": "valid", "seq": True, "name": "cl"},
            {"kind": "flatten", "name": "f"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (2, 3, 7, 7, 1), seed=8)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_separable_conv1d_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [10, 3]},
            {"kind": "sepconv1d", "filters": 5, "kernel": 3, "act": "relu",
             "padding": "same", "name": "sc"},
            {"kind": "gap1d", "name": "gp"},
            {"kind": "dense", "units": 4, "act": "softmax", "name": "out"},
        ], (3, 10, 3), seed=9)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_masking_lstm_golden(self, tmp_path):
        """Masking really suppresses the zeroed tail: golden equality
        against keras AND a no-masking import must differ."""
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6, 4]},
            {"kind": "masking", "value": 0.0, "name": "mask"},
            {"kind": "lstm", "units": 5, "name": "l1"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (3, 6, 4), seed=10, zero_tail=[2, 4, 6])
        net = import_keras_model_and_weights(h5)
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)
        # the masked rows (zero tails) must actually matter
        from deeplearning4j_tpu.nn.layers import MaskZeroLayer
        assert any(isinstance(l, MaskZeroLayer) for l in net.layers)

    def test_permute_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [5, 3]},
            {"kind": "permute", "dims": [2, 1], "name": "perm"},
            {"kind": "flatten", "name": "f"},
            {"kind": "dense", "units": 4, "act": "softmax", "name": "out"},
        ], (2, 5, 3), seed=11)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_locally_connected_2d_keras2_config(self):
        """LocallyConnected was removed in Keras 3, so the golden is the
        classic Keras-2 config JSON + weights dict, verified against a
        hand-rolled numpy unshared-conv reference."""
        import json as _json
        from deeplearning4j_tpu.importers.keras import (import_sequential,
                                                        load_weights)
        rng = np.random.default_rng(12)
        H = W = 5
        kh = kw = 3
        cin, F = 2, 3
        oh = ow = H - kh + 1
        model_json = _json.dumps({
            "class_name": "Sequential",
            "config": {"layers": [
                {"class_name": "LocallyConnected2D",
                 "config": {"name": "lc", "filters": F,
                            "kernel_size": [kh, kw], "strides": [1, 1],
                            "padding": "valid", "activation": "linear",
                            "batch_input_shape": [None, H, W, cin]}},
                {"class_name": "Flatten", "config": {"name": "fl"}},
                {"class_name": "Dense",
                 "config": {"name": "out", "units": 2,
                            "activation": "softmax"}},
            ]}})
        kernel = rng.normal(0, 0.3,
                            (oh * ow, kh * kw * cin, F)).astype(np.float32)
        bias = rng.normal(0, 0.1, (oh, ow, F)).astype(np.float32)
        dW = rng.normal(0, 0.3, (oh * ow * F, 2)).astype(np.float32)
        db = np.zeros(2, np.float32)
        net = import_sequential(model_json)
        load_weights(net, {"lc": [kernel, bias], "out": [dW, db]})

        x = rng.normal(size=(2, H, W, cin)).astype(np.float32)
        # numpy reference: per-position patch dot (keras patch order is
        # (ki, kj, c) — row-major over the window, channels innermost)
        ref_lc = np.zeros((2, oh, ow, F), np.float32)
        for i in range(oh):
            for j in range(ow):
                patch = x[:, i:i + kh, j:j + kw, :].reshape(2, -1)
                ref_lc[:, i, j, :] = patch @ kernel[i * ow + j] + bias[i, j]
        logits = ref_lc.reshape(2, -1) @ dW + db
        e = np.exp(logits - logits.max(-1, keepdims=True))
        ref = e / e.sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(net.output(x)), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_locally_connected_1d_keras2_config(self):
        import json as _json
        from deeplearning4j_tpu.importers.keras import (import_sequential,
                                                        load_weights)
        rng = np.random.default_rng(13)
        T, C, F, k = 8, 3, 4, 3
        ot = T - k + 1
        model_json = _json.dumps({
            "class_name": "Sequential",
            "config": {"layers": [
                {"class_name": "LocallyConnected1D",
                 "config": {"name": "lc1", "filters": F, "kernel_size": [k],
                            "strides": [1], "padding": "valid",
                            "activation": "tanh",
                            "batch_input_shape": [None, T, C]}},
            ]}})
        kernel = rng.normal(0, 0.3, (ot, k * C, F)).astype(np.float32)
        bias = rng.normal(0, 0.1, (ot, F)).astype(np.float32)
        net = import_sequential(model_json)
        load_weights(net, {"lc1": [kernel, bias]})
        x = rng.normal(size=(2, T, C)).astype(np.float32)
        ref = np.zeros((2, ot, F), np.float32)
        for t in range(ot):
            patch = x[:, t:t + k, :].reshape(2, -1)
            ref[:, t, :] = np.tanh(patch @ kernel[t] + bias[t])
        np.testing.assert_allclose(np.asarray(net.output(x)), ref,
                                   rtol=1e-4, atol=1e-5)


class TestKerasFinetuneAfterImport:
    def test_cnn_finetune_reduces_loss(self, tmp_path):
        """Train-after-import golden (VERDICT r4 weak #7): the imported
        .h5 CNN fit()s — loss decreases over a few steps on one batch,
        catching dtype/layout drift in the backward pass."""
        import jax
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train import Trainer

        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [8, 8, 2]},
            {"kind": "conv2d", "filters": 4, "kernel": 3, "act": "relu",
             "padding": "same", "name": "c1"},
            {"kind": "flatten", "name": "f"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (8, 8, 8, 2), seed=14)
        net = import_keras_model_and_weights(h5)
        rng = np.random.default_rng(14)
        labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        batch = DataSet(x, labels)
        trainer = Trainer(net)
        key = jax.random.key(0)
        losses = [float(trainer.fit_batch(batch, key)) for _ in range(8)]
        assert np.all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # imported weights actually moved
        w = np.asarray(net.params_[0]["W"])
        assert np.all(np.isfinite(w))


class TestRound5BidirectionalTail:
    """Bidirectional beyond LSTM (GRU/SimpleRNN inner cells) + the last
    activation/pooling converters."""

    def test_bidirectional_gru_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [6, 5]},
            {"kind": "bidi_gru", "units": 7, "name": "bg"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (3, 6, 5), seed=21)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_bidirectional_simplernn_sequences_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [5, 4]},
            {"kind": "bidi_rnn", "units": 6, "seq": True, "name": "br"},
            {"kind": "gap1d", "name": "gp"},
            {"kind": "dense", "units": 3, "act": "softmax", "name": "out"},
        ], (2, 5, 4), seed=22)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)

    def test_thresholded_relu_and_gap3d_golden(self, tmp_path):
        h5, x, golden = _make_fixture(tmp_path, [
            {"kind": "input", "shape": [3, 4, 4, 2]},
            {"kind": "conv3d", "filters": 3, "kernel": 2, "act": "linear",
             "padding": "same", "name": "c3"},
            {"kind": "thresholded_relu", "theta": 0.5, "name": "tr"},
            {"kind": "gap3d", "name": "gp"},
            {"kind": "dense", "units": 2, "act": "softmax", "name": "out"},
        ], (2, 3, 4, 4, 2), seed=23)
        net = import_keras_model_and_weights(h5)
        np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                                   rtol=1e-4, atol=1e-5)


class TestMaskingValidation:
    """Masking wrap targeting (ADVICE r5): wraps only time-axis layers,
    defers past sentinel-preserving per-timestep layers, and fails loud
    on anything else (incl. a dangling trailing Masking)."""

    @staticmethod
    def _seq(layer_cfgs, input_shape=(6, 4)):
        import json as _json
        layers = [{"class_name": c, "config": dict(cfg)}
                  for c, cfg in layer_cfgs]
        layers[0]["config"]["batch_input_shape"] = \
            [None] + list(input_shape)
        return _json.dumps({"class_name": "Sequential",
                            "config": {"layers": layers}})

    def test_masking_before_dense_raises(self):
        from deeplearning4j_tpu.importers.keras import import_sequential
        js = self._seq([("Masking", {"mask_value": 0.0, "name": "m"}),
                        ("Dense", {"units": 4, "activation": "linear",
                                   "name": "d"})])
        with pytest.raises(ValueError, match="Masking must be followed"):
            import_sequential(js)

    def test_trailing_masking_raises(self):
        from deeplearning4j_tpu.importers.keras import import_sequential
        js = self._seq([("LSTM", {"units": 3, "name": "l",
                                  "return_sequences": True}),
                        ("Masking", {"mask_value": 0.0, "name": "m"})])
        with pytest.raises(ValueError, match="dangling"):
            import_sequential(js)

    def test_masking_defers_past_dropout_to_lstm(self):
        from deeplearning4j_tpu.importers.keras import import_sequential
        from deeplearning4j_tpu.nn.layers import DropoutLayer, MaskZeroLayer
        js = self._seq([("Masking", {"mask_value": 0.0, "name": "m"}),
                        ("Dropout", {"rate": 0.2, "name": "dr"}),
                        ("LSTM", {"units": 3, "name": "l"}),
                        ("Dense", {"units": 2, "activation": "softmax",
                                   "name": "out"})])
        net = import_sequential(js)
        assert isinstance(net.layers[0], DropoutLayer)      # NOT wrapped
        assert isinstance(net.layers[1], MaskZeroLayer)     # LSTM wrapped

    def test_masking_does_not_defer_past_sigmoid_activation(self):
        # sigmoid(0) != 0 destroys the sentinel rows the deferred wrap
        # would re-derive the mask from
        from deeplearning4j_tpu.importers.keras import import_sequential
        js = self._seq([("Masking", {"mask_value": 0.0, "name": "m"}),
                        ("Activation", {"activation": "sigmoid",
                                        "name": "a"}),
                        ("LSTM", {"units": 3, "name": "l"})])
        with pytest.raises(ValueError, match="Masking must be followed"):
            import_sequential(js)
