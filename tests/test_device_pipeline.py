"""Device-feed pipeline: async prefetch, shape-bucketing recompile
guard, tBPTT tail padding, the process-level step cache, and the TPU307
lint rule (ISSUE 3 acceptance: one-compile epochs proven via jit cache
stats, bucketed loss == unpadded loss to 1e-6)."""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.config import set_config
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.device_pipeline import (
    DeviceFeeder, FedBatch, choose_bucket, ensure_feature_mask,
    pad_segment, pad_to_bucket, synth_example_mask)
from deeplearning4j_tpu.data.iterators import (
    ArrayDataSetIterator, AsyncDataSetIterator, ListDataSetIterator)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import (
    MetricsRegistry, get_registry, set_registry)
from deeplearning4j_tpu.train import step_cache
from deeplearning4j_tpu.train.trainer import (
    Trainer, _tbptt_segments, make_loss_fn)
from deeplearning4j_tpu.train.updaters import Sgd


@pytest.fixture
def registry():
    """Isolated process-wide registry (restored afterwards) so counter
    assertions aren't polluted by other tests."""
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


@pytest.fixture(autouse=True)
def _default_pipeline_config():
    """Pin the pipeline knobs to defaults for every test here (some
    tests flip them) and leave the step cache clean."""
    set_config(device_feed=True, shape_bucketing=True, prefetch_size=2)
    yield
    set_config(device_feed=True, shape_bucketing=True, prefetch_size=2)


def _mlp_conf(seed, n_in=6, n_hidden=16, n_out=3, lr=0.05):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=n_hidden, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _mlp_data(n, n_in=6, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


# ------------------------------------------------------- recompile guard
def test_ragged_epoch_compiles_train_step_once(registry):
    """103 examples at batch 32 → tail of 7, padded to the 32 bucket:
    the donating train step traces exactly ONE program."""
    x, y = _mlp_data(103)
    net = MultiLayerNetwork(_mlp_conf(seed=11)).init()
    trainer = Trainer(net)
    trainer.fit(ArrayDataSetIterator(x, y, batch_size=32), epochs=2)
    assert trainer._step._cache_size() == 1
    assert registry.counter("tpudl_train_recompiles_total").value == 1
    # real example count, not the padded shape
    assert registry.counter("tpudl_train_examples_total").value == 206
    # 4 steps/epoch (incl. the padded tail), 2 epochs
    assert registry.counter("tpudl_train_steps_total").value == 8


def test_ragged_epoch_recompiles_without_bucketing(registry):
    """Control: with the guard off, the 7-row tail compiles a second
    program — the cliff the bucket removes."""
    set_config(shape_bucketing=False)
    x, y = _mlp_data(103)
    net = MultiLayerNetwork(_mlp_conf(seed=12)).init()
    trainer = Trainer(net)
    trainer.fit(ArrayDataSetIterator(x, y, batch_size=32), epochs=1)
    assert trainer._step._cache_size() == 2
    assert registry.counter("tpudl_train_recompiles_total").value == 2


def test_bucketed_loss_matches_unpadded():
    x, y = _mlp_data(7, seed=3)
    net = MultiLayerNetwork(_mlp_conf(seed=13, lr=0.0)).init()
    trainer = Trainer(net)
    plain = float(trainer.eval_loss(DataSet(x, y)))
    padded, real = pad_to_bucket(DataSet(x, y), 32)
    assert real == 7
    assert padded.features.shape[0] == 32
    assert float(np.sum(np.asarray(padded.labels_mask))) == 7.0
    assert abs(float(trainer.eval_loss(padded)) - plain) <= 1e-6


def test_padded_rows_contribute_zero_gradient():
    """Grad of the padded+masked batch == grad of the unpadded batch."""
    x, y = _mlp_data(7, seed=4)
    net = MultiLayerNetwork(_mlp_conf(seed=14)).init()
    loss_fn = make_loss_fn(net)

    def grads_for(batch):
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            net.params_, net.state_, batch.features, batch.labels,
            batch.features_mask, batch.labels_mask, None)
        return grads

    g_plain = grads_for(DataSet(x, y))
    padded, _ = pad_to_bucket(DataSet(x, y), 32)
    g_padded = grads_for(padded)
    flat_a = jax.flatten_util.ravel_pytree(g_plain)[0]
    flat_b = jax.flatten_util.ravel_pytree(g_padded)[0]
    np.testing.assert_allclose(np.asarray(flat_a), np.asarray(flat_b),
                               atol=1e-6)


def test_bucketed_training_matches_mean_semantics():
    """End-to-end: fitting the ragged epoch with bucketing produces the
    same parameters as fitting with the guard off (masked mean divides
    by the real count — DL4J mini_batch=True semantics)."""
    x, y = _mlp_data(39, seed=5)

    def fit(bucketing, seed):
        set_config(shape_bucketing=bucketing, device_feed=bucketing)
        net = MultiLayerNetwork(_mlp_conf(seed=seed)).init()
        Trainer(net).fit(ArrayDataSetIterator(x, y, batch_size=16),
                         epochs=2)
        return jax.flatten_util.ravel_pytree(net.params_)[0]

    # identical seed → identical init; only the pipeline differs
    p_on = fit(True, seed=15)
    p_off = fit(False, seed=15)
    np.testing.assert_allclose(np.asarray(p_on), np.asarray(p_off),
                               atol=1e-5)


# ------------------------------------------------------------ tBPTT tail
def _rnn_conf(seed, n_in=5, n_out=4, fwd=4):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.01))
            .list()
            .layer(LSTM(n_out=12))
            .layer(RnnOutputLayer(n_out=n_out, activation="softmax"))
            .set_input_type(InputType.recurrent(n_in))
            .backprop_type("tbptt", fwd_length=fwd, back_length=fwd)
            .build())


def test_tbptt_nondivisible_compiles_once():
    """T=10 at tbptt_fwd_length=4 → segments 4,4,2; the tail pads to 4
    with a masked tail and the tBPTT step traces exactly ONE program."""
    rng = np.random.default_rng(1)
    xs = rng.normal(size=(8, 10, 5)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (8, 10))]
    net = MultiLayerNetwork(_rnn_conf(seed=16)).init()
    trainer = Trainer(net)
    trainer.fit(ListDataSetIterator([DataSet(xs, ys)]), epochs=2)
    assert trainer._tbptt_step._cache_size() == 1


def test_tbptt_padded_tail_loss_matches_unpadded():
    rng = np.random.default_rng(2)
    xs = rng.normal(size=(8, 10, 5)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (8, 10))]
    net = MultiLayerNetwork(_rnn_conf(seed=17)).init()
    loss_fn = make_loss_fn(net, with_carries=True)
    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer
    carries = [l.init_carry(8, np.float32)
               if isinstance(l, BaseRecurrentLayer) else None
               for l in net.layers]
    batch = DataSet(xs, ys)
    padded = list(_tbptt_segments(ensure_feature_mask(batch), 4))
    raw = list(_tbptt_segments(batch, 4, pad_tail=False))
    assert padded[-1].features.shape[1] == 4       # tail 2 → 4
    assert raw[-1].features.shape[1] == 2
    for seg_p, seg_r in zip(padded, raw):
        loss_p, (_, carries_p) = loss_fn(
            net.params_, net.state_, carries, seg_p.features, seg_p.labels,
            seg_p.features_mask, seg_p.labels_mask, None)
        loss_r, (_, carries_r) = loss_fn(
            net.params_, net.state_, carries, seg_r.features, seg_r.labels,
            seg_r.features_mask, seg_r.labels_mask, None)
        assert abs(float(loss_p) - float(loss_r)) <= 1e-6
        # masked steps are carry-through: padded-tail carries == unpadded
        for cp, cr in zip(carries_p, carries_r):
            if cp is None:
                continue
            for a, b in zip(jax.tree_util.tree_leaves(cp),
                            jax.tree_util.tree_leaves(cr)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=1e-6)
        carries = carries_r


# ------------------------------------------------------------- the feeder
def test_feeder_yields_all_batches_in_order(registry):
    x, y = _mlp_data(103, seed=6)
    feeder = DeviceFeeder(depth=2)
    fed = list(feeder.feed(ArrayDataSetIterator(x, y, batch_size=32)))
    assert [f.n_examples for f in fed] == [32, 32, 32, 7]
    assert all(isinstance(f, FedBatch) for f in fed)
    assert [f.batch.features.shape[0] for f in fed] == [32, 32, 32, 32]
    assert fed[-1].padded == 25
    # sticky bucket: first batch defined the one static shape
    assert feeder.buckets == (32,)
    # metrics flowed
    assert registry.histogram("tpudl_data_etl_wait_seconds").count == 4
    # real rows ride through unchanged
    np.testing.assert_allclose(
        np.asarray(fed[-1].batch.features)[:7], x[96:])


def test_feeder_abandonment_stops_producer():
    x, y = _mlp_data(400, seed=7)
    feeder = DeviceFeeder(depth=2)
    before = threading.active_count()
    for i, _ in enumerate(feeder.feed(ArrayDataSetIterator(x, y, 10))):
        if i == 2:
            break
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_feeder_propagates_producer_errors():
    def gen():
        yield DataSet(*_mlp_data(4, seed=8))
        raise RuntimeError("ETL exploded")

    feeder = DeviceFeeder(bucketing=False)
    with pytest.raises(RuntimeError, match="ETL exploded"):
        list(feeder.feed(gen()))


def test_feeder_producer_failure_hygiene():
    """Producer-thread death mid-epoch: the consumer re-raises the
    ORIGINAL exception object (traceback intact, pointing into the ETL
    generator), the queue drains, and the daemon thread exits — no
    leaked threads across tests."""
    import traceback

    def gen():
        for i in range(4):
            yield DataSet(*_mlp_data(4, seed=8))
        raise RuntimeError("ETL exploded at batch 4")

    feeder = DeviceFeeder(bucketing=False, depth=2)
    before = threading.active_count()
    with pytest.raises(RuntimeError, match="ETL exploded") as exc_info:
        for _ in feeder.feed(gen()):
            pass
    frames = traceback.extract_tb(exc_info.value.__traceback__)
    assert any(f.name == "gen" for f in frames), (
        "original producer traceback was lost in the thread handoff")
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before, "feeder thread leaked"


def test_bucket_helpers():
    assert choose_bucket(7, (32, 64)) == 32
    assert choose_bucket(33, (32, 64)) == 64
    assert choose_bucket(100, (32, 64)) == 100
    m = synth_example_mask(np.zeros((7, 3)), real=5, total=7)
    assert m.shape == (7,) and m.sum() == 5
    m3 = synth_example_mask(np.zeros((4, 9, 3)), real=2, total=4)
    assert m3.shape == (4, 9) and m3.sum() == 18
    seg = pad_segment(DataSet(np.ones((2, 3, 5), np.float32),
                              features_mask=np.ones((2, 3), np.float32)), 8)
    assert seg.features.shape == (2, 8, 5)
    assert seg.features_mask.shape == (2, 8)
    assert float(seg.features_mask[:, 3:].sum()) == 0.0


# --------------------------------------------------- async iterator rework
def test_async_iterator_resets_etl_wait_per_epoch(registry):
    x, y = _mlp_data(50, seed=9)
    it = AsyncDataSetIterator(ArrayDataSetIterator(x, y, 10), queue_size=2)
    for _ in it:
        time.sleep(0.002)   # make the producer's head start measurable
    first_epoch = it.etl_wait_s
    assert len(list(it)) == 5     # second epoch works after reset
    assert it.etl_wait_s >= 0.0
    assert first_epoch >= 0.0
    # per-epoch reset: the attribute is NOT cumulative across epochs
    assert registry.histogram("tpudl_data_etl_wait_seconds").count == 10


def test_async_iterator_no_thread_leak_on_break():
    x, y = _mlp_data(1000, seed=10)
    it = AsyncDataSetIterator(ArrayDataSetIterator(x, y, 10), queue_size=2)
    before = threading.active_count()
    for i, _ in enumerate(it):
        if i == 3:
            break
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


# ------------------------------------------------------------- step cache
def test_step_cache_shared_across_trainers(registry):
    conf = _mlp_conf(seed=18)
    t1 = Trainer(MultiLayerNetwork(conf).init())
    t1._ensure_ready()
    t2 = Trainer(MultiLayerNetwork(conf).init())
    t2._ensure_ready()
    assert t1._step is t2._step
    assert registry.counter("tpudl_train_step_cache_hits_total").value >= 1
    # fitting BOTH trainers still traces one program (same step object)
    x, y = _mlp_data(32, seed=11)
    key = jax.random.key(0)
    float(t1.fit_batch(DataSet(x, y), key))
    float(t2.fit_batch(DataSet(x, y), key))
    assert t1._step._cache_size() == 1


def test_step_cache_distinct_configs_do_not_collide():
    t1 = Trainer(MultiLayerNetwork(_mlp_conf(seed=19)).init())
    t2 = Trainer(MultiLayerNetwork(_mlp_conf(seed=19, n_hidden=32)).init())
    t1._ensure_ready()
    t2._ensure_ready()
    assert t1._step is not t2._step


def test_step_cache_opts_out_for_per_layer_updaters():
    from deeplearning4j_tpu.train.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(20).updater(Sgd(0.01))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu",
                              updater=Adam(0.05)))
            .layer(OutputLayer(n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(6)).build())
    trainer = Trainer(MultiLayerNetwork(conf).init())
    assert trainer._cache_sig is None
    assert trainer._step_key("train") is None


def test_eval_loss_reuses_cached_step():
    conf = _mlp_conf(seed=21)
    x, y = _mlp_data(16, seed=12)
    t1 = Trainer(MultiLayerNetwork(conf).init())
    float(t1.eval_loss(DataSet(x, y)))
    t2 = Trainer(MultiLayerNetwork(conf).init())
    float(t2.eval_loss(DataSet(x, y)))
    assert t1._eval_loss_fn is t2._eval_loss_fn
    assert t1._eval_loss_fn._cache_size() == 1


# ------------------------------------------------------------ TPU307 lint
def test_tpu307_flags_inline_transfer_in_training_loop(tmp_path):
    from deeplearning4j_tpu.analyze.lint import lint_paths
    bad = tmp_path / "bad_loop.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def train(step, iterator, params):\n"
        "    for batch in iterator:\n"
        "        params = step(params, jnp.asarray(batch.features),\n"
        "                      jax.device_put(batch.labels))\n"
        "    return params\n")
    report = lint_paths([str(bad)])
    hits = report.by_rule("TPU307")
    assert len(hits) == 2
    assert all("bypasses the device feeder" in d.message for d in hits)
    assert report.exit_code() == 1


def test_tpu307_clean_cases(tmp_path):
    from deeplearning4j_tpu.analyze.lint import lint_paths
    ok = tmp_path / "ok_loop.py"
    ok.write_text(
        "import jax.numpy as jnp\n"
        "from deeplearning4j_tpu.data.device_pipeline import DeviceFeeder\n"
        "def train(step, iterator, params):\n"
        "    feeder = DeviceFeeder(lambda b: jnp.asarray(b))\n"
        "    for fed in feeder.feed(iterator):\n"
        "        params = step(params, fed.batch)\n"
        "    return params\n"
        "def setup(arrays):\n"
        "    out = []\n"
        "    for a in arrays:           # no step call in this loop\n"
        "        out.append(jnp.asarray(a))\n"
        "    return out\n")
    report = lint_paths([str(ok)])
    assert report.by_rule("TPU307") == []


# ------------------------------------------------------- persistent cache
def test_compile_cache_dir_applied(tmp_path, monkeypatch):
    import deeplearning4j_tpu.config as config_mod
    prev = jax.config.jax_compilation_cache_dir
    monkeypatch.setattr(config_mod, "_compile_cache_applied", None)
    try:
        set_config(compile_cache_dir=str(tmp_path / "xla-cache"))
        assert jax.config.jax_compilation_cache_dir == \
            str(tmp_path / "xla-cache")
        # an empty path REVERTS the persistent cache, it is not a no-op
        set_config(compile_cache_dir="")
        assert jax.config.jax_compilation_cache_dir is None
    finally:
        set_config(compile_cache_dir="")
        jax.config.update("jax_compilation_cache_dir", prev)
