"""Layer catalog smoke tests: init + forward shape for every registered
layer kind (OpValidation-style coverage base; golden numerics in
test_ops_golden.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, OutputLayer, ActivationLayer, DropoutLayer, EmbeddingLayer,
    EmbeddingSequenceLayer, BatchNormalization, ConvolutionLayer,
    Convolution1DLayer, Convolution3DLayer, SeparableConvolution2D,
    DepthwiseConvolution2D, Deconvolution2D, SubsamplingLayer,
    Subsampling1DLayer, Subsampling3DLayer, UpsamplingLayer, ZeroPaddingLayer,
    CroppingLayer, SpaceToDepthLayer, GlobalPoolingLayer,
    LocalResponseNormalization, LSTM, GravesLSTM, SimpleRnn, GRU,
    Bidirectional, LastTimeStep, TimeDistributed, RnnOutputLayer,
    SelfAttentionLayer, LearnedSelfAttentionLayer, LayerNormalization,
    PReLULayer,
    ZeroPadding1DLayer, Cropping1DLayer, Upsampling1DLayer,
    ZeroPadding3DLayer, Cropping3DLayer, Upsampling3DLayer,
    SpaceToBatchLayer, GaussianDropoutLayer, GaussianNoiseLayer,
    AlphaDropoutLayer, SpatialDropoutLayer, LocallyConnected1D,
    LocallyConnected2D, ElementWiseMultiplicationLayer, RepeatVector,
    MaskZeroLayer, GravesBidirectionalLSTM, VariationalAutoencoder,
    PrimaryCapsules, CapsuleLayer, CapsuleStrengthLayer,
    RecurrentAttentionLayer,
)

KEY = jax.random.key(0)
B = 4

CASES = [
    (DenseLayer(n_out=8, activation="relu"), InputType.feed_forward(12), (B, 8)),
    (OutputLayer(n_out=5, activation="softmax"), InputType.feed_forward(12), (B, 5)),
    (ActivationLayer(activation="tanh"), InputType.feed_forward(12), (B, 12)),
    (DropoutLayer(dropout=0.5), InputType.feed_forward(12), (B, 12)),
    (BatchNormalization(), InputType.feed_forward(12), (B, 12)),
    (LayerNormalization(), InputType.feed_forward(12), (B, 12)),
    (PReLULayer(), InputType.feed_forward(12), (B, 12)),
    (ConvolutionLayer(n_out=6, kernel_size=(3, 3)), InputType.convolutional(8, 8, 3), (B, 6, 6, 6)),
    (ConvolutionLayer(n_out=6, kernel_size=(3, 3), convolution_mode="same"),
     InputType.convolutional(8, 8, 3), (B, 8, 8, 6)),
    (Convolution3DLayer(n_out=4, kernel_size=(2, 2, 2)),
     InputType.convolutional3d(5, 6, 6, 2), (B, 4, 5, 5, 4)),
    (Deconvolution2D(n_out=5, kernel_size=(2, 2), stride=(2, 2)),
     InputType.convolutional(4, 4, 3), (B, 8, 8, 5)),
    (DepthwiseConvolution2D(kernel_size=(3, 3), depth_multiplier=2),
     InputType.convolutional(8, 8, 3), (B, 6, 6, 6)),
    (SeparableConvolution2D(n_out=7, kernel_size=(3, 3)),
     InputType.convolutional(8, 8, 3), (B, 6, 6, 7)),
    (SubsamplingLayer(pooling_type="max"), InputType.convolutional(8, 8, 3), (B, 4, 4, 3)),
    (SubsamplingLayer(pooling_type="avg"), InputType.convolutional(8, 8, 3), (B, 4, 4, 3)),
    (SubsamplingLayer(pooling_type="pnorm", pnorm=2), InputType.convolutional(8, 8, 3), (B, 4, 4, 3)),
    (Subsampling3DLayer(), InputType.convolutional3d(4, 4, 4, 2), (B, 2, 2, 2, 2)),
    (UpsamplingLayer(size=2), InputType.convolutional(4, 4, 3), (B, 8, 8, 3)),
    (ZeroPaddingLayer(padding=(1, 2)), InputType.convolutional(4, 4, 3), (B, 6, 8, 3)),
    (CroppingLayer(cropping=(1, 1)), InputType.convolutional(6, 6, 3), (B, 4, 4, 3)),
    (SpaceToDepthLayer(block_size=2), InputType.convolutional(6, 6, 3), (B, 3, 3, 12)),
    (GlobalPoolingLayer(pooling_type="avg"), InputType.convolutional(6, 6, 5), (B, 5)),
    (LocalResponseNormalization(), InputType.convolutional(6, 6, 8), (B, 6, 6, 8)),
    (LSTM(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (GravesLSTM(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (SimpleRnn(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (GRU(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (Bidirectional(fwd=LSTM(n_out=6), mode="concat"), InputType.recurrent(5, 7), (B, 7, 12)),
    (Bidirectional(fwd=LSTM(n_out=6), mode="add"), InputType.recurrent(5, 7), (B, 7, 6)),
    (LastTimeStep(underlying=LSTM(n_out=6)), InputType.recurrent(5, 7), (B, 6)),
    (TimeDistributed(underlying=DenseLayer(n_out=4)), InputType.recurrent(5, 7), (B, 7, 4)),
    (RnnOutputLayer(n_out=3, activation="softmax"), InputType.recurrent(5, 7), (B, 7, 3)),
    (SelfAttentionLayer(n_heads=2, head_size=4), InputType.recurrent(8, 6), (B, 6, 8)),
    (LearnedSelfAttentionLayer(n_heads=2, head_size=4, n_queries=3),
     InputType.recurrent(8, 6), (B, 3, 8)),
    (GlobalPoolingLayer(pooling_type="max"), InputType.recurrent(5, 7), (B, 5)),
    # ---- layer-catalog tail (nn/layers/extra.py) -----------------------
    (ZeroPadding1DLayer(padding=2), InputType.recurrent(5, 7), (B, 11, 5)),
    (Cropping1DLayer(cropping=1), InputType.recurrent(5, 7), (B, 5, 5)),
    (Upsampling1DLayer(size=3), InputType.recurrent(5, 4), (B, 12, 5)),
    (ZeroPadding3DLayer(padding=1), InputType.convolutional3d(3, 4, 5, 2), (B, 5, 6, 7, 2)),
    (Cropping3DLayer(cropping=1), InputType.convolutional3d(4, 5, 6, 2), (B, 2, 3, 4, 2)),
    (Upsampling3DLayer(size=2), InputType.convolutional3d(2, 3, 4, 2), (B, 4, 6, 8, 2)),
    (GaussianDropoutLayer(rate=0.2), InputType.feed_forward(12), (B, 12)),
    (GaussianNoiseLayer(stddev=0.1), InputType.feed_forward(12), (B, 12)),
    (AlphaDropoutLayer(p=0.9), InputType.feed_forward(12), (B, 12)),
    (SpatialDropoutLayer(p=0.9), InputType.convolutional(6, 6, 3), (B, 6, 6, 3)),
    (LocallyConnected2D(n_out=5, kernel=3), InputType.convolutional(6, 6, 2), (B, 4, 4, 5)),
    (LocallyConnected1D(n_out=5, kernel=3), InputType.recurrent(2, 6), (B, 4, 5)),
    (ElementWiseMultiplicationLayer(), InputType.feed_forward(9), (B, 9)),
    (RepeatVector(n=6), InputType.feed_forward(5), (B, 6, 5)),
    (MaskZeroLayer(underlying=LSTM(n_out=4)), InputType.recurrent(3, 6), (B, 6, 4)),
    (GravesBidirectionalLSTM(n_out=5), InputType.recurrent(3, 6), (B, 6, 5)),
    (VariationalAutoencoder(n_out=4, encoder_layer_sizes=(8,),
                            decoder_layer_sizes=(8,)), InputType.feed_forward(10), (B, 4)),
    (PrimaryCapsules(capsules=2, capsule_dimensions=4, kernel=3, stride=2),
     InputType.convolutional(7, 7, 2), (B, 18, 4)),
    (CapsuleLayer(capsules=3, capsule_dimensions=5, routings=2),
     InputType.recurrent(4, 6), (B, 3, 5)),
    (CapsuleStrengthLayer(), InputType.recurrent(4, 6), (B, 6)),
    (RecurrentAttentionLayer(n_out=6), InputType.recurrent(3, 5), (B, 5, 6)),
]


def test_space_to_batch_shape():
    """SpaceToBatch changes the batch dim — checked outside the generic
    harness (which assumes batch B in == batch out)."""
    layer = SpaceToBatchLayer(blocks=2)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(B, 4, 6, 3)).astype(np.float32))
    y, _ = layer.apply({}, {}, x)
    assert y.shape == (B * 4, 2, 3, 3)
    out_type = layer.get_output_type(InputType.convolutional(4, 6, 3))
    assert (out_type.height, out_type.width, out_type.channels) == (2, 3, 3)


def test_center_loss_and_yolo_heads():
    from deeplearning4j_tpu.nn.layers import CenterLossOutputLayer, Yolo2OutputLayer
    cl = CenterLossOutputLayer(n_out=3, activation="softmax", loss="mcxent")
    itype = InputType.feed_forward(6)
    params = cl.init_params(KEY, itype)
    assert params["centers"].shape == (3, 6)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(B, 6)).astype(np.float32))
    labels = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 1, 2, 0]])
    score = cl.compute_score_array(params, {}, x, labels)
    assert score.shape == (B,) and np.all(np.isfinite(np.asarray(score)))

    yolo = Yolo2OutputLayer(anchors=((1.0, 1.0),), num_classes=2)
    g = np.random.default_rng(0).normal(size=(B, 3, 3, 7)).astype(np.float32)
    y = np.zeros_like(g)
    y[..., 4] = 1.0
    y[..., 5] = 1.0
    score = yolo.compute_score_array({}, {}, jnp.asarray(g), jnp.asarray(y))
    assert score.shape == (B,) and np.all(np.asarray(score) > 0)
    # apply() returns ACTIVATED predictions (YoloUtils.activate parity)
    out, _ = yolo.apply({}, {}, jnp.asarray(g))
    out = np.asarray(out).reshape(B, 3, 3, 1, 7)
    assert np.all((out[..., 0:2] >= 0) & (out[..., 0:2] <= 1))   # sigmoid xy
    assert np.all(out[..., 2:4] > 0)                             # exp wh
    assert np.all((out[..., 4] >= 0) & (out[..., 4] <= 1))       # sigmoid conf
    np.testing.assert_allclose(out[..., 5:].sum(-1), 1.0, rtol=1e-5)


def test_time_geometry_layers_transform_masks():
    """Time-axis-changing layers reshape the propagated [B,T] mask
    (Layer.feedForwardMaskArray parity; review regression)."""
    from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers import RnnOutputLayer
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.train import Trainer, Sgd
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(ZeroPadding1DLayer(padding=1))       # T 4 → 6
            .layer(LSTM(n_out=5))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 4)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 4, 3)).astype(np.float32)
    y = np.zeros((2, 6, 2), np.float32); y[..., 0] = 1
    fmask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)
    lmask = np.ones((2, 6), np.float32)
    batch = DataSet(x, y, features_mask=fmask, labels_mask=lmask)
    loss = float(Trainer(net).fit_batch(batch, jax.random.key(0)))
    assert np.isfinite(loss)          # crashes pre-fix: [B,4] mask at T=6
    # per-layer transforms agree with shapes
    assert ZeroPadding1DLayer(padding=1).transform_mask(
        jnp.ones((2, 4))).shape == (2, 6)
    assert Cropping1DLayer(cropping=1).transform_mask(
        jnp.ones((2, 6))).shape == (2, 4)
    assert Upsampling1DLayer(size=2).transform_mask(
        jnp.ones((2, 4))).shape == (2, 8)
    assert GlobalPoolingLayer().transform_mask(jnp.ones((2, 4))) is None


def test_extra_layers_preprocessor_adaptation():
    """cnn_flat input auto-reshapes into the new CNN-kind layers, and CNN
    activations auto-flatten into the new FF-kind layers (review
    regression: expected_kind must cover the catalog tail)."""
    from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers import (LocallyConnected2D,
                                              GlobalPoolingLayer, OutputLayer,
                                              ElementWiseMultiplicationLayer)
    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(LocallyConnected2D(n_out=4, kernel=3, activation="relu"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(ElementWiseMultiplicationLayer(activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(6, 6, 1)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 36)).astype(np.float32)
    out = net.output(x)          # crashes without the preprocessor mapping
    assert out.shape == (2, 3)


@pytest.mark.parametrize("layer,itype,expected_shape",
                         CASES, ids=[f"{type(c[0]).__name__}_{i}" for i, c in enumerate(CASES)])
def test_layer_forward_shape(layer, itype, expected_shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=itype.batch_shape(B)).astype(np.float32))
    params = layer.init_params(KEY, itype) if layer.has_params() else {}
    state = layer.init_state(itype)
    y, new_state = layer.apply(params, state, x, train=False)
    assert y.shape == expected_shape, f"{type(layer).__name__}: {y.shape} != {expected_shape}"
    assert np.all(np.isfinite(np.asarray(y)))
    # shape inference agrees with runtime
    out_type = layer.get_output_type(itype)
    assert tuple(out_type.batch_shape(B)) == tuple(expected_shape)


def test_embedding_layers():
    layer = EmbeddingLayer(n_in=20, n_out=6)
    params = layer.init_params(KEY, InputType.feed_forward(1))
    idx = jnp.asarray(np.array([[1], [2], [3], [19]], dtype=np.int32))
    y, _ = layer.apply(params, {}, idx)
    assert y.shape == (4, 6)

    seq = EmbeddingSequenceLayer(n_in=20, n_out=6)
    params = seq.init_params(KEY, InputType.recurrent(1, 5))
    idx = jnp.asarray(np.random.default_rng(0).integers(0, 20, (4, 5)).astype(np.int32))
    y, _ = seq.apply(params, {}, idx)
    assert y.shape == (4, 5, 6)


def test_lstm_masking_carries_state():
    """Masked steps must not change the carry and must output zeros."""
    layer = LSTM(n_out=4)
    itype = InputType.recurrent(3, 6)
    params = layer.init_params(KEY, itype)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 3)).astype(np.float32))
    mask = jnp.asarray(np.array([[1, 1, 1, 0, 0, 0], [1, 1, 1, 1, 1, 1]], dtype=np.float32))
    y, _ = layer.apply(params, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0.0, atol=1e-7)
    assert np.any(np.asarray(y[1, 3:]) != 0.0)


def test_batchnorm_running_stats_update():
    layer = BatchNormalization(decay=0.5)
    itype = InputType.feed_forward(4)
    params = layer.init_params(KEY, itype)
    state = layer.init_state(itype)
    x = jnp.asarray(np.random.default_rng(0).normal(3.0, 2.0, size=(64, 4)).astype(np.float32))
    y, new_state = layer.apply(params, state, x, train=True)
    # train output ~ normalized
    assert abs(float(jnp.mean(y))) < 0.1
    # running mean moved toward batch mean (decay 0.5 → halfway)
    assert np.all(np.asarray(new_state["mean"]) > 1.0)
    # inference uses running stats
    y2, s2 = layer.apply(params, new_state, x, train=False)
    assert s2 is new_state


def test_bidirectional_last_step_masked_backward():
    """Right-padded mask: the backward half's final state is at reversed
    position T-1 and must equal running the truncated sequence (review
    regression)."""
    from deeplearning4j_tpu.nn.layers import BidirectionalLastStep, LSTM
    layer = BidirectionalLastStep(fwd=LSTM(n_out=4), mode="concat")
    itype = InputType.recurrent(3, 5)
    params = layer.init_params(KEY, itype)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 5, 3)).astype(np.float32))
    mask = jnp.asarray(np.array([[1, 1, 1, 0, 0]], np.float32))
    out, _ = layer.apply(params, {}, x, mask=mask)
    # ground truth: run the 3-step truncated sequence unmasked
    x3 = x[:, :3]
    ref, _ = layer.apply(params, {}, x3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
