"""Layer catalog smoke tests: init + forward shape for every registered
layer kind (OpValidation-style coverage base; golden numerics in
test_ops_golden.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.input_type import InputType
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, OutputLayer, ActivationLayer, DropoutLayer, EmbeddingLayer,
    EmbeddingSequenceLayer, BatchNormalization, ConvolutionLayer,
    Convolution1DLayer, Convolution3DLayer, SeparableConvolution2D,
    DepthwiseConvolution2D, Deconvolution2D, SubsamplingLayer,
    Subsampling1DLayer, Subsampling3DLayer, UpsamplingLayer, ZeroPaddingLayer,
    CroppingLayer, SpaceToDepthLayer, GlobalPoolingLayer,
    LocalResponseNormalization, LSTM, GravesLSTM, SimpleRnn, GRU,
    Bidirectional, LastTimeStep, TimeDistributed, RnnOutputLayer,
    SelfAttentionLayer, LearnedSelfAttentionLayer, LayerNormalization,
    PReLULayer,
)

KEY = jax.random.key(0)
B = 4

CASES = [
    (DenseLayer(n_out=8, activation="relu"), InputType.feed_forward(12), (B, 8)),
    (OutputLayer(n_out=5, activation="softmax"), InputType.feed_forward(12), (B, 5)),
    (ActivationLayer(activation="tanh"), InputType.feed_forward(12), (B, 12)),
    (DropoutLayer(dropout=0.5), InputType.feed_forward(12), (B, 12)),
    (BatchNormalization(), InputType.feed_forward(12), (B, 12)),
    (LayerNormalization(), InputType.feed_forward(12), (B, 12)),
    (PReLULayer(), InputType.feed_forward(12), (B, 12)),
    (ConvolutionLayer(n_out=6, kernel_size=(3, 3)), InputType.convolutional(8, 8, 3), (B, 6, 6, 6)),
    (ConvolutionLayer(n_out=6, kernel_size=(3, 3), convolution_mode="same"),
     InputType.convolutional(8, 8, 3), (B, 8, 8, 6)),
    (Convolution3DLayer(n_out=4, kernel_size=(2, 2, 2)),
     InputType.convolutional3d(5, 6, 6, 2), (B, 4, 5, 5, 4)),
    (Deconvolution2D(n_out=5, kernel_size=(2, 2), stride=(2, 2)),
     InputType.convolutional(4, 4, 3), (B, 8, 8, 5)),
    (DepthwiseConvolution2D(kernel_size=(3, 3), depth_multiplier=2),
     InputType.convolutional(8, 8, 3), (B, 6, 6, 6)),
    (SeparableConvolution2D(n_out=7, kernel_size=(3, 3)),
     InputType.convolutional(8, 8, 3), (B, 6, 6, 7)),
    (SubsamplingLayer(pooling_type="max"), InputType.convolutional(8, 8, 3), (B, 4, 4, 3)),
    (SubsamplingLayer(pooling_type="avg"), InputType.convolutional(8, 8, 3), (B, 4, 4, 3)),
    (SubsamplingLayer(pooling_type="pnorm", pnorm=2), InputType.convolutional(8, 8, 3), (B, 4, 4, 3)),
    (Subsampling3DLayer(), InputType.convolutional3d(4, 4, 4, 2), (B, 2, 2, 2, 2)),
    (UpsamplingLayer(size=2), InputType.convolutional(4, 4, 3), (B, 8, 8, 3)),
    (ZeroPaddingLayer(padding=(1, 2)), InputType.convolutional(4, 4, 3), (B, 6, 8, 3)),
    (CroppingLayer(cropping=(1, 1)), InputType.convolutional(6, 6, 3), (B, 4, 4, 3)),
    (SpaceToDepthLayer(block_size=2), InputType.convolutional(6, 6, 3), (B, 3, 3, 12)),
    (GlobalPoolingLayer(pooling_type="avg"), InputType.convolutional(6, 6, 5), (B, 5)),
    (LocalResponseNormalization(), InputType.convolutional(6, 6, 8), (B, 6, 6, 8)),
    (LSTM(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (GravesLSTM(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (SimpleRnn(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (GRU(n_out=9), InputType.recurrent(5, 7), (B, 7, 9)),
    (Bidirectional(fwd=LSTM(n_out=6), mode="concat"), InputType.recurrent(5, 7), (B, 7, 12)),
    (Bidirectional(fwd=LSTM(n_out=6), mode="add"), InputType.recurrent(5, 7), (B, 7, 6)),
    (LastTimeStep(underlying=LSTM(n_out=6)), InputType.recurrent(5, 7), (B, 6)),
    (TimeDistributed(underlying=DenseLayer(n_out=4)), InputType.recurrent(5, 7), (B, 7, 4)),
    (RnnOutputLayer(n_out=3, activation="softmax"), InputType.recurrent(5, 7), (B, 7, 3)),
    (SelfAttentionLayer(n_heads=2, head_size=4), InputType.recurrent(8, 6), (B, 6, 8)),
    (LearnedSelfAttentionLayer(n_heads=2, head_size=4, n_queries=3),
     InputType.recurrent(8, 6), (B, 3, 8)),
    (GlobalPoolingLayer(pooling_type="max"), InputType.recurrent(5, 7), (B, 5)),
]


@pytest.mark.parametrize("layer,itype,expected_shape",
                         CASES, ids=[f"{type(c[0]).__name__}_{i}" for i, c in enumerate(CASES)])
def test_layer_forward_shape(layer, itype, expected_shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=itype.batch_shape(B)).astype(np.float32))
    params = layer.init_params(KEY, itype) if layer.has_params() else {}
    state = layer.init_state(itype)
    y, new_state = layer.apply(params, state, x, train=False)
    assert y.shape == expected_shape, f"{type(layer).__name__}: {y.shape} != {expected_shape}"
    assert np.all(np.isfinite(np.asarray(y)))
    # shape inference agrees with runtime
    out_type = layer.get_output_type(itype)
    assert tuple(out_type.batch_shape(B)) == tuple(expected_shape)


def test_embedding_layers():
    layer = EmbeddingLayer(n_in=20, n_out=6)
    params = layer.init_params(KEY, InputType.feed_forward(1))
    idx = jnp.asarray(np.array([[1], [2], [3], [19]], dtype=np.int32))
    y, _ = layer.apply(params, {}, idx)
    assert y.shape == (4, 6)

    seq = EmbeddingSequenceLayer(n_in=20, n_out=6)
    params = seq.init_params(KEY, InputType.recurrent(1, 5))
    idx = jnp.asarray(np.random.default_rng(0).integers(0, 20, (4, 5)).astype(np.int32))
    y, _ = seq.apply(params, {}, idx)
    assert y.shape == (4, 5, 6)


def test_lstm_masking_carries_state():
    """Masked steps must not change the carry and must output zeros."""
    layer = LSTM(n_out=4)
    itype = InputType.recurrent(3, 6)
    params = layer.init_params(KEY, itype)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 3)).astype(np.float32))
    mask = jnp.asarray(np.array([[1, 1, 1, 0, 0, 0], [1, 1, 1, 1, 1, 1]], dtype=np.float32))
    y, _ = layer.apply(params, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0.0, atol=1e-7)
    assert np.any(np.asarray(y[1, 3:]) != 0.0)


def test_batchnorm_running_stats_update():
    layer = BatchNormalization(decay=0.5)
    itype = InputType.feed_forward(4)
    params = layer.init_params(KEY, itype)
    state = layer.init_state(itype)
    x = jnp.asarray(np.random.default_rng(0).normal(3.0, 2.0, size=(64, 4)).astype(np.float32))
    y, new_state = layer.apply(params, state, x, train=True)
    # train output ~ normalized
    assert abs(float(jnp.mean(y))) < 0.1
    # running mean moved toward batch mean (decay 0.5 → halfway)
    assert np.all(np.asarray(new_state["mean"]) > 1.0)
    # inference uses running stats
    y2, s2 = layer.apply(params, new_state, x, train=False)
    assert s2 is new_state
