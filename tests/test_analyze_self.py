"""Tier-1 gate: the AST linter over ``deeplearning4j_tpu/`` itself.

Runs the whole TPU-antipattern rule set over the framework tree
in-process and asserts zero errors — a PR introducing a host sync inside
a jit step, an unfenced timing loop, or an off-convention metric name
fails the suite, not a later TPU run.
"""

import os

from deeplearning4j_tpu.analyze import lint_package, lint_paths
from deeplearning4j_tpu.analyze.__main__ import main as analyze_main
from deeplearning4j_tpu.analyze.diagnostics import RULES, rule_catalog_markdown

import deeplearning4j_tpu

PACKAGE_DIR = os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__))
REPO_ROOT = os.path.dirname(PACKAGE_DIR)


def test_framework_tree_is_lint_clean():
    report = lint_package()
    errors = report.errors()
    assert errors == [], "TPU antipatterns in the tree:\n" + "\n".join(
        d.render() for d in errors)
    assert report.context["files_linted"] > 100
    assert report.context["metrics_checked"] > 0
    assert report.context["ops_checked"] > 300


def test_self_cli_exits_zero():
    assert analyze_main(["--self"]) == 0


def test_bench_harness_is_lint_clean():
    """bench.py is where an unfenced timing loop would hurt most."""
    report = lint_paths([os.path.join(REPO_ROOT, "bench.py")])
    assert report.errors() == [], "\n".join(
        d.render() for d in report.errors())


def test_rule_catalog_documented():
    """Every rule ID in the registry appears in docs/static_analysis.md
    (the doc embeds the generated catalog table)."""
    doc_path = os.path.join(REPO_ROOT, "docs", "static_analysis.md")
    with open(doc_path) as f:
        doc = f.read()
    for rule_id in RULES:
        assert rule_id in doc, f"{rule_id} missing from docs/static_analysis.md"
    # the generated table is embedded verbatim, so docs can't drift
    assert rule_catalog_markdown() in doc
    # the concurrency family has its own rationale section, one
    # "**TPU4xx slug.**" block per rule (TPU400 lives in the pragma
    # paragraph and the table)
    assert "## Concurrency" in doc
    for rule_id, info in RULES.items():
        if rule_id.startswith("TPU4") and rule_id != "TPU400":
            assert f"**{rule_id} {info.slug}.**" in doc, \
                f"{rule_id} rationale missing from the Concurrency section"
    # same contract for the dataflow family: one "**TPU5xx slug.**"
    # rationale block per rule
    assert "## Whole-program dataflow" in doc
    for rule_id, info in RULES.items():
        if rule_id.startswith("TPU5"):
            assert f"**{rule_id} {info.slug}.**" in doc, \
                f"{rule_id} rationale missing from the dataflow section"
