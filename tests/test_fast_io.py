"""Native CSV parser vs the python oracle (SURVEY §7.9: native code under
round-trip properties; the ETL decode hot path)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.data.records import CSVRecordReader, FileSplit
from deeplearning4j_tpu.native import fast_io


needs_native = pytest.mark.skipif(not fast_io.available(),
                                  reason="g++/native build unavailable")


def _write(tmp_path, text, name="data.csv"):
    p = str(tmp_path / name)
    with open(p, "w", newline="") as f:
        f.write(text)
    return p


@needs_native
def test_simple_matrix(tmp_path):
    p = _write(tmp_path, "1,2,3\n4,5,6\n")
    arr, errs = fast_io.read_csv_floats(p)
    np.testing.assert_array_equal(arr, [[1, 2, 3], [4, 5, 6]])
    assert errs == 0


@needs_native
def test_crlf_skip_rows_and_no_trailing_newline(tmp_path):
    p = _write(tmp_path, "h1,h2\r\n1.5,2.5\r\n-3,4e2")
    arr, errs = fast_io.read_csv_floats(p, skip_rows=1)
    np.testing.assert_allclose(arr, [[1.5, 2.5], [-3.0, 400.0]])
    assert errs == 0


@needs_native
def test_bad_cells_and_short_rows(tmp_path):
    p = _write(tmp_path, "1,x,3\n4,5\n")
    arr, errs = fast_io.read_csv_floats(p)
    assert arr.shape == (2, 3)
    assert np.isnan(arr[0, 1]) and errs == 1
    assert arr[1, 0] == 4 and arr[1, 1] == 5
    assert np.isnan(arr[1, 2])     # short-row padding (fill=NaN, no error)


@needs_native
def test_long_cells_match_python_path(tmp_path):
    # cells >= 63 chars used to hit the native stack-buffer cap and come
    # back NaN; both paths must now parse them identically
    long_num = "0." + "1" * 80            # 82-char valid float
    long_junk = "z" * 100                 # 100-char invalid cell
    p = _write(tmp_path, f"{long_num},2\n{long_junk},4\n")
    arr, errs = fast_io.read_csv_floats(p)
    np.testing.assert_allclose(arr[0], [float(long_num), 2.0])
    assert np.isnan(arr[1, 0]) and arr[1, 1] == 4
    assert errs == 1


@needs_native
def test_matches_python_oracle_random(tmp_path):
    rng = np.random.default_rng(0)
    ref = rng.normal(size=(200, 7)).astype(np.float32)
    lines = "\n".join(",".join(f"{v:.6g}" for v in row) for row in ref)
    p = _write(tmp_path, lines + "\n")
    arr, errs = fast_io.read_csv_floats(p)
    assert errs == 0
    # %.6g keeps ~6 significant digits; parse must match within that
    np.testing.assert_allclose(arr, ref.astype(np.float32),
                               rtol=1e-5, atol=1e-6)


def test_load_array_native_equals_python(tmp_path):
    """CSVRecordReader.load_array must give identical output whichever
    backend runs."""
    text = "a,b,c\n1,2,3\n4,,6\n7,8\n"
    p = _write(tmp_path, text)
    reader = CSVRecordReader(FileSplit(p), skip_lines=1)
    got = reader.load_array()
    assert got.shape == (3, 3)
    np.testing.assert_array_equal(got[0], [1, 2, 3])
    assert np.isnan(got[1, 1]) and got[1, 2] == 6
    assert np.isnan(got[2, 2])

    if fast_io.available():
        # force the python path and compare elementwise (NaN == NaN)
        native, fast_io._lib = fast_io._lib, None
        failed = fast_io._build_failed
        fast_io._build_failed = True
        try:
            py = reader.load_array()
        finally:
            fast_io._lib, fast_io._build_failed = native, failed
        np.testing.assert_array_equal(np.isnan(got), np.isnan(py))
        np.testing.assert_array_equal(got[~np.isnan(got)], py[~np.isnan(py)])


@needs_native
def test_empty_and_blank_lines(tmp_path):
    p = _write(tmp_path, "\n1,2\n\n3,4\n")
    arr, errs = fast_io.read_csv_floats(p)
    np.testing.assert_array_equal(arr, [[1, 2], [3, 4]])
    p2 = _write(tmp_path, "", name="empty.csv")
    arr2, _ = fast_io.read_csv_floats(p2)
    assert arr2.shape == (0, 0)
