"""Fixed-seed bitwise determinism (SURVEY §5.2: the race-detection/
sanitizer discipline translated to TPU — XLA programs are data-race-free
by construction, so the observable guarantee is bitwise reproducibility
of a seeded run; this pins it)."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (BatchNormalization, DenseLayer,
                                          DropoutLayer, LSTM, OutputLayer,
                                          RnnOutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Adam


def _iter(seed=0, n=96, batch=32):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return ListDataSetIterator([DataSet(x[i:i + batch], y[i:i + batch])
                                for i in range(0, n, batch)])


def _train_once(with_dropout=True):
    b = (NeuralNetConfiguration.builder().seed(777).updater(Adam(1e-2)).list()
         .layer(DenseLayer(n_out=16, activation="relu")))
    if with_dropout:
        b = b.layer(DropoutLayer(dropout=0.7))
    conf = (b.layer(BatchNormalization())
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(_iter(), epochs=2)
    return net


def _flat(net):
    import jax
    return np.concatenate([np.ravel(np.asarray(l))
                           for l in jax.tree_util.tree_leaves(net.params_)])


def test_training_bitwise_deterministic():
    a, b = _train_once(), _train_once()
    pa, pb = _flat(a), _flat(b)
    np.testing.assert_array_equal(pa, pb)   # BITWISE, not allclose


def test_rnn_training_bitwise_deterministic():
    def run():
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=12, activation="tanh"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(4, 6)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(12, 6, 4)).astype(np.float32)
        y = np.zeros((12, 6, 3), np.float32)
        y[:, :, 0] = 1.0
        net.fit(ListDataSetIterator([DataSet(x, y)]), epochs=2)
        return _flat(net)

    np.testing.assert_array_equal(run(), run())


def test_different_seed_differs():
    """The determinism test must not pass vacuously: changing the seed
    must change the trained parameters."""
    a = _train_once()
    conf = (NeuralNetConfiguration.builder().seed(778).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DropoutLayer(dropout=0.7))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    b = MultiLayerNetwork(conf).init()
    b.fit(_iter(), epochs=2)
    assert not np.array_equal(_flat(a), _flat(b))
