"""tpudl.nn.quantize + the quantized serve path (ISSUE 11 tentpole).

Acceptance: the fused int8 dequant-matmul kernel matches the jnp oracle
in the 1e-2 band; a quantized net's predictions match full precision
within its CALIBRATED tolerance band; ``ModelRegistry.deploy(...,
precision="int8")`` serves a quantized variant that shares the
step-cache/bucket machinery; ``GatedDeployer`` demonstrably refuses an
accuracy-regressing quantization (test-injected) before any flip; and
hot-swapping between warmed bf16 and int8 variants of one architecture
under concurrent load drops zero requests and triggers zero
shared-bucket recompiles.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn import quantize
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)
from deeplearning4j_tpu.ops.pallas import (int8_matmul_pallas,
                                           int8_matmul_reference)
from deeplearning4j_tpu.serve import InferenceEngine, ModelRegistry
from deeplearning4j_tpu.train import Sgd

N_IN, N_OUT = 12, 4


@pytest.fixture
def metrics():
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


def _net(seed=3):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Sgd(0.1)).weight_init("xavier").list()
        .layer(DenseLayer(n_out=24, activation="relu"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()).init()


def _clustered_data(n=96, seed=0):
    """Linearly separable 4-class blobs — a net trained on these holds
    a real accuracy for the gate to defend."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, N_OUT, n)
    centers = rng.normal(size=(N_OUT, N_IN)) * 4.0
    x = centers[labels] + rng.normal(size=(n, N_IN)) * 0.3
    y = np.eye(N_OUT, dtype=np.float32)[labels]
    return x.astype(np.float32), y


# -------------------------------------------------------------- kernel
class TestInt8MatmulKernel:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_kernel_matches_oracle_in_band(self, dtype):
        """Interpreter-mode Pallas kernel vs the pure-jnp oracle: the
        1e-2 relative band (quantization noise dwarfs kernel rounding)."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(37, 64)).astype(np.float32)
                        ).astype(dtype)
        w = jnp.asarray(rng.normal(size=(64, 48)).astype(np.float32) * 0.4)
        w_q, scale = quantize.quantize_weight(w)
        yk = np.asarray(int8_matmul_pallas(x, w_q, scale, interpret=True),
                        np.float32)
        yo = np.asarray(int8_matmul_reference(x, w_q, scale), np.float32)
        np.testing.assert_allclose(yk, yo, rtol=1e-2, atol=1e-2)
        # and the whole quantized product stays in the band vs full
        # precision
        fp = np.asarray(x.astype(jnp.float32) @ w, np.float32)
        assert np.max(np.abs(yo - fp)) < 1e-2 * max(1.0, np.abs(fp).max())

    def test_kernel_pads_ragged_m_and_keeps_dtype(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(13, 32)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        w_q, scale = quantize.quantize_weight(
            jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32)))
        y = int8_matmul_pallas(x, w_q, scale, interpret=True, block_m=8)
        assert y.shape == (13, 16) and y.dtype == jnp.bfloat16

    def test_quantize_weight_roundtrip_error_bound(self):
        """Symmetric per-channel int8: reconstruction error <= scale/2
        per channel (round-to-nearest)."""
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.normal(size=(40, 24)).astype(np.float32))
        w_q, scale = quantize.quantize_weight(w)
        assert w_q.dtype == jnp.int8 and scale.shape == (24,)
        err = np.abs(np.asarray(quantize.dequantize_weight(w_q, scale) - w))
        assert np.all(err <= np.asarray(scale)[None, :] * 0.5 + 1e-7)


# --------------------------------------------------------- quantize_net
class TestQuantizeNet:
    def test_predictions_within_calibrated_band(self):
        net = _net()
        x, y = _clustered_data(seed=5)
        it = ArrayDataSetIterator(x, y, 32)
        qnet = quantize.quantize_net(net, calibration=it)
        report = qnet.quantization_
        assert qnet.quantized_ == "int8"
        assert report.layers_quantized == 2
        assert report.compression_ratio > 3.0
        assert report.tolerance_band is not None
        fp = np.asarray(net.output(x))
        q = np.asarray(qnet.output(x))
        assert np.max(np.abs(q - fp)) <= report.tolerance_band
        # the source net is untouched (it keeps serving while the
        # quantized candidate is scored)
        assert "W" in net.params_[0] and "W_q" in qnet.params_[0]

    def test_non_mln_model_rejected(self):
        with pytest.raises(TypeError, match="MultiLayerNetwork"):
            quantize.quantize_net(object())


# ------------------------------------------------------ serve precision
class TestQuantizedServe:
    def test_deploy_precision_int8_serves_and_stamps_gauges(
            self, tmp_path, metrics):
        net = _net(7)
        x, y = _clustered_data(seed=6)
        p = str(tmp_path / "m.zip")
        net.save(p)
        registry = ModelRegistry(max_batch=8, max_latency_ms=2)
        entry = registry.deploy("m", p, precision="int8",
                                calibration=ArrayDataSetIterator(x, y, 32))
        assert entry.precision == "int8"
        assert entry.to_dict()["precision"] == "int8"
        assert entry.engine.precision == "int8"
        out = np.asarray(registry.predict("m", x[:4], timeout_s=30))
        fp = np.asarray(net.output(x[:4]))
        assert np.max(np.abs(out - fp)) < 0.05
        assert metrics.gauge(
            "tpudl_serve_quantized_weight_bytes").value > 0
        assert metrics.gauge(
            "tpudl_serve_quantized_compression_ratio").value > 3.0
        assert metrics.gauge(
            "tpudl_serve_quantized_max_abs_err").value >= 0
        assert metrics.counter(
            "tpudl_serve_quantized_batches_total").value >= 1
        registry.close()

    def test_unknown_precision_rejected_before_flip(self, tmp_path, metrics):
        net = _net(8)
        p = str(tmp_path / "m.zip")
        net.save(p)
        registry = ModelRegistry(max_batch=4, max_latency_ms=2)
        registry.deploy("m", p)
        with pytest.raises(ValueError, match="precision"):
            registry.deploy("m", p, precision="int4")
        assert registry.get("m").version == 1     # incumbent untouched
        registry.close()

    def test_rollback_restores_precision(self, tmp_path, metrics):
        net = _net(9)
        p = str(tmp_path / "m.zip")
        net.save(p)
        registry = ModelRegistry(max_batch=4, max_latency_ms=2)
        registry.deploy("m", p, precision="int8")
        registry.deploy("m", p)                   # v2: bf16
        rolled = registry.rollback("m")           # back to the int8 variant
        assert rolled.precision == "int8"
        registry.close()

    def test_hot_swap_bf16_int8_zero_drops_zero_recompiles(
            self, tmp_path, metrics):
        """The acceptance flagship: warmed bf16 and int8 variants of ONE
        architecture swap under concurrent load with zero dropped
        requests and zero shared-bucket recompiles."""
        net = _net(11)
        x, _ = _clustered_data(seed=7)
        p = str(tmp_path / "m.zip")
        net.save(p)
        registry = ModelRegistry(max_batch=4, max_latency_ms=2,
                                 queue_limit=512, buckets=(4,))
        registry.deploy("m", p)
        registry.predict("m", x[:4], timeout_s=30)      # warm bf16 bucket
        registry.deploy("m", p, precision="int8")
        registry.predict("m", x[:4], timeout_s=30)      # warm int8 bucket
        fp = np.asarray(net.output(x))
        recompiles_warm = metrics.counter(
            "tpudl_serve_recompiles_total").value
        programs_warm = registry.get("m").engine.compiled_programs

        errors, results = [], []
        stop = threading.Event()

        def client(cid):
            rng = np.random.default_rng(cid)
            count = 0
            while not (stop.is_set() and count >= 10):
                i = int(rng.integers(0, x.shape[0] - 4))
                try:
                    out = registry.predict("m", x[i:i + 4], timeout_s=30)
                    results.append((i, np.asarray(out)))
                except BaseException as e:  # noqa: BLE001 — test collects
                    errors.append(e)
                count += 1
                if count > 400:
                    break

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(4)]
        for t in threads:
            t.start()
        # swap precision back and forth mid-traffic
        registry.deploy("m", p)                         # → bf16
        time.sleep(0.1)
        registry.deploy("m", p, precision="int8")       # → int8
        time.sleep(0.1)
        registry.deploy("m", p)                         # → bf16
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert not errors, errors[:3]
        assert len(results) >= 40
        for i, rows in results:
            # every answer is a valid output of one of the two variants
            # (int8 sits inside the calibrated band of bf16)
            assert np.max(np.abs(rows - fp[i:i + 4])) < 0.05, \
                f"garbled response for rows {i}..{i + 4}"
        # zero shared-bucket recompiles: both precisions were warm, so
        # the swaps traced nothing new
        assert metrics.counter("tpudl_serve_recompiles_total").value \
            == recompiles_warm
        assert registry.get("m").engine.compiled_programs == programs_warm
        registry.close()


# ------------------------------------------------------------- the gate
class TestQuantizedGate:
    def _trained_net_and_holdout(self, tmp_path):
        x, y = _clustered_data(n=128, seed=13)
        net = _net(13)
        net.fit(ArrayDataSetIterator(x[:96], y[:96], 32), epochs=30)
        holdout = ArrayDataSetIterator(x[96:], y[96:], 32)
        acc = net.evaluate(holdout).accuracy()
        assert acc > 0.9, f"fixture net failed to train (acc={acc})"
        p = str(tmp_path / "m.zip")
        net.save(p)
        return net, holdout, p

    def test_gate_accepts_accuracy_preserving_quantization(
            self, tmp_path, metrics):
        from deeplearning4j_tpu.online.gate import EvalGate, GatedDeployer
        net, holdout, p = self._trained_net_and_holdout(tmp_path)
        registry = ModelRegistry(max_batch=8, max_latency_ms=2)
        registry.deploy("m", p)                     # bf16 incumbent
        deployer = GatedDeployer(registry, EvalGate(holdout, "accuracy"))
        decision = deployer.deploy_if_better("m", p, precision="int8")
        assert decision.deploy, decision.reason
        assert registry.get("m").precision == "int8"
        assert metrics.counter("tpudl_online_deploys_total").value == 1
        registry.close()

    def test_gate_refuses_accuracy_regressing_quantization(
            self, tmp_path, metrics, monkeypatch):
        """Test-injected regression: a quantization that destroys the
        weights must be refused BEFORE any flip — the bf16 incumbent
        keeps serving."""
        from deeplearning4j_tpu.online.gate import EvalGate, GatedDeployer
        net, holdout, p = self._trained_net_and_holdout(tmp_path)
        registry = ModelRegistry(max_batch=8, max_latency_ms=2)
        registry.deploy("m", p)
        incumbent_out = np.asarray(
            registry.predict("m", holdout.features[:4], timeout_s=30))

        def broken_quantize_weight(w):
            w_q = jnp.zeros(np.asarray(w).shape, jnp.int8)
            return w_q, jnp.ones((np.asarray(w).shape[-1],), jnp.float32)

        monkeypatch.setattr(quantize, "quantize_weight",
                            broken_quantize_weight)
        deployer = GatedDeployer(registry, EvalGate(holdout, "accuracy"))
        decision = deployer.deploy_if_better("m", p, precision="int8")
        assert not decision.deploy
        assert "regression" in decision.reason
        assert metrics.counter("tpudl_online_refusals_total").value == 1
        # the flip never happened: same version, same precision, and the
        # incumbent still answers with its own weights
        entry = registry.get("m")
        assert entry.version == 1 and entry.precision == "bf16"
        np.testing.assert_allclose(
            np.asarray(registry.predict("m", holdout.features[:4],
                                        timeout_s=30)),
            incumbent_out, rtol=1e-5, atol=1e-6)
        registry.close()
