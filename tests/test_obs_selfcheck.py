"""Tier-1 wiring for the observability self-check and the bench
harness's tunnel-down contract.

- ``python -m deeplearning4j_tpu.obs.selfcheck`` must exit 0: registry
  lint, metric↔doc parity, a CPU cost_analysis smoke, and a
  flight-recorder dump round-trip.
- ``bench.py``'s device-probe "skipped" path (BENCH_r05: a down TPU
  tunnel) must exit 0 AND still emit the CPU-measurable records with
  the roofline stamp lifted into the top-level detail.
"""

import importlib.util
import json
import os
import subprocess
import sys

import deeplearning4j_tpu

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(
    deeplearning4j_tpu.__file__)))


def test_selfcheck_entry_point_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.obs.selfcheck"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs.selfcheck OK" in proc.stdout


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_main", os.path.join(REPO_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_treats_cpu_fallback_as_tunnel_down():
    """Some environments hang on a down tunnel; this one falls back to
    CPU.  Both must take the skip path — the TPU bench grinding the
    full suite on a CPU for hours would end as an rc=124 with a
    meaningless vs_baseline (conftest pins JAX_PLATFORMS=cpu, so the
    probe subprocess deterministically answers with a CpuDevice)."""
    bench = _load_bench()
    probe = bench._probe_device(timeout_s=120.0)
    assert probe is not None
    status, message = probe
    assert status == "skipped"
    assert "CPU" in message


def test_bench_skip_path_runs_cpu_records_and_exits_zero(monkeypatch,
                                                         capsys):
    """A probe timeout (tunnel down) must produce a structured 'skipped'
    record with rc=0 that still carries the feed_overlap and serving
    rows AND the cost-model stamp (mfu/hbm_util/arith_intensity) lifted
    to the record's detail — a tunnel-down round produces data, not an
    rc=1 with an empty detail (BENCH_r05)."""
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_device",
                        lambda timeout_s=30.0: ("skipped",
                                                "device probe timed out"))
    monkeypatch.setattr(
        bench, "bench_feed_overlap",
        lambda: {"metric": "feed_overlap", "speedup": 1.4,
                 "mfu": 0.012, "hbm_util": 0.05, "arith_intensity": 1.9,
                 "perf": {"source": "xla_cost_analysis"}})
    monkeypatch.setattr(
        bench, "bench_serving",
        lambda: {"metric": "serving_requests_per_s", "value": 100.0,
                 "mfu": 0.02, "hbm_util": 0.06, "arith_intensity": 3.7,
                 "quantized": {"speedup": 1.4, "p99_ratio": 0.8,
                               "wins": True, "intensity_gain": 1.25,
                               "arith_intensity_int8": 4.6},
                 "cold_start": {"speedup": 2.2,
                                "first_response_speedup": 19.7,
                                "zero_jit_after_warm": True,
                                "wins": True},
                 "load_sweep": {"value": 1.9, "p99_held_2x": True,
                                "offered_load_x": 10.0,
                                "replicas_per_stage": [1, 1, 4, 4],
                                "shed_by_lane": {"interactive": 0,
                                                 "batch": 21},
                                "zero_dropped_or_garbled": True,
                                "wins": True}})
    monkeypatch.setattr(
        bench, "bench_multichip",
        lambda: {"metric": "multichip_scaling_efficiency", "value": 0.8,
                 "per_chip_scaling_efficiency": 0.8,
                 "straggler_skew": 1.1, "n_workers": 4,
                 "mesh_sweep": {
                     "metric": "mesh_layout_sweep",
                     "layouts": {
                         "dp4": {"steps_per_s": 280.0,
                                 "arith_intensity": 7.5,
                                 "collective_bytes_per_step": 506928},
                         "dp2xpp2": {"steps_per_s": 90.0,
                                     "arith_intensity": 1.5,
                                     "collective_bytes_per_step": 806976}}},
                 "elastic": {
                     "metric": "elastic_pool", "value": 1.0,
                     "grow": {"from_width": 2, "to_width": 4,
                              "post_boundary_max_loss_delta": 0.0,
                              "matches_fixed_width": True},
                     "arbiter": {"p99_held": True,
                                 "grow_back_mttr_s": 0.04,
                                 "zero_dropped_or_garbled": True,
                                 "width_restored": True}}})
    monkeypatch.setattr(
        bench, "bench_online",
        lambda: {"metric": "online_feedback_to_deploy_seconds",
                 "value": 0.21, "gate_eval_s": 0.1,
                 "rollback_mttr_s": 0.006, "rolled_back": True})
    rc = bench.main()
    out = capsys.readouterr().out
    assert rc == 0
    record = json.loads(out.strip().splitlines()[-1])
    assert record["status"] == "skipped"
    assert record["detail"]["feed_overlap"]["speedup"] == 1.4
    assert record["detail"]["serving"]["value"] == 100.0
    # the ISSUE-11 quantized row (int8 vs bf16 + the cost-model
    # intensity stamps) rides the tunnel-down record inside the serving
    # row — a down tunnel still produces the quantized evidence
    quantized = record["detail"]["serving"]["quantized"]
    assert quantized["wins"] is True
    assert quantized["intensity_gain"] == 1.25
    # ... and the ISSUE-12 cold-start row (restart → first response,
    # before/after the compiled-artifact store) rides the same record —
    # a down tunnel still produces the warm-restart evidence
    cold_start = record["detail"]["serving"]["cold_start"]
    assert cold_start["zero_jit_after_warm"] is True
    assert cold_start["first_response_speedup"] == 19.7
    # ... and the ISSUE-13 load-sweep row (10x offered load vs replica
    # autoscaling, fan-out swap + all-replica rollback under load)
    # rides the same tunnel-down record — traffic-scale evidence is
    # CPU-measurable too
    load_sweep = record["detail"]["serving"]["load_sweep"]
    assert load_sweep["p99_held_2x"] is True
    assert load_sweep["offered_load_x"] == 10.0
    assert load_sweep["replicas_per_stage"][-1] == 4
    assert load_sweep["zero_dropped_or_garbled"] is True
    assert load_sweep["shed_by_lane"]["interactive"] == 0
    # the multichip scaling row rides the tunnel-down record too —
    # federated telemetry is CPU-measurable, so rc=0 with data, not rc=1
    multichip = record["detail"]["multichip"]
    assert multichip["per_chip_scaling_efficiency"] == 0.8
    assert multichip["straggler_skew"] == 1.1
    # ... and the ISSUE-14 unified-mesh layout sweep rides inside the
    # multichip record on both paths: per-layout steps/s + collective
    # bytes + cost-model arith intensity stay CPU-measurable
    sweep = multichip["mesh_sweep"]
    assert set(sweep["layouts"]) == {"dp4", "dp2xpp2"}
    for row in sweep["layouts"].values():
        assert row["steps_per_s"] > 0
        assert row["collective_bytes_per_step"] > 0
        assert "arith_intensity" in row
    # ... and the ISSUE-19 elastic-pool row rides the same record on
    # both paths: the grow 1e-6 contract and the borrow/return cycle
    # (serve p99 held, gang grown back) are CPU-measurable evidence
    elastic = multichip["elastic"]
    assert elastic["grow"]["matches_fixed_width"] is True
    assert elastic["grow"]["post_boundary_max_loss_delta"] <= 1e-6
    assert elastic["arbiter"]["p99_held"] is True
    assert elastic["arbiter"]["zero_dropped_or_garbled"] is True
    assert elastic["arbiter"]["width_restored"] is True
    assert elastic["arbiter"]["grow_back_mttr_s"] is not None
    # ... and so does the continual-learning loop row: feedback→deploy
    # latency, gate eval seconds and rollback MTTR are CPU-measurable
    online = record["detail"]["online"]
    assert online["value"] == 0.21
    assert online["gate_eval_s"] == 0.1
    assert online["rollback_mttr_s"] == 0.006
    # the roofline stamp is lifted to the top-level detail
    assert record["detail"]["mfu"] == 0.012
    assert record["detail"]["hbm_util"] == 0.05
    assert record["detail"]["arith_intensity"] == 1.9
    assert record["detail"]["perf"]["source"] == "xla_cost_analysis"


def test_bench_probe_error_still_exits_nonzero(monkeypatch, capsys):
    """A device that ANSWERED with a failure keeps the error contract
    (rc=1) while still emitting the CPU rows."""
    bench = _load_bench()
    monkeypatch.setattr(bench, "_probe_device",
                        lambda timeout_s=30.0: ("error",
                                                "device probe failed"))
    monkeypatch.setattr(bench, "bench_feed_overlap", lambda: {"ok": 1})
    monkeypatch.setattr(bench, "bench_serving", lambda: {"ok": 1})
    monkeypatch.setattr(bench, "bench_multichip", lambda: {"ok": 1})
    monkeypatch.setattr(bench, "bench_online", lambda: {"ok": 1})
    rc = bench.main()
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert record["status"] == "error"
    assert record["detail"]["feed_overlap"] == {"ok": 1}
    assert record["detail"]["multichip"] == {"ok": 1}
    assert record["detail"]["online"] == {"ok": 1}
