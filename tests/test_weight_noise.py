"""Weight-noise family tests (VERDICT r2 missing #7: IWeightNoise /
DropConnect — DL4J ``nn/conf/weightnoise/``)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weight_noise import (DropConnect, WeightNoise,
                                                apply_noise, from_dict,
                                                to_dict)


def _net(noise):
    conf = (NeuralNetConfiguration.builder().seed(3).list()
            .layer(DenseLayer(n_out=16, activation="tanh",
                              weight_noise=noise))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


class TestTransforms:
    def test_drop_connect_zeros_and_rescales(self):
        w = jnp.ones((64, 64))
        out = DropConnect(p=0.8).transform(w, jax.random.key(0))
        vals = np.unique(np.asarray(out).round(6))
        assert set(vals) <= {0.0, np.float32(1 / 0.8).round(6)}
        frac = float((np.asarray(out) == 0).mean())
        assert 0.1 < frac < 0.3            # ~1-p dropped
        # inverted scaling keeps the expectation ~unchanged
        assert abs(float(jnp.mean(out)) - 1.0) < 0.05

    def test_weight_noise_additive_and_multiplicative(self):
        w = jnp.full((32, 32), 2.0)
        add = WeightNoise(stddev=0.1).transform(w, jax.random.key(1))
        assert abs(float(jnp.mean(add)) - 2.0) < 0.05
        assert float(jnp.std(add)) > 0.05
        mul = WeightNoise(mean=1.0, stddev=0.1,
                          additive=False).transform(w, jax.random.key(1))
        assert abs(float(jnp.mean(mul)) - 2.0) < 0.1

    def test_bias_excluded_by_default(self):
        params = {"W": jnp.ones((4, 4)), "b": jnp.ones((4,))}
        out = apply_noise(DropConnect(p=0.5), params, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(out["b"]), 1.0)
        assert float(jnp.sum(out["W"] == 0)) > 0
        out2 = apply_noise(DropConnect(p=0.5, apply_to_bias=True), params,
                           jax.random.key(0))
        assert float(jnp.sum(out2["b"] == 0)) >= 0  # transformed stream


class TestSerde:
    def test_round_trip(self):
        for noise in (DropConnect(p=0.7),
                      WeightNoise(mean=0.1, stddev=0.2, additive=False,
                                  apply_to_bias=True)):
            back = from_dict(to_dict(noise))
            assert back == noise

    def test_layer_json_round_trip(self):
        net = _net(DropConnect(p=0.9))
        d = net.conf.to_dict()
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        import json
        conf2 = MultiLayerConfiguration.from_dict(
            json.loads(json.dumps(d)))
        assert conf2.layers[0].weight_noise == DropConnect(p=0.9)


class TestInNetwork:
    def test_train_noisy_eval_clean(self):
        net = _net(DropConnect(p=0.6))
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(4, 8)).astype(np.float32))
        clean = net._forward(net.params_, net.state_, x, train=False)[0]
        noisy = net._forward(net.params_, net.state_, x, train=True,
                             rng=jax.random.key(5))[0]
        clean2 = net._forward(net.params_, net.state_, x, train=False)[0]
        np.testing.assert_array_equal(np.asarray(clean), np.asarray(clean2))
        assert not np.allclose(np.asarray(clean), np.asarray(noisy))

    def test_noise_is_rng_deterministic(self):
        net = _net(WeightNoise(stddev=0.05))
        x = jnp.ones((2, 8))
        a = net._forward(net.params_, net.state_, x, train=True,
                         rng=jax.random.key(7))[0]
        b = net._forward(net.params_, net.state_, x, train=True,
                         rng=jax.random.key(7))[0]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("noise", [DropConnect(p=0.8),
                                       WeightNoise(stddev=0.05)])
    def test_gradcheck_through_noise(self, noise):
        """Fixed rng → the noised forward is deterministic and (a.e.)
        differentiable; grads must match finite differences (f64, same
        rig as test_gradchecks)."""
        from deeplearning4j_tpu.autodiff.gradcheck import check_gradients
        from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
        jax.config.update("jax_enable_x64", True)
        set_dtype_policy(DTypePolicy(param_dtype=jnp.float64,
                                     compute_dtype=jnp.float64,
                                     output_dtype=jnp.float64))
        try:
            net = _net(noise)
            rng = jax.random.key(11)
            x = jnp.asarray(np.random.default_rng(1)
                            .normal(size=(4, 8)).astype(np.float64))
            labels = jnp.asarray(np.eye(4, dtype=np.float64)[[0, 1, 2, 3]])
            params64 = jax.tree_util.tree_map(
                lambda a: jnp.asarray(a, jnp.float64), net.params_)

            def loss(params):
                out, _, score = net._forward(params, net.state_, x,
                                             train=True, rng=rng,
                                             labels=labels)
                return jnp.mean(score)

            report = check_gradients(loss, params64, eps=1e-5,
                                     max_rel_error=2e-2)
            assert report["checked"] > 0
        finally:
            set_dtype_policy(DTypePolicy.f32())
            jax.config.update("jax_enable_x64", False)

    def test_fit_decreases_loss(self):
        from deeplearning4j_tpu.train.trainer import Trainer
        from deeplearning4j_tpu.data.dataset import DataSet
        rng = np.random.default_rng(2)
        net = _net(DropConnect(p=0.9))
        trainer = Trainer(net)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
        ds = DataSet(jnp.asarray(x), jnp.asarray(y))
        key = jax.random.key(0)
        losses = []
        for i in range(25):
            key, sub = jax.random.split(key)
            losses.append(float(trainer.fit_batch(ds, sub)))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])
