"""Expert-parallel MoE tests.

No reference anchor — the reference (pre-MoE era) ships DP only
(SURVEY.md §2.7); expert parallelism is beyond-parity TPU capability.
Tests mirror the strategy used for TP/PP/SP: sharded path must equal
the dense single-device oracle on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.unified import (
    init_moe_params, moe_ffn, moe_ffn_dense, shard_moe_params,
    _dispatch_tensors, _top_k_gates)
from deeplearning4j_tpu.parallel.mesh import make_mesh


D, H, E = 8, 16, 4


def _params(seed=0, dtype=jnp.float32):
    return init_moe_params(jax.random.key(seed), D, H, E, dtype)


class TestGatingDispatch:
    def test_top_k_weights_normalized(self):
        logits = jnp.asarray(np.random.default_rng(0).normal(size=(10, E)),
                             jnp.float32)
        w, idx = _top_k_gates(logits, 2)
        np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, atol=1e-6)
        assert np.all(np.asarray(idx) < E)
        # the two selected experts are distinct
        assert np.all(np.asarray(idx[:, 0] != idx[:, 1]))

    def test_capacity_positions_unique(self):
        """No two (token, slot) routings may share an (expert, position)
        capacity cell — including across gate slots."""
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(16, E)), jnp.float32)
        gates, idx = _top_k_gates(logits, 2)
        combine, dispatch = _dispatch_tensors(gates, idx, E, capacity=16)
        # each capacity cell used at most once
        cell_use = np.asarray(dispatch).sum(axis=0)        # [E, C]
        assert cell_use.max() <= 1.0
        # with ample capacity nothing is dropped: every token contributes
        # weight 1 total
        np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                                   1.0, atol=1e-5)

    def test_ranks_exact_in_bf16_policy(self):
        """Rank bookkeeping must be int even when gates are bf16 — a
        bf16 cumsum cannot represent ranks past 256 and tokens would
        collide in capacity cells."""
        n = 600   # > 256 tokens all routed to one expert
        logits = jnp.tile(jnp.asarray([[10.0, 0, 0, 0]], jnp.bfloat16),
                          (n, 1))
        gates, idx = _top_k_gates(logits, 1)
        combine, dispatch = _dispatch_tensors(gates, idx, E, capacity=n)
        cell_use = np.asarray(dispatch, np.float32).sum(axis=0)
        assert cell_use.max() <= 1.0          # no collisions
        assert float(np.asarray(dispatch, np.float32).sum()) == n

    def test_capacity_drops_over_limit(self):
        # all tokens route to expert 0 (logits force it)
        logits = jnp.tile(jnp.asarray([[10.0, 0, 0, 0]]), (8, 1))
        gates, idx = _top_k_gates(logits, 1)
        combine, dispatch = _dispatch_tensors(gates, idx, E, capacity=3)
        assert float(np.asarray(dispatch).sum()) == 3.0     # only 3 kept


class TestDenseOracle:
    def test_output_shape_and_finite(self):
        params = _params()
        x = jnp.asarray(np.random.default_rng(0).normal(size=(24, D)),
                        jnp.float32)
        y = moe_ffn_dense(params, x, top_k=2, capacity_factor=float(E))
        assert y.shape == (24, D)
        assert np.all(np.isfinite(np.asarray(y)))

    def test_matches_manual_expert_mix(self):
        """With ample capacity, each token's output must equal the
        gate-weighted sum of its top-k experts' FFNs."""
        params = _params(3)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(12, D)), jnp.float32)
        y = np.asarray(moe_ffn_dense(params, x, top_k=2,
                                     capacity_factor=float(E)))
        logits = np.asarray(x @ params["gate"])
        gates, idx = _top_k_gates(jnp.asarray(logits), 2)
        gates, idx = np.asarray(gates), np.asarray(idx)

        def expert(e, xi):
            h = jax.nn.gelu(xi @ params["w_in"][e] + params["b_in"][e])
            return np.asarray(h @ params["w_out"][e] + params["b_out"][e])

        for t in range(12):
            ref = sum(gates[t, s] * expert(idx[t, s], x[t]) for s in range(2))
            np.testing.assert_allclose(y[t], ref, atol=1e-5)


class TestExpertParallel:
    def test_sharded_matches_dense(self):
        params = _params(7)
        mesh = make_mesh(data=1, expert=4,
                         devices=jax.devices()[:4])
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(32, D)), jnp.float32)
        dense = moe_ffn_dense(params, x, top_k=2, capacity_factor=float(E))
        sharded_params = shard_moe_params(params, mesh)
        with mesh:
            ep = moe_ffn(sharded_params, x, mesh, top_k=2,
                         capacity_factor=float(E))
        np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                                   rtol=2e-4, atol=1e-5)

    def test_dp_x_ep_matches_dense(self):
        params = _params(9)
        mesh = make_mesh(data=2, expert=4, devices=jax.devices()[:8])
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(size=(48, D)), jnp.float32)
        dense = moe_ffn_dense(params, x, top_k=1, capacity_factor=float(E))
        sharded_params = shard_moe_params(params, mesh)
        with mesh:
            ep = moe_ffn(sharded_params, x, mesh, data_axis="data", top_k=1,
                         capacity_factor=float(E))
        np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                                   rtol=2e-4, atol=1e-5)

    def test_gradients_flow_through_all_to_all(self):
        params = _params(11)
        mesh = make_mesh(data=1, expert=4, devices=jax.devices()[:4])
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)
        sharded_params = shard_moe_params(params, mesh)

        def loss(p):
            y = moe_ffn(p, x, mesh, top_k=2, capacity_factor=float(E))
            return jnp.mean(y * y)

        with mesh:
            g = jax.jit(jax.grad(loss))(sharded_params)
        flat = jax.tree_util.tree_leaves(g)
        assert all(np.all(np.isfinite(np.asarray(l))) for l in flat)
        assert any(float(jnp.abs(l).max()) > 0 for l in flat)

    def test_validation_errors(self):
        params = _params()
        mesh = make_mesh(data=1, expert=8, devices=jax.devices()[:8])
        x = jnp.zeros((16, D))
        with pytest.raises(ValueError, match="not divisible"):
            with mesh:
                moe_ffn(params, x, mesh)   # E=4 experts on ep=8

    def test_mesh_without_expert_axis_falls_back_to_dense(self):
        params = _params()
        mesh = make_mesh(data=4, devices=jax.devices()[:4])
        x = jnp.asarray(np.random.default_rng(1).normal(size=(8, D)),
                        jnp.float32)
        with mesh:
            y = moe_ffn(params, x, mesh)
        ref = moe_ffn_dense(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)
