"""Fault-tolerance layer (ISSUE 4): atomic/verified checkpoints, exact
kill-and-resume, retry/backoff policy, and the deterministic fault-
injection harness across the trainer, parallel stack and device pipeline.

Acceptance pins:
- a run interrupted by an injected crash at step k, resumed via
  ``resume_from``, matches the uninterrupted run's per-step losses to
  1e-6 (with dropout in the net, so RNG-key capture is really proven);
- an injected truncated checkpoint is detected and SKIPPED (discovery
  falls back to the newest intact one) rather than loaded.
"""

import json
import os
import threading
import time
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.device_pipeline import DeviceFeeder
from deeplearning4j_tpu.data.iterators import (
    ListDataSetIterator, ResumableIterator)
from deeplearning4j_tpu.io.checkpoint import CheckpointListener
from deeplearning4j_tpu.io.model_serializer import (
    read_training_state, restore_model, write_model)
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, DropoutLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.listeners import CollectScoresListener
from deeplearning4j_tpu.obs.registry import (
    MetricsRegistry, get_registry, set_registry)
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.checkpoint import (
    AsyncCheckpointer, CheckpointCorruptError, is_valid_checkpoint,
    verify_checkpoint)
from deeplearning4j_tpu.resilience.faults import (
    FaultPlan, InjectedCrash, InjectedFault)
from deeplearning4j_tpu.resilience.retry import (
    RetryPolicy, TransientError, default_retryable, with_retries)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.trainer import Trainer


@pytest.fixture
def registry():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


@pytest.fixture(autouse=True)
def _no_ambient_fault_plan():
    """Every test starts and ends with no active fault plan."""
    faults.clear_fault_plan()
    yield
    faults.clear_fault_plan()


def _conf(seed=42, n_in=6, n_out=3):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DropoutLayer(dropout=0.8))   # resume must replay RNG too
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _data_iter(n=96, batch=16, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return ListDataSetIterator([DataSet(x[i:i + batch], y[i:i + batch])
                                for i in range(0, n, batch)])


# ================================================== durable checkpoint zips
def test_checkpoint_zip_has_manifest_and_verifies(tmp_path):
    net = MultiLayerNetwork(_conf()).init()
    path = str(tmp_path / "model.zip")
    net.save(path)
    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        manifest = json.loads(zf.read("manifest.json").decode())
    assert "manifest.json" in names and "trainingState.json" in names
    # every non-manifest entry is digest-covered
    assert set(manifest["entries"]) == names - {"manifest.json"}
    assert verify_checkpoint(path) == []
    assert is_valid_checkpoint(path)


def test_corrupt_checkpoint_detected_and_load_raises(tmp_path):
    net = MultiLayerNetwork(_conf()).init()
    path = str(tmp_path / "model.zip")
    net.save(path)
    # flip bytes INSIDE an entry's compressed stream (not just the tail)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xde\xad\xbe\xef")
    assert verify_checkpoint(path) != []
    with pytest.raises(CheckpointCorruptError):
        restore_model(path)


def test_truncated_zip_detected(tmp_path):
    net = MultiLayerNetwork(_conf()).init()
    path = str(tmp_path / "model.zip")
    net.save(path)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 64)
    problems = verify_checkpoint(path)
    assert problems, "truncated zip must not verify"
    with pytest.raises(CheckpointCorruptError):
        MultiLayerNetwork.load(path)


def test_atomic_write_preserves_previous_on_crash(tmp_path, registry):
    """An injected crash mid-save (inside the atomic region) leaves the
    previously-published checkpoint intact and no temp litter."""
    net = MultiLayerNetwork(_conf()).init()
    path = str(tmp_path / "model.zip")
    net.save(path)
    before = open(path, "rb").read()
    with faults.inject("checkpoint.write@0:crash"):
        with pytest.raises(InjectedCrash):
            net.save(path)
    assert open(path, "rb").read() == before
    assert [n for n in os.listdir(tmp_path) if ".tmp-" in n] == []
    assert is_valid_checkpoint(path)


def test_training_state_captured(tmp_path):
    net = MultiLayerNetwork(_conf()).init()
    ckpt = CheckpointListener(str(tmp_path / "mid"),
                              save_every_n_iterations=1)
    Trainer(net, listeners=[ckpt]).fit(_data_iter(), epochs=1)
    # a checkpoint written DURING fit captures the live post-split key
    mid = read_training_state(ckpt.last_checkpoint())
    assert mid["rng_key_data"], "mid-fit RNG key must be captured"
    # a save after a COMPLETED fit records counters but deliberately no
    # continuation key — the next fit() restarts from the seed
    path = str(tmp_path / "model.zip")
    net.save(path)
    state = read_training_state(path)
    assert state["iteration"] == 6 and state["epoch"] == 1
    assert state["epoch_batches"] == 0          # epoch boundary
    assert "rng_key_data" not in state
    assert state["dtype_policy"]["param_dtype"] == "float32"


# ============================================= checkpoint listener + index
def test_listener_rebuilds_index_and_prunes_across_restarts(tmp_path):
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    first = CheckpointListener(d, save_every_n_iterations=1, keep_last=5)
    for i in range(1, 4):
        first.iteration_done(net, i, 0, 0.5)
    assert len(first._saved) == 3
    # "restart": a fresh listener must rediscover the 3 prior checkpoints
    # from the directory (not trust its empty memory) and keep pruning
    second = CheckpointListener(d, save_every_n_iterations=1, keep_last=3)
    assert len(second._saved) == 3
    for i in range(4, 6):
        second.iteration_done(net, i, 0, 0.5)
    remaining = sorted(n for n in os.listdir(d) if n.endswith(".zip"))
    assert remaining == ["checkpoint_iter3_epoch0.zip",
                         "checkpoint_iter4_epoch0.zip",
                         "checkpoint_iter5_epoch0.zip"]
    index = json.load(open(os.path.join(d, "checkpoints.json")))
    assert [os.path.basename(p) for p in index["checkpoints"]] == remaining


def test_last_checkpoint_in_skips_corrupt_falls_back_to_intact(
        tmp_path, registry):
    """Acceptance: an injected truncated checkpoint is detected and
    skipped — discovery returns the newest INTACT one."""
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(d, save_every_n_iterations=1, keep_all=True)
    # newest checkpoint (iter2) gets torn by the fault plan post-publish
    with faults.inject("checkpoint.write@1:truncate:2000"):
        listener.iteration_done(net, 1, 0, 0.5)
        listener.iteration_done(net, 2, 0, 0.4)
    newest = os.path.join(d, "checkpoint_iter2_epoch0.zip")
    assert not is_valid_checkpoint(newest)
    picked = CheckpointListener.last_checkpoint_in(d)
    assert picked == os.path.join(d, "checkpoint_iter1_epoch0.zip")
    assert registry.counter(
        "tpudl_resilience_corrupt_checkpoints_total").value >= 1
    # unverified legacy behavior would have handed back the corrupt one
    assert CheckpointListener.last_checkpoint_in(d, verify=False) == newest
    # every checkpoint corrupt → None, not garbage
    with open(picked, "r+b") as f:
        f.truncate(100)
    assert CheckpointListener.last_checkpoint_in(d) is None


def test_last_checkpoint_in_survives_moved_directory(tmp_path):
    """A checkpoint dir copied/moved elsewhere has an index recording
    the OLD paths — discovery must rebase onto the new location instead
    of declaring every checkpoint missing."""
    import shutil
    old = str(tmp_path / "old")
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(old, save_every_n_iterations=1)
    listener.iteration_done(net, 1, 0, 0.5)
    listener.iteration_done(net, 2, 0, 0.4)
    new = str(tmp_path / "new")
    shutil.move(old, new)
    index = json.load(open(os.path.join(new, "checkpoints.json")))
    assert not any(os.path.exists(p) for p in index["checkpoints"])
    picked = CheckpointListener.last_checkpoint_in(new)
    assert picked == os.path.join(new, "checkpoint_iter2_epoch0.zip")
    # and resume actually works from the moved directory
    net2 = MultiLayerNetwork(_conf()).init()
    Trainer(net2).resume_state(new)


def test_last_checkpoint_in_survives_missing_index(tmp_path):
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(d, save_every_n_iterations=1)
    listener.iteration_done(net, 1, 0, 0.5)
    os.remove(os.path.join(d, "checkpoints.json"))
    assert CheckpointListener.last_checkpoint_in(d) == os.path.join(
        d, "checkpoint_iter1_epoch0.zip")


def test_background_checkpointer_writes_and_flushes(tmp_path):
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(d, save_every_n_iterations=1,
                                  background=True)
    try:
        for i in range(1, 4):
            listener.iteration_done(net, i, 0, 0.5)
        listener.flush()
        assert len([n for n in os.listdir(d) if n.endswith(".zip")]) == 3
        assert listener.last_checkpoint() == os.path.join(
            d, "checkpoint_iter3_epoch0.zip")
        assert is_valid_checkpoint(listener.last_checkpoint())
    finally:
        listener.close()


def test_background_save_failure_surfaces_on_flush(tmp_path):
    saver = AsyncCheckpointer()

    def boom():
        raise OSError("disk gone")

    saver.submit(boom)
    with pytest.raises(RuntimeError, match="background checkpoint save"):
        saver.flush()
    saver.close()


# ======================================================== kill-and-resume
def _run_uninterrupted(epochs=2):
    scores = CollectScoresListener()
    net = MultiLayerNetwork(_conf()).init()
    Trainer(net, listeners=[scores]).fit(_data_iter(), epochs=epochs)
    return net, scores.scores


def test_kill_and_resume_matches_uninterrupted_losses(tmp_path):
    """THE acceptance test: crash injected at step 7 of a 12-step run
    (mid-epoch 1, dropout active); resume via ``resume_from`` reproduces
    the uninterrupted per-step losses to 1e-6 and the final params."""
    net_a, losses_a = _run_uninterrupted(epochs=2)

    d = str(tmp_path)
    scores_b = CollectScoresListener()
    net_b = MultiLayerNetwork(_conf()).init()
    ckpt = CheckpointListener(d, save_every_n_iterations=1, keep_last=3)
    with faults.inject("trainer.step@7:crash"):
        with pytest.raises(InjectedCrash):
            Trainer(net_b, listeners=[scores_b, ckpt]).fit(
                ResumableIterator(_data_iter()), epochs=2)
    assert len(scores_b.scores) == 7            # steps 0..6 committed

    # "new process": fresh net + fresh iterator, resume from the dir
    scores_c = CollectScoresListener()
    net_c = MultiLayerNetwork(_conf()).init()
    trainer_c = Trainer(net_c, listeners=[scores_c])
    trainer_c.fit(ResumableIterator(_data_iter()), epochs=2, resume_from=d)

    assert len(scores_c.scores) == 5            # steps 7..11 only
    np.testing.assert_allclose(scores_b.scores + scores_c.scores, losses_a,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(net_c.params()),
                               np.asarray(net_a.params()), atol=1e-6)
    assert net_c.iteration == net_a.iteration
    assert net_c.epoch == net_a.epoch


def test_resume_skips_truncated_checkpoint(tmp_path):
    """Crash at step 7 with the LAST checkpoint torn: resume must fall
    back to the previous intact checkpoint and still converge to the
    uninterrupted trajectory (it replays step 6 exactly)."""
    _, losses_a = _run_uninterrupted(epochs=2)

    d = str(tmp_path)
    net_b = MultiLayerNetwork(_conf()).init()
    ckpt = CheckpointListener(d, save_every_n_iterations=1, keep_all=True)
    # checkpoints land at iters 1..6; the one named iter6 gets torn
    with faults.inject("trainer.step@7:crash; checkpoint.write@5:truncate:3000"):
        with pytest.raises(InjectedCrash):
            Trainer(net_b, listeners=[ckpt]).fit(
                ResumableIterator(_data_iter()), epochs=2)
    assert not is_valid_checkpoint(
        os.path.join(d, "checkpoint_iter6_epoch0.zip"))

    scores_c = CollectScoresListener()
    net_c = MultiLayerNetwork(_conf()).init()
    Trainer(net_c, listeners=[scores_c]).fit(
        ResumableIterator(_data_iter()), epochs=2, resume_from=d)
    assert len(scores_c.scores) == 6            # steps 6..11 replayed
    np.testing.assert_allclose(scores_c.scores, losses_a[6:], atol=1e-6)


def test_kill_and_resume_with_shuffling_iterator(tmp_path):
    """The 1e-6 contract must hold for shuffling pipelines too: the
    permutation derives from (seed, epoch), so the resumed run replays
    the interrupted epoch's exact batch order."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    rng = np.random.default_rng(3)
    x = rng.normal(size=(96, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 96)]

    def shuffled():
        return ResumableIterator(
            ArrayDataSetIterator(x, y, batch_size=16, shuffle=True, seed=13))

    scores_a = CollectScoresListener()
    net_a = MultiLayerNetwork(_conf()).init()
    Trainer(net_a, listeners=[scores_a]).fit(shuffled(), epochs=2)

    d = str(tmp_path)
    net_b = MultiLayerNetwork(_conf()).init()
    scores_b = CollectScoresListener()
    ckpt = CheckpointListener(d, save_every_n_iterations=1, keep_last=3)
    with faults.inject("trainer.step@8:crash"):    # mid-epoch 1
        with pytest.raises(InjectedCrash):
            Trainer(net_b, listeners=[scores_b, ckpt]).fit(shuffled(),
                                                           epochs=2)

    scores_c = CollectScoresListener()
    net_c = MultiLayerNetwork(_conf()).init()
    Trainer(net_c, listeners=[scores_c]).fit(shuffled(), epochs=2,
                                             resume_from=d)
    np.testing.assert_allclose(scores_b.scores + scores_c.scores,
                               scores_a.scores, atol=1e-6)


def test_last_checkpoint_in_ignores_stray_old_checkpoint_position(tmp_path):
    """A stray OLD checkpoint the index doesn't know about (backup
    restore, crashed prune) must not outrank newer indexed ones just
    because the directory scan appended it last."""
    import shutil
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(d, save_every_n_iterations=1, keep_last=2)
    for i in range(1, 6):
        listener.iteration_done(net, i, 0, 0.5)   # keeps iter4, iter5
    # a pruned-era checkpoint reappears from a backup, bypassing the index
    shutil.copy(os.path.join(d, "checkpoint_iter4_epoch0.zip"),
                os.path.join(d, "checkpoint_iter2_epoch0.zip"))
    assert CheckpointListener.last_checkpoint_in(d) == os.path.join(
        d, "checkpoint_iter5_epoch0.zip")


def test_completed_fit_restores_seed_rng_semantics():
    """Pre-resilience reproducibility baseline: after a COMPLETED fit,
    the next fit() derives its RNG from the seed again — two nets taking
    different fit-call paths to the same total epochs stay bitwise
    equal.  (A crash skips the reset, which is what makes resume exact.)"""
    net_a = MultiLayerNetwork(_conf()).init()
    Trainer(net_a).fit(_data_iter(), epochs=1)
    assert getattr(net_a, "_rng_key", None) is None
    Trainer(net_a).fit(_data_iter(), epochs=1)
    net_b = MultiLayerNetwork(_conf()).init()
    for _ in range(2):
        Trainer(net_b).fit(_data_iter(), epochs=1)
    np.testing.assert_array_equal(np.asarray(net_a.params()),
                                  np.asarray(net_b.params()))


def test_resume_from_epoch_boundary_checkpoint(tmp_path):
    _, losses_a = _run_uninterrupted(epochs=2)
    d = str(tmp_path)
    net_b = MultiLayerNetwork(_conf()).init()
    ckpt = CheckpointListener(d, save_every_n_epochs=1)
    Trainer(net_b, listeners=[ckpt]).fit(_data_iter(), epochs=1)

    scores_c = CollectScoresListener()
    net_c = MultiLayerNetwork(_conf()).init()
    # epoch-boundary resume needs no ResumableIterator (nothing to skip)
    Trainer(net_c, listeners=[scores_c]).fit(_data_iter(), epochs=2,
                                             resume_from=d)
    np.testing.assert_allclose(scores_c.scores, losses_a[6:], atol=1e-6)


def test_resume_requires_resumable_iterator_mid_epoch(tmp_path):
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    ckpt = CheckpointListener(d, save_every_n_iterations=1)
    with faults.inject("trainer.step@3:crash"):
        with pytest.raises(InjectedCrash):
            Trainer(net, listeners=[ckpt]).fit(
                ResumableIterator(_data_iter()), epochs=2)
    net2 = MultiLayerNetwork(_conf()).init()
    with pytest.raises(ValueError, match="mid-epoch"):
        Trainer(net2).fit(_data_iter(), epochs=2, resume_from=d)


def test_resume_from_empty_dir_raises(tmp_path):
    net = MultiLayerNetwork(_conf()).init()
    with pytest.raises(FileNotFoundError, match="no intact checkpoint"):
        Trainer(net).fit(_data_iter(), epochs=1,
                         resume_from=str(tmp_path))


# ============================================================ retry policy
def test_retry_policy_backoff_schedule_and_success(registry):
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientError("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.0)
    assert with_retries(flaky, policy=policy, site="t",
                        sleep=slept.append) == "ok"
    assert calls["n"] == 3
    np.testing.assert_allclose(slept, [0.1, 0.2])   # exponential
    assert registry.counter("tpudl_resilience_retries_total").value == 2
    assert registry.counter("tpudl_resilience_attempts_total").value == 3
    assert registry.counter("tpudl_resilience_giveups_total").value == 0


def test_retry_gives_up_after_max_attempts(registry):
    def always():
        raise TransientError("down")

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
    with pytest.raises(TransientError):
        with_retries(always, policy=policy, sleep=lambda s: None)
    assert registry.counter("tpudl_resilience_giveups_total").value == 1
    assert registry.counter("tpudl_resilience_attempts_total").value == 3


def test_retry_nonretryable_raises_immediately(registry):
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ValueError("config bug, not a flake")

    with pytest.raises(ValueError):
        with_retries(fatal, policy=RetryPolicy(max_attempts=5),
                     sleep=lambda s: None)
    assert calls["n"] == 1


def test_retry_deadline_stops_backoff():
    slept = []

    def always():
        raise TransientError("down")

    # 2nd delay (0.2) would overrun the 0.25s deadline → give up early
    policy = RetryPolicy(max_attempts=10, base_delay_s=0.2, jitter=0.0,
                         deadline_s=0.25)
    with pytest.raises(TransientError):
        with_retries(always, policy=policy, sleep=slept.append)
    assert len(slept) <= 1


def test_retryable_classification():
    assert default_retryable(TimeoutError())
    assert default_retryable(ConnectionResetError())
    assert default_retryable(TransientError("x"))
    assert default_retryable(InjectedFault("x"))
    assert not default_retryable(InjectedCrash("x"))   # process death
    assert not default_retryable(ValueError("x"))
    assert not default_retryable(FileNotFoundError(2, "gone"))


def test_jitter_is_deterministic_and_bounded():
    p = RetryPolicy(base_delay_s=0.1, jitter=0.5)
    d1 = p.delay_for(1, "site-a")
    assert d1 == p.delay_for(1, "site-a")       # reproducible
    assert 0.1 <= d1 <= 0.15
    assert p.delay_for(1, "site-b") != d1 or True   # spread (not pinned)


# ============================================================= fault plans
def test_fault_plan_parsing_and_env(monkeypatch):
    plan = FaultPlan.parse(
        "trainer.step@7:crash; dcn.exchange@2:error:0:3;"
        "feeder.stage@1:delay:0.25")
    kinds = {(r.site, r.action) for r in plan.rules}
    assert kinds == {("trainer.step", "crash"), ("dcn.exchange", "error"),
                     ("feeder.stage", "delay")}
    assert plan.rules[1].times == 3
    monkeypatch.setenv(faults.ENV_VAR, "trainer.step@5:crash")
    env_plan = FaultPlan.from_env()
    assert env_plan.rules[0].at == 5
    with pytest.raises(ValueError, match="bad fault rule"):
        FaultPlan.parse("nonsense")


def test_fault_plan_deterministic_indexing(registry):
    plan = FaultPlan.parse("s@2:error")
    plan.fire("s")          # 0
    plan.fire("s")          # 1
    with pytest.raises(InjectedFault):
        plan.fire("s")      # 2 → fires
    plan.fire("s")          # 3 → past the window
    # explicit index overrides the counter
    with pytest.raises(InjectedFault):
        plan.fire("s", index=2)
    assert registry.counter(
        "tpudl_resilience_faults_injected_total").value == 2


# =============================================== wired paths under faults
def test_feeder_retries_transient_stage_fault(registry):
    """One injected transient staging failure: the producer retries and
    every batch still arrives, in order."""
    it = _data_iter(n=64, batch=16)
    feeder = DeviceFeeder(bucketing=False,
                          retry_policy=RetryPolicy(max_attempts=2,
                                                   base_delay_s=0.0,
                                                   jitter=0.0))
    with faults.inject("feeder.stage@1:error"):
        fed = list(feeder.feed(it))
    assert [f.n_examples for f in fed] == [16, 16, 16, 16]
    assert registry.counter("tpudl_resilience_retries_total").value == 1


def test_feeder_persistent_fault_reraises_with_traceback():
    """Satellite: producer-thread failure re-raises on the consumer with
    the ORIGINAL traceback (pointing into stage), the queue drains, and
    the daemon thread exits."""
    import traceback
    it = _data_iter(n=64, batch=16)
    feeder = DeviceFeeder(bucketing=False,
                          retry_policy=RetryPolicy(max_attempts=2,
                                                   base_delay_s=0.0,
                                                   jitter=0.0))
    before = threading.active_count()
    with faults.inject("feeder.stage@1:error:0:8"):   # outlasts retries
        with pytest.raises(InjectedFault) as exc_info:
            list(feeder.feed(it))
    frames = traceback.extract_tb(exc_info.value.__traceback__)
    assert any("stage" in f.name for f in frames), (
        "original producer traceback lost")
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before, "feeder thread leaked"


def test_multislice_exchange_retries_injected_faults(registry):
    """Two slices over InProcessTransport with transient exchange faults:
    with_retries absorbs them, training completes, slices stay
    byte-identical, and the retry counters tick."""
    import jax
    from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
    conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(5)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    batch = DataSet(x, y)
    trainer = MultiSliceTrainer(
        net, n_slices=2, data_per_slice=1, devices=jax.devices()[:2],
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                 jitter=0.0))
    try:
        # each slice's first exchange attempt fails once (events 0 and 1
        # are the two slices' first calls), then a slow hop at event 4
        with faults.inject(
                "dcn.exchange@0:error:0:2; dcn.exchange@4:delay:0.05"):
            losses = [trainer.fit_batch(batch, jax.random.key(i))
                      for i in range(4)]
        assert trainer.max_param_divergence() == 0.0
        assert np.isfinite(losses).all()
        assert registry.counter("tpudl_resilience_retries_total").value >= 2
    finally:
        trainer.close()


def test_multislice_exchange_giveup_propagates():
    """A non-transient exchange failure (crash action) must NOT be
    retried — it propagates like real preemption."""
    import jax
    from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
    net = MultiLayerNetwork(_conf()).init()
    x, y = (np.zeros((8, 6), np.float32),
            np.eye(3, dtype=np.float32)[np.zeros(8, int)])
    trainer = MultiSliceTrainer(
        net, n_slices=2, data_per_slice=1, devices=jax.devices()[:2],
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                 jitter=0.0))
    try:
        with faults.inject("dcn.exchange@0:crash:0:99"):
            with pytest.raises(InjectedCrash):
                trainer.fit_batch(DataSet(x, y), jax.random.key(0))
    finally:
        trainer.close()


# ================================================================ launcher
def _cluster_workers():
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    import cluster_workers
    return cluster_workers


_CLUSTER_ENV = {"PYTHONPATH": os.path.dirname(os.path.abspath(__file__))
                + os.pathsep + os.environ.get("PYTHONPATH", "")}


def test_spawn_local_cluster_timeout_kills_gang_with_stderr(registry):
    """Satellite: a wedged gang member times the cluster out; ALL
    children are terminated-then-killed and the RuntimeError carries
    each child's stderr tail (jax swallows SIGTERM via its preemption
    notifier, so the kill fallback is load-bearing)."""
    from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster
    workers = _cluster_workers()
    t0 = time.monotonic()
    with pytest.raises(RuntimeError) as exc_info:
        spawn_local_cluster(workers.hang_worker, n_processes=1, port=13421,
                            timeout=6.0, extra_env=_CLUSTER_ENV)
    msg = str(exc_info.value)
    assert "timed out" in msg and "process 0" in msg
    assert "wedged on purpose" in msg, "child stderr tail missing"
    # terminate-then-kill bounded: no lingering 120s default wait
    assert time.monotonic() - t0 < 30.0


def test_spawn_local_cluster_retries_startup_flake(registry):
    """An injected transient failure on the first spawn attempt is
    retried on a shifted port; the cluster then comes up."""
    from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster
    workers = _cluster_workers()
    with faults.inject("launcher.spawn@0:error"):
        results = spawn_local_cluster(workers.trivial_worker,
                                      n_processes=1, port=13431,
                                      timeout=60.0, extra_env=_CLUSTER_ENV)
    assert results == [{"pid": 0, "n": 1}]
    assert registry.counter("tpudl_resilience_retries_total").value == 1


# ============================================ early-stopping durable saver
def test_local_file_saver_rejects_corrupt_best_model(tmp_path):
    from deeplearning4j_tpu.train.early_stopping import LocalFileModelSaver
    saver = LocalFileModelSaver(str(tmp_path))
    net = MultiLayerNetwork(_conf()).init()
    saver.save_best_model(net, 1.0)
    assert saver.get_best_model() is not None
    with open(saver.best_path, "r+b") as f:
        f.truncate(os.path.getsize(saver.best_path) - 128)
    with pytest.raises(CheckpointCorruptError):
        saver.get_best_model()


def test_write_model_snapshot_roundtrip(tmp_path):
    """A NetSnapshot (host copies) serializes identically to the live
    net — the background-save path's correctness contract."""
    from deeplearning4j_tpu.resilience.checkpoint import snapshot_net
    net = MultiLayerNetwork(_conf()).init()
    Trainer(net).fit(_data_iter(), epochs=1)
    live, snap = str(tmp_path / "live.zip"), str(tmp_path / "snap.zip")
    write_model(net, live)
    write_model(snapshot_net(net), snap)
    a, b = restore_model(live), restore_model(snap)
    np.testing.assert_array_equal(np.asarray(a.params()),
                                  np.asarray(b.params()))
    assert read_training_state(live) == read_training_state(snap)


# ================================================ process-death actions
def test_fault_plan_parses_kill_and_sigterm():
    plan = FaultPlan.parse("trainer.step@7:kill; dcn.exchange@2:sigterm")
    assert {(r.site, r.action) for r in plan.rules} == \
        {("trainer.step", "kill"), ("dcn.exchange", "sigterm")}


def test_kill_and_sigterm_actions_are_real_process_death():
    """``kill``/``sigterm`` are REAL signals, not Python exceptions: a
    process that fires them dies with the signal's rc — exactly what
    the ClusterSupervisor must classify and recover from.  SIGKILL in
    particular is uncatchable: no handler, no black box, no goodbye."""
    import signal as _signal
    import subprocess
    import sys as _sys
    code = ("from deeplearning4j_tpu.resilience import faults\n"
            "faults.install_fault_plan(faults.FaultPlan.parse('x@0:{a}'))\n"
            "faults.fire('x')\n"
            "print('survived')\n")
    for action, sig in (("kill", _signal.SIGKILL),
                        ("sigterm", _signal.SIGTERM)):
        proc = subprocess.run(
            [_sys.executable, "-c", code.format(a=action)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == -sig, (action, proc.returncode)
        assert "survived" not in proc.stdout


# ========================================= save_now vs background saves
def test_save_now_races_background_save_thread(tmp_path):
    """Satellite: the HealthMonitor's ``checkpoint`` action
    (``save_now``) can fire from another thread while a
    ``background=True`` periodic save is mid-flight.  The
    checkpoints.json index must never tear, keep-last-K must hold
    exactly (no double-removes, no orphans), and every indexed zip must
    verify."""
    d = str(tmp_path)
    net = MultiLayerNetwork(_conf()).init()
    listener = CheckpointListener(d, save_every_n_iterations=1,
                                  keep_last=3, background=True)
    errors: list = []

    def hammer():
        try:
            for i in range(1000, 1012):
                listener.save_now(net, iteration=i, epoch=0)
        except BaseException as e:       # surfaced to the main thread
            errors.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for i in range(1, 25):
            listener.iteration_done(net, i, 0, 0.5)
    finally:
        t.join(timeout=60)
        listener.flush()
        listener.close()
    assert not errors, errors
    index = json.load(open(os.path.join(d, "checkpoints.json")))
    saved = index["checkpoints"]
    assert len(saved) <= 3                       # keep-last-K honored
    zips = sorted(n for n in os.listdir(d) if n.endswith(".zip"))
    # no orphans, no phantoms: disk and index agree exactly
    assert sorted(os.path.basename(p) for p in saved) == zips
    for p in saved:
        assert is_valid_checkpoint(p), f"torn checkpoint {p} in index"
    # and the newest indexed checkpoint is loadable for resume
    picked = CheckpointListener.last_checkpoint_in(d)
    assert picked is not None
