"""tpudl.obs.slo (ISSUE 16): burn-rate SLOs + breach wiring.

Acceptance pins:
- burn-rate math on synthetic event streams with a fake clock: steady
  burn above threshold breaches; a burst the long window vetoes does
  not; a counter reset (process restart) discards history instead of
  breaching or reading as recovery;
- a breach does the full action set: ``tpudl_slo_*`` metrics, a
  flight-recorder dump with ``reason="slo:<name>"``, a ``/cluster``
  annotation, the ``on_breach`` callback, and ``breach_count()``;
- END TO END: an injected error burst (``faults.py``) against a served
  model breaches the availability SLO within one evaluation —
  ``tpudl_slo_burn_rate`` crosses the threshold, the flight dump
  lands, and ``DeployWatch(slo_monitor=...)`` rolls the deploy back.
"""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import flight_recorder, slo
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)
from deeplearning4j_tpu.obs.remote import ClusterStore
from deeplearning4j_tpu.online import DeployWatch
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.serve import ModelRegistry
from deeplearning4j_tpu.train import Adam


@pytest.fixture
def metrics():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


@pytest.fixture(autouse=True)
def _no_ambient_fault_plan():
    faults.clear_fault_plan()
    yield
    faults.clear_fault_plan()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


WINDOWS = (slo.BurnWindow("fast", 60.0, 300.0, 10.0),)


def _availability_monitor(metrics, clock, **kw):
    return slo.SLOMonitor([slo.AvailabilitySLO(target=0.99)],
                          registry=metrics, windows=WINDOWS,
                          clock=clock, **kw)


# ------------------------------------------------------- burn-rate math
def test_window_burn_math():
    # 5 bad over 1000 total against a 1% budget: burn 0.5x
    snaps = [(0.0, 0.0, 0.0), (100.0, 5.0, 1000.0)]
    burn = slo.SLOMonitor._window_burn(snaps, 100.0, 100.0, 0.01)
    assert burn == pytest.approx(0.5)
    # bad fraction exactly at budget burns at 1.0x (sustainable)
    snaps = [(0.0, 0.0, 0.0), (100.0, 10.0, 1000.0)]
    assert slo.SLOMonitor._window_burn(snaps, 100.0, 100.0, 0.01) \
        == pytest.approx(1.0)
    # one snapshot / zero traffic: no verdict, not zero
    assert slo.SLOMonitor._window_burn([(0.0, 0.0, 0.0)], 0, 60, 0.01) is None
    assert slo.SLOMonitor._window_burn(
        [(0.0, 1.0, 10.0), (10.0, 1.0, 10.0)], 10, 60, 0.01) is None


def test_steady_burn_breaches_on_both_windows(metrics):
    clock = FakeClock()
    requests = metrics.labeled_counter("tpudl_serve_requests_total")
    mon = _availability_monitor(metrics, clock)
    for _ in range(3):                    # 90% errors vs a 1% budget
        requests.inc(9, status="error")
        requests.inc(1, status="ok")
        mon.evaluate_once()
        clock.advance(10.0)
    assert mon.breach_count() == 1        # transition fires exactly once
    status = mon.status()["availability"]
    assert not status.healthy
    assert status.burn_rate > WINDOWS[0].threshold
    assert status.budget_remaining == 0.0
    # the published family crossed with it
    burn_g = metrics.labeled_gauge("tpudl_slo_burn_rate",
                                   label_names=("slo",))
    assert burn_g.labeled_value(slo="availability") > WINDOWS[0].threshold
    healthy_g = metrics.labeled_gauge("tpudl_slo_healthy",
                                      label_names=("slo",))
    assert healthy_g.labeled_value(slo="availability") == 0.0
    breaches = metrics.labeled_counter("tpudl_slo_breaches_total",
                                       label_names=("slo",))
    assert breaches.labeled_value(slo="availability") == 1
    assert metrics.counter("tpudl_slo_evaluations_total").value == 3


def test_burst_is_vetoed_by_the_long_window(metrics):
    # an hour of sustainable traffic, then ONE bursty tick: the short
    # window spikes past threshold but the long window still sees a
    # sub-threshold average — no page (the whole point of the pairing)
    clock = FakeClock()
    requests = metrics.labeled_counter("tpudl_serve_requests_total")
    mon = slo.SLOMonitor(
        [slo.AvailabilitySLO(target=0.99)], registry=metrics,
        windows=(slo.BurnWindow("fast", 60.0, 600.0, 5.0),),
        clock=clock)
    for _ in range(61):                   # 1% errors: burn 1.0x
        requests.inc(1, status="error")
        requests.inc(99, status="ok")
        mon.evaluate_once()
        clock.advance(10.0)
    requests.inc(30, status="error")      # the burst tick
    requests.inc(70, status="ok")
    statuses = mon.evaluate_once()
    st = statuses["availability"]
    assert st.healthy and mon.breach_count() == 0
    assert st.burn_rate > 5.0             # the short window DID spike


def test_breach_rearms_after_the_burn_clears(metrics):
    clock = FakeClock()
    requests = metrics.labeled_counter("tpudl_serve_requests_total")
    mon = _availability_monitor(metrics, clock)
    for _ in range(2):
        requests.inc(9, status="error")
        requests.inc(1, status="ok")
        mon.evaluate_once()
        clock.advance(10.0)
    assert mon.breach_count() == 1
    # quiet, clean traffic until both windows roll past the burst
    for _ in range(40):
        requests.inc(100, status="ok")
        mon.evaluate_once()
        clock.advance(10.0)
    status = mon.status()["availability"]
    assert status.healthy                 # re-armed
    assert mon.breach_count() == 1        # no double-fire on the way out
    healthy_g = metrics.labeled_gauge("tpudl_slo_healthy",
                                      label_names=("slo",))
    assert healthy_g.labeled_value(slo="availability") == 1.0


def test_counter_reset_discards_history_instead_of_breaching(metrics):
    # a restarted serving process re-zeroes its counters: the monitor
    # must drop pre-reset snapshots, not diff across the restart
    clock = FakeClock()
    reg1 = MetricsRegistry()
    reg1.labeled_counter("tpudl_serve_requests_total").inc(
        50, status="error")
    reg1.labeled_counter("tpudl_serve_requests_total").inc(
        950, status="ok")
    mon = slo.SLOMonitor([slo.AvailabilitySLO(target=0.99)],
                         registry=reg1, windows=WINDOWS, clock=clock)
    mon.evaluate_once()
    clock.advance(10.0)
    # restart: fresh registry, tiny clean totals (bad 50 → 0)
    reg2 = MetricsRegistry()
    reg2.labeled_counter("tpudl_serve_requests_total").inc(
        10, status="ok")
    mon.registry = reg2
    mon.evaluate_once()                   # reset detected, history cleared
    clock.advance(10.0)
    reg2.labeled_counter("tpudl_serve_requests_total").inc(
        90, status="ok")
    statuses = mon.evaluate_once()
    st = statuses["availability"]
    assert mon.breach_count() == 0
    assert st.healthy
    assert st.burn_rate == pytest.approx(0.0)   # only post-reset deltas
    assert st.budget_remaining == pytest.approx(1.0)


# ------------------------------------------------------- objective math
def test_latency_slo_counts_from_bucket_edges(metrics):
    h = metrics.histogram("tpudl_serve_latency_seconds")
    for _ in range(97):
        h.observe(0.01)
    for _ in range(3):
        h.observe(2.0)                    # above the 0.5s objective
    objective = slo.LatencySLO(target=0.99, threshold_s=0.5)
    bad, total = objective.counts(metrics)
    assert total == 100 and bad == 3


def test_freshness_slo_counts_stale_workers(metrics):
    g = metrics.labeled_gauge("tpudl_cluster_worker_last_seen_time",
                              label_names=("worker",))
    now = 1000.0
    g.set(now - 5.0, worker="w0")
    g.set(now - 300.0, worker="w1")       # silent for 5 minutes
    objective = slo.FreshnessSLO(max_age_s=60.0, wall_clock=lambda: now)
    bad, total = objective.counts(metrics)
    assert (bad, total) == (1.0, 2.0)
    assert objective.cumulative is False


def test_slo_counts_none_when_metric_absent(metrics):
    for objective in slo.default_slos():
        assert objective.counts(MetricsRegistry()) is None
    # and an evaluation over an empty registry stays healthy
    mon = slo.SLOMonitor(registry=MetricsRegistry(),
                         windows=WINDOWS, clock=FakeClock())
    statuses = mon.evaluate_once()
    assert all(st.healthy for st in statuses.values())


def test_slo_target_validation():
    with pytest.raises(ValueError):
        slo.AvailabilitySLO(target=1.0)
    with pytest.raises(ValueError):
        slo.SLOMonitor([slo.AvailabilitySLO(), slo.AvailabilitySLO()],
                       registry=MetricsRegistry())


# ------------------------------------------------------- breach actions
def test_breach_fires_dump_annotation_and_callback(tmp_path, metrics):
    clock = FakeClock()
    requests = metrics.labeled_counter("tpudl_serve_requests_total")
    cluster = ClusterStore()
    events = []
    dump_path = str(tmp_path / "slo_flight.jsonl")
    mon = _availability_monitor(metrics, clock, cluster=cluster,
                                dump_path=dump_path,
                                on_breach=events.append)
    for _ in range(2):
        requests.inc(9, status="error")
        requests.inc(1, status="ok")
        mon.evaluate_once()
        clock.advance(10.0)
    assert len(events) == 1
    event = events[0]
    assert event.slo == "availability"
    assert event.burn_rate > WINDOWS[0].threshold
    assert "fast" in event.windows
    # flight dump with the slo: reason landed at the configured path
    assert os.path.exists(dump_path)
    lines = flight_recorder.read_dump(dump_path)
    header = next(l for l in lines if l.get("type") == "header")
    assert header["reason"] == "slo:availability"
    assert "burn rate" in header["detail"]["message"]
    # /cluster dashboard annotation
    notes = cluster.summary()["annotations"]
    assert any(n["kind"] == "slo_breach" and n["slo"] == "availability"
               for n in notes)
    assert mon.breach_count("availability") == 1
    assert mon.breach_count("latency_p99_500ms") == 0


def test_on_breach_exceptions_do_not_kill_the_evaluator(metrics):
    clock = FakeClock()
    requests = metrics.labeled_counter("tpudl_serve_requests_total")

    def boom(event):
        raise RuntimeError("pager down")

    mon = _availability_monitor(metrics, clock, on_breach=boom)
    for _ in range(2):
        requests.inc(9, status="error")
        requests.inc(1, status="ok")
        mon.evaluate_once()               # must not raise
        clock.advance(10.0)
    assert mon.breach_count() == 1


def test_background_evaluator_thread_starts_and_joins(metrics):
    metrics.labeled_counter("tpudl_serve_requests_total").inc(
        10, status="ok")
    mon = slo.SLOMonitor([slo.AvailabilitySLO(target=0.99)],
                         registry=metrics, windows=WINDOWS, poll_s=0.01)
    with mon:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if metrics.counter("tpudl_slo_evaluations_total").value >= 2:
                break
            time.sleep(0.01)
    assert metrics.counter("tpudl_slo_evaluations_total").value >= 2
    assert mon._thread is None            # close() joined it
    mon.close()                           # idempotent


# ----------------------------------------------------------- end to end
N_IN, N_OUT = 6, 3


def _conf(seed=42):
    return (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="identity",
                               loss="mse"))
            .set_input_type(InputType.feed_forward(N_IN)).build())


def _trained_zip(tmp_path, name, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(64, N_IN)).astype(np.float32)
    y = rng.normal(size=(64, N_OUT)).astype(np.float32)
    net = MultiLayerNetwork(_conf(seed)).init()
    net.fit(ListDataSetIterator([DataSet(x[i:i + 16], y[i:i + 16])
                                 for i in range(0, 64, 16)]), epochs=1)
    path = str(tmp_path / name)
    net.save(path)
    return path


def test_injected_error_burst_breaches_slo_and_rolls_back(tmp_path,
                                                          metrics):
    """The ISSUE 16 end-to-end pin: a deployed model serves clean
    traffic, then a faults.py error burst drives the availability
    budget — one SLOMonitor evaluation breaches, the flight dump
    lands with reason="slo:availability", and DeployWatch's rollback
    path restores the previous version naming the breach."""
    v1 = _trained_zip(tmp_path, "v1.zip", seed=7)
    v2 = _trained_zip(tmp_path, "v2.zip", seed=8)
    dump_path = str(tmp_path / "slo_flight.jsonl")
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    mon = slo.SLOMonitor(
        [slo.AvailabilitySLO(target=0.999)],
        windows=(slo.BurnWindow("fast", 300.0, 3600.0, 14.4),),
        dump_path=dump_path)
    x = np.zeros((1, N_IN), np.float32)
    try:
        registry.deploy("m", v1)
        registry.deploy("m", v2)                 # the suspect deploy
        for _ in range(4):                       # clean baseline traffic
            registry.predict("m", x)
        mon.evaluate_once()                      # healthy first snapshot
        assert mon.status()["availability"].healthy
        # the burst: every dispatch for the next 9 events raises inside
        # the engine and takes the REAL per-request error path
        with faults.inject("serve.dispatch@0:error:0:9"):
            for _ in range(9):
                with pytest.raises(faults.InjectedFault):
                    registry.predict("m", x)
        watch = DeployWatch(registry, "m", window_s=10.0, poll_s=0.02,
                            min_requests=10_000,      # only the SLO path
                            error_rate_max=1.0,
                            slo_monitor=mon)
        verdict = watch.run()
        assert verdict["rolled_back"]
        assert "SLO breach" in verdict["reason"]
        assert "availability" in verdict["reason"]
        assert registry.get("m").version == 3    # v1's zip, new version
        assert registry.get("m").path == v1
        assert metrics.counter("tpudl_online_rollbacks_total").value == 1
        # the breach crossed in the published burn-rate family
        burn = metrics.labeled_gauge(
            "tpudl_slo_burn_rate",
            label_names=("slo",)).labeled_value(slo="availability")
        assert burn > 14.4
        assert mon.breach_count("availability") == 1
        # and the black-box dump landed with the slo: reason
        lines = flight_recorder.read_dump(dump_path)
        header = next(l for l in lines if l.get("type") == "header")
        assert header["reason"] == "slo:availability"
        assert any(l.get("kind") == "slo_breach" for l in lines
                   if l.get("type") == "event")
    finally:
        registry.close()
