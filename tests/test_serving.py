"""tpudl.serve — inference engine, model registry, HTTP server.

Acceptance (ISSUE 5): dynamic-batched outputs match per-request outputs
to 1e-6 under ragged shapes with ≤1 compile per bucket; hot-swap during
concurrent traffic loses zero in-flight requests; a truncated checkpoint
is refused at deploy and the previous version keeps serving; queue
saturation sheds with ``Overloaded`` (bounded memory) and increments
``tpudl_serve_shed_total``.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.checkpoint import CheckpointCorruptError
from deeplearning4j_tpu.serve import (DeadlineExceeded, InferenceEngine,
                                      ModelRegistry, ModelServer, Overloaded)
from deeplearning4j_tpu.serve.server import error_status
from deeplearning4j_tpu.train import Sgd

N_IN, N_OUT = 8, 4


def _net(seed=11):
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder()
        .seed(seed).updater(Sgd(0.1)).weight_init("xavier").list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=N_OUT, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(N_IN))
        .build()).init()


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, N_IN)).astype(np.float32)


@pytest.fixture
def metrics():
    """Isolated process-wide registry per test."""
    prev = set_registry(MetricsRegistry())
    yield get_registry()
    set_registry(prev)


class _BlockingModel:
    """Fallback-path model whose forward blocks on an event — the
    deterministic way to fill the engine queue."""

    def __init__(self):
        self.release = threading.Event()

    def output(self, x):
        self.release.wait(timeout=30)
        return np.zeros((x.shape[0], 2), np.float32)


# ---------------------------------------------------------------- batching
def test_size_flush_beats_deadline(metrics):
    net = _net(21)
    x = _data(16, 1)
    with InferenceEngine(net, name="sz", max_batch=4, max_latency_ms=5000,
                         queue_limit=32) as eng:
        eng.predict(x[:4], timeout_s=60)   # compile bucket 4 up front
        before = metrics.counter("tpudl_serve_batches_total").value
        t0 = time.perf_counter()
        futures = [eng.submit(x[i:i + 1]) for i in range(4)]
        for f in futures:
            f.result(timeout=60)
        elapsed = time.perf_counter() - t0
        # 4 rows hit max_batch → flushed long before the 5s deadline
        assert elapsed < 2.0
        assert metrics.counter("tpudl_serve_batches_total").value \
            == before + 1
        assert metrics.gauge("tpudl_serve_batch_size").value == 4


def test_deadline_flush_for_partial_batch(metrics):
    net = _net(22)
    x = _data(4, 2)
    with InferenceEngine(net, name="dl", max_batch=64, max_latency_ms=150,
                         queue_limit=32) as eng:
        eng.predict(x[:1], timeout_s=60)   # compile bucket 1 up front
        t0 = time.perf_counter()
        out = eng.submit(x[:1]).result(timeout=60)
        elapsed = time.perf_counter() - t0
        # nothing else arrived: the batch waited out the 150ms deadline
        assert elapsed >= 0.1
        assert out.shape == (1, N_OUT)
        assert metrics.labeled_counter(
            "tpudl_serve_requests_total").labeled_value(status="ok") >= 2


def test_ragged_batched_outputs_match_per_request(metrics):
    """Mixed-size concurrent traffic through sticky buckets: every
    request's rows equal the unbatched forward to 1e-6, with at most
    one compile per bucket."""
    net = _net(23)
    x = _data(48, 3)
    expected = np.asarray(net.output(x))
    sizes = [1, 3, 2, 4, 3, 5, 2, 4, 1, 6, 3, 2]      # sums to 36
    with InferenceEngine(net, name="rb", max_batch=8, max_latency_ms=10,
                         queue_limit=64, buckets=(4, 8)) as eng:
        futures, offset = [], 0
        for n in sizes:
            futures.append((offset, n, eng.submit(x[offset:offset + n])))
            offset += n
        for off, n, fut in futures:
            got = fut.result(timeout=60)
            assert got.shape == (n, N_OUT)
            np.testing.assert_allclose(got, expected[off:off + n],
                                       rtol=1e-6, atol=1e-6)
        # rows per dispatch never exceed max_batch → only buckets {4, 8}
        # were used → at most one XLA program per bucket
        assert set(eng.buckets) == {4, 8}
        assert eng.compiled_programs <= 2
        assert metrics.counter("tpudl_serve_recompiles_total").value <= 2


def test_caller_masks_ride_along(metrics):
    """Per-request feature masks concatenate and bucket-pad with the
    features; requests without a mask get all-ones rows."""
    net = _net(24)
    x = _data(8, 4)
    mask = np.ones((2,), np.float32)
    with InferenceEngine(net, name="mk", max_batch=8, max_latency_ms=20,
                         queue_limit=16) as eng:
        f1 = eng.submit(x[:2], mask=mask)
        f2 = eng.submit(x[2:5])                      # no mask
        out1, out2 = f1.result(timeout=60), f2.result(timeout=60)
    np.testing.assert_allclose(out1, np.asarray(net.output(x[:2])),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out2, np.asarray(net.output(x[2:5])),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------ load shedding
def test_shed_on_full_queue(metrics):
    model = _BlockingModel()
    eng = InferenceEngine(model, name="shed", max_batch=1, max_latency_ms=1,
                          queue_limit=2)
    try:
        first = eng.submit(np.zeros((1, 4), np.float32))
        time.sleep(0.2)        # worker picks up `first`, blocks in forward
        held = [eng.submit(np.zeros((1, 4), np.float32)) for _ in range(2)]
        with pytest.raises(Overloaded):
            eng.submit(np.zeros((1, 4), np.float32))
        assert metrics.counter("tpudl_serve_shed_total").value == 1
        assert metrics.labeled_counter(
            "tpudl_serve_requests_total").labeled_value(status="shed") == 1
        model.release.set()
        # bounded queue, zero stranded futures: everything held resolves
        assert first.result(timeout=30).shape == (1, 2)
        for f in held:
            assert f.result(timeout=30).shape == (1, 2)
    finally:
        model.release.set()
        eng.shutdown()


def test_request_deadline_cancellation(metrics):
    model = _BlockingModel()
    eng = InferenceEngine(model, name="ddl", max_batch=1, max_latency_ms=1,
                          queue_limit=8)
    try:
        blocked = eng.submit(np.zeros((1, 4), np.float32))
        time.sleep(0.1)
        doomed = eng.submit(np.zeros((1, 4), np.float32), deadline_ms=50)
        time.sleep(0.2)        # deadline passes while the worker is busy
        model.release.set()
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=30)
        blocked.result(timeout=30)
        assert metrics.labeled_counter(
            "tpudl_serve_requests_total").labeled_value(status="expired") == 1
    finally:
        model.release.set()
        eng.shutdown()


def test_worker_exception_propagates_to_future(metrics):
    class Exploding:
        def output(self, x):
            raise ValueError("boom")

    eng = InferenceEngine(Exploding(), name="ex", max_batch=2,
                          max_latency_ms=1, queue_limit=8)
    try:
        fut = eng.submit(np.zeros((1, 4), np.float32))
        with pytest.raises(ValueError, match="boom"):
            fut.result(timeout=30)
        assert metrics.labeled_counter(
            "tpudl_serve_requests_total").labeled_value(status="error") == 1
        # the worker survived: a second request still gets an answer
        with pytest.raises(ValueError, match="boom"):
            eng.submit(np.zeros((1, 4), np.float32)).result(timeout=30)
    finally:
        eng.shutdown()


# ------------------------------------------------------------- registry
def test_hot_swap_under_concurrent_load(tmp_path, metrics):
    """Deploy v2 while clients hammer v1: zero dropped requests, every
    response is a valid output of exactly one of the two versions, and
    the version gauge flips."""
    net1, net2 = _net(31), _net(32)
    x = _data(16, 5)
    exp1 = np.asarray(net1.output(x))
    exp2 = np.asarray(net2.output(x))
    p1, p2 = str(tmp_path / "v1.zip"), str(tmp_path / "v2.zip")
    net1.save(p1)
    net2.save(p2)

    registry = ModelRegistry(max_batch=8, max_latency_ms=2, queue_limit=512)
    registry.deploy("m", p1)
    assert metrics.labeled_gauge(
        "tpudl_serve_model_version").labeled_value(model="m") == 1

    errors: list = []
    results: list = []
    stop = threading.Event()

    def client(cid):
        rng = np.random.default_rng(cid)
        count = 0
        while not (stop.is_set() and count >= 20):
            i = int(rng.integers(0, x.shape[0]))
            try:
                out = registry.predict("m", x[i:i + 1], timeout_s=30)
                results.append((i, np.asarray(out)[0]))
            except BaseException as e:   # noqa: BLE001 — test collects all
                errors.append(e)
            count += 1
            if count > 500:
                break

    threads = [threading.Thread(target=client, args=(c,)) for c in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    registry.deploy("m", p2)          # hot swap mid-traffic
    stop.set()
    for t in threads:
        t.join(timeout=60)

    assert not errors, errors[:3]
    assert len(results) >= 120        # clients really ran
    for i, row in results:
        ok1 = np.allclose(row, exp1[i], rtol=1e-5, atol=1e-5)
        ok2 = np.allclose(row, exp2[i], rtol=1e-5, atol=1e-5)
        assert ok1 or ok2, f"garbled response for row {i}"
    assert registry.get("m").version == 2
    assert metrics.labeled_gauge(
        "tpudl_serve_model_version").labeled_value(model="m") == 2
    assert registry.ready()
    registry.close()


def test_corrupt_checkpoint_deploy_refused(tmp_path, metrics):
    """FaultPlan-truncated zip is refused at deploy; v1 keeps serving."""
    net1, net2 = _net(41), _net(42)
    x = _data(4, 6)
    p1, p2 = str(tmp_path / "v1.zip"), str(tmp_path / "v2.zip")
    net1.save(p1)
    with faults.inject("checkpoint.write@0:truncate:200"):
        net2.save(p2)                 # published, then torn on disk

    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p1)
    with pytest.raises(CheckpointCorruptError):
        registry.deploy("m", p2)
    entry = registry.get("m")
    assert entry.version == 1 and entry.status == "serving"
    assert registry.ready()
    out = registry.predict("m", x[:2], timeout_s=30)
    np.testing.assert_allclose(out, np.asarray(net1.output(x[:2])),
                               rtol=1e-5, atol=1e-6)
    assert metrics.labeled_gauge(
        "tpudl_serve_model_version").labeled_value(model="m") == 1
    registry.close()


def test_rollback_redeploys_previous_zip(tmp_path, metrics):
    net1, net2 = _net(51), _net(52)
    x = _data(4, 7)
    p1, p2 = str(tmp_path / "v1.zip"), str(tmp_path / "v2.zip")
    net1.save(p1)
    net2.save(p2)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p1)
    registry.deploy("m", p2)
    rolled = registry.rollback("m")
    assert rolled.version == 3 and rolled.path == p1
    out = registry.predict("m", x[:2], timeout_s=30)
    np.testing.assert_allclose(out, np.asarray(net1.output(x[:2])),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(KeyError):
        registry.get("nope")
    registry.close()


def test_swap_reuses_compiled_forward(tmp_path, metrics):
    """Same-architecture hot swap costs zero recompiles: both versions
    hit the step-cached forward keyed by (config sha, dtype policy)."""
    net = _net(61)
    p1, p2 = str(tmp_path / "v1.zip"), str(tmp_path / "v2.zip")
    net.save(p1)
    it = ArrayDataSetIterator(_data(32, 8),
                              np.eye(N_OUT, dtype=np.float32)[
                                  np.random.default_rng(0).integers(
                                      0, N_OUT, 32)], 16)
    net.fit(it, epochs=1)             # v2 = same config, moved weights
    net.save(p2)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p1)
    x = _data(4, 9)
    registry.predict("m", x, timeout_s=30)        # compile bucket 4
    compiles_before = registry.get("m").engine.compiled_programs
    registry.deploy("m", p2)
    out2 = registry.predict("m", x, timeout_s=30)
    assert registry.get("m").engine.compiled_programs == compiles_before
    assert metrics.counter("tpudl_serve_recompiles_total").value \
        == compiles_before
    np.testing.assert_allclose(
        out2, np.asarray(
            MultiLayerNetwork.load(p2, load_updater=False).output(x)),
        rtol=1e-5, atol=1e-6)
    registry.close()


# ----------------------------------------------------------- HTTP server
def test_http_endpoints(tmp_path, metrics):
    net = _net(71)
    x = _data(4, 10)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("mnist", p)
    with ModelServer(registry) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)

        def req(method, path, body=None):
            conn.request(method, path, body=body)
            r = conn.getresponse()
            return r.status, json.loads(r.read().decode())

        status, body = req("GET", "/healthz")
        assert status == 200 and body["status"] == "ok"

        status, body = req("GET", "/v1/models")
        assert status == 200
        assert body["models"][0]["name"] == "mnist"
        assert body["models"][0]["version"] == 1

        status, body = req("GET", "/v1/models/mnist")
        assert status == 200 and body["status"] == "serving"

        payload = json.dumps({"instances": x[:2].tolist()})
        status, body = req("POST", "/v1/models/mnist:predict", payload)
        assert status == 200 and body["model_version"] == 1
        np.testing.assert_allclose(np.asarray(body["predictions"],
                                              np.float32),
                                   np.asarray(net.output(x[:2])),
                                   rtol=1e-4, atol=1e-5)

        status, body = req("POST", "/v1/models/nope:predict", payload)
        assert status == 404

        status, body = req("POST", "/v1/models/mnist:predict", "{broken")
        assert status == 400
        status, body = req("POST", "/v1/models/mnist:predict",
                           json.dumps({"inputs": [1]}))
        assert status == 400

        # /metrics is the same scrape surface the dashboard exposes,
        # labeled serve series included
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        text = r.read().decode()
        assert r.status == 200
        assert 'tpudl_serve_requests_total{status="ok"}' in text
        assert 'tpudl_serve_model_version{model="mnist"} 1' in text
    registry.close()


def test_healthz_503_while_swap_in_flight(tmp_path, metrics):
    net = _net(72)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p)
    with ModelServer(registry) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        with registry._swap():        # the deploy-time readiness window
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 503
            assert json.loads(r.read())["status"] == "swapping"
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
    registry.close()


def test_error_status_mapping():
    assert error_status(Overloaded("x")) == 429
    assert error_status(DeadlineExceeded("x")) == 504
    assert error_status(TimeoutError()) == 504
    assert error_status(KeyError("m")) == 404
    assert error_status(ValueError("bad")) == 400
    assert error_status(RuntimeError("other")) == 500


# ----------------------------------------------------- ParallelInference
def test_parallel_inference_shim_shed_mode(metrics):
    from deeplearning4j_tpu.parallel import ParallelInference
    model = _BlockingModel()
    pi = ParallelInference(model, batch_limit=1, queue_limit=1,
                           timeout_ms=1, shed=True)
    try:
        first = pi.output_async(np.zeros((1, 4), np.float32))
        time.sleep(0.2)
        held = pi.output_async(np.zeros((1, 4), np.float32))
        with pytest.raises(Overloaded):
            pi.output_async(np.zeros((1, 4), np.float32))
        model.release.set()
        first.result(timeout=30)
        held.result(timeout=30)
        assert pi.engine.queue_limit == 1
    finally:
        model.release.set()
        pi.shutdown()


def test_parallel_inference_shim_propagates_submit_side_errors(metrics):
    class Exploding:
        def output(self, x):
            raise RuntimeError("forward failed")

    with pytest.raises(RuntimeError, match="forward failed"):
        from deeplearning4j_tpu.parallel import ParallelInference
        with ParallelInference(Exploding(), batch_limit=4,
                               timeout_ms=1) as pi:
            pi.output(np.zeros((1, 4), np.float32))


def test_trace_id_propagates_to_span_ring_and_response(tmp_path, metrics):
    """X-Trace-Id rides the whole path: request header → engine serve
    span + flight-recorder ring → response header (echoed on errors
    too); absent header → a trace id is minted and echoed."""
    from deeplearning4j_tpu.obs import flight_recorder, tracing
    net = _net(73)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("mnist", p)
    flight_recorder.get_recorder().clear()
    tracer = tracing.Tracer(enabled=True)
    with tracing.use_tracer(tracer), ModelServer(registry) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        payload = json.dumps({"instances": _data(2, 1).tolist()})
        conn.request("POST", "/v1/models/mnist:predict", body=payload,
                     headers={"X-Trace-Id": "req-abc-123"})
        r = conn.getresponse()
        r.read()
        assert r.status == 200
        assert r.getheader("X-Trace-Id") == "req-abc-123"

        # errors echo the id too
        conn.request("POST", "/v1/models/nope:predict", body=payload,
                     headers={"X-Trace-Id": "req-err-9"})
        r = conn.getresponse()
        r.read()
        assert r.status == 404
        assert r.getheader("X-Trace-Id") == "req-err-9"

        # even the pre-dispatch 404 (path not a :predict route) echoes it
        conn.request("POST", "/v1/other", body=payload,
                     headers={"X-Trace-Id": "req-err-10"})
        r = conn.getresponse()
        r.read()
        assert r.status == 404
        assert r.getheader("X-Trace-Id") == "req-err-10"

        # no header → minted and echoed
        conn.request("POST", "/v1/models/mnist:predict", body=payload)
        r = conn.getresponse()
        r.read()
        minted = r.getheader("X-Trace-Id")
        assert minted and len(minted) >= 8
    registry.close()
    serve_spans = [s for s in tracer.spans if s.name == "serve"]
    assert any("req-abc-123" in s.attributes.get("trace_ids", "")
               for s in serve_spans)
    ring = flight_recorder.get_recorder().events()
    serve_events = [e for e in ring if e["kind"] == "serve"]
    assert any("req-abc-123" in e.get("trace_ids", [])
               for e in serve_events)


# ------------------------------------------------------------- feedback
def _req(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path,
                 body=None if body is None else json.dumps(body),
                 headers=headers or {})
    r = conn.getresponse()
    out = json.loads(r.read().decode() or "{}")
    trace = r.getheader("X-Trace-Id")
    conn.close()
    return r.status, out, trace


def test_feedback_endpoint_spools_and_counts_accepted(tmp_path, metrics):
    from deeplearning4j_tpu.serve import FeedbackLog
    from deeplearning4j_tpu.serve import feedback as fb
    net = _net(81)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p)
    log = FeedbackLog(str(tmp_path / "spool"))
    with ModelServer(registry, feedback=log) as srv:
        x = _data(6, 4)
        y = np.eye(N_OUT, dtype=np.float32)[np.arange(6) % N_OUT]
        status, body, trace = _req(
            srv.port, "POST", "/v1/models/m:feedback",
            {"instances": x.tolist(), "labels": y.tolist(),
             "weights": 2.0},
            headers={"X-Trace-Id": "fb-1"})
        assert status == 200
        assert body == {"accepted": 6, "rejected": 0}
        assert trace == "fb-1"
    log.flush()
    log.close()
    registry.close()
    records = fb.read_records(str(tmp_path / "spool"))
    assert len(records) == 6
    assert records[0][1]["trace_id"] == "fb-1"
    assert records[0][1]["model"] == "m"
    assert records[0][1]["w"] == 2.0
    np.testing.assert_allclose(records[3][1]["x"], x[3], atol=1e-6)
    assert metrics.counter(
        "tpudl_serve_feedback_accepted_total").value == 6
    assert metrics.counter(
        "tpudl_serve_feedback_rejected_total").value == 0


def test_feedback_rejections_counted_and_echo_trace_id(tmp_path, metrics):
    """Every refusal shape counts into the rejected counter and echoes
    X-Trace-Id — spool loss is visible, never silent."""
    from deeplearning4j_tpu.serve import FeedbackLog
    net = _net(82)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p)
    log = FeedbackLog(str(tmp_path / "spool"))
    rejected = metrics.counter("tpudl_serve_feedback_rejected_total")
    with ModelServer(registry, feedback=log) as srv:
        x = _data(4, 5).tolist()
        y = np.eye(N_OUT, dtype=np.float32)[:3].tolist()
        # malformed body (no labels)
        status, body, trace = _req(srv.port, "POST",
                                   "/v1/models/m:feedback",
                                   {"instances": x},
                                   headers={"X-Trace-Id": "fb-bad-1"})
        assert status == 400 and trace == "fb-bad-1"
        assert rejected.value == 1
        # mismatched lengths: every offered row counts as refused
        status, body, trace = _req(srv.port, "POST",
                                   "/v1/models/m:feedback",
                                   {"instances": x, "labels": y},
                                   headers={"X-Trace-Id": "fb-bad-2"})
        assert status == 400 and trace == "fb-bad-2"
        assert rejected.value == 5
        # unknown model
        status, body, trace = _req(srv.port, "POST",
                                   "/v1/models/ghost:feedback",
                                   {"instances": x[:2], "labels": y[:2]},
                                   headers={"X-Trace-Id": "fb-bad-3"})
        assert status == 404 and trace == "fb-bad-3"
        assert rejected.value == 7
    log.close()
    # no spool configured → 503, rows counted
    with ModelServer(registry) as srv:
        status, body, trace = _req(srv.port, "POST",
                                   "/v1/models/m:feedback",
                                   {"instances": x[:2], "labels": y[:2]},
                                   headers={"X-Trace-Id": "fb-bad-4"})
        assert status == 503 and trace == "fb-bad-4"
        assert "spool" in body["error"]
        assert rejected.value == 9
    registry.close()
    assert metrics.counter(
        "tpudl_serve_feedback_accepted_total").value == 0


def test_labeled_predict_tap_spools_after_answering(tmp_path, metrics):
    from deeplearning4j_tpu.serve import FeedbackLog
    from deeplearning4j_tpu.serve import feedback as fb
    net = _net(83)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p)
    log = FeedbackLog(str(tmp_path / "spool"))
    with ModelServer(registry, feedback=log) as srv:
        x = _data(3, 6)
        y = np.eye(N_OUT, dtype=np.float32)[:3]
        status, body, _ = _req(srv.port, "POST", "/v1/models/m:predict",
                               {"instances": x.tolist(),
                                "labels": y.tolist()},
                               headers={"X-Trace-Id": "tap-1"})
        assert status == 200 and len(body["predictions"]) == 3
        # an unlabeled predict is NOT tapped
        status, body, _ = _req(srv.port, "POST", "/v1/models/m:predict",
                               {"instances": x.tolist()})
        assert status == 200
    log.flush()
    log.close()
    registry.close()
    records = fb.read_records(str(tmp_path / "spool"))
    assert len(records) == 3
    assert records[0][1]["trace_id"] == "tap-1"
    assert metrics.counter(
        "tpudl_serve_feedback_accepted_total").value == 3


def test_unknown_get_route_echoes_trace_id(tmp_path, metrics):
    net = _net(84)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p)
    with ModelServer(registry) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        conn.request("GET", "/nope", headers={"X-Trace-Id": "get-404"})
        r = conn.getresponse()
        r.read()
        assert r.status == 404
        assert r.getheader("X-Trace-Id") == "get-404"
        conn.request("GET", "/v1/models/ghost",
                     headers={"X-Trace-Id": "get-405"})
        r = conn.getresponse()
        r.read()
        assert r.status == 404
        assert r.getheader("X-Trace-Id") == "get-405"
        conn.close()
    registry.close()


def test_feedback_bad_weight_rows_rejected_not_crashed(tmp_path, metrics):
    """A non-numeric weights entry must cost a counted per-row
    rejection and a 200 — never an aborted connection."""
    from deeplearning4j_tpu.serve import FeedbackLog
    net = _net(85)
    p = str(tmp_path / "m.zip")
    net.save(p)
    registry = ModelRegistry(max_batch=4, max_latency_ms=2)
    registry.deploy("m", p)
    log = FeedbackLog(str(tmp_path / "spool"))
    with ModelServer(registry, feedback=log) as srv:
        x = _data(3, 7).tolist()
        y = np.eye(N_OUT, dtype=np.float32)[:3].tolist()
        status, body, _ = _req(srv.port, "POST", "/v1/models/m:feedback",
                               {"instances": x, "labels": y,
                                "weights": [1.0, "x", 2.0]})
        assert status == 200
        assert body == {"accepted": 2, "rejected": 1}
    log.close()
    registry.close()
    assert metrics.counter(
        "tpudl_serve_feedback_accepted_total").value == 2
    assert metrics.counter(
        "tpudl_serve_feedback_rejected_total").value == 1
