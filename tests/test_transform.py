"""DataVec TransformProcess/Schema ETL tests (VERDICT #4).

Parity anchors: ``datavec-api org/datavec/api/transform/TransformProcess.java``,
``schema/Schema.java``, ``join/Join.java``, ``AnalyzeLocal``.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data.transform import (
    Schema, ColumnType, TransformProcess, ColumnCondition, BooleanCondition,
    StringRegexColumnCondition, NullWritableColumnCondition, Join, analyze,
    TransformProcessRecordReader)
from deeplearning4j_tpu.data.records import (
    CollectionRecordReader, RecordReaderDataSetIterator)


def iris_like_schema():
    return (Schema.builder()
            .add_column_double("sepal_len", "sepal_wid")
            .add_column_categorical("species", ["setosa", "versicolor", "virginica"])
            .build())


class TestSchema:
    def test_builder_and_queries(self):
        s = iris_like_schema()
        assert s.names() == ["sepal_len", "sepal_wid", "species"]
        assert s.column("species").type == ColumnType.CATEGORICAL
        assert s.index_of("sepal_wid") == 1
        with pytest.raises(ValueError):
            s.index_of("nope")

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            Schema.builder().add_column_double("a", "a").build()

    def test_json_round_trip(self):
        s = iris_like_schema()
        assert Schema.from_json(s.to_json()) == s


class TestBasicTransforms:
    def test_chain_and_eager_validation(self):
        s = iris_like_schema()
        tp = (TransformProcess.builder(s)
              .math_op("sepal_len", "multiply", 10.0)
              .rename_column("sepal_wid", "width")
              .categorical_to_integer("species")
              .build())
        assert tp.final_schema().names() == ["sepal_len", "width", "species"]
        out = tp.execute([[5.1, 3.5, "setosa"], [6.2, 2.9, "virginica"]])
        assert out == [[51.0, 3.5, 0], [62.0, 2.9, 2]]

    def test_bad_column_fails_at_build_time(self):
        s = iris_like_schema()
        with pytest.raises(ValueError):
            TransformProcess.builder(s).remove_columns("nope")
        with pytest.raises(ValueError):
            TransformProcess.builder(s).categorical_to_integer("sepal_len")

    def test_one_hot(self):
        s = iris_like_schema()
        tp = TransformProcess.builder(s).categorical_to_one_hot("species").build()
        assert tp.final_schema().names() == [
            "sepal_len", "sepal_wid", "species[setosa]", "species[versicolor]",
            "species[virginica]"]
        out = tp.execute([[1.0, 2.0, "versicolor"]])
        assert out == [[1.0, 2.0, 0, 1, 0]]

    def test_remove_keep_duplicate(self):
        s = iris_like_schema()
        tp = (TransformProcess.builder(s)
              .duplicate_column("sepal_len", "sl2")
              .remove_columns("sepal_wid")
              .build())
        out = tp.execute([[5.0, 3.0, "setosa"]])
        assert out == [[5.0, "setosa", 5.0]]
        tp2 = TransformProcess.builder(s).remove_all_columns_except("species").build()
        assert tp2.execute([[5.0, 3.0, "setosa"]]) == [["setosa"]]

    def test_columns_math_and_string_ops(self):
        s = (Schema.builder().add_column_double("a", "b")
             .add_column_string("name").build())
        tp = (TransformProcess.builder(s)
              .columns_math_op("a+b", "add", "a", "b")
              .string_fn("name", "upper")
              .string_map("name", {"BOB": "ROBERT"})
              .build())
        out = tp.execute([[1.0, 2.0, "bob"], [3.0, 4.0, "eve"]])
        assert out == [[1.0, 2.0, "ROBERT", 3.0], [3.0, 4.0, "EVE", 7.0]]

    def test_string_to_time(self):
        s = Schema.builder().add_column_string("ts").build()
        tp = TransformProcess.builder(s).string_to_time("ts", "%Y-%m-%d").build()
        out = tp.execute([["1970-01-02"]])
        assert out == [[86400000]]            # epoch millis, UTC
        assert tp.final_schema().column("ts").type == ColumnType.TIME

    def test_replace_invalid_and_conditional(self):
        s = Schema.builder().add_column_double("x").add_column_integer("y").build()
        tp = (TransformProcess.builder(s)
              .replace_invalid_with("x", 0.0)
              .conditional_replace("y", -1, ColumnCondition("y", "<", 0))
              .build())
        out = tp.execute([["", 5], [float("nan"), -7], [2.5, 3]])
        assert out == [[0.0, 5], [0.0, -1], [2.5, 3]]


class TestConditionsAndFilters:
    def test_filter_drops_matching(self):
        s = Schema.builder().add_column_integer("x").build()
        tp = (TransformProcess.builder(s)
              .filter(ColumnCondition("x", ">=", 10)).build())
        assert tp.execute([[5], [15], [9], [10]]) == [[5], [9]]

    def test_boolean_combinators(self):
        s = Schema.builder().add_column_integer("x").add_column_string("s").build()
        cond = BooleanCondition("and", [ColumnCondition("x", ">", 0),
                                        StringRegexColumnCondition("s", "a.*")])
        assert cond.test([1, "abc"], s)
        assert not cond.test([0, "abc"], s)
        assert not cond.test([1, "xyz"], s)
        neg = BooleanCondition("not", [cond])
        assert neg.test([0, "abc"], s)

    def test_null_condition(self):
        s = Schema.builder().add_column_string("v").build()
        cond = NullWritableColumnCondition("v")
        assert cond.test([""], s) and cond.test([None], s)
        assert not cond.test(["x"], s)


class TestReduceJoinSequence:
    def test_reducer_group_by(self):
        s = (Schema.builder().add_column_string("key")
             .add_column_double("val").build())
        tp = (TransformProcess.builder(s)
              .reduce("key", val="sum")
              .build())
        out = tp.execute([["a", 1.0], ["b", 2.0], ["a", 3.0]])
        assert out == [["a", 4.0], ["b", 2.0]]
        assert tp.final_schema().names() == ["key", "sum(val)"]

    def test_reducer_multiple_ops(self):
        s = (Schema.builder().add_column_string("k")
             .add_column_double("v").build())
        tp = TransformProcess.builder(s).reduce("k", v="mean").build()
        out = tp.execute([["a", 1.0], ["a", 3.0]])
        assert out == [["a", 2.0]]

    def test_join_inner_and_outer(self):
        left = (Schema.builder().add_column_integer("id")
                .add_column_string("name").build())
        right = (Schema.builder().add_column_integer("id")
                 .add_column_double("score").build())
        join = Join(left, right, ["id"], "inner")
        assert join.output_schema().names() == ["id", "name", "score"]
        out = join.execute([[1, "a"], [2, "b"], [3, "c"]],
                           [[1, 9.0], [3, 7.0], [3, 8.0]])
        assert out == [[1, "a", 9.0], [3, "c", 7.0], [3, "c", 8.0]]
        louter = Join(left, right, ["id"], "left_outer")
        out = louter.execute([[1, "a"], [2, "b"]], [[1, 9.0]])
        assert out == [[1, "a", 9.0], [2, "b", None]]
        fouter = Join(left, right, ["id"], "full_outer")
        out = fouter.execute([[1, "a"]], [[2, 5.0]])
        assert out == [[1, "a", None], [2, None, 5.0]]

    def test_convert_to_sequence(self):
        s = (Schema.builder().add_column_string("device")
             .add_column_integer("t").add_column_double("v").build())
        tp = (TransformProcess.builder(s)
              .convert_to_sequence("device", "t")
              .build())
        seqs = tp.execute_to_sequence([
            ["a", 2, 1.0], ["b", 1, 9.0], ["a", 1, 0.5], ["b", 2, 8.0]])
        assert seqs == [[["a", 1, 0.5], ["a", 2, 1.0]],
                        [["b", 1, 9.0], ["b", 2, 8.0]]]

    def test_sequence_gap_split_and_offset(self):
        s = (Schema.builder().add_column_string("k")
             .add_column_integer("t").add_column_double("v").build())
        tp = (TransformProcess.builder(s)
              .convert_to_sequence("k", "t")
              .split_sequence_when_gap("t", 10)
              .build())
        seqs = tp.execute_to_sequence(
            [["a", 1, 1.0], ["a", 2, 2.0], ["a", 50, 3.0], ["a", 51, 4.0]])
        assert seqs == [[["a", 1, 1.0], ["a", 2, 2.0]],
                        [["a", 50, 3.0], ["a", 51, 4.0]]]
        # offset: label column shifted from t+1 (next-step target)
        s2 = Schema.builder().add_column_double("x", "y").build()
        tp2 = TransformProcess(s2, [])
        from deeplearning4j_tpu.data.transform import SequenceOffsetTransform
        seq = [[1.0, 10.0], [2.0, 20.0], [3.0, 30.0]]
        out = SequenceOffsetTransform(["y"], 1).apply_sequence(seq, s2)
        assert out == [[1.0, 20.0], [2.0, 30.0]]


class TestSerdeAndAnalysis:
    def test_transform_process_json_round_trip(self):
        s = iris_like_schema()
        tp = (TransformProcess.builder(s)
              .math_op("sepal_len", "multiply", 2.0)
              .filter(ColumnCondition("sepal_wid", "<", 1.0))
              .categorical_to_one_hot("species")
              .build())
        tp2 = TransformProcess.from_json(tp.to_json())
        data = [[1.0, 2.0, "setosa"], [4.0, 0.5, "virginica"]]
        assert tp2.execute(data) == tp.execute(data)
        assert tp2.final_schema() == tp.final_schema()

    def test_analyze(self):
        s = iris_like_schema()
        stats = analyze(s, [[1.0, 5.0, "setosa"], [3.0, float("nan"), "setosa"],
                            [2.0, 4.0, "virginica"]])
        a = stats["sepal_len"]
        assert a.count == 3 and a.min == 1.0 and a.max == 3.0 and a.mean == 2.0
        assert stats["sepal_wid"].count_missing == 1
        assert stats["species"].histogram == {"setosa": 2, "virginica": 1}


class TestExecutorGuards:
    def test_split_works_on_execute_sequences(self):
        """Gap-split must work on already-sequential input, not just after
        ConvertToSequence (review regression)."""
        s = (Schema.builder().add_column_integer("t")
             .add_column_double("v").build())
        tp = TransformProcess.builder(s).split_sequence_when_gap("t", 10).build()
        seqs = tp.execute_sequences([[[1, 1.0], [2, 2.0], [50, 3.0]]])
        assert seqs == [[[1, 1.0], [2, 2.0]], [[50, 3.0]]]

    def test_reducer_rejected_in_bridge_and_after_sequence(self):
        s = (Schema.builder().add_column_string("k")
             .add_column_integer("t").add_column_double("v").build())
        tp = TransformProcess.builder(s).reduce("k", v="sum").build()
        with pytest.raises(ValueError):
            TransformProcessRecordReader(CollectionRecordReader([]), tp)
        tp2 = (TransformProcess.builder(s).convert_to_sequence("k", "t")
               .reduce("k", v="sum").build())
        with pytest.raises(ValueError):
            tp2.execute_to_sequence([["a", 1, 2.0]])

    def test_reducer_before_sequence_conversion_ok(self):
        s = (Schema.builder().add_column_string("k")
             .add_column_integer("t").add_column_double("v").build())
        tp = (TransformProcess.builder(s)
              .reduce(["k", "t"], v="sum")
              .convert_to_sequence("k", "t")
              .build())
        seqs = tp.execute_to_sequence(
            [["a", 1, 1.0], ["a", 1, 2.0], ["a", 2, 5.0]])
        assert seqs == [[["a", 1, 3.0], ["a", 2, 5.0]]]

    def test_string_to_categorical_validates_column(self):
        s = Schema.builder().add_column_string("name").build()
        with pytest.raises(ValueError):
            TransformProcess.builder(s).string_to_categorical("typo", ["a"])

    def test_all_steps_validate_columns_at_build_time(self):
        """Eager-validation contract holds for every step kind
        (review regression)."""
        s = (Schema.builder().add_column_double("x")
             .add_column_string("name").add_column_integer("t").build())
        b = lambda: TransformProcess.builder(s)
        with pytest.raises(ValueError):
            b().math_op("typo", "add", 1.0)
        with pytest.raises(ValueError):
            b().math_op("x", "frobnicate", 1.0)
        with pytest.raises(ValueError):
            b().string_map("typo", {})
        with pytest.raises(ValueError):
            b().string_fn("typo", "lower")
        with pytest.raises(ValueError):
            b().replace_invalid_with("typo", 0)
        with pytest.raises(ValueError):
            b().conditional_replace("x", 0, ColumnCondition("typo", ">", 1))
        with pytest.raises(ValueError):
            b().filter(ColumnCondition("typo", ">", 1))
        with pytest.raises(ValueError):
            b().convert_to_sequence("typo", "t")
        with pytest.raises(ValueError):
            b().offset_sequence(["typo"], 1)
        with pytest.raises(ValueError):
            b().split_sequence_when_gap("typo", 1.0)


class TestIteratorBridge:
    def test_csv_to_dataset_flow(self):
        """The canonical dl4j-examples ETL flow: raw records → schema'd
        transform → RecordReaderDataSetIterator → DataSet."""
        raw = [[5.1, 3.5, "setosa"], [6.2, 2.9, "virginica"],
               [5.9, 3.0, "versicolor"], [5.0, 3.3, "setosa"]]
        s = iris_like_schema()
        tp = (TransformProcess.builder(s)
              .math_op("sepal_len", "subtract", 5.0)
              .categorical_to_integer("species")
              .build())
        reader = TransformProcessRecordReader(CollectionRecordReader(raw), tp)
        it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                         num_classes=3)
        batches = list(it)
        assert len(batches) == 2
        assert batches[0].features.shape == (2, 2)
        assert batches[0].labels.shape == (2, 3)
        np.testing.assert_allclose(np.asarray(batches[0].features)[0],
                                   [0.1, 3.5], rtol=1e-6)
        assert np.asarray(batches[0].labels)[0].argmax() == 0   # setosa
        # reset works through the bridge
        it.reset()
        assert len(list(it)) == 2
