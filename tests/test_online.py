"""tpudl.online (ISSUE 9): closed-loop continual learning.

Acceptance pins:
- end-to-end scenario: with a model serving concurrent HTTP traffic,
  injected labeled feedback triggers a background fine-tune whose
  candidate (a) deploys via verified hot-swap when it improves the gate
  metric with zero dropped/garbled in-flight requests, and (b) is
  refused — incumbent keeps serving — when a faults-injected
  regression (NaN poisoning / a corrupted candidate zip) makes it
  worse; a post-deploy metric regression triggers automatic rollback;
  every decision is visible in ``tpudl_online_*``.
- resume semantics: a loop killed mid-fine-tune and restarted trains
  no feedback record twice and skips none (per-step losses match the
  uninterrupted round to 1e-6 — the spool position rides the exact-
  resume contract from tests/test_resilience.py).
"""

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                               ResumableIterator)
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, DropoutLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.listeners import CollectScoresListener
from deeplearning4j_tpu.obs.registry import (MetricsRegistry, get_registry,
                                             set_registry)
from deeplearning4j_tpu.online import (DeployWatch, EvalGate, FeedbackSource,
                                       GatedDeployer, OnlineConfig,
                                       OnlineTrainer)
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.faults import InjectedCrash
from deeplearning4j_tpu.serve import FeedbackLog, ModelRegistry, ModelServer
from deeplearning4j_tpu.serve import feedback as fb
from deeplearning4j_tpu.train import Adam

N_IN, N_OUT = 6, 3


@pytest.fixture
def metrics():
    prev = set_registry(MetricsRegistry())
    try:
        yield get_registry()
    finally:
        set_registry(prev)


@pytest.fixture(autouse=True)
def _no_ambient_fault_plan():
    faults.clear_fault_plan()
    yield
    faults.clear_fault_plan()


_TEACHER = np.random.default_rng(99).normal(size=(N_IN, N_OUT)).astype(
    np.float32)


def _make_xy(n, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[np.argmax(x @ _TEACHER, -1)]
    return x, y


def _conf(seed=42, dropout=False):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=16, activation="tanh")))
    if dropout:
        b = b.layer(DropoutLayer(dropout=0.8))
    return (b.layer(OutputLayer(n_out=N_OUT, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())


def _holdout(seed=77, n=96):
    x, y = _make_xy(n, seed)
    return ListDataSetIterator([DataSet(x, y)])


def _spool_with(tmp_path, n, seed, name="spool", **log_kw):
    d = str(tmp_path / name)
    log = FeedbackLog(d, **log_kw)
    x, y = _make_xy(n, seed)
    assert log.extend(x, y) == n
    assert log.flush()
    log.close()
    return d


# ================================================================ spool
def test_spool_rotation_keeps_global_indices_stable(tmp_path, metrics):
    d = str(tmp_path / "spool")
    log = FeedbackLog(d, max_records_per_segment=8, max_segments=10)
    x, y = _make_xy(20, 1)
    log.extend(x, y)
    assert log.flush()
    log.close()
    assert fb.record_count(d) == 20
    segments = fb.list_segments(d)
    assert [s for s, _ in segments] == [0, 8, 16]
    records = fb.read_records(d)
    assert [i for i, _ in records] == list(range(20))
    np.testing.assert_allclose(records[13][1]["x"], x[13], atol=1e-6)
    assert metrics.counter("tpudl_online_spool_records_total").value == 20
    # a new log on the same directory resumes the global write position
    log2 = FeedbackLog(d, max_records_per_segment=8)
    assert log2.written() == 20
    log2.close()


def test_spool_retention_prunes_oldest_and_counts(tmp_path, metrics):
    d = str(tmp_path / "spool")
    log = FeedbackLog(d, max_records_per_segment=4, max_segments=2)
    x, y = _make_xy(20, 2)
    log.extend(x, y)
    assert log.flush()
    log.close()
    # at most max_segments * max_records_per_segment survive on disk,
    # later indices intact, pruned records counted as drops
    records = fb.read_records(d)
    assert records, "retention must not drop everything"
    assert records[-1][0] == 19
    assert len(records) <= 12    # 2 sealed segments + the active one
    assert metrics.counter("tpudl_online_spool_dropped_total").value > 0


def test_spool_torn_line_skipped_not_guessed(tmp_path, metrics):
    d = _spool_with(tmp_path, 6, 3)
    seg_path = fb.list_segments(d)[-1][1]
    with open(seg_path, "a", encoding="utf-8") as f:
        f.write('{"t": 1.0, "x": [0.1, 0.2')   # crash mid-append
    records, torn = fb.read_segment(seg_path)
    assert len(records) == 6 and torn == 1
    assert fb.record_count(d) == 6
    # ... and the writer resumes cleanly after the torn tail
    log = FeedbackLog(d)
    x, y = _make_xy(2, 4)
    log.extend(x, y)
    assert log.flush()
    log.close()
    assert fb.record_count(d) == 8


def test_spool_append_never_blocks_when_writer_is_wedged(tmp_path, metrics,
                                                        monkeypatch):
    """The never-block contract: with the writer thread dead, appends
    still return immediately; overflow drops the OLDEST buffered record
    and counts it."""
    monkeypatch.setattr(FeedbackLog, "_run", lambda self: None)
    log = FeedbackLog(str(tmp_path / "spool"), max_buffer=4)
    x, y = _make_xy(10, 5)
    t0 = time.perf_counter()
    for i in range(10):
        assert log.append(x[i], y[i]) is True
    assert time.perf_counter() - t0 < 1.0
    assert log.pending() == 4
    assert metrics.counter("tpudl_online_spool_dropped_total").value == 6
    # malformed payloads are rejected (counted), never raised
    assert log.append(object(), y[0]) is False
    log.close(timeout_s=0.2)


# =============================================================== source
def test_source_rounds_partition_the_spool_exactly(tmp_path, metrics):
    d = _spool_with(tmp_path, 25, 6)
    src = FeedbackSource(d, batch_size=4, max_records_per_round=10)
    seen = []
    for r in range(3):
        src.pin_round(r)
        for _ in src:
            seen.extend(src._last_batch_indices)
    assert seen == list(range(25))          # no dup, no gap, in order
    assert src.pending() == 0
    assert src.consumed() == 25


def test_source_round_stamp_pins_window_against_new_arrivals(tmp_path,
                                                             metrics):
    d = _spool_with(tmp_path, 12, 7)
    def indices(source):
        out = []
        for _ in source:
            out.append(source._last_batch_indices[:])
        return out

    src = FeedbackSource(d, batch_size=4, max_records_per_round=12)
    src.pin_round(0)
    first = indices(src)
    # 8 more records arrive "during the crash"
    log = FeedbackLog(d)
    x, y = _make_xy(8, 8)
    log.extend(x, y)
    log.flush()
    log.close()
    # a restarted round 0 replays the IDENTICAL window
    src2 = FeedbackSource(d, batch_size=4, max_records_per_round=12)
    src2.pin_round(0)
    assert indices(src2) == first
    # the new arrivals belong to round 1
    stamp = src2.stamp_round(1)
    assert (stamp["start"], stamp["stop"]) == (12, 20)


@pytest.mark.parametrize("sampling", ["reservoir", "recency"])
def test_source_sampling_is_deterministic_per_round(tmp_path, metrics,
                                                    sampling):
    d = _spool_with(tmp_path, 30, 9)
    kw = dict(batch_size=8, max_records_per_round=16, sampling=sampling,
              seed=3)
    src = FeedbackSource(d, **kw)
    src.pin_round(0)
    a = [src._last_batch_indices[:] for _ in src]
    src2 = FeedbackSource(d, **kw)
    src2.pin_round(0)
    b = [src2._last_batch_indices[:] for _ in src2]
    assert a == b and a, "sampled rounds must replay identically"


def test_source_resumable_fast_forward_no_dup_no_skip(tmp_path, metrics):
    """The record-level half of the exact-resume contract: break the
    pass mid-round, restore the checkpointed position into a FRESH
    iterator, and the consumed record indices concatenate to exactly
    the uninterrupted pass."""
    d = _spool_with(tmp_path, 24, 10)

    def consume(it, src, upto=None):
        out, n = [], 0
        for _ in it:
            out.append(src._last_batch_indices[:])
            n += 1
            if upto is not None and n >= upto:
                break
        return out

    full_src = FeedbackSource(d, batch_size=4, max_records_per_round=24)
    full_src.pin_round(0)
    full = consume(ResumableIterator(full_src), full_src)

    src_a = FeedbackSource(d, batch_size=4, max_records_per_round=24)
    src_a.pin_round(0)
    it_a = ResumableIterator(src_a)
    part_a = consume(it_a, src_a, upto=3)        # "killed" after 3 batches
    state = it_a.state() | {"batch_index": 3}

    src_b = FeedbackSource(d, batch_size=4, max_records_per_round=24)
    src_b.pin_round(0)
    it_b = ResumableIterator(src_b)              # fresh process
    it_b.set_state(state)
    part_b = consume(it_b, src_b)
    assert part_a + part_b == full


# ================================================================= gate
def _trained_zip(tmp_path, name, seed, records=96, epochs=2):
    net = MultiLayerNetwork(_conf(seed)).init()
    x, y = _make_xy(records, seed)
    net.fit(ListDataSetIterator([DataSet(x[i:i + 16], y[i:i + 16])
                                 for i in range(0, records, 16)]),
            epochs=epochs)
    path = str(tmp_path / name)
    net.save(path)
    return path


def _untrained_zip(tmp_path, name, seed=1):
    net = MultiLayerNetwork(_conf(seed)).init()
    path = str(tmp_path / name)
    net.save(path)
    return path


def test_gate_deploys_improvement_and_refuses_regression(tmp_path, metrics):
    weak = _untrained_zip(tmp_path, "weak.zip")
    strong = _trained_zip(tmp_path, "strong.zip", seed=2)
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    try:
        registry.deploy("m", weak)
        deployer = GatedDeployer(registry, EvalGate(_holdout(),
                                                    metric="accuracy"))
        decision = deployer.deploy_if_better("m", strong)
        assert decision.deploy and decision.version == 2
        assert decision.candidate_score > decision.incumbent_score
        assert metrics.counter("tpudl_online_deploys_total").value == 1
        assert metrics.gauge("tpudl_online_gate_delta").value == \
            pytest.approx(decision.delta)
        # now the strong one is the incumbent: the weak zip is refused
        decision = deployer.deploy_if_better("m", weak)
        assert not decision.deploy
        assert "regression" in decision.reason
        assert registry.get("m").version == 2     # incumbent untouched
        assert metrics.counter("tpudl_online_refusals_total").value == 1
        assert metrics.histogram("tpudl_online_gate_seconds").count == 2
    finally:
        registry.close()


def test_gate_refuses_corrupt_candidate_before_scoring(tmp_path, metrics):
    base = _trained_zip(tmp_path, "base.zip", seed=3)
    candidate = _trained_zip(tmp_path, "cand.zip", seed=4)
    with open(candidate, "r+b") as f:
        f.truncate(os.path.getsize(candidate) - 64)   # torn zip
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    try:
        registry.deploy("m", base)
        deployer = GatedDeployer(registry, EvalGate(_holdout()))
        decision = deployer.deploy_if_better("m", candidate)
        assert not decision.deploy
        assert "verification" in decision.reason
        assert registry.get("m").version == 1
        assert metrics.counter("tpudl_online_refusals_total").value == 1
    finally:
        registry.close()


def test_gate_refuses_non_finite_candidate_score(tmp_path, metrics):
    base = _trained_zip(tmp_path, "base.zip", seed=5)
    import jax
    net = MultiLayerNetwork(_conf(6)).init()
    net.params_ = jax.tree_util.tree_map(lambda a: a * np.nan, net.params_)
    poisoned = str(tmp_path / "poisoned.zip")
    net.save(poisoned)
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    try:
        registry.deploy("m", base)
        deployer = GatedDeployer(
            registry, EvalGate(_holdout(), metric="loss"))
        decision = deployer.deploy_if_better("m", poisoned)
        assert not decision.deploy
        assert "non-finite" in decision.reason
        assert registry.get("m").version == 1
    finally:
        registry.close()


# ========================================================== deploy watch
def test_deploy_watch_rolls_back_on_error_burst(tmp_path, metrics):
    v1 = _trained_zip(tmp_path, "v1.zip", seed=7)
    v2 = _trained_zip(tmp_path, "v2.zip", seed=8)
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    try:
        registry.deploy("m", v1)
        registry.deploy("m", v2)                 # the suspect deploy
        requests = metrics.labeled_counter("tpudl_serve_requests_total")
        watch = DeployWatch(registry, "m", window_s=10.0, poll_s=0.02,
                            error_rate_max=0.25, min_requests=4)

        def burst():
            time.sleep(0.05)
            requests.inc(9, status="error")
            requests.inc(1, status="ok")

        threading.Thread(target=burst, daemon=True).start()
        verdict = watch.run()
        assert verdict["rolled_back"]
        assert "error rate" in verdict["reason"]
        # rollback re-deploys v1's zip as a NEW version
        assert registry.get("m").version == 3
        assert registry.get("m").path == v1
        assert metrics.counter("tpudl_online_rollbacks_total").value == 1
    finally:
        registry.close()


def test_deploy_watch_clean_window_keeps_the_deploy(tmp_path, metrics):
    v1 = _trained_zip(tmp_path, "v1.zip", seed=9)
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    try:
        registry.deploy("m", v1)
        watch = DeployWatch(registry, "m", window_s=0.2, poll_s=0.02)
        verdict = watch.run()
        assert not verdict["rolled_back"]
        assert registry.get("m").version == 1
        assert metrics.counter("tpudl_online_rollbacks_total").value == 0
    finally:
        registry.close()


# ============================================================ loop rounds
def _online_setup(tmp_path, metrics, records=48, min_delta=1.0,
                  base_seed=1, **cfg_kw):
    base = _untrained_zip(tmp_path, "base.zip", seed=base_seed)
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    registry.deploy("m", base)
    spool = _spool_with(tmp_path, records, seed=11)
    cfg = OnlineConfig(min_records=records, batch_size=8,
                       max_records_per_round=records,
                       checkpoint_every_n_iterations=1, **cfg_kw)
    gate = EvalGate(_holdout(), metric="accuracy", min_delta=min_delta)
    trainer = OnlineTrainer(registry, "m", spool,
                            str(tmp_path / "online"), gate, base,
                            config=cfg)
    return registry, spool, trainer


def test_loop_round_trains_gates_deploys_and_promotes(tmp_path, metrics):
    registry, spool, trainer = _online_setup(tmp_path, metrics,
                                             min_delta=0.0)
    try:
        decision = trainer.run_once()
        assert decision["status"] == "deployed"
        assert registry.get("m").version == 2
        # the deployed candidate became the lineage head
        assert "lineage" in trainer.lineage_head()
        assert trainer.next_round() == 1
        # no new feedback → the next round is a counted skip
        assert trainer.run_once()["status"] == "skipped"
        assert metrics.counter("tpudl_online_candidates_total").value == 1
        assert metrics.counter("tpudl_online_deploys_total").value == 1
        assert metrics.gauge("tpudl_online_spool_depth").value == 0
    finally:
        registry.close()


def test_loop_aborts_nan_poisoned_candidate(tmp_path, metrics):
    """faults 'nan' poisoning mid-fine-tune: the HealthMonitor halts the
    fit, the candidate never reaches the gate, the incumbent serves."""
    registry, spool, trainer = _online_setup(tmp_path, metrics)
    try:
        with faults.inject("trainer.step@2:nan"):
            decision = trainer.run_once()
        assert decision["status"] == "aborted"
        assert decision["anomaly"] == "non_finite_loss"
        assert registry.get("m").version == 1       # incumbent untouched
        assert metrics.counter(
            "tpudl_online_candidates_aborted_total").value == 1
        assert metrics.counter("tpudl_online_deploys_total").value == 0
        assert metrics.labeled_counter(
            "tpudl_health_anomalies_total",
            label_names=("kind",)).labeled_value(kind="non_finite_loss") == 1
        # the aborted round advanced: the loop is not wedged on poison
        assert trainer.next_round() == 1
    finally:
        registry.close()


def test_loop_kill_mid_finetune_resumes_exactly(tmp_path, metrics):
    """THE resume acceptance: kill the loop mid-fine-tune (dropout
    active), restart it, and the resumed round's per-step losses
    concatenate to the uninterrupted round's to 1e-6 — no feedback
    record trained twice, none skipped."""
    # uninterrupted twin: identical base/conf/spool content
    base_u = str(tmp_path / "base_u.zip")
    MultiLayerNetwork(_conf(21, dropout=True)).init().save(base_u)
    spool_u = _spool_with(tmp_path, 48, seed=13, name="spool_u")
    reg_u = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    reg_u.deploy("m", base_u)
    scores_u = CollectScoresListener()
    trainer_u = OnlineTrainer(
        reg_u, "m", spool_u, str(tmp_path / "online_u"),
        EvalGate(_holdout(), min_delta=1.0), base_u,
        config=OnlineConfig(min_records=48, batch_size=8,
                            max_records_per_round=48,
                            checkpoint_every_n_iterations=1),
        listeners=[scores_u])
    decision_u = trainer_u.run_once()
    reg_u.close()
    assert decision_u["status"] in ("deployed", "refused")
    assert len(scores_u.scores) == 6              # 48 records / batch 8

    # interrupted twin: crash at step 3 of the fine-tune, then restart
    base_i = str(tmp_path / "base_i.zip")
    MultiLayerNetwork(_conf(21, dropout=True)).init().save(base_i)
    spool_i = _spool_with(tmp_path, 48, seed=13, name="spool_i")
    reg_i = ModelRegistry(max_batch=8, max_latency_ms=1.0)
    reg_i.deploy("m", base_i)
    scores_i = CollectScoresListener()

    def make_trainer():
        return OnlineTrainer(
            reg_i, "m", spool_i, str(tmp_path / "online_i"),
            EvalGate(_holdout(), min_delta=1.0), base_i,
            config=OnlineConfig(min_records=48, batch_size=8,
                                max_records_per_round=48,
                                checkpoint_every_n_iterations=1),
            listeners=[scores_i])

    with faults.inject("trainer.step@3:crash"):
        with pytest.raises(InjectedCrash):
            make_trainer().run_once()
    assert len(scores_i.scores) == 3              # steps 0..2 committed
    # "new process": a FRESH OnlineTrainer on the same directories
    decision_i = make_trainer().run_once()
    reg_i.close()
    assert decision_i["status"] == decision_u["status"]
    assert len(scores_i.scores) == 6              # steps 3..5 only, once
    np.testing.assert_allclose(scores_i.scores, scores_u.scores, atol=1e-6)
    # spool position: the killed+resumed loop consumed exactly one
    # round's window, same as the uninterrupted one
    src = FeedbackSource(spool_i, batch_size=8, max_records_per_round=48)
    assert src.consumed() == 48 and src.pending() == 0


def test_loop_background_thread_triggers_and_supervision_budget(tmp_path,
                                                                metrics):
    registry, spool, trainer = _online_setup(tmp_path, metrics,
                                             interval_s=0.0, poll_s=0.05)
    try:
        trainer.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and registry.get("m").version < 2:
            time.sleep(0.05)
        trainer.stop()
        assert registry.get("m").version == 2
        assert trainer.failed is None
    finally:
        trainer.stop()
        registry.close()


# ====================================================== end-to-end scenario
def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", path, body=json.dumps(body))
    response = conn.getresponse()
    out = json.loads(response.read().decode())
    conn.close()
    return response.status, out


def test_e2e_serve_feedback_finetune_gate_swap_rollback(tmp_path, metrics):
    """The ISSUE-9 acceptance scenario, CPU-runnable."""
    base = _untrained_zip(tmp_path, "base.zip", seed=31)
    registry = ModelRegistry(max_batch=8, max_latency_ms=1.0,
                             queue_limit=256)
    registry.deploy("clf", base)
    feedback = FeedbackLog(str(tmp_path / "spool"))
    server = ModelServer(registry, feedback=feedback)
    gate = EvalGate(_holdout(), metric="accuracy", min_delta=0.05)
    trainer = OnlineTrainer(
        registry, "clf", feedback.directory, str(tmp_path / "online"),
        gate, base,
        config=OnlineConfig(min_records=48, batch_size=8,
                            max_records_per_round=48,
                            checkpoint_every_n_iterations=2))

    stop = threading.Event()
    failures: list = []
    versions_seen: set = set()

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            n = int(rng.integers(1, 4))
            x = rng.normal(size=(n, N_IN)).astype(np.float32).tolist()
            try:
                status, body = _post(server.port,
                                     "/v1/models/clf:predict",
                                     {"instances": x})
                if status != 200 or len(body["predictions"]) != n:
                    failures.append((status, body))
                else:
                    versions_seen.add(body["model_version"])
            except Exception as e:            # noqa: BLE001 — recorded
                failures.append(("exc", repr(e)))
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(s,), daemon=True)
               for s in range(4)]
    for t in threads:
        t.start()
    try:
        # ---- (a) labeled feedback over HTTP → fine-tune → gated swap
        x1, y1 = _make_xy(48, 41)
        status, body = _post(server.port, "/v1/models/clf:feedback",
                             {"instances": x1.tolist(),
                              "labels": y1.tolist()})
        assert status == 200 and body["accepted"] == 48
        feedback.flush()
        decision1 = trainer.run_once()
        assert decision1["status"] == "deployed", decision1
        # trained-on-teacher beats the untrained incumbent outright
        assert decision1["gate"]["candidate_score"] > \
            decision1["gate"]["incumbent_score"]
        assert registry.get("clf").version == 2

        # ---- (b) injected regression: NaN-poisoned fine-tune is
        # refused before the gate; the incumbent keeps serving
        x2, y2 = _make_xy(48, 42)
        status, body = _post(server.port, "/v1/models/clf:feedback",
                             {"instances": x2.tolist(),
                              "labels": y2.tolist()})
        assert status == 200 and body["accepted"] == 48
        feedback.flush()
        # round 2 resumes from the deployed candidate (iteration 6)
        with faults.inject("trainer.step@8:nan"):
            decision2 = trainer.run_once()
        assert decision2["status"] == "aborted", decision2
        assert registry.get("clf").version == 2   # incumbent serving

        # ---- (b') corrupted candidate zip: refused at the gate
        x3, y3 = _make_xy(48, 43)
        _post(server.port, "/v1/models/clf:feedback",
              {"instances": x3.tolist(), "labels": y3.tolist()})
        feedback.flush()
        with faults.inject("checkpoint.write@0:truncate:4000:50"):
            decision3 = trainer.run_once()
        assert decision3["status"] == "refused", decision3
        assert "verification" in decision3["gate"]["reason"]
        assert registry.get("clf").version == 2   # still the incumbent

        # ---- post-deploy metric regression → automatic rollback
        x4, y4 = _make_xy(48, 44)
        _post(server.port, "/v1/models/clf:feedback",
              {"instances": x4.tolist(), "labels": y4.tolist()})
        feedback.flush()
        decision4 = trainer.run_once()
        assert decision4["status"] == "deployed", decision4
        deployed_version = registry.get("clf").version
        requests_c = metrics.labeled_counter("tpudl_serve_requests_total")
        watch = DeployWatch(registry, "clf", window_s=20.0, poll_s=0.05,
                            error_rate_max=0.9, min_requests=64)

        def burst():
            time.sleep(0.1)
            requests_c.inc(4096, status="error")

        threading.Thread(target=burst, daemon=True).start()
        verdict = watch.run()
        assert verdict["rolled_back"], verdict
        assert registry.get("clf").version == deployed_version + 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        server.stop()
        registry.close()
        feedback.close()

    # zero dropped/garbled in-flight requests across both hot-swaps,
    # the aborted/refused rounds, and the rollback
    assert failures == []
    assert versions_seen, "traffic must have flowed"
    # every decision is visible in the tpudl_online_* family
    assert metrics.counter("tpudl_online_candidates_total").value == 4
    assert metrics.counter("tpudl_online_deploys_total").value == 2
    assert metrics.counter(
        "tpudl_online_candidates_aborted_total").value == 1
    assert metrics.counter("tpudl_online_refusals_total").value == 1
    assert metrics.counter("tpudl_online_rollbacks_total").value == 1
    assert metrics.counter("tpudl_online_spool_records_total").value == 192


def test_spool_writer_survives_disk_failures(tmp_path, metrics,
                                             monkeypatch):
    """A disk hiccup (ENOSPC, yanked volume) must cost counted drops
    and a reopen — never a silently dead writer behind 200 responses."""
    real_open = FeedbackLog._open_active
    fail = {"n": 2}

    def flaky_open(self):
        if fail["n"] > 0:
            fail["n"] -= 1
            raise OSError("disk full")
        return real_open(self)

    monkeypatch.setattr(FeedbackLog, "_open_active", flaky_open)
    log = FeedbackLog(str(tmp_path / "spool"), flush_interval_s=0.02)
    x, y = _make_xy(5, 14)
    assert log.extend(x, y) == 5
    assert log.flush(timeout_s=10)          # recovered and drained
    log.close()
    assert fb.record_count(str(tmp_path / "spool")) == 5
    assert metrics.counter("tpudl_online_spool_records_total").value == 5


def test_extend_rejects_unusable_weights_without_raising(tmp_path,
                                                         metrics):
    log = FeedbackLog(str(tmp_path / "spool"))
    x, y = _make_xy(3, 15)
    accepted = log.extend(x, y, weights=[1.0, "nope", 2.0])
    assert accepted == 2
    assert log.flush()
    log.close()
    assert fb.record_count(str(tmp_path / "spool")) == 2
    assert metrics.counter("tpudl_online_spool_dropped_total").value == 1
