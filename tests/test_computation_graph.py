"""ComputationGraph: DAG build, skip connections, multi-input, serde."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer, ConvolutionLayer, BatchNormalization
from deeplearning4j_tpu.nn.vertices import MergeVertex, ElementWiseVertex, ScaleVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator


def build_skip_graph():
    """Residual block pattern: in → d1 → d2, out = d1 + d2 (ElementWise add)."""
    return (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(1e-2))
            .graph()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(16))
            .add_layer("d1", DenseLayer(n_out=32, activation="relu"), "in")
            .add_layer("d2", DenseLayer(n_out=32, activation="relu"), "d1")
            .add_vertex("residual", ElementWiseVertex(op="add"), "d1", "d2")
            .add_layer("out", OutputLayer(n_out=4, activation="softmax", loss="mcxent"), "residual")
            .set_outputs("out")
            .build())


def test_graph_builds_and_trains():
    conf = build_skip_graph()
    net = ComputationGraph(conf).init()
    assert net.num_params() == 16 * 32 + 32 + 32 * 32 + 32 + 32 * 4 + 4

    rng = np.random.default_rng(0)
    n = 256
    x = rng.normal(size=(n, 16)).astype(np.float32)
    w = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, axis=-1)]
    it = ArrayDataSetIterator(x, y, 64)
    net.fit(it, epochs=30)
    acc = net.evaluate(it).accuracy()
    assert acc > 0.9, f"accuracy {acc}"


def test_graph_json_roundtrip_and_save(tmp_path):
    conf = build_skip_graph()
    conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
    assert conf2.to_json() == conf.to_json()

    net = ComputationGraph(conf).init()
    path = str(tmp_path / "graph.zip")
    net.save(path)
    net2 = ComputationGraph.load(path)
    x = np.random.default_rng(1).normal(size=(3, 16)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-6)


def test_multi_input_merge():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .graph()
            .add_inputs("a", "b")
            .set_input_types(InputType.feed_forward(4), InputType.feed_forward(6))
            .add_vertex("merged", MergeVertex(), "a", "b")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax"), "merged")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    a = np.zeros((5, 4), np.float32)
    b = np.zeros((5, 6), np.float32)
    out = np.asarray(net.output(a, b))
    assert out.shape == (5, 2)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_cycle_detection():
    from deeplearning4j_tpu.nn.graph import VertexSpec
    conf = ComputationGraphConfiguration(
        inputs=["in"], outputs=["x"],
        vertices=[VertexSpec("x", "vertex", ScaleVertex(scale=1.0), ["y"]),
                  VertexSpec("y", "vertex", ScaleVertex(scale=1.0), ["x"])],
        input_types=[InputType.feed_forward(2)])
    with pytest.raises(ValueError, match="cycle"):
        conf.topo_order()


def test_attention_vertex_self_and_cross():
    """AttentionVertex (``conf/graph/AttentionVertex.java`` parity):
    self-attention in a graph with projection Dense layers, and the raw
    vertex math vs ops.attention directly."""
    from deeplearning4j_tpu.nn.vertices import AttentionVertex
    from deeplearning4j_tpu.nn.layers import DenseLayer, RnnOutputLayer
    from deeplearning4j_tpu.ops.attention import multi_head_attention
    import jax.numpy as jnp

    # vertex math == the op (self-attention)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 6, 8)).astype(np.float32))
    v = AttentionVertex(n_heads=2, causal=True)
    np.testing.assert_allclose(
        np.asarray(v.apply([x])),
        np.asarray(multi_head_attention(x, x, x, n_heads=2, causal=True)),
        rtol=1e-6)
    # cross-attention arity + shape inference
    q = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
    out = v.apply([q, x, x])
    assert out.shape == (2, 4, 8)
    with pytest.raises(ValueError):
        v.apply([q, x])

    # inside a ComputationGraph: projections as Dense layers (the
    # projectInput=true decomposition), trains end-to-end
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
            .graph().add_inputs("in")
            .set_input_types(InputType.recurrent(5, 6))
            .add_layer("q", DenseLayer(n_out=8, activation="identity"), "in")
            .add_layer("k", DenseLayer(n_out=8, activation="identity"), "in")
            .add_layer("v", DenseLayer(n_out=8, activation="identity"), "in")
            .add_vertex("attn", AttentionVertex(n_heads=2), "q", "k", "v")
            .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                             loss="mcxent"), "attn")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    xs = np.random.default_rng(1).normal(size=(4, 6, 5)).astype(np.float32)
    out = net.output(xs)
    assert out.shape == (4, 6, 3)
    # json round-trip keeps the vertex
    rt = ComputationGraphConfiguration.from_json(conf.to_json())
    assert any(s.name == "attn" for s in rt.vertices)
