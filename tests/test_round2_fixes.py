"""Regression tests for round-2 verdict/advice fixes: tBPTT state carry,
ParameterAveraging mode, GlobalPooling CNN masks, normalizer label revert,
native codec in-place accumulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, OutputLayer, RnnOutputLayer, LSTM, GlobalPoolingLayer,
    ConvolutionLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Sgd
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator


def _rnn_net(tbptt=False, length=4):
    b = NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.05)).list()
    if tbptt:
        b = b.backprop_type("tbptt", length)
    return MultiLayerNetwork(
        b
        .layer(LSTM(n_out=8))
        .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(4, 12))
        .build()).init()


def test_tbptt_forward_state_carries_across_segments():
    """Forward states flow between tBPTT segments: running the net
    segment-by-segment with carried state must equal the full-sequence
    forward (DL4J rnnActivateUsingStoredState semantics)."""
    net = _rnn_net()
    x = np.random.default_rng(0).normal(size=(2, 12, 4)).astype(np.float32)
    full, _, _ = net._forward(net.params_, net.state_, jnp.asarray(x), train=False)

    carries = [None] * len(net.layers)
    outs = []
    for s in range(0, 12, 4):
        seg = jnp.asarray(x[:, s:s + 4])
        y, _, _, carries = net._forward_impl(
            net.params_, net.state_, seg, carries, train=False)
        outs.append(y)
    seg_out = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(seg_out),
                               rtol=1e-5, atol=1e-5)


def test_tbptt_fit_trains_and_converges():
    net = _rnn_net(tbptt=True, length=4)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 12, 4)).astype(np.float32)
    y = np.zeros((8, 12, 3), np.float32)
    y[..., 1] = 1.0
    it = ArrayDataSetIterator(x, y, 8)
    net.fit(it, epochs=1)
    first = net.score()
    net.fit(it, epochs=6)
    assert net.score() < first


def test_parallel_wrapper_averaging_mode():
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    pw = ParallelWrapper(net, mesh=mesh, averaging_frequency=2)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    it = ArrayDataSetIterator(x, y, 32)
    w_before = np.asarray(net.params_[0]["W"]).copy()
    pw.fit(it, epochs=4)
    # after fit the stacked replica axis is collapsed back — the net is a
    # plain usable model (ParameterAveragingTrainingMaster hands back the
    # averaged net)
    w = np.asarray(net.params_[0]["W"])
    assert w.shape == w_before.shape
    assert not np.allclose(w, w_before)  # training happened
    assert not np.isnan(net.score())
    out = np.asarray(net.output(x[:4]))  # model usable post-fit
    assert out.shape == (4, 2)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_parallel_wrapper_averaging_decreases_loss():
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.3)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    pw = ParallelWrapper(net, mesh=make_mesh(data=4, devices=jax.devices()[:4]), averaging_frequency=3)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] + x[:, 1] > 0).astype(int)]
    it = ArrayDataSetIterator(x, y, 64)
    pw.fit(it, epochs=1)
    first = net.score()
    pw.fit(it, epochs=10)
    assert net.score() < first


def test_tbptt_under_parallel_wrapper_shards():
    """tBPTT routes segments through ParallelWrapper's sharding hook."""
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    net = _rnn_net(tbptt=True, length=4)
    mesh = make_mesh(data=4, devices=jax.devices()[:4])
    pw = ParallelWrapper(net, mesh=mesh)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 12, 4)).astype(np.float32)
    y = np.zeros((8, 12, 3), np.float32)
    y[..., 1] = 1.0
    it = ArrayDataSetIterator(x, y, 8)
    pw.fit(it, epochs=1)
    first = net.score()
    pw.fit(it, epochs=5)
    assert net.score() < first
    with pytest.raises(NotImplementedError):
        ParallelWrapper(net, mesh=mesh, averaging_frequency=2)._fit_tbptt(None, None)


def test_global_pooling_cnn_mask():
    layer = GlobalPoolingLayer(pooling_type="avg")
    x = np.random.default_rng(0).normal(size=(2, 4, 4, 3)).astype(np.float32)
    mask = np.zeros((2, 4, 4), np.float32)
    mask[:, :2, :2] = 1.0  # only top-left 2x2 valid
    y, _ = layer.apply({}, {}, jnp.asarray(x), mask=jnp.asarray(mask))
    expected = x[:, :2, :2, :].reshape(2, 4, 3).mean(axis=1)
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5, atol=1e-5)
    # max variant
    ymax, _ = GlobalPoolingLayer(pooling_type="max").apply(
        {}, {}, jnp.asarray(x), mask=jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(ymax), x[:, :2, :2, :].reshape(2, 4, 3).max(axis=1),
        rtol=1e-5, atol=1e-5)


def test_normalizer_standardize_reverts_labels():
    from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
    rng = np.random.default_rng(0)
    x = rng.normal(3.0, 2.0, size=(64, 5)).astype(np.float32)
    y = rng.normal(-1.0, 0.5, size=(64, 2)).astype(np.float32)
    ds = DataSet(x, y)
    norm = NormalizerStandardize(fit_labels=True)
    norm.fit([ds])
    transformed = norm.transform(ds)
    assert abs(float(np.mean(transformed.labels))) < 0.1
    reverted = norm.revert(transformed)
    np.testing.assert_allclose(np.asarray(reverted.features), x, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(reverted.labels), y, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(norm.revert_labels(transformed.labels), y,
                               rtol=1e-4, atol=1e-4)


def test_native_codec_inplace_accumulation():
    from deeplearning4j_tpu.native import codec
    if not codec.available():
        pytest.skip("native codec unavailable (no g++)")
    grad = np.array([0.0, 0.5, -0.7, 0.0, 0.2], np.float32)
    msg = codec.threshold_encode(grad, 0.3)
    target = np.ones(5, np.float32)
    out = codec.threshold_decode(msg, (5,), out=target)
    # in-place accumulation into the caller's contiguous f32 buffer,
    # matching the numpy oracle in parallel.compression
    np.testing.assert_allclose(target, out)
    assert target[1] != 1.0  # mutated in place
