"""Zoo tail model tests (VERDICT missing #11): build + forward shape for
every remaining reference model family, at reduced input sizes so the
suite stays fast.

Parity anchors: ``deeplearning4j-zoo org/deeplearning4j/zoo/model/``
SqueezeNet/Darknet19/TinyYOLO/YOLO2/UNet/Xception/InceptionResNetV1/NASNet.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models import (
    squeezenet, darknet19, tiny_yolo, yolo2, unet, xception,
    inception_resnet_v1, nasnet_mobile)


def _x(h, w, c=3, b=2, seed=0):
    return np.random.default_rng(seed).normal(size=(b, h, w, c)).astype(np.float32)


class TestZooTail:
    def test_squeezenet(self):
        net = squeezenet(height=96, width=96, num_classes=10).init()
        out = net.output(_x(96, 96))
        assert out.shape == (2, 10)
        np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-4)

    def test_darknet19(self):
        net = darknet19(height=64, width=64, num_classes=12).init()
        out = net.output(_x(64, 64))
        assert out.shape == (2, 12)
        # 19 conv layers (18 body + 1 head) — the name
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer
        n_convs = sum(isinstance(l, ConvolutionLayer) for l in net.layers)
        assert n_convs == 19

    def test_tiny_yolo(self):
        net = tiny_yolo(height=96, width=96, num_classes=4).init()
        out = net.output(_x(96, 96))
        # 5 pools: 96/32 = 3; 5 anchors × (5+4) = 45 channels
        assert out.shape == (2, 3, 3, 45)
        out = np.asarray(out).reshape(2, 3, 3, 5, 9)
        assert np.all((out[..., 4] >= 0) & (out[..., 4] <= 1))   # conf activated

    def test_yolo2_passthrough_graph(self):
        net = yolo2(height=128, width=128, num_classes=3).init()
        out = net.output(_x(128, 128))
        assert out.shape == (2, 4, 4, 5 * (5 + 3))   # 128/32 grid

    def test_unet(self):
        net = unet(height=64, width=64, num_classes=1).init()
        out = net.output(_x(64, 64))
        assert out.shape == (2, 64, 64, 1)           # same-size segmentation
        assert np.all((np.asarray(out) >= 0) & (np.asarray(out) <= 1))

    def test_xception(self):
        net = xception(height=96, width=96, num_classes=7, middle_blocks=2).init()
        out = net.output(_x(96, 96))
        assert out.shape == (2, 7)

    def test_inception_resnet_v1(self):
        net = inception_resnet_v1(height=96, width=96, num_classes=16,
                                  blocks_a=1, blocks_b=1, blocks_c=1).init()
        out = net.output(_x(96, 96))
        assert out.shape == (2, 16)

    def test_nasnet_mobile(self):
        net = nasnet_mobile(height=64, width=64, num_classes=9, cells=1).init()
        out = net.output(_x(64, 64))
        assert out.shape == (2, 9)

    def test_full_size_configs_build(self):
        """Reference-sized configs construct + shape-infer without init
        (no params allocated — config-time validation only)."""
        for model, kw in ((squeezenet, {}), (darknet19, {}),
                          (tiny_yolo, {}), (yolo2, {}),
                          (unet, {"height": 256, "width": 256}),
                          (xception, {}),
                          (inception_resnet_v1, {}),
                          (nasnet_mobile, {})):
            net = model(**kw)
            assert net.conf is not None

    def test_zoo_tail_config_round_trip(self):
        """Graph/MLN configs of the tail serialize and rebuild."""
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        net = darknet19(height=64, width=64, num_classes=5)
        rt = MultiLayerConfiguration.from_json(net.conf.to_json())
        assert len(rt.layers) == len(net.conf.layers)
        from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
        g = squeezenet(height=96, width=96, num_classes=4)
        rt2 = ComputationGraphConfiguration.from_json(g.conf.to_json())
        assert [v.name for v in rt2.vertices] == [v.name for v in g.conf.vertices]
