"""Word2Vec / GloVe / ParagraphVectors / DeepWalk tests.

Mirrors the reference's embedding test strategy (deeplearning4j-nlp
``Word2VecTests.java``: train on a small corpus, assert nearest-neighbor
structure and serialization round-trips; deeplearning4j-graph
``DeepWalkGradientCheck``-adjacent structural tests) on a tiny
deterministic corpus so the suite stays hermetic and fast.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    CollectionSentenceIterator, DeepWalk, DefaultTokenizerFactory, Glove,
    Graph, LineSentenceIterator, ParagraphVectors, VocabCache, Word2Vec,
    random_walks)


def _two_topic_corpus(n=120, seed=0):
    """Sentences drawn from two disjoint topic vocabularies: words within
    a topic co-occur, across topics never — embeddings must reflect it."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "sheep", "cow"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    out = []
    for i in range(n):
        words = rng.choice(animals if i % 2 == 0 else tech, size=6)
        out.append(" ".join(words))
    return out


class TestVocabCache:
    def test_frequency_ordered(self):
        tok = DefaultTokenizerFactory()
        vocab = VocabCache.build(tok.create(s) for s in
                                 ["a a a b b c", "a b c d"])
        assert vocab.words[0] == "a"
        assert vocab.counts[0] == 4
        assert vocab.id("a") == 0 and "d" in vocab

    def test_min_count_filters(self):
        tok = DefaultTokenizerFactory()
        vocab = VocabCache.build((tok.create(s) for s in ["a a b"]),
                                 min_count=2)
        assert "b" not in vocab and "a" in vocab

    def test_huffman_codes_prefix_free(self):
        tok = DefaultTokenizerFactory()
        vocab = VocabCache.build(tok.create(s) for s in
                                 ["a a a a b b b c c d"])
        codes, points, lens = vocab.huffman()
        strs = ["".join(str(b) for b in codes[w, :lens[w]])
                for w in range(len(vocab))]
        assert len(set(strs)) == len(strs)          # unique
        for i, a in enumerate(strs):                # prefix-free
            for j, b in enumerate(strs):
                if i != j:
                    assert not b.startswith(a)
        # frequent words get shorter codes
        assert lens[vocab.id("a")] <= lens[vocab.id("d")]
        assert points.max() < len(vocab) - 1


class TestSentenceIterators:
    def test_line_iterator(self, tmp_path):
        p = os.path.join(tmp_path, "corpus.txt")
        with open(p, "w") as f:
            f.write("one two\n\nthree four\n")
        it = LineSentenceIterator(p)
        assert list(it) == ["one two", "three four"]
        assert list(it) == ["one two", "three four"]  # resettable

    def test_collection_iterator(self):
        it = CollectionSentenceIterator(["a b", "c d"])
        assert list(it) == ["a b", "c d"]


@pytest.mark.parametrize("kw", [
    dict(negative=5, hs=False),            # skip-gram + negative sampling
    dict(negative=0, hs=True),             # skip-gram + hierarchical softmax
    dict(negative=5, hs=False, cbow=True), # CBOW + negative sampling
], ids=["sg-ns", "sg-hs", "cbow-ns"])
def test_word2vec_topic_structure(kw):
    model = Word2Vec(vector_size=24, window=3, epochs=10, seed=7,
                     sample=0.0, batch_size=256, **kw)
    model.fit(_two_topic_corpus())
    within = model.similarity("cat", "dog")
    across = model.similarity("cat", "gpu")
    assert within > across + 0.2, (within, across)
    near = model.words_nearest("cpu", top=4)
    assert set(near) <= {"gpu", "tpu", "ram", "disk"}, near


def test_word2vec_text_serde_roundtrip(tmp_path):
    model = Word2Vec(vector_size=16, window=2, epochs=2, seed=3)
    model.fit(_two_topic_corpus(40))
    p = os.path.join(tmp_path, "vecs.txt")
    model.save_text(p)
    loaded = Word2Vec.load_text(p)
    assert loaded.vocab.words == model.vocab.words
    np.testing.assert_allclose(loaded.syn0, model.syn0, atol=1e-5)
    assert abs(loaded.similarity("cat", "dog")
               - model.similarity("cat", "dog")) < 1e-5


def test_word2vec_sentence_iterator_input():
    model = Word2Vec(vector_size=12, epochs=2, seed=1)
    model.fit(CollectionSentenceIterator(_two_topic_corpus(40)))
    assert model.has_word("cat") and not model.has_word("zebra")


def test_glove_topic_structure():
    model = Glove(vector_size=24, window=3, epochs=30, seed=7)
    model.fit(_two_topic_corpus())
    within = model.similarity("cat", "dog")
    across = model.similarity("cat", "gpu")
    assert within > across + 0.2, (within, across)


@pytest.mark.parametrize("dm", [True, False], ids=["pv-dm", "pv-dbow"])
def test_paragraph_vectors_doc_structure(dm):
    docs = _two_topic_corpus(60)
    labels = [f"animal_{i}" if i % 2 == 0 else f"tech_{i}"
              for i in range(len(docs))]
    model = ParagraphVectors(dm=dm, vector_size=24, window=3, epochs=20,
                             seed=5, sample=0.0)
    model.fit(docs, labels)
    assert model.doc_vecs.shape == (60, 24)
    # documents of the same topic should be closer than across topics
    d = model.doc_vecs / np.linalg.norm(model.doc_vecs, axis=1, keepdims=True)
    sims = d @ d.T
    same = np.mean([sims[i, j] for i in range(0, 20, 2)
                    for j in range(i + 2, 20, 2)])
    diff = np.mean([sims[i, j] for i in range(0, 20, 2)
                    for j in range(1, 20, 2)])
    assert same > diff + 0.1, (same, diff)


def test_paragraph_vectors_short_doc_keeps_label_alignment():
    """Docs with <2 in-vocab tokens are skipped for training but must NOT
    shift later documents' doc-vector rows."""
    docs = ["cat dog cat dog cat dog"] * 6 + ["zzz"] + ["cpu gpu cpu gpu cpu gpu"] * 6
    labels = [f"a{i}" for i in range(6)] + ["junk"] + [f"t{i}" for i in range(6)]
    m = ParagraphVectors(dm=True, vector_size=12, window=2, epochs=10,
                         seed=4, sample=0.0, min_count=2)
    m.fit(docs, labels)
    d = m.doc_vecs / np.linalg.norm(m.doc_vecs, axis=1, keepdims=True)
    # tech doc rows (after the dropped doc) must cluster with each other,
    # not with the animal docs — misalignment would mix them
    tech = [labels.index(f"t{i}") for i in range(6)]
    animal = [labels.index(f"a{i}") for i in range(6)]
    t_sim = np.mean([d[i] @ d[j] for i in tech for j in tech if i != j])
    cross = np.mean([d[i] @ d[j] for i in tech for j in animal])
    assert t_sim > cross, (t_sim, cross)


def test_paragraph_vectors_infer_vector():
    docs = _two_topic_corpus(60)
    model = ParagraphVectors(dm=True, vector_size=24, window=3, epochs=20,
                             seed=5, sample=0.0)
    model.fit(docs)
    v_animal = model.infer_vector("cat dog sheep cow horse dog")
    v_tech = model.infer_vector("cpu gpu ram disk tpu gpu")
    d = model.doc_vecs / np.linalg.norm(model.doc_vecs, axis=1, keepdims=True)

    def mean_sim(v, rows):
        v = v / np.linalg.norm(v)
        return float(np.mean(d[rows] @ v))

    animal_rows = list(range(0, 60, 2))
    tech_rows = list(range(1, 60, 2))
    assert mean_sim(v_animal, animal_rows) > mean_sim(v_animal, tech_rows)
    assert mean_sim(v_tech, tech_rows) > mean_sim(v_tech, animal_rows)


def _two_cliques(k=6):
    """Two k-cliques joined by one bridge edge."""
    edges = [(i, j) for i in range(k) for j in range(i + 1, k)]
    edges += [(k + i, k + j) for i in range(k) for j in range(i + 1, k)]
    edges.append((0, k))
    return Graph.from_edges(2 * k, edges)


class TestDeepWalk:
    def test_random_walks_stay_on_graph(self):
        g = _two_cliques()
        walks = random_walks(g, walk_length=10, walks_per_vertex=2, seed=0)
        assert len(walks) == 24
        for w in walks:
            for a, b in zip(w, w[1:]):
                assert b in g.neighbors(a)

    def test_community_structure_recovered(self):
        g = _two_cliques()
        dw = DeepWalk(vector_size=16, window=3, walk_length=12,
                      walks_per_vertex=12, epochs=2, seed=3)
        dw.fit(g)
        within = dw.similarity(1, 2)      # same clique
        across = dw.similarity(1, 8)      # other clique
        assert within > across, (within, across)
        near = dw.vertices_nearest(2, top=3)
        assert set(near) <= set(range(6)), near

    def test_isolated_vertex_walks_skipped(self):
        g = Graph.from_edges(3, [(0, 1)])
        walks = random_walks(g, walk_length=5, walks_per_vertex=1, seed=0)
        assert all(2 not in w for w in walks)
