"""Test harness config.

Multi-host-without-a-cluster parity (SURVEY.md §4.2 #3, the DummyTransport
translation): all tests run on CPU with 8 virtual XLA devices so mesh /
shard_map / DP / TP code paths execute real collectives deterministically,
no TPU pod needed.  Must be set before jax initializes its backends.
"""

import os

# Force CPU even when the environment presets JAX_PLATFORMS (this machine's
# sitecustomize pins the "axon" TPU platform regardless of the env var) —
# tests need the deterministic 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import gc

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_memory_per_module():
    """Drop jit executables + buffers between test modules — the suite
    compiles hundreds of programs (gradchecks alone build ~120 nets in
    f64) and the accumulated cache otherwise OOMs the process before the
    last modules run.  The process-level step cache pins the nets its
    cached closures capture, so it is cleared alongside."""
    yield
    from deeplearning4j_tpu.train.step_cache import clear_step_cache
    clear_step_cache()
    gc.collect()
    jax.clear_caches()
