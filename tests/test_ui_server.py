"""Live UI server tests (reference: deeplearning4j-ui ``UIServer`` /
``VertxUIServer`` — attach a StatsStorage, serve the training dashboard)."""

import json
import urllib.request

import pytest

from deeplearning4j_tpu.obs import InMemoryStatsStorage, UIServer


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


@pytest.fixture
def server():
    s = UIServer(port=0)
    yield s
    s.stop()


def _storage_with_records():
    st = InMemoryStatsStorage()
    for i in range(5):
        st.put({"type": "score", "iteration": i, "epoch": 0,
                "score": 1.0 / (i + 1)})
    st.put({"type": "stats", "iteration": 5, "epoch": 0, "score": 0.1,
            "params": {"0": {"norm": 1.0, "mean": 0.0, "stdev": 0.1,
                             "mean_magnitude": 0.05, "min": -1.0, "max": 1.0,
                             "hist_counts": [1, 2, 3], "hist_min": -1.0,
                             "hist_max": 1.0}}})
    return st


def test_dashboard_served_live(server):
    status, body = _get(server.url)
    assert status == 200 and "No StatsStorage attached" in body

    st = _storage_with_records()
    server.attach(st)
    status, body = _get(server.url)
    assert status == 200
    assert "Score (loss)" in body and "polyline" in body
    assert "http-equiv='refresh'" in body

    # new records appear on next fetch without restart — the live part
    st.put({"type": "score", "iteration": 6, "epoch": 0, "score": 0.01})
    _, body2 = _get(server.url + "data/0.json")
    assert any(r["iteration"] == 6 for r in json.loads(body2))


def test_multiple_sessions_and_detach(server):
    a, b = _storage_with_records(), InMemoryStatsStorage()
    server.attach(a)
    server.attach(b)
    assert _get(server.url + "train/1")[0] == 200
    server.detach(a)
    status, body = _get(server.url + "data/0.json")
    assert status == 200 and json.loads(body) == []   # b is now index 0


def test_healthz_and_404(server):
    assert json.loads(_get(server.url + "healthz")[1])["status"] == "ok"
    server.attach(InMemoryStatsStorage())
    with pytest.raises(urllib.error.HTTPError):
        _get(server.url + "train/7")


# ----------------------------------------- ISSUE-7: concurrency contracts
def test_attach_detach_racing_do_get(server):
    """Attach/detach churning under a barrage of concurrent GETs: every
    response is a clean 200 or 404, never a 500 from the handler racing
    the storages list (do_GET snapshots under the lock)."""
    import concurrent.futures
    import threading
    import urllib.error

    stop = threading.Event()
    errors = []

    def churn():
        storages = [_storage_with_records() for _ in range(3)]
        while not stop.is_set():
            for st in storages:
                server.attach(st)
            for st in storages:
                server.detach(st)

    def hammer():
        for _ in range(40):
            for path in ("", "train/1", "data/0.json", "data/2.json"):
                try:
                    status, _ = _get(server.url + path)
                    if status not in (200, 404):
                        errors.append((path, status))
                except urllib.error.HTTPError as e:
                    if e.code != 404:
                        errors.append((path, e.code))
                except Exception as e:      # connection reset = server died
                    errors.append((path, repr(e)))

    churner = threading.Thread(target=churn, daemon=True)
    churner.start()
    try:
        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            list(pool.map(lambda _: hammer(), range(4)))
    finally:
        stop.set()
        churner.join(timeout=5)
    assert errors == []
    # the server is still alive and coherent afterwards
    assert _get(server.url + "healthz")[0] == 200


def test_stale_data_index_after_detach_is_404(server):
    """A bookmarked /data/<i>.json whose storage was detached must 404
    (typed), never 500 or silently serve another session's records."""
    import urllib.error

    a, b = _storage_with_records(), _storage_with_records()
    server.attach(a)
    server.attach(b)
    assert _get(server.url + "data/1.json")[0] == 200
    server.detach(b)
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(server.url + "data/1.json")          # stale index
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(server.url + "data/notanumber.json")
    assert err.value.code == 404


def test_bind_host_configurable_for_cross_host_federation():
    """The coordinator can bind a non-loopback interface (host= or
    DL4J_TPU_UI_HOST) so remote workers can reach /remote/stats; the
    advertised url never names an unconnectable wildcard address."""
    server = UIServer(port=0, host="0.0.0.0")
    try:
        assert server.host == "0.0.0.0"
        assert server.url.startswith("http://127.0.0.1:")
        with urllib.request.urlopen(server.url + "healthz", timeout=5) as r:
            assert json.loads(r.read())["status"] == "ok"
    finally:
        server.stop()
    # default stays loopback-only
    dflt = UIServer(port=0)
    try:
        assert dflt.host == "127.0.0.1"
    finally:
        dflt.stop()


def test_get_instance_port_is_a_contract():
    """get_instance(port=...) with a running instance: port=0 and the
    instance's own port return it; any OTHER port raises rather than
    silently ignoring the ask (documented return-or-raise)."""
    inst = UIServer.get_instance(port=0)
    try:
        assert UIServer.get_instance() is inst
        assert UIServer.get_instance(port=0) is inst
        assert UIServer.get_instance(port=inst.port) is inst
        other = inst.port + 1 if inst.port < 65535 else inst.port - 1
        with pytest.raises(RuntimeError) as err:
            UIServer.get_instance(port=other)
        assert str(inst.port) in str(err.value)
    finally:
        inst.stop()
    # stop() clears the singleton: a fresh ask constructs a new one
    fresh = UIServer.get_instance(port=0)
    try:
        assert fresh is not inst
    finally:
        fresh.stop()
