"""Live UI server tests (reference: deeplearning4j-ui ``UIServer`` /
``VertxUIServer`` — attach a StatsStorage, serve the training dashboard)."""

import json
import urllib.request

import pytest

from deeplearning4j_tpu.obs import InMemoryStatsStorage, UIServer


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


@pytest.fixture
def server():
    s = UIServer(port=0)
    yield s
    s.stop()


def _storage_with_records():
    st = InMemoryStatsStorage()
    for i in range(5):
        st.put({"type": "score", "iteration": i, "epoch": 0,
                "score": 1.0 / (i + 1)})
    st.put({"type": "stats", "iteration": 5, "epoch": 0, "score": 0.1,
            "params": {"0": {"norm": 1.0, "mean": 0.0, "stdev": 0.1,
                             "mean_magnitude": 0.05, "min": -1.0, "max": 1.0,
                             "hist_counts": [1, 2, 3], "hist_min": -1.0,
                             "hist_max": 1.0}}})
    return st


def test_dashboard_served_live(server):
    status, body = _get(server.url)
    assert status == 200 and "No StatsStorage attached" in body

    st = _storage_with_records()
    server.attach(st)
    status, body = _get(server.url)
    assert status == 200
    assert "Score (loss)" in body and "polyline" in body
    assert "http-equiv='refresh'" in body

    # new records appear on next fetch without restart — the live part
    st.put({"type": "score", "iteration": 6, "epoch": 0, "score": 0.01})
    _, body2 = _get(server.url + "data/0.json")
    assert any(r["iteration"] == 6 for r in json.loads(body2))


def test_multiple_sessions_and_detach(server):
    a, b = _storage_with_records(), InMemoryStatsStorage()
    server.attach(a)
    server.attach(b)
    assert _get(server.url + "train/1")[0] == 200
    server.detach(a)
    status, body = _get(server.url + "data/0.json")
    assert status == 200 and json.loads(body) == []   # b is now index 0


def test_healthz_and_404(server):
    assert json.loads(_get(server.url + "healthz")[1])["status"] == "ok"
    server.attach(InMemoryStatsStorage())
    with pytest.raises(urllib.error.HTTPError):
        _get(server.url + "train/7")
