"""Multi-process cluster tests (VERDICT #6): spawn_local_cluster actually
runs — DP gradient-sharing equivalence across processes, checkpoint under
sharding, and kill-one-process fault injection with checkpoint restart +
iterator fast-forward.

Parity anchors: SURVEY §4.2-3 (DummyTransport in-process cluster rig),
§5.3 (failure recovery = fast checkpoint/restart + iterator fast-forward),
§5.4 (resumable iterator state in the checkpoint zip).

These spawn REAL processes with a real ``jax.distributed`` runtime over
loopback — slow (~15-30s each), marked accordingly.
"""

import functools
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import cluster_workers  # noqa: E402

from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster  # noqa: E402

import jax  # noqa: E402

# jax < 0.5 (no jax.shard_map) also lacks multiprocess collectives on the
# CPU backend ("Multiprocess computations aren't implemented on the CPU
# backend") — the local-cluster rig needs them
_needs_mp_cpu = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax's CPU backend lacks multiprocess collectives")

_ENV = {"PYTHONPATH": os.path.dirname(__file__) + os.pathsep +
        os.environ.get("PYTHONPATH", "")}


@_needs_mp_cpu
class TestLocalCluster:
    def test_collective_across_processes(self):
        """2 procs × 4 local devices: the distributed runtime forms and a
        cross-process allgather returns both processes' contributions."""
        results = spawn_local_cluster(cluster_workers.psum_worker,
                                      n_processes=2, port=12711,
                                      local_devices=4, extra_env=_ENV)
        assert len(results) == 2
        for r in results:
            assert r["n_processes"] == 2
            assert r["n_devices"] == 8           # global view
            assert r["allgather_sum"] == 3.0     # (pid0+1) + (pid1+1)

    def test_dp_gradient_sharing_matches_single_process(self):
        """Cross-process gradient averaging == full-batch single-process
        step (the SharedTrainingMaster → dense-allreduce swap, proven over
        a real process boundary)."""
        import jax
        from deeplearning4j_tpu.train.trainer import make_loss_fn
        from deeplearning4j_tpu.utils.pytree import flat_param_vector

        results = spawn_local_cluster(cluster_workers.dp_step_worker,
                                      n_processes=2, port=12713,
                                      local_devices=2, extra_env=_ENV)
        assert len(results) == 2
        np.testing.assert_array_equal(results[0]["params"], results[1]["params"])

        # single-process full-batch reference
        net = cluster_workers._small_net()
        x, y = cluster_workers.global_batch()
        loss_fn = make_loss_fn(net)
        grads = jax.grad(lambda p: loss_fn(p, net.state_, x, y,
                                           None, None, None)[0])(net.params_)
        ref = jax.tree_util.tree_map(lambda p, g: np.asarray(p) - 0.1 * np.asarray(g),
                                     net.params_, grads)
        np.testing.assert_allclose(results[0]["params"],
                                   np.asarray(flat_param_vector(ref)), rtol=2e-5)

    def test_fault_injection_and_checkpoint_restart(self, tmp_path):
        """Kill one process mid-training → gang fails (RuntimeError);
        restart from the checkpoint with iterator fast-forward → final
        params identical to an uninterrupted run, no batch replayed."""
        wd = str(tmp_path)
        # uninterrupted reference run
        full = spawn_local_cluster(
            functools.partial(cluster_workers.fault_tolerant_train_worker,
                              phase="full", workdir=wd + "/full"),
            n_processes=2, port=12715, local_devices=1, extra_env=_ENV)
        assert all(r["all_equal"] for r in full)
        assert full[0]["batches_seen"] == 6

        # fault run: process 1 hard-exits at batch 5, after the checkpoint
        with pytest.raises(RuntimeError):
            spawn_local_cluster(
                functools.partial(cluster_workers.fault_tolerant_train_worker,
                                  phase="fail", workdir=wd + "/fail"),
                n_processes=2, port=12717, local_devices=1, timeout=90.0,
                extra_env=_ENV)
        ckpt = wd + "/fail/cluster_ckpt.zip"
        assert os.path.exists(ckpt), "checkpoint must have landed pre-fault"

        # restart: restore + fast-forward, finish the epoch
        resumed = spawn_local_cluster(
            functools.partial(cluster_workers.fault_tolerant_train_worker,
                              phase="resume", workdir=wd + "/fail"),
            n_processes=2, port=12719, local_devices=1, extra_env=_ENV)
        assert all(r["all_equal"] for r in resumed)
        assert resumed[0]["batches_seen"] == 3      # fast-forwarded past 3
        np.testing.assert_allclose(resumed[0]["params"], full[0]["params"],
                                   rtol=1e-6)


@_needs_mp_cpu
class TestMultiProcessDcnFit:
    def test_multislice_fit_and_fault_restart(self, tmp_path):
        """VERDICT r4 next #1c: multi-process MultiSliceTrainer.fit over a
        real TCP ring (device encode + overlapped exchange), surviving
        kill+restart with codec-state (residual+τ) checkpointing."""
        wd = str(tmp_path)
        full = spawn_local_cluster(
            functools.partial(cluster_workers.dcn_multislice_fit_worker,
                              phase="full", workdir=wd + "/full"),
            n_processes=2, port=12721, local_devices=1, extra_env=_ENV)
        assert all(r["all_equal"] for r in full)
        assert full[0]["batches_seen"] == 6
        # the wire carries capacity-bounded messages + frame headers —
        # for this 67-param toy the codec can't beat dense f32 (frames
        # dominate; the real compression claim is measured at ResNet
        # scale in bench_dcn_multislice / test_resnet50_multislice_fit),
        # so assert the capacity bound, not a compression ratio.
        # Frame size comes from the transport; the message bound restates
        # the trainer's value-coded worst case (header 3 ints + 2 ints
        # per entry at capacity (grad_size-4)//2 — dcn_trainer.__init__),
        # intentionally duplicated here as the SPEC under test.
        from deeplearning4j_tpu.parallel.dcn import _FRAME
        grad_size = full[0]["dense_bytes_per_step"] // 4
        capacity = (grad_size - 4) // 2
        cap_msg_bytes = (3 + 2 * capacity) * 4
        assert 0 < full[0]["bytes_sent"] <= (cap_msg_bytes
                                             + _FRAME.size) * 6

        with pytest.raises(RuntimeError):
            spawn_local_cluster(
                functools.partial(cluster_workers.dcn_multislice_fit_worker,
                                  phase="fail", workdir=wd + "/fail"),
                n_processes=2, port=12723, local_devices=1, timeout=120.0,
                extra_env=_ENV)
        assert os.path.exists(wd + "/fail/dcn_ckpt.zip")

        resumed = spawn_local_cluster(
            functools.partial(cluster_workers.dcn_multislice_fit_worker,
                              phase="resume", workdir=wd + "/fail"),
            n_processes=2, port=12725, local_devices=1, extra_env=_ENV)
        assert all(r["all_equal"] for r in resumed)
        assert resumed[0]["batches_seen"] == 3
        np.testing.assert_allclose(resumed[0]["params"], full[0]["params"],
                                   rtol=1e-6)


class TestResumableIterator:
    def _it(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                       ResumableIterator)
        data = [DataSet(np.full((2, 4), i, np.float32),
                        np.eye(3, dtype=np.float32)[[i % 3, (i + 1) % 3]])
                for i in range(5)]
        return ResumableIterator(ListDataSetIterator(data))

    def test_tracks_position_and_epoch(self):
        it = self._it()
        for i, _ in enumerate(it):
            if i == 2:
                break
        assert it.state() == {"epoch": 0, "batch_index": 3}
        it.reset()
        assert it.state() == {"epoch": 1, "batch_index": 0}
        assert len(list(it)) == 5

    def test_fast_forward_skips_consumed(self):
        it = self._it()
        it.set_state({"epoch": 2, "batch_index": 3})
        seen = [float(np.asarray(b.features)[0, 0]) for b in it]
        assert seen == [3.0, 4.0]            # batches 0-2 not replayed
        assert it.state() == {"epoch": 2, "batch_index": 5}
        it.reset()
        assert len(list(it)) == 5            # next epoch is full again

    def test_resume_through_trainer_fit(self):
        """set_state → Trainer.fit (which reset()s at epoch start) must
        fast-forward, not replay (review regression)."""
        from deeplearning4j_tpu.train import Trainer
        net = cluster_workers._small_net()
        it = self._it()
        it.set_state({"epoch": 0, "batch_index": 3})
        Trainer(net).fit(it, epochs=1)
        assert it.batch_index == 5             # only batches 3..4 trained
        assert it.epoch == 0
        # second epoch is full again
        Trainer(net).fit(it, epochs=1)
        assert it.epoch == 1 and it.batch_index == 5

    def test_ring_attention_head_axis_divisibility(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        from deeplearning4j_tpu.parallel.unified import ring_attention
        mesh = make_mesh(data=1, model=2, seq=4)
        q = jnp.zeros((2, 16, 24), jnp.float32)   # 3 heads × dh 8
        with pytest.raises(ValueError):
            ring_attention(q, q, q, mesh, axis="seq", n_heads=3,
                           head_axis="model")

    def test_checkpoint_listener_stores_iterator_state(self, tmp_path):
        from deeplearning4j_tpu.io.checkpoint import CheckpointListener
        from deeplearning4j_tpu.io.model_serializer import read_iterator_state
        from deeplearning4j_tpu.train import Trainer
        net = cluster_workers._small_net()
        it = self._it()
        listener = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                      iterator=it)
        Trainer(net, listeners=[listener]).fit(it, epochs=1)
        state = read_iterator_state(listener.last_checkpoint())
        assert state is not None and state["batch_index"] > 0


class TestCheckpointUnderSharding:
    def test_sharded_params_round_trip(self, tmp_path):
        """Checkpoint save/restore with params laid out on an 8-device
        mesh: device→host gather on save, identical outputs on load."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        net = cluster_workers._small_net()
        mesh = make_mesh(data=8)
        with mesh:
            sharding = NamedSharding(mesh, P())          # replicated layout
            net.params_ = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), net.params_)
            # shard the big dense weight over the data axis
            w = net.params_[0]["W"]                      # [4, 8]
            net.params_[0]["W"] = jax.device_put(
                w, NamedSharding(mesh, P(None, "data")))
        assert len(net.params_[0]["W"].sharding.device_set) == 8
        x = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
        before = np.asarray(net.output(x))
        path = str(tmp_path / "sharded.zip")
        net.save(path)
        net2 = type(net).load(path)
        np.testing.assert_allclose(np.asarray(net2.output(x)), before,
                                   rtol=1e-6)
