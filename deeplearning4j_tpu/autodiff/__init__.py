"""Autodiff / graph engine — SameDiff parity, the TPU way.

The reference's SameDiff (nd4j-api ``org/nd4j/autodiff/samediff/``) is a
define-by-graph engine: explicit graph container, hand-written backward
builders per op (``doDiff``), a topological interpreter
(``InferenceSession``), and FlatBuffers serialization.  The TPU-native
equivalents:

- graph build  → python tracing (jax.make_jaxpr); no god-object
- doDiff       → jax.grad (program transformation)
- InferenceSession → XLA executable; ``trace`` exposes the jaxpr for
  debugging (the interpreter's introspection role)
- FlatBuffers serde (``SameDiff.asFlatBuffers``/``save``) → StableHLO
  export via jax.export (``export``/``load`` round-trip, serving parity)
- GradCheckUtil / OpValidation → ``gradcheck`` + the op coverage ledger
  (``validation``)
"""

from deeplearning4j_tpu.autodiff.export import (
    export_stablehlo, save_exported, load_exported, stablehlo_text, trace,
)
from deeplearning4j_tpu.autodiff.gradcheck import check_gradients, check_model_gradients
from deeplearning4j_tpu.autodiff.validation import op_inventory, CoverageLedger

__all__ = [
    "export_stablehlo", "save_exported", "load_exported", "stablehlo_text",
    "trace", "check_gradients", "check_model_gradients", "op_inventory",
    "CoverageLedger",
]
