"""Gradient checking — GradCheckUtil / GradientCheckUtil parity.

Reference: ``org/nd4j/autodiff/validation/GradCheckUtil.java`` (per-op,
central difference vs analytic) and deeplearning4j-nn
``gradientcheck/GradientCheckUtil.java`` (whole-network double-precision
checks used by GradientCheckTests/CNNGradientCheckTest/
LSTMGradientCheckTests).  Same method here: central difference
(f(x+ε) - f(x-ε)) / 2ε per parameter against jax.grad, with the
max-relative-error criterion the reference uses.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(loss_fn: Callable[[Any], jnp.ndarray], params: Any,
                    eps: float = 1e-3, max_rel_error: float = 1e-2,
                    abs_error_floor: float = 1e-6,
                    max_checks_per_leaf: int = 25,
                    seed: int = 0) -> dict:
    """Validate jax.grad(loss_fn) against central differences.

    Checks up to ``max_checks_per_leaf`` randomly-chosen entries per
    parameter leaf (the reference subsamples large params the same way).
    Returns a report dict; raises AssertionError on failure.
    """
    grads = jax.grad(loss_fn)(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    grad_leaves = jax.tree_util.tree_leaves(grads)
    rng = np.random.default_rng(seed)
    worst = 0.0
    n_checked = 0
    failures = []
    for li, (leaf, grad_leaf) in enumerate(zip(leaves, grad_leaves)):
        flat = np.asarray(leaf, dtype=np.float64).ravel()
        gflat = np.asarray(grad_leaf, dtype=np.float64).ravel()
        idxs = (np.arange(flat.size) if flat.size <= max_checks_per_leaf
                else rng.choice(flat.size, max_checks_per_leaf, replace=False))
        for i in idxs:
            def perturbed(delta, i=i, li=li):
                # perturb in f64, then measure the value the device array
                # ACTUALLY holds — dtype rounding of p±ε (f32: up to ~0.3%
                # of ε) would otherwise read as a systematic "gradient
                # error"; dividing by the realized perturbation keeps the
                # check exact in any dtype
                pl = flat.copy()
                pl[i] += delta
                new_leaves = list(leaves)
                new_leaves[li] = jnp.asarray(pl.reshape(leaf.shape),
                                             dtype=leaf.dtype)
                realized_v = float(np.asarray(new_leaves[li]).ravel()[i])
                return (jax.tree_util.tree_unflatten(treedef, new_leaves),
                        realized_v)

            args_plus, p_plus = perturbed(+eps)
            args_minus, p_minus = perturbed(-eps)
            f_plus = float(loss_fn(args_plus))
            f_minus = float(loss_fn(args_minus))
            realized = p_plus - p_minus
            if realized == 0.0:
                continue  # eps below dtype resolution for this entry
            numeric = (f_plus - f_minus) / realized
            analytic = gflat[i]
            denom = max(abs(numeric), abs(analytic))
            if denom < abs_error_floor:
                continue
            rel = abs(numeric - analytic) / denom
            worst = max(worst, rel)
            n_checked += 1
            if rel > max_rel_error and abs(numeric - analytic) > abs_error_floor:
                failures.append((li, int(i), float(analytic), float(numeric), float(rel)))
    if failures:
        lines = [f"leaf {li} idx {i}: analytic={a:.6g} numeric={n:.6g} rel={r:.3g}"
                 for li, i, a, n, r in failures[:10]]
        raise AssertionError(
            f"gradient check failed on {len(failures)}/{n_checked} entries "
            f"(worst rel {worst:.3g}):\n" + "\n".join(lines))
    if n_checked == 0 and any(np.asarray(l).size for l in leaves):
        raise AssertionError(
            "gradient check validated ZERO entries — eps below the param "
            "dtype's resolution (or all gradients under the error floor); "
            "a silent pass here would mean nothing was checked")
    return {"checked": n_checked, "max_rel_error": worst}


def check_model_gradients(net, batch, eps: float = 1e-3,
                          max_rel_error: float = 1e-2, **kw) -> dict:
    """Whole-network gradient check (GradientCheckUtil parity): validates
    the end-to-end loss gradient through every layer against central
    differences on the given batch."""
    from deeplearning4j_tpu.train.trainer import make_loss_fn
    if net.params_ is None:
        net.init()
    loss_fn_full = make_loss_fn(net)
    params = net.params_
    if jax.config.jax_enable_x64:
        # double-precision whole-network check (the reference's
        # GradientCheckUtil runs nets cast to DOUBLE the same way)
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float64)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
    features = jnp.asarray(batch.features)
    labels = jnp.asarray(batch.labels)
    fmask = None if batch.features_mask is None else jnp.asarray(batch.features_mask)
    lmask = None if batch.labels_mask is None else jnp.asarray(batch.labels_mask)

    def loss_fn(params):
        loss, _ = loss_fn_full(params, net.state_, features, labels, fmask, lmask, None)
        return loss

    return check_gradients(loss_fn, params, eps=eps,
                           max_rel_error=max_rel_error, **kw)
