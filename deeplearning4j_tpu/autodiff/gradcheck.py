"""Gradient checking — GradCheckUtil / GradientCheckUtil parity.

Reference: ``org/nd4j/autodiff/validation/GradCheckUtil.java`` (per-op,
central difference vs analytic) and deeplearning4j-nn
``gradientcheck/GradientCheckUtil.java`` (whole-network double-precision
checks used by GradientCheckTests/CNNGradientCheckTest/
LSTMGradientCheckTests).  Same method here: central difference
(f(x+ε) - f(x-ε)) / 2ε per parameter against jax.grad, with the
max-relative-error criterion the reference uses.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(loss_fn: Callable[[Any], jnp.ndarray], params: Any,
                    eps: float = 1e-3, max_rel_error: float = 1e-2,
                    abs_error_floor: float = 1e-6,
                    max_checks_per_leaf: int = 25,
                    seed: int = 0) -> dict:
    """Validate jax.grad(loss_fn) against central differences.

    Checks up to ``max_checks_per_leaf`` randomly-chosen entries per
    parameter leaf (the reference subsamples large params the same way).
    Returns a report dict; raises AssertionError on failure.
    """
    grads = jax.grad(loss_fn)(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    grad_leaves = jax.tree_util.tree_leaves(grads)
    rng = np.random.default_rng(seed)
    worst = 0.0
    n_checked = 0
    failures = []
    for li, (leaf, grad_leaf) in enumerate(zip(leaves, grad_leaves)):
        flat = np.asarray(leaf, dtype=np.float64).ravel()
        gflat = np.asarray(grad_leaf, dtype=np.float64).ravel()
        idxs = (np.arange(flat.size) if flat.size <= max_checks_per_leaf
                else rng.choice(flat.size, max_checks_per_leaf, replace=False))
        for i in idxs:
            def perturbed(delta, i=i, li=li):
                new_leaves = list(leaves)
                pl = np.asarray(new_leaves[li]).copy().ravel()
                pl[i] += delta
                new_leaves[li] = jnp.asarray(pl.reshape(leaves[li].shape),
                                             leaves[li].dtype)
                return jax.tree_util.tree_unflatten(treedef, new_leaves)

            f_plus = float(loss_fn(perturbed(+eps)))
            f_minus = float(loss_fn(perturbed(-eps)))
            numeric = (f_plus - f_minus) / (2 * eps)
            analytic = gflat[i]
            denom = max(abs(numeric), abs(analytic))
            if denom < abs_error_floor:
                continue
            rel = abs(numeric - analytic) / denom
            worst = max(worst, rel)
            n_checked += 1
            if rel > max_rel_error and abs(numeric - analytic) > abs_error_floor:
                failures.append((li, int(i), float(analytic), float(numeric), float(rel)))
    if failures:
        lines = [f"leaf {li} idx {i}: analytic={a:.6g} numeric={n:.6g} rel={r:.3g}"
                 for li, i, a, n, r in failures[:10]]
        raise AssertionError(
            f"gradient check failed on {len(failures)}/{n_checked} entries "
            f"(worst rel {worst:.3g}):\n" + "\n".join(lines))
    return {"checked": n_checked, "max_rel_error": worst}


def check_model_gradients(net, batch, eps: float = 1e-3,
                          max_rel_error: float = 1e-2, **kw) -> dict:
    """Whole-network gradient check (GradientCheckUtil parity): validates
    the end-to-end loss gradient through every layer against central
    differences on the given batch."""
    from deeplearning4j_tpu.train.trainer import make_loss_fn
    if net.params_ is None:
        net.init()
    loss_fn_full = make_loss_fn(net)
    features = jnp.asarray(batch.features)
    labels = jnp.asarray(batch.labels)
    fmask = None if batch.features_mask is None else jnp.asarray(batch.features_mask)
    lmask = None if batch.labels_mask is None else jnp.asarray(batch.labels_mask)

    def loss_fn(params):
        loss, _ = loss_fn_full(params, net.state_, features, labels, fmask, lmask, None)
        return loss

    return check_gradients(loss_fn, net.params_, eps=eps,
                           max_rel_error=max_rel_error, **kw)
