"""StableHLO export — the SameDiff-FlatBuffers serialization parity.

Reference: ``SameDiff.asFlatBuffers()/save()`` serializes the op graph +
weights for the C++ ``GraphExecutioner`` (libnd4j ``include/graph/``) and
``.sdz`` deployment.  Here a traced jax function exports to a
**StableHLO** artifact (``jax.export``): portable, versioned (compatible
across jax/XLA releases per the StableHLO guarantees), executable without
python (serving), and inspectable as MLIR text.

``export_stablehlo(fn, *example_args)`` → ``jax.export.Exported``;
``save_exported``/``load_exported`` round-trip the serialized bytes;
``call`` on the loaded object re-executes inside jax.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax import export as jax_export


def trace(fn: Callable, *example_args, **kwargs):
    """Expose the traced jaxpr (the debugging role of SameDiff's graph
    introspection / ``InferenceSession`` stepping)."""
    return jax.make_jaxpr(fn, **kwargs)(*example_args)


def export_stablehlo(fn: Callable, *example_args,
                     platforms: tuple[str, ...] | None = None):
    """Trace+lower ``fn`` and return the jax.export artifact."""
    kwargs = {}
    if platforms is not None:
        kwargs["platforms"] = list(platforms)
    return jax_export.export(jax.jit(fn), **kwargs)(*example_args)


def stablehlo_text(fn: Callable, *example_args) -> str:
    """StableHLO MLIR of the traced fn (inspection/debug)."""
    return export_stablehlo(fn, *example_args).mlir_module()


def save_exported(exported, path: str) -> None:
    with open(path, "wb") as f:
        f.write(exported.serialize())


def load_exported(path: str):
    with open(path, "rb") as f:
        return jax_export.deserialize(f.read())


def export_model_forward(net, batch_size: int = 1, path: str | None = None):
    """Export a network's inference forward at a fixed batch size — the
    ``SameDiff.save`` / ``.sdz``-for-serving analog."""
    import jax.numpy as jnp

    x_shape = net.conf.input_type.batch_shape(batch_size) if hasattr(net.conf, "input_type") \
        else net.conf.input_types[0].batch_shape(batch_size)

    params, state = net.params_, net.state_

    def forward(x):
        y, _, _ = net._forward(params, state, x, train=False)
        return y

    exported = export_stablehlo(forward, jnp.zeros(x_shape, jnp.float32))
    if path is not None:
        save_exported(exported, path)
    return exported
