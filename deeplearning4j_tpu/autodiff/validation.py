"""Op-coverage ledger — OpValidation parity.

Reference: ``org/nd4j/autodiff/validation/OpValidation.java`` tracks which
registered ops have test coverage (forward values + gradients + shape fn)
and FAILS the suite when coverage regresses.  Here the op inventory is
enumerated from the ``ops`` namespaces; golden tests register the ops they
cover; the ledger compares against a checked-in baseline
(``tests/op_coverage.json``) and fails on regression.
"""

from __future__ import annotations

import json
import os
from types import SimpleNamespace
from typing import Iterable


def op_inventory() -> dict[str, list[str]]:
    """namespace → sorted op names, from the live ops module."""
    from deeplearning4j_tpu.ops import namespaces as ns
    inventory = {}
    for name in ("math", "nn", "cnn", "rnn", "loss", "linalg", "random",
                 "image", "bitwise", "scatter", "base"):
        space = getattr(ns, name)
        ops = [k for k, v in vars(space).items()
               if not k.startswith("_") and callable(v)]
        inventory[name] = sorted(ops)
    return inventory


class CoverageLedger:
    def __init__(self, baseline_path: str):
        self.baseline_path = baseline_path
        self.covered: set[str] = set()   # "namespace.op" keys

    def record(self, *qualified_ops: str) -> None:
        self.covered.update(qualified_ops)

    def total_ops(self) -> int:
        return sum(len(v) for v in op_inventory().values())

    def check(self, update_baseline: bool = False) -> dict:
        """Fail if coverage dropped below the checked-in baseline; report
        uncovered ops.  ``update_baseline=True`` rewrites the baseline
        (run deliberately when coverage grows)."""
        inventory = op_inventory()
        all_ops = {f"{ns}.{op}" for ns, ops in inventory.items() for op in ops}
        unknown = self.covered - all_ops
        if unknown:
            raise AssertionError(f"ledger records unknown ops: {sorted(unknown)}")
        coverage = len(self.covered) / max(len(all_ops), 1)
        baseline = {"covered": [], "coverage": 0.0}
        if os.path.exists(self.baseline_path):
            with open(self.baseline_path) as f:
                baseline = json.load(f)
        lost = set(baseline["covered"]) - self.covered
        if lost:
            keys = "\n".join(f"  - {k}" for k in sorted(lost))
            raise AssertionError(
                f"op coverage REGRESSED — {len(lost)} previously-covered "
                f"namespace.op key(s) now untested:\n{keys}\n"
                f"If the removal is intentional, regenerate the baseline "
                f"with:\n"
                f"  rm {self.baseline_path} && JAX_PLATFORMS=cpu "
                f"python -m pytest tests/test_op_coverage.py -q")
        if update_baseline or len(self.covered) > len(baseline["covered"]):
            with open(self.baseline_path, "w") as f:
                json.dump({"covered": sorted(self.covered),
                           "coverage": round(coverage, 4)}, f, indent=1)
        return {"covered": len(self.covered), "total": len(all_ops),
                "coverage": coverage,
                "uncovered": sorted(all_ops - self.covered)}
