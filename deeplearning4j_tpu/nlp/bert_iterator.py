"""BertIterator — MLM and sequence-classification batch builder.

Parity: the reference's ``org/deeplearning4j/iterator/BertIterator.java``
with ``Task.UNSUPERVISED`` (masked-LM batches via
``BertMaskedLMMasker``, 80/10/10 mask/random/keep at 15% of positions)
and ``Task.SEQ_CLASSIFICATION`` (labelled sentence batches), fed by
sentence providers (``CollectionSentenceProvider`` /
``CollectionLabeledSentenceProvider``).

Output batches are numpy dicts matching ``models.bert`` inputs:
``input_ids``, ``token_type_ids``, ``attention_mask``, and for MLM
``labels`` + ``label_weights`` (1.0 exactly at masked positions), for
classification a one-hot ``labels`` array.  Batches have static shapes
([batch, seq_len]) so the jit'd train step compiles once.
"""

from __future__ import annotations

import numpy as np
from typing import Iterator, Optional, Sequence

from deeplearning4j_tpu.nlp.tokenization import BertWordPieceTokenizer, Vocabulary


class CollectionSentenceProvider:
    """In-memory sentence source (reference: CollectionSentenceProvider)."""

    def __init__(self, sentences: Sequence[str]):
        self.sentences = list(sentences)

    def __iter__(self):
        return iter(self.sentences)

    def __len__(self):
        return len(self.sentences)


class CollectionLabeledSentenceProvider:
    """Labelled sentences (reference: CollectionLabeledSentenceProvider)."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str]):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels length mismatch")
        self.sentences = list(sentences)
        self.labels = list(labels)
        self.label_set = sorted(set(self.labels))
        self.label_index = {l: i for i, l in enumerate(self.label_set)}

    def __iter__(self):
        return iter(zip(self.sentences, self.labels))

    def __len__(self):
        return len(self.sentences)

    @property
    def num_classes(self) -> int:
        return len(self.label_set)


class BertMaskedLMMasker:
    """80/10/10 MLM masking (reference: BertMaskedLMMasker).

    For each maskable position, with probability ``mask_prob`` the token
    is selected; a selected token is replaced by [MASK] 80% of the time,
    by a random vocab token 10%, kept unchanged 10%.  Special tokens
    ([CLS]/[SEP]/[PAD]) are never selected.
    """

    def __init__(self, mask_prob: float = 0.15, mask_token_prob: float = 0.8,
                 random_token_prob: float = 0.1, seed: int = 12345):
        self.mask_prob = mask_prob
        self.mask_token_prob = mask_token_prob
        self.random_token_prob = random_token_prob
        self.rng = np.random.default_rng(seed)

    def mask_sequence(self, ids: np.ndarray, vocab: Vocabulary,
                      maskable: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """→ (masked_ids, labels, label_weights); labels hold the ORIGINAL
        ids everywhere, weights are 1.0 only where masked."""
        ids = np.asarray(ids, dtype=np.int32)
        labels = ids.copy()
        out = ids.copy()
        selected = (self.rng.random(ids.shape) < self.mask_prob) & maskable
        if not selected.any() and maskable.any():
            # guarantee >=1 masked position per sequence (reference masks at
            # least one token so the loss is never vacuous)
            idx = self.rng.choice(np.flatnonzero(maskable))
            selected[idx] = True
        action = self.rng.random(ids.shape)
        mask_here = selected & (action < self.mask_token_prob)
        random_here = selected & (action >= self.mask_token_prob) & \
            (action < self.mask_token_prob + self.random_token_prob)
        out[mask_here] = vocab.mask_id
        if random_here.any():
            out[random_here] = self.rng.integers(
                0, len(vocab), size=int(random_here.sum()), dtype=np.int32)
        weights = selected.astype(np.float32)
        return out, labels, weights


class BertIterator:
    """Static-shape batch iterator over a sentence provider.

    task="unsupervised" → MLM dicts; task="seq_classification" → one-hot
    labelled dicts.  Masking follows the reference's preserved-RNG
    behavior: each epoch draws FRESH masks (epoch index folded into the
    seed), while two iterators built with the same seed replay the same
    epoch sequence — deterministic but not mask-frozen.  Pass
    ``static_masks=True`` to reuse epoch-0 masks every epoch.

    Every batch has the same static shape [batch_size, seq_len]: the
    final partial batch is padded by duplicating rows, with the returned
    ``sample_weights`` vector 0 on padding rows (MLM ``label_weights``
    are zeroed there too, so padding never contributes loss).
    """

    UNSUPERVISED = "unsupervised"
    SEQ_CLASSIFICATION = "seq_classification"

    def __init__(self, tokenizer: BertWordPieceTokenizer, provider,
                 task: str = UNSUPERVISED, seq_len: int = 128,
                 batch_size: int = 32, masker: Optional[BertMaskedLMMasker] = None,
                 seed: int = 12345, static_masks: bool = False,
                 pad_final_batch: bool = True):
        if task not in (self.UNSUPERVISED, self.SEQ_CLASSIFICATION):
            raise ValueError(f"unknown task {task!r}")
        if task == self.SEQ_CLASSIFICATION and not hasattr(provider, "num_classes"):
            raise ValueError("seq_classification needs a labelled provider")
        self.tokenizer = tokenizer
        self.vocab = tokenizer.vocab
        self.provider = provider
        self.task = task
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.seed = seed
        self.static_masks = static_masks
        self.pad_final_batch = pad_final_batch
        self.masker = masker or BertMaskedLMMasker(seed=seed)
        self._epoch = 0

    # --------------------------------------------------------- encoding
    def _encode_sentence(self, text: str) -> tuple[np.ndarray, np.ndarray]:
        """→ (ids[seq_len], attention_mask[seq_len]) with [CLS] ... [SEP]
        framing, truncation and [PAD] padding."""
        ids = self.tokenizer.encode(text)[: self.seq_len - 2]
        ids = [self.vocab.cls_id] + ids + [self.vocab.sep_id]
        n = len(ids)
        ids = ids + [self.vocab.pad_id] * (self.seq_len - n)
        mask = np.zeros(self.seq_len, dtype=np.float32)
        mask[:n] = 1.0
        return np.asarray(ids, dtype=np.int32), mask

    def _maskable(self, ids: np.ndarray, attn: np.ndarray) -> np.ndarray:
        special = (ids == self.vocab.cls_id) | (ids == self.vocab.sep_id) | \
            (ids == self.vocab.pad_id)
        return (attn > 0) & ~special

    # --------------------------------------------------------- iteration
    def __iter__(self) -> Iterator[dict]:
        epoch = 0 if self.static_masks else self._epoch
        self.masker.rng = np.random.default_rng([self.seed, epoch])
        batch_items = []
        for item in self.provider:
            batch_items.append(item)
            if len(batch_items) == self.batch_size:
                yield self._build_batch(batch_items)
                batch_items = []
        if batch_items:
            yield self._build_batch(batch_items)

    def reset(self) -> None:
        self._epoch += 1

    def _pad_rows(self, n_real: int):
        """Row indices duplicating the batch up to batch_size + weights."""
        if not self.pad_final_batch or n_real == self.batch_size:
            idx = np.arange(n_real)
            return idx, np.ones(n_real, dtype=np.float32)
        idx = np.concatenate([np.arange(n_real),
                              np.arange(self.batch_size - n_real) % n_real])
        weights = np.zeros(self.batch_size, dtype=np.float32)
        weights[:n_real] = 1.0
        return idx, weights

    def _build_batch(self, items) -> dict:
        if self.task == self.UNSUPERVISED:
            rows = [self._encode_sentence(t) for t in items]
            ids = np.stack([r[0] for r in rows])
            attn = np.stack([r[1] for r in rows])
            masked, labels, weights = [], [], []
            for row_ids, row_attn in zip(ids, attn):
                m, l, w = self.masker.mask_sequence(
                    row_ids, self.vocab, self._maskable(row_ids, row_attn))
                masked.append(m); labels.append(l); weights.append(w)
            idx, sample_w = self._pad_rows(len(items))
            return {"input_ids": np.stack(masked)[idx],
                    "token_type_ids": np.zeros_like(ids)[idx],
                    "attention_mask": attn[idx],
                    "labels": np.stack(labels)[idx],
                    "label_weights": np.stack(weights)[idx] * sample_w[:, None],
                    "sample_weights": sample_w}
        # seq_classification
        texts = [t for t, _ in items]
        label_ids = [self.provider.label_index[l] for _, l in items]
        rows = [self._encode_sentence(t) for t in texts]
        ids = np.stack([r[0] for r in rows])
        attn = np.stack([r[1] for r in rows])
        onehot = np.eye(self.provider.num_classes, dtype=np.float32)[label_ids]
        idx, sample_w = self._pad_rows(len(items))
        return {"input_ids": ids[idx],
                "token_type_ids": np.zeros_like(ids)[idx],
                "attention_mask": attn[idx],
                "labels": onehot[idx],
                "sample_weights": sample_w}
