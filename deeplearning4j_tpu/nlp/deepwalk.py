"""DeepWalk graph embeddings.

Parity: the reference's ``deeplearning4j-graph`` module
(``org/deeplearning4j/graph/models/deepwalk/DeepWalk.java``,
``graph/iterator/RandomWalkIterator.java``, ``graph/graph/Graph.java``):
uniform random walks over a graph, fed to a skip-gram trainer.

The walk generator is host-side ETL (numpy); training reuses the batched
jit-compiled :class:`~deeplearning4j_tpu.nlp.embeddings.Word2Vec` step,
so the device program is the same one-SGD-step-per-batch XLA executable.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.embeddings import Word2Vec


class Graph:
    """Undirected-or-directed adjacency-list graph
    (reference ``org/deeplearning4j/graph/graph/Graph.java``)."""

    def __init__(self, n_vertices: int, directed: bool = False):
        self.n_vertices = n_vertices
        self.directed = directed
        self._adj: list[list[int]] = [[] for _ in range(n_vertices)]

    def add_edge(self, a: int, b: int) -> None:
        self._adj[a].append(b)
        if not self.directed:
            self._adj[b].append(a)

    def neighbors(self, v: int) -> list[int]:
        return self._adj[v]

    @staticmethod
    def from_edges(n_vertices: int, edges: Sequence[tuple[int, int]],
                   directed: bool = False) -> "Graph":
        g = Graph(n_vertices, directed)
        for a, b in edges:
            g.add_edge(a, b)
        return g


def random_walks(graph: Graph, walk_length: int, walks_per_vertex: int = 1,
                 seed: int = 0) -> list[list[int]]:
    """Uniform random walks from every vertex
    (reference ``RandomWalkIterator``: fixed length, restart per vertex)."""
    rng = np.random.default_rng(seed)
    walks: list[list[int]] = []
    for _ in range(walks_per_vertex):
        for start in rng.permutation(graph.n_vertices):
            walk = [int(start)]
            while len(walk) < walk_length:
                nbrs = graph.neighbors(walk[-1])
                if not nbrs:
                    break
                walk.append(int(nbrs[rng.integers(len(nbrs))]))
            if len(walk) > 1:
                walks.append(walk)
    return walks


class _VertexTokenizer:
    """Adapter: a walk is already a token list (vertex ids as strings)."""

    def create(self, text: str) -> list[str]:
        return text.split()


class DeepWalk:
    """DeepWalk: random walks → skip-gram vertex embeddings
    (reference ``DeepWalk.Builder``: vectorSize, windowSize, walkLength,
    learningRate)."""

    def __init__(self, vector_size: int = 64, window: int = 4,
                 walk_length: int = 20, walks_per_vertex: int = 8,
                 epochs: int = 2, learning_rate: float = 0.025,
                 negative: int = 5, hs: bool = False, seed: int = 0):
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.seed = seed
        self._w2v = Word2Vec(vector_size=vector_size, window=window,
                             min_count=1, negative=negative, hs=hs,
                             sample=0.0, epochs=epochs,
                             learning_rate=learning_rate, seed=seed,
                             tokenizer=_VertexTokenizer())
        self.graph: Optional[Graph] = None

    def fit(self, graph: Graph) -> "DeepWalk":
        self.graph = graph
        walks = random_walks(graph, self.walk_length, self.walks_per_vertex,
                             self.seed)
        sentences = [" ".join(str(v) for v in w) for w in walks]
        self._w2v.fit(sentences)
        return self

    def vertex_vector(self, v: int) -> np.ndarray:
        return self._w2v.word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._w2v.similarity(str(a), str(b))

    def vertices_nearest(self, v: int, top: int = 10) -> list[int]:
        return [int(w) for w in self._w2v.words_nearest(str(v), top)]
