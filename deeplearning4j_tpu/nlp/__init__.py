"""NLP: tokenization, BERT data pipeline, and embedding models.

Parity scope (SURVEY.md §2.6): the reference's ``deeplearning4j-nlp``
wordpiece tokenization (``BertWordPieceTokenizer``), the ``BertIterator``
MLM/classification batch builder that feeds the BERT fine-tune workload
(BASELINE config #4), the embedding stack (Word2Vec / GloVe /
ParagraphVectors with sentence iterators and a vocab cache), and
``deeplearning4j-graph``'s DeepWalk vertex embeddings.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    BasicTokenizer, WordpieceTokenizer, BertWordPieceTokenizer,
    Vocabulary, build_vocab)
from deeplearning4j_tpu.nlp.bert_iterator import (
    BertIterator, BertMaskedLMMasker, CollectionSentenceProvider,
    CollectionLabeledSentenceProvider)
from deeplearning4j_tpu.nlp.embeddings import (
    Word2Vec, Glove, ParagraphVectors, VocabCache, SentenceIterator,
    CollectionSentenceIterator, LineSentenceIterator,
    DefaultTokenizerFactory)
from deeplearning4j_tpu.nlp.deepwalk import DeepWalk, Graph, random_walks

__all__ = [
    "BasicTokenizer", "WordpieceTokenizer", "BertWordPieceTokenizer",
    "Vocabulary", "build_vocab", "BertIterator", "BertMaskedLMMasker",
    "CollectionSentenceProvider", "CollectionLabeledSentenceProvider",
    "Word2Vec", "Glove", "ParagraphVectors", "VocabCache",
    "SentenceIterator", "CollectionSentenceIterator", "LineSentenceIterator",
    "DefaultTokenizerFactory", "DeepWalk", "Graph", "random_walks",
]
