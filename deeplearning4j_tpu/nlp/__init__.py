"""NLP: tokenization + BERT data pipeline.

Parity scope (SURVEY.md §2.6): the reference's ``deeplearning4j-nlp``
wordpiece tokenization (``BertWordPieceTokenizer``) and the
``BertIterator`` MLM/classification batch builder that feeds the BERT
fine-tune workload (BASELINE config #4).  Word2Vec/GloVe/ParagraphVectors
are out of v1 scope per SURVEY.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    BasicTokenizer, WordpieceTokenizer, BertWordPieceTokenizer,
    Vocabulary, build_vocab)
from deeplearning4j_tpu.nlp.bert_iterator import (
    BertIterator, BertMaskedLMMasker, CollectionSentenceProvider,
    CollectionLabeledSentenceProvider)

__all__ = [
    "BasicTokenizer", "WordpieceTokenizer", "BertWordPieceTokenizer",
    "Vocabulary", "build_vocab", "BertIterator", "BertMaskedLMMasker",
    "CollectionSentenceProvider", "CollectionLabeledSentenceProvider",
]
